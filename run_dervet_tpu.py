"""CLI entry point (mirrors reference run_DERVET.py:73-92).

Usage:  python run_dervet_tpu.py <model_parameters.csv> [-v] [--backend auto|jax|cpu]
                                 [--base-path DIR] [--out DIR]
                                 [--checkpoint-dir DIR]

Exit codes: 0 success, 75 preempted (EX_TEMPFAIL — checkpoints and the
resume manifest were flushed; re-run with the same --checkpoint-dir).
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))

from dervet_tpu.api import DERVET


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="run_dervet_tpu",
        description="TPU-native DER valuation: dispatch optimization, sizing, "
                    "reliability, and cost-benefit analysis")
    parser.add_argument("parameters_filename",
                        help="model parameters CSV/JSON file")
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("--backend", default="auto",
                        choices=["auto", "jax", "cpu"],
                        help="dispatch solver backend (auto = jax for large "
                             "dispatches, cpu below the compile-amortization "
                             "threshold; jax = batched PDHG on TPU; cpu = "
                             "scipy HiGHS cross-validation path)")
    parser.add_argument("--base-path", default=None,
                        help="root for relative referenced-data paths "
                             "(default: the parameters file's directory)")
    parser.add_argument("--out", default=None,
                        help="override results output directory")
    parser.add_argument("--checkpoint-dir", default=None,
                        help="directory for per-window solve checkpoints and "
                             "the sweep-level run_manifest.json (resume an "
                             "interrupted run from here)")
    args = parser.parse_args(argv)

    from dervet_tpu.utils.errors import PreemptedError
    from dervet_tpu.utils.supervisor import EXIT_PREEMPTED

    case = DERVET(args.parameters_filename, verbose=args.verbose,
                  base_path=args.base_path)
    try:
        results = case.solve(backend=args.backend,
                             checkpoint_dir=args.checkpoint_dir)
    except PreemptedError as e:
        # EX_TEMPFAIL so job schedulers requeue instead of failing the job;
        # checkpoints + run_manifest.json were flushed before this raised
        print(f"preempted: {e}", file=sys.stderr)
        raise SystemExit(EXIT_PREEMPTED)
    results.save_as_csv(args.out)
    return results


if __name__ == "__main__":
    main()
