"""Slack (kappa-penalty soft constraints) + binary startup costs
(VERDICT r2 #4; reference surfaces: storagevet Scenario slack/kappa_* keys
and EnergyStorage incl_startup/p_start_ch/p_start_dis wired via
ESSSizing.py:389-396)."""
from pathlib import Path

import numpy as np
import pytest

from dervet_tpu.io.params import Params
from dervet_tpu.scenario.scenario import MicrogridScenario
from dervet_tpu.utils.errors import SolverError

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"


def _case(days=1, **scenario_overrides):
    case = Params.initialize(MP / "000-DA_battery_month.csv",
                             base_path=REF)[0]
    case.scenario["allow_partial_year"] = True
    case.scenario.update(scenario_overrides)
    case.datasets.time_series = case.datasets.time_series.iloc[: 24 * days]
    return case


def _battery_keys(case):
    return next(keys for tag, _id, keys in case.ders if tag == "Battery")


class TestStartupCosts:
    def test_startup_cost_in_objective(self):
        case = _case(binary=1)
        keys = _battery_keys(case)
        keys["startup"] = 1
        keys["p_start_dis"] = 50.0
        keys["p_start_ch"] = 25.0
        s = MicrogridScenario(case)
        s.optimize_problem_loop(backend="cpu")
        obj = next(iter(s.objective_values.values()))
        name = s.ders[0].name
        assert f"{name} startup" in obj, sorted(obj)
        # the battery cycles at least once a day, so starts were paid
        assert obj[f"{name} startup"] > 0
        # startup charges match the rising edges of the on-state INDICATORS
        # (not of ch/dis power: the solver may hold an indicator on through
        # an idle gap to avoid paying a second start); first step free
        v = s.ders[0].variables_df
        on_c = v["on_c"].to_numpy() > 0.5
        on_d = v["on_d"].to_numpy() > 0.5
        n_start_ch = int(np.sum(~on_c[:-1] & on_c[1:]))
        n_start_dis = int(np.sum(~on_d[:-1] & on_d[1:]))
        expect = 25.0 * n_start_ch + 50.0 * n_start_dis
        assert obj[f"{name} startup"] == pytest.approx(expect, rel=1e-6)

    def test_startup_reduces_cycling(self):
        """With steep startup costs the optimum uses no more starts than
        the free-startup dispatch — and the objective reflects the fee."""
        base = MicrogridScenario(_case(binary=1))
        base.optimize_problem_loop(backend="cpu")

        case = _case(binary=1)
        keys = _battery_keys(case)
        keys["startup"] = 1
        keys["p_start_dis"] = 500.0
        keys["p_start_ch"] = 500.0
        s = MicrogridScenario(case)
        s.optimize_problem_loop(backend="cpu")

        def n_starts(scn):
            res = scn.timeseries_results()
            bat = scn.ders[0]
            on = (res[bat.col("Charge (kW)")].to_numpy() > 1e-6) | \
                 (res[bat.col("Discharge (kW)")].to_numpy() > 1e-6)
            return int(np.sum(~on[:-1] & on[1:]))

        assert n_starts(s) <= n_starts(base)

    def test_startup_without_binary_warns_and_ignores(self):
        case = _case(binary=0)
        keys = _battery_keys(case)
        keys["startup"] = 1
        keys["p_start_dis"] = 50.0
        s = MicrogridScenario(case)
        s.optimize_problem_loop(backend="cpu")
        obj = next(iter(s.objective_values.values()))
        assert f"{s.ders[0].name} startup" not in obj


class TestSlackConstraints:
    def _with_energy_floor(self, slack, kappa=None):
        case = _case(binary=0, slack=slack)
        if kappa is not None:
            case.scenario["kappa_ene_min"] = kappa
        case.streams["User"] = {"price": 0.0}
        ts = case.datasets.time_series
        bat = _battery_keys(case)
        # an energy floor ABOVE the battery's usable maximum for two hours:
        # infeasible as a hard constraint, coverable only by slack
        floor = np.zeros(len(ts))
        floor[10:12] = float(bat.get("ene_max_rated", 0) or 0) * 2.0
        ts["Aggregate Energy Min (kWh)"] = floor
        return case

    def test_hard_constraints_infeasible(self):
        s = MicrogridScenario(self._with_energy_floor(slack=0))
        with pytest.raises(SolverError):
            s.optimize_problem_loop(backend="cpu")

    def test_slack_solves_and_prices_violation(self):
        s = MicrogridScenario(self._with_energy_floor(slack=1, kappa=1000.0))
        s.optimize_problem_loop(backend="cpu")
        obj = next(iter(s.objective_values.values()))
        assert "Slack" in obj, sorted(obj)
        # two hours of (2*E - E) kWh violation at kappa each
        bat = _battery_keys(self._with_energy_floor(slack=1))
        e_max = float(bat.get("ene_max_rated", 0) or 0)
        ulsoc = float(bat.get("ulsoc", 100) or 100) / 100.0
        expect = 1000.0 * 2 * (2.0 * e_max - ulsoc * e_max)
        assert obj["Slack"] == pytest.approx(expect, rel=1e-4)
