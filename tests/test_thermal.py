"""CHP thermal balance at the POI (reference MicrogridPOI.py:215-258 +
CombinedHeatPower.py:77-107: recovered steam/hot water must cover site
thermal loads; steam <= max_steam_ratio * hotwater;
(steam + hotwater) * electric_heat_ratio == elec)."""
from pathlib import Path

import numpy as np
import pytest

from dervet_tpu.io.params import Params
from dervet_tpu.scenario.scenario import MicrogridScenario
from dervet_tpu.utils.errors import ParameterError

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"


def _chp_case(steam=True, hotwater=True):
    cases = Params.initialize(MP / "000-DA_battery_month.csv", base_path=REF)
    case = cases[0]
    case.scenario["incl_thermal_load"] = True
    ts = case.datasets.time_series
    if steam:
        ts["Site Steam Thermal Load (BTU/hr)"] = 2e5
    if hotwater:
        ts["Site Hot Water Thermal Load (BTU/hr)"] = 1e5
    case.ders.append(("CHP", "1", {
        "name": "chp1", "rated_capacity": 500, "n": 1,
        "electric_heat_ratio": 0.0015, "max_steam_ratio": 10,
        "heat_rate": 9000, "variable_om_cost": 0.001, "fixed_om_cost": 0,
        "ccost": 0, "ccost_kW": 1000}))
    return case


def test_chp_covers_thermal_loads():
    s = MicrogridScenario(_chp_case())
    s.optimize_problem_loop(backend="cpu")
    ts = s.timeseries_results()
    steam = ts["CHP: chp1 Steam Heat Recovered (BTU/hr)"].to_numpy()
    hot = ts["CHP: chp1 Hot Water Heat Recovered (BTU/hr)"].to_numpy()
    assert (steam >= 2e5 - 1e-3).all()
    assert (hot >= 1e5 - 1e-3).all()
    # heat recovery tied to electric output
    elec = ts["CHP: chp1 Electric Generation (kW)"].to_numpy()
    np.testing.assert_allclose((steam + hot) * 0.0015, elec, rtol=1e-5,
                               atol=1e-3)
    # steam ratio constraint
    assert (steam <= 10 * hot + 1e-3).all()


def test_chp_missing_thermal_columns_raises():
    case = _chp_case(steam=False, hotwater=False)
    s = MicrogridScenario(case)
    with pytest.raises(ParameterError):
        s.optimize_problem_loop(backend="cpu")


def test_thermal_ignored_without_flag():
    case = _chp_case()
    case.scenario["incl_thermal_load"] = False
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    ts = s.timeseries_results()
    # without the balance the CHP has no reason to generate heat
    assert ts["CHP: chp1 Steam Heat Recovered (BTU/hr)"].sum() < \
        len(ts) * 2e5
