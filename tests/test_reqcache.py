"""Request-level memoization tests (service/reqcache.py + the router's
admission plane): cache-key safety, the certificate store guard,
collision handling, warm-memory invalidation propagation, per-window
delta digests, and the router-level hit / dedup / kill-switch paths.

Two tiers: pure-unit tests over the cache module, and stub-replica
router tests (precise control over when the leader answers, so the
co-pending dedup window is deterministic).  One real LocalReplica
end-to-end test proves a repeat request is answered from the cache with
zero replica dispatches and byte-identical CSV artifacts.
"""
import copy
import json
import pickle
import time

import numpy as np
import pytest

from dervet_tpu.benchlib import synthetic_sensitivity_cases
from dervet_tpu.ops.lp import LP
from dervet_tpu.ops.warmstart import SolutionMemory, opts_tag
from dervet_tpu.service import (FleetRouter, LocalReplica,
                                ScenarioService)
from dervet_tpu.service import reqcache
from dervet_tpu.service.fleet import ReplicaHandle


def _cases(n=1, window=None, months=1, variant=0):
    kwargs = {"months": months}
    if window is not None:
        kwargs["n"] = window
    cases = synthetic_sensitivity_cases(n, **kwargs)
    for c in cases:
        for tag, _, keys in c.ders:
            if tag == "Battery":
                keys["ene_max_rated"] = \
                    float(keys["ene_max_rated"]) + 0.5 * variant
    return {i: c for i, c in enumerate(cases)}


CASES = None


def _shared_cases():
    global CASES
    if CASES is None:
        CASES = _cases()
    return CASES


def _wait(pred, timeout=10.0, msg="condition not reached"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(msg)


# ---------------------------------------------------------------------------
# Key material
# ---------------------------------------------------------------------------

def _clean_health(**over):
    h = {"windows": {"clean": 4, "inaccurate": 0, "retried": 0,
                     "cpu_fallback": 0, "quarantined": 0, "skipped": 0},
         "cases_quarantined": [],
         "certification": {"enabled": True, "windows_certified": 4,
                           "windows": {"certified": 4,
                                       "certified_loose": 0,
                                       "rejected": 0,
                                       "rejected_then_recovered": 0,
                                       "rejected_final": 0}},
         "invariant_audit": {"ok": True, "cases_audited": 1,
                             "failing": []}}
    h.update(over)
    return h


class TestKeyMaterial:
    def test_tolerance_tag_changes_key(self):
        cases = _shared_cases()
        a = reqcache.key_material(cases, tolerance_tag="default")
        b = reqcache.key_material(cases, tolerance_tag="loose-1e-2")
        assert reqcache.material_key(a) != reqcache.material_key(b)

    def test_solver_version_changes_key(self):
        cases = _shared_cases()
        a = reqcache.key_material(cases)
        b = reqcache.key_material(cases, solver_version="pdhg-99.0")
        assert a["solver_version"] != "unknown"
        assert reqcache.material_key(a) != reqcache.material_key(b)

    def test_content_changes_data_not_structure(self):
        # same LP structure, different battery rating: the affinity
        # fingerprint matches but the content digest must not
        a = reqcache.key_material(_cases(variant=0))
        b = reqcache.key_material(_cases(variant=7))
        assert a["structure"] == b["structure"]
        assert a["data"] != b["data"]
        assert reqcache.material_key(a) != reqcache.material_key(b)

    def test_precomputed_digest_matches_inline(self):
        cases = _shared_cases()
        digest = reqcache.request_content_digest(cases)
        assert reqcache.key_material(cases) == \
            reqcache.key_material(cases, content_digest=digest)


# ---------------------------------------------------------------------------
# The store guard
# ---------------------------------------------------------------------------

class TestCacheable:
    def test_certified_clean_ok(self):
        ok, why = reqcache.cacheable(_clean_health(), "certified")
        assert ok, why

    def test_degraded_fidelity_refused(self):
        assert not reqcache.cacheable(_clean_health(), "degraded")[0]

    def test_missing_run_health_refused(self):
        assert not reqcache.cacheable(None, "certified")[0]

    def test_quarantined_case_refused(self):
        h = _clean_health(cases_quarantined=["3"])
        assert not reqcache.cacheable(h, "certified")[0]

    def test_rejected_final_refused(self):
        h = _clean_health()
        h["certification"]["windows"]["rejected_final"] = 1
        assert not reqcache.cacheable(h, "certified")[0]

    def test_rejected_then_recovered_still_cacheable(self):
        # a rejection the escalation ladder RECOVERED ends certified —
        # refusing it would starve the cache for no trust gain
        h = _clean_health()
        h["certification"]["windows"]["rejected"] = 1
        h["certification"]["windows"]["rejected_then_recovered"] = 1
        assert reqcache.cacheable(h, "certified")[0]

    def test_failed_invariant_audit_refused(self):
        h = _clean_health()
        h["invariant_audit"] = {"ok": False, "failing": ["0"]}
        assert not reqcache.cacheable(h, "certified")[0]


# ---------------------------------------------------------------------------
# The on-disk LRU cache
# ---------------------------------------------------------------------------

class _Answer:
    """Minimal picklable stand-in for an in-process Result."""

    def __init__(self, tag="a", run_health=None, fidelity="certified"):
        self.tag = tag
        self.run_health = (_clean_health() if run_health is None
                           else run_health)
        self.fidelity = fidelity

    def __eq__(self, other):
        return isinstance(other, _Answer) and other.tag == self.tag


class TestResultCache:
    def _material(self, salt="x"):
        return {"structure": "s" * 16, "data": f"d-{salt}",
                "tolerance": "default", "cert_policy": "{}",
                "solver_version": "pdhg-test"}

    def test_store_and_hit_roundtrip(self, tmp_path):
        cache = reqcache.RequestResultCache(tmp_path / "rc")
        m = self._material()
        assert cache.store("k1", m, rid="r1", result=_Answer("one"),
                           run_health=_clean_health(),
                           fidelity="certified")
        hit = cache.lookup("k1", m)
        assert hit is not None and hit.rid == "r1"
        assert hit.result == _Answer("one")
        assert cache.snapshot()["hits"] == 1

    def test_collision_never_serves_wrong_answer(self, tmp_path):
        # same 256-bit key, DIFFERENT material (the forced-collision
        # drill): the full material compare must miss, not serve
        cache = reqcache.RequestResultCache(tmp_path / "rc")
        cache.store("k1", self._material("x"), rid="r1",
                    result=_Answer("one"), run_health=_clean_health(),
                    fidelity="certified")
        assert cache.lookup("k1", self._material("y")) is None
        snap = cache.snapshot()
        assert snap["collisions"] == 1 and snap["hits"] == 0

    def test_refused_store_leaves_zero_disk_state(self, tmp_path):
        root = tmp_path / "rc"
        cache = reqcache.RequestResultCache(root)
        h = _clean_health()
        h["certification"]["windows"]["rejected_final"] = 2
        assert not cache.store("k1", self._material(), rid="r1",
                               result=_Answer(), run_health=h,
                               fidelity="certified")
        assert not root.exists()        # lazy mkdir never ran
        assert cache.snapshot()["refused"] == 1

    def test_lru_eviction_removes_disk_entry(self, tmp_path):
        root = tmp_path / "rc"
        cache = reqcache.RequestResultCache(root, max_entries=2)
        for i in range(3):
            cache.store(f"k{i}", self._material(str(i)), rid=f"r{i}",
                        result=_Answer(str(i)),
                        run_health=_clean_health(),
                        fidelity="certified")
        assert len(cache) == 2
        assert cache.lookup("k0", self._material("0")) is None
        assert not (root / "k0").exists()
        assert (root / "k2" / reqcache.ENTRY_FILE).exists()

    def test_adopts_prior_entries_from_disk(self, tmp_path):
        root = tmp_path / "rc"
        m = self._material()
        reqcache.RequestResultCache(root).store(
            "k1", m, rid="r1", result=_Answer("one"),
            run_health=_clean_health(), fidelity="certified")
        reborn = reqcache.RequestResultCache(root)
        hit = reborn.lookup("k1", m)
        assert hit is not None and hit.result == _Answer("one")

    def test_memory_invalidation_clears_live_caches(self, tmp_path):
        # the PR-4 trust chain: a certificate rejection invalidating a
        # warm-memory entry must clear every live request cache
        import scipy.sparse as sp
        cache = reqcache.open_cache(tmp_path / "rc")
        m = self._material()
        cache.store("k1", m, rid="r1", result=_Answer(),
                    run_health=_clean_health(), fidelity="certified")
        assert cache.lookup("k1", m) is not None

        class _Opts:
            eps_abs = 1e-4
            eps_rel = 1e-4
            max_iters = 1000
            inaccurate_factor = 10.0
            dtype = np.float32

        rng = np.random.default_rng(0)
        lp = LP(c=rng.normal(size=6),
                K=sp.csr_matrix(rng.normal(size=(4, 6))),
                q=rng.normal(size=4), n_eq=2, l=np.full(6, -10.0),
                u=np.full(6, 10.0), var_refs={}, row_groups={})
        mem = SolutionMemory(max_entries=16)
        tag = opts_tag(_Opts)
        mem.store("s1", lp, tag, np.ones(lp.n), np.ones(lp.m), 1.0)
        assert mem.invalidate("s1", lp) == 1
        assert len(cache) == 0
        assert cache.lookup("k1", m) is None
        assert cache.snapshot()["invalidations"] == 1


# ---------------------------------------------------------------------------
# Per-window delta digests
# ---------------------------------------------------------------------------

class TestDeltaDigests:
    def test_identical_requests_zero_changed(self):
        base = _cases(window=24)
        diff = reqcache.diff_request(base, copy.deepcopy(base))
        assert diff is not None
        assert diff["windows_changed"] == 0
        assert diff["windows_total"] > 5

    def test_single_window_edit_isolated(self):
        base = _cases(window=24)
        edited = copy.deepcopy(base)
        ts = edited[0].datasets.time_series
        # poke one load value inside the SECOND 24h window only
        col = [c for c in ts.columns if "load" in str(c).lower()][0]
        ts.iloc[30, ts.columns.get_loc(col)] += 1.0
        diff = reqcache.diff_request(base, edited)
        assert diff is not None
        assert diff["windows_changed"] == 1
        per = diff["per_case"]["0"]
        assert per["changed"] == [1]
        assert per["total"] == diff["windows_total"]

    def test_non_timeseries_edit_not_comparable(self):
        # a rating change touches every window's LP: the diff must
        # refuse to claim window-locality (None -> all changed)
        base = _cases(window=24)
        edited = _cases(window=24, variant=3)
        assert reqcache.diff_case(base[0], edited[0]) is None
        assert reqcache.diff_request(base, edited) is None


# ---------------------------------------------------------------------------
# Router-level admission: hit / dedup / kill switch
# ---------------------------------------------------------------------------

class StubReplica(ReplicaHandle):
    """Scripted replica: answers under test control."""

    def __init__(self, name):
        super().__init__(name)
        self.reqs = {}
        self.answers = {}

    def submit(self, cases, rid, *, priority=0, deadline_epoch=None,
               payload=None, trace_ctx=None, extra=None):
        self.reqs[rid] = cases

    def poll(self, rid):
        return self.answers.get(rid)

    def heartbeat(self):
        return {"t": time.time(), "name": self.name}


def _router(reps, tmp_path, **kw):
    kw.setdefault("heartbeat_timeout_s", 2.0)
    kw.setdefault("tick_s", 0.02)
    kw.setdefault("startup_grace_s", 5.0)
    kw.setdefault("fleet_dir", tmp_path / "fleet")
    return FleetRouter(reps, **kw).start()


class TestRouterMemoization:
    def test_hit_serves_with_zero_replica_dispatch(self, tmp_path):
        rep = StubReplica("a")
        r = _router([rep], tmp_path)
        try:
            fut = r.submit(_shared_cases(), request_id="m1")
            rep.answers["m1"] = ("done", _Answer("solved"))
            assert fut.result(timeout=10).result == _Answer("solved")
            _wait(lambda: r.metrics()["request_cache"]["stores"] == 1,
                  msg="answer never stored")
            res = r.submit(_shared_cases(),
                           request_id="m2").result(timeout=10)
            assert res.cached and res.replica == "request_cache"
            assert res.result == _Answer("solved")
            assert "m2" not in rep.reqs       # zero replica dispatches
            c = r.metrics()["routing"]
            assert c["request_cache_hits"] == 1
            assert c["completed"] == 2
            # both rids journaled to completion (exactly-once surface)
            events = [json.loads(ln) for ln in
                      (tmp_path / "fleet" /
                       "fleet_journal.jsonl").read_text().splitlines()]
            done = {e["rid"] for e in events
                    if e["event"] == "completed"}
            assert {"m1", "m2"} <= done
        finally:
            r.close(terminate_replicas=False)

    def test_uncacheable_answer_misses_next_time(self, tmp_path):
        rep = StubReplica("a")
        r = _router([rep], tmp_path)
        try:
            fut = r.submit(_shared_cases(), request_id="u1")
            h = _clean_health()
            h["certification"]["windows"]["rejected_final"] = 1
            rep.answers["u1"] = ("done", _Answer("bad", run_health=h))
            fut.result(timeout=10)
            _wait(lambda: r.metrics()["request_cache"]["refused"] == 1,
                  msg="store was not refused")
            fut2 = r.submit(_shared_cases(), request_id="u2")
            assert "u2" in rep.reqs           # re-dispatched, no hit
            rep.answers["u2"] = ("done", _Answer("bad2", run_health=h))
            assert not fut2.result(timeout=10).cached
        finally:
            r.close(terminate_replicas=False)

    def test_co_pending_identical_requests_coalesce(self, tmp_path):
        rep = StubReplica("a")
        r = _router([rep], tmp_path)
        try:
            f1 = r.submit(_shared_cases(), request_id="d1")
            f2 = r.submit(_shared_cases(), request_id="d2")
            f3 = r.submit(_shared_cases(), request_id="d3")
            # ONE solve for three identical co-pending requests
            assert set(rep.reqs) == {"d1"}
            rep.answers["d1"] = ("done", _Answer("once"))
            r1, r2, r3 = (f.result(timeout=10) for f in (f1, f2, f3))
            assert r1.result == r2.result == r3.result
            assert not r1.coalesced and r2.coalesced and r3.coalesced
            assert r2.rid == "d2" and r3.rid == "d3"
            c = r.metrics()["routing"]
            assert c["duplicates_coalesced"] == 2
            assert c["completed"] == 3
            events = [json.loads(ln) for ln in
                      (tmp_path / "fleet" /
                       "fleet_journal.jsonl").read_text().splitlines()]
            assert {e["rid"] for e in events
                    if e["event"] == "completed"} == {"d1", "d2", "d3"}
            assert {e["rid"] for e in events
                    if e["event"] == "coalesced"} == {"d2", "d3"}
        finally:
            r.close(terminate_replicas=False)

    def test_follower_rid_is_once_only(self, tmp_path):
        rep = StubReplica("a")
        r = _router([rep], tmp_path)
        try:
            r.submit(_shared_cases(), request_id="d1")
            r.submit(_shared_cases(), request_id="d2")
            with pytest.raises(ValueError, match="once-only"):
                r.submit(_shared_cases(), request_id="d2")
        finally:
            r.close(terminate_replicas=False)

    def test_kill_switch_restores_plain_path(self, tmp_path,
                                             monkeypatch):
        monkeypatch.setenv(reqcache.ENV, "0")
        rep = StubReplica("a")
        r = _router([rep], tmp_path)
        try:
            f1 = r.submit(_shared_cases(), request_id="k1")
            rep.answers["k1"] = ("done", _Answer("one"))
            f1.result(timeout=10)
            f2 = r.submit(_shared_cases(), request_id="k2")
            assert "k2" in rep.reqs           # no hit, no dedup
            rep.answers["k2"] = ("done", _Answer("two"))
            assert not f2.result(timeout=10).cached
            c = r.metrics()["routing"]
            assert c["request_cache_hits"] == 0
            assert c["request_cache_misses"] == 0
            # zero cache files OR dirs on disk
            assert not (tmp_path / "fleet" / "result_cache").exists()
        finally:
            r.close(terminate_replicas=False)

    def test_delta_submit_annotates_and_counts(self, tmp_path):
        rep = StubReplica("a")
        r = _router([rep], tmp_path)
        try:
            base = _cases(window=24)
            edited = copy.deepcopy(base)
            ts = edited[0].datasets.time_series
            col = [c for c in ts.columns
                   if "load" in str(c).lower()][0]
            ts.iloc[30, ts.columns.get_loc(col)] += 1.0
            fut = r.submit_delta(base, edited, request_id="dl1")
            assert "dl1" in rep.reqs
            rep.answers["dl1"] = ("done", _Answer("delta"))
            fut.result(timeout=10)
            assert r.metrics()["routing"]["delta_requests"] == 1
            events = [json.loads(ln) for ln in
                      (tmp_path / "fleet" /
                       "fleet_journal.jsonl").read_text().splitlines()]
            note = [e for e in events if e["event"] == "delta"]
            assert note and note[0]["windows_changed"] == 1
            assert note[0]["windows_total"] > 5
        finally:
            r.close(terminate_replicas=False)


# ---------------------------------------------------------------------------
# Client serialize-once (the queue-full retry re-pickling fix)
# ---------------------------------------------------------------------------

class TestClientSerializeOnce:
    def test_blob_and_digest_computed_once_across_retries(
            self, monkeypatch):
        from concurrent.futures import Future
        from dervet_tpu.service import ScenarioClient
        from dervet_tpu.service.queue import QueueFullError
        digests = []
        real = reqcache.request_content_digest
        monkeypatch.setattr(
            reqcache, "request_content_digest",
            lambda cases: digests.append(1) or real(cases))
        submits = []

        class _Svc:
            rejects = 2

            def submit(self, cases, *, request_id=None, priority=0,
                       deadline_s=None, cases_blob=None,
                       content_digest=None):
                submits.append((cases_blob, content_digest))
                if _Svc.rejects:
                    _Svc.rejects -= 1
                    raise QueueFullError("full", retry_after_s=0.0)
                f = Future()
                f.set_result("ok")
                return f

        client = ScenarioClient(_Svc(), max_retries=5, jitter_seed=1)
        assert client.submit(_shared_cases(),
                             request_id="c1").result() == "ok"
        assert len(submits) == 3
        # pickled ONCE before the retry loop: every attempt carries the
        # same bytes object and the digest was computed exactly once
        assert len(digests) == 1
        assert len({id(b) for b, _ in submits}) == 1
        assert all(isinstance(b, bytes) and d for b, d in submits)


# ---------------------------------------------------------------------------
# Real end-to-end: repeat request, byte-identical artifacts, no dispatch
# ---------------------------------------------------------------------------

class TestEndToEndCachedSolve:
    def test_repeat_request_byte_identical_zero_dispatch(self, tmp_path):
        service = ScenarioService(backend="cpu", max_wait_s=0.0)
        service.start()
        rep = LocalReplica("n0", service)
        r = _router([rep], tmp_path, heartbeat_timeout_s=5.0)
        try:
            res1 = r.submit(_cases(), request_id="e1").result(timeout=300)
            assert res1.result is not None and not res1.cached
            res2 = r.submit(_cases(), request_id="e2").result(timeout=300)
            assert res2.cached and res2.replica == "request_cache"
            assert "e2" not in rep._futures   # replica never touched
            d1, d2 = tmp_path / "out1", tmp_path / "out2"
            res1.result.save_as_csv(d1)
            res2.result.save_as_csv(d2)
            s1 = {p.name: p.read_bytes() for p in sorted(d1.glob("*.csv"))}
            s2 = {p.name: p.read_bytes() for p in sorted(d2.glob("*.csv"))}
            assert s1 and s1 == s2            # byte-identical artifacts
            # hit-path latency is microseconds-to-milliseconds, never a
            # solve: three orders of magnitude under the cold solve
            assert res2.latency_s < max(0.5, 0.05 * res1.latency_s)
        finally:
            r.close(terminate_replicas=False)
            service.close()
