"""Test configuration: force an 8-virtual-device CPU platform.

Multi-chip sharding paths are exercised on a virtual CPU mesh
(``xla_force_host_platform_device_count``) — the real TPU bench path is
driven by ``bench.py`` / ``__graft_entry__.py`` instead.

Note: this environment pre-imports jax at interpreter startup (sitecustomize
registers the TPU backend), so setting JAX_PLATFORMS here is too late — we
must force the platform through jax.config before any backend is touched.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# never persist/reload XLA:CPU executables in tests: the remote-compile
# terminal AOT-compiles them with the COMPILE machine's CPU features and
# reloading on this host can SIGILL (killed a --runslow run, r4)
os.environ["DERVET_TPU_NO_XLA_CACHE"] = "1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption("--runslow", action="store_true", default=False,
                     help="run tests marked slow")


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: mark test as slow to run")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip_slow = pytest.mark.skip(reason="need --runslow option to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)
