"""Overlapped-dispatch pipeline tests (perf PR r6).

The dispatch pipeline (staged uploads, overlapped group solves, fused
readback, early per-case completion) must be a pure EXECUTION-ORDER
optimization: grouping, batch contents, and solver inputs are identical
to the strict serial path, so results are byte-identical — asserted
here, not trusted.  The per-group solve ledger is the other contract:
every dispatch publishes a schema-valid decomposition of the solve phase
whose line items sum to the measured ``dispatch_solve_s``.
"""
import numpy as np
import pytest

from dervet_tpu.benchlib import (synthetic_sensitivity_cases,
                                 validate_solve_ledger)
from dervet_tpu.scenario.scenario import (MicrogridScenario,
                                          _stack_group_data, run_dispatch,
                                          stage_group_data)


def _fanout_scenarios(n_cases=3, months=2):
    return [MicrogridScenario(c)
            for c in synthetic_sensitivity_cases(n_cases, months=months)]


@pytest.fixture(scope="module")
def pipelined():
    """One small fan-out dispatched through the pipeline, with the
    case-completion hook recording its firings."""
    import os
    os.environ.pop("DERVET_TPU_PIPELINE", None)   # default: pipeline on
    scens = _fanout_scenarios()
    fired = []
    run_dispatch(scens, backend="jax",
                 on_case_solved=lambda s: fired.append(s.case.case_id))
    return scens, fired


@pytest.fixture(scope="module")
def serial():
    """The identical fan-out through the strict serial reference path."""
    import os
    os.environ["DERVET_TPU_PIPELINE"] = "0"
    try:
        scens = _fanout_scenarios()
        run_dispatch(scens, backend="jax")
    finally:
        os.environ.pop("DERVET_TPU_PIPELINE", None)
    return scens


class TestByteIdentical:
    def test_objectives_identical(self, pipelined, serial):
        for sp, ss in zip(pipelined[0], serial):
            assert sp.objective_values.keys() == ss.objective_values.keys()
            for label in sp.objective_values:
                bp = sp.objective_values[label]
                bs = ss.objective_values[label]
                assert bp.keys() == bs.keys()
                for col in bp:
                    # byte-identical, not approx: the pipeline may not
                    # change WHAT is solved, only when
                    assert bp[col] == bs[col], (label, col)

    def test_solution_arrays_identical(self, pipelined, serial):
        for sp, ss in zip(pipelined[0], serial):
            assert set(sp._solution) == set(ss._solution)
            for name in sp._solution:
                assert np.array_equal(sp._solution[name],
                                      ss._solution[name]), name

    def test_results_csv_identical(self, pipelined, serial, tmp_path):
        """The full results CSV surface — what a user actually reads —
        is byte-identical between the pipelined and serial paths."""
        from dervet_tpu.results.result import CaseResult
        sp, ss = pipelined[0][0], serial[0]
        for s, sub in ((sp, "pipe"), (ss, "serial")):
            inst = CaseResult(s)
            inst.collect_results()
            inst.calculate_cba()
            inst.save_as_csv(tmp_path / sub)
        pipe_files = sorted(p.name for p in (tmp_path / "pipe").iterdir())
        serial_files = sorted(p.name
                              for p in (tmp_path / "serial").iterdir())
        assert pipe_files == serial_files and pipe_files
        for name in pipe_files:
            a = (tmp_path / "pipe" / name).read_bytes()
            b = (tmp_path / "serial" / name).read_bytes()
            assert a == b, f"{name} differs between pipelined and serial"

    def test_pipeline_flag_recorded(self, pipelined, serial):
        assert pipelined[0][0].solve_metadata["solve_ledger"]["pipeline"] \
            is True
        assert serial[0].solve_metadata["solve_ledger"]["pipeline"] is False


class TestSolveLedger:
    def test_schema_valid(self, pipelined):
        for s in pipelined[0]:
            validate_solve_ledger(s.solve_metadata["solve_ledger"])

    def test_line_items_sum_to_dispatch_solve(self, pipelined):
        """Acceptance gate: ledger line items sum to within 10% of the
        measured dispatch_solve_s, and each jax entry's in-wall split
        reconstructs its own wall."""
        led = pipelined[0][0].solve_metadata["solve_ledger"]
        af = led["accounted_fraction"]
        assert af is not None and abs(af - 1.0) <= 0.10, led
        for g in led["groups"]:
            if g.get("backend") == "cpu":
                continue
            parts = g["stack_s"] + g["h2d_s"] + g["sync_wait_s"] \
                + g["result_fetch_s"] + g["other_s"]
            assert parts == pytest.approx(g["solve_s"], abs=2e-3), g

    def test_ledger_covers_all_windows(self, pipelined):
        scens = pipelined[0]
        led = scens[0].solve_metadata["solve_ledger"]
        n_windows = sum(len(s.windows) for s in scens)
        initial = [g for g in led["groups"] if g.get("rung") == "initial"]
        assert sum(g["batch"] for g in initial) == n_windows
        assert led["totals"]["windows"] >= n_windows
        assert "iters" in led and led["iters"]["p50"] > 0

    def test_device_traffic_observables_present(self, pipelined):
        led = pipelined[0][0].solve_metadata["solve_ledger"]
        tot = led["totals"]
        assert tot["dispatches"] > 0
        assert tot["chunks"] > 0
        assert tot["readbacks"] > 0
        assert tot["compile_events"] > 0
        assert tot["h2d_bytes"] > 0
        assert tot["result_bytes"] > 0

    def test_ledger_on_cpu_backend(self):
        """The cpu backend publishes a (smaller) ledger too — so the CI
        smoke and the sensitivity leg's serial-CPU comparison carry the
        same observable."""
        scens = _fanout_scenarios(n_cases=2, months=1)
        run_dispatch(scens, backend="cpu")
        led = scens[0].solve_metadata["solve_ledger"]
        assert led["pipeline"] is False
        assert all(g["backend"] == "cpu" for g in led["groups"])
        assert abs(led["accounted_fraction"] - 1.0) <= 0.10


class TestCaseCompletionHook:
    def test_fires_once_per_case_with_complete_solution(self, pipelined):
        scens, fired = pipelined
        assert sorted(fired) == sorted(s.case.case_id for s in scens)
        # at fire time every window was solved; solutions stayed complete
        for s in scens:
            assert {ctx.label for ctx in s.windows} <= s._solved


class TestApiOverlapPath:
    """The api-level overlap machinery (on_case_solved scatter + worker-
    pool build_instance + late registration in case order + pool
    shutdown) exercised end-to-end through ``DERVET.solve`` — with
    ``Params.initialize`` monkeypatched to the synthetic fan-out, so this
    runs in CI without the reference dataset."""

    def _solve(self, monkeypatch, pipeline: str):
        import os
        from dervet_tpu.api import DERVET
        from dervet_tpu.io.params import Params
        monkeypatch.setattr(
            Params, "initialize",
            classmethod(lambda cls, path, base_path=None, verbose=False:
                        dict(enumerate(
                            synthetic_sensitivity_cases(3, months=2)))))
        monkeypatch.setenv("DERVET_TPU_PIPELINE", pipeline)
        try:
            return DERVET("synthetic://fanout").solve(backend="jax")
        finally:
            os.environ.pop("DERVET_TPU_PIPELINE", None)

    def test_overlapped_post_matches_serial_csvs(self, monkeypatch,
                                                 tmp_path):
        res_p = self._solve(monkeypatch, "1")
        res_s = self._solve(monkeypatch, "0")
        assert sorted(res_p.instances) == sorted(res_s.instances) \
            == [0, 1, 2]
        # registration order is the cases' original order either way
        assert list(res_p.instances) == list(res_s.instances)
        res_p.save_as_csv(tmp_path / "pipe")
        res_s.save_as_csv(tmp_path / "serial")
        pipe = sorted(p.name for p in (tmp_path / "pipe").iterdir())
        serial = sorted(p.name for p in (tmp_path / "serial").iterdir())
        assert pipe == serial and pipe
        for name in pipe:
            if name == "run_health.json":
                continue   # carries wall-clock retry_seconds
            a = (tmp_path / "pipe" / name).read_bytes()
            b = (tmp_path / "serial" / name).read_bytes()
            assert a == b, f"{name} differs between overlapped and serial"
        assert res_p.solve_ledger is not None
        assert res_p.solve_ledger["pipeline"] is True
        assert res_s.solve_ledger["pipeline"] is False


class TestStagedUploads:
    def test_staged_solve_matches_host_path(self):
        """stage_group_data's stacked+uploaded arrays produce bit-equal
        solver results vs handing the solver host arrays (the staged
        upload is a transport change only)."""
        from dervet_tpu.ops.pdhg import CompiledLPSolver
        from tests.test_pdhg import battery_like_lp

        lp = battery_like_lp(T=48)
        rng = np.random.default_rng(3)
        lps = []
        for i in range(4):
            import copy
            lp_i = copy.deepcopy(lp)
            lp_i.c[:] = lp.c * (1.0 + 0.1 * rng.standard_normal(lp.n))
            lps.append(lp_i)
        items = [(None, None, lp_i) for lp_i in lps]
        staged = stage_group_data(items, None, force=True)
        assert staged is not None
        assert staged.h2d_bytes > 0
        solver = CompiledLPSolver(lp)
        res_staged = solver.solve(c=staged.arrays[0], q=staged.arrays[1],
                                  l=staged.arrays[2], u=staged.arrays[3])
        C, Q, L, U = _stack_group_data(
            lps, np.dtype(solver.opts.dtype), multi_dev=False)
        res_host = solver.solve(c=C, q=Q, l=L, u=U)
        np.testing.assert_array_equal(np.asarray(res_staged.x),
                                      np.asarray(res_host.x))
        np.testing.assert_array_equal(np.asarray(res_staged.obj),
                                      np.asarray(res_host.obj))

    def test_identical_vectors_collapse_to_shared(self):
        """The 1-D dedup collapse survives in the staging path: vectors
        identical across the group stay 1-D (no (B, n) block upload)."""
        from tests.test_pdhg import battery_like_lp
        lp = battery_like_lp(T=24)
        lps = [lp, lp, lp]
        C, Q, L, U = _stack_group_data(lps, np.dtype(np.float32),
                                       multi_dev=False)
        assert C.ndim == Q.ndim == L.ndim == U.ndim == 1

    def test_solve_stats_populated(self):
        """CompiledLPSolver.last_stats carries the ledger raw material."""
        from dervet_tpu.ops.pdhg import CompiledLPSolver
        from tests.test_pdhg import battery_like_lp
        lp = battery_like_lp(T=24)
        solver = CompiledLPSolver(lp)
        res = solver.solve()
        assert bool(np.asarray(res.converged))
        st = solver.last_stats
        assert st is not None
        assert st.dispatches > 0 and st.chunks > 0 and st.readbacks > 0
        assert st.h2d_bytes > 0       # c/q/l/u defaults were host arrays
        assert st.compile_events > 0
        # a second solve of the same shape recompiles nothing
        solver.solve()
        assert solver.last_stats.compile_events == 0
