"""Warm-start subsystem: solution memory seeding PDHG across requests,
refinement tiers, and escalation rungs (ops/warmstart.py).

The contract under test:

* a seeded solve converges in FEWER iterations than a cold one, and a
  zero seed reproduces the cold start bit for bit;
* a warm service's results are BYTE-IDENTICAL to a cold service's on
  repeat requests (exact-match substitution re-verifies the stored
  solution against the full convergence criteria in float64, then ships
  it verbatim), with 100% certification and zero device dispatches /
  compile events on the warm pass;
* ``DERVET_TPU_WARMSTART=0`` kills the subsystem live (cold path, no
  ``warm`` ledger entries);
* the memory is a bounded LRU — a tiny ``DERVET_TPU_WARMSTART_CAP``
  evicts but never crashes a round;
* the escalation ladder's retry rung seeds from the failed member's
  last iterate and converges in fewer iterations than the original
  attempt;
* the ``stale_seed`` fault corrupts a seed and the solve STILL
  converges and certifies — seed corruption costs iterations, never
  correctness — with the extra iterations attributed in the ledger;
* the design screen's refinement tiers seed each other through the
  shared memory, and a repeat design request reproduces the certified
  frontier exactly.
"""
import logging
import os

import numpy as np
import pytest

from dervet_tpu.benchlib import synthetic_sensitivity_cases
from dervet_tpu.ops import warmstart
from dervet_tpu.ops.lp import LPBuilder
from dervet_tpu.ops.pdhg import (STATUS_CONVERGED, CompiledLPSolver,
                                 PDHGOptions)
from dervet_tpu.scenario.scenario import (MicrogridScenario, SolverCache,
                                          resolve_group, run_dispatch)
from dervet_tpu.utils import faultinject


def _arb_lp(T=48, seed=1):
    """Small battery-arbitrage LP (same block structure the dispatch
    engine emits)."""
    rng = np.random.default_rng(seed)
    price = rng.uniform(10, 80, T) / 1000
    b = LPBuilder()
    ch = b.var("ch", T, 0.0, 250.0)
    dis = b.var("dis", T, 0.0, 250.0)
    ene = b.var("ene", T, 0.0, 1000.0)
    D = np.eye(T) - np.eye(T, k=-1)
    rhs = np.zeros(T)
    rhs[0] = 500.0
    b.add_rows("soe", [(ene, D), (ch, -0.85), (dis, 1.0)], "eq", rhs)
    b.add_cost(ch, price)
    b.add_cost(dis, -price)
    return b.build()


def _run_round(cases, cache):
    """One dispatch round over fresh scenarios; returns (scenarios,
    summarized solve ledger)."""
    scens = [MicrogridScenario(c) for c in cases]
    run_dispatch(scens, backend="jax", solver_cache=cache)
    return scens, scens[0].solve_metadata.get("solve_ledger")


def _assert_solutions_equal(a, b):
    for s, v in zip(a, b):
        assert s.objective_values == v.objective_values
        assert set(s._solution) == set(v._solution)
        for name in s._solution:
            assert np.array_equal(s._solution[name], v._solution[name]), \
                name


# ---------------------------------------------------------------------------
# Solver-level seeding (init_state x0/y0 override)
# ---------------------------------------------------------------------------

class TestSeededSolver:
    def test_seeded_solve_converges_faster(self):
        lp = _arb_lp()
        solver = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=False))
        cold = solver.solve()
        assert bool(cold.converged)
        warm = solver.solve(x0=np.asarray(cold.x), y0=np.asarray(cold.y))
        assert bool(warm.converged)
        assert int(warm.iters) < int(cold.iters)

    def test_zero_seed_is_cold_start_bitwise(self):
        """clip(0 / dc) == clip(0): the seeded program with zero seeds
        reproduces the unseeded program's result exactly — the property
        that lets partially-seeded batches leave cold members' results
        untouched."""
        lp = _arb_lp()
        solver = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=False))
        C = np.stack([lp.c, lp.c * 1.01, lp.c * 0.99])
        cold = solver.solve(c=C)
        zero = solver.solve(c=C, x0=np.zeros((3, lp.n)),
                            y0=np.zeros((3, lp.m)))
        assert np.array_equal(np.asarray(cold.x), np.asarray(zero.x))
        assert np.array_equal(np.asarray(cold.obj), np.asarray(zero.obj))
        assert np.array_equal(np.asarray(cold.iters),
                              np.asarray(zero.iters))

    def test_out_of_box_seed_is_clipped_and_converges(self):
        """A stale seed outside the instance's box is clipped into it —
        it can cost iterations, never break the solve."""
        lp = _arb_lp()
        solver = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=False))
        bad_x = np.full(lp.n, 1e6)
        bad_y = np.full(lp.m, -1e3)
        res = solver.solve(x0=bad_x, y0=bad_y)
        assert bool(res.converged)

    def test_partial_seed_leaves_cold_members_bitwise(self):
        """Mixed batch: member 0 seeded from its own solution, members
        1-2 zero-seeded — the cold members' results match the fully-cold
        batch bit for bit."""
        lp = _arb_lp()
        solver = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=False))
        C = np.stack([lp.c, lp.c * 1.02, lp.c * 0.98])
        cold = solver.solve(c=C)
        X0 = np.zeros((3, lp.n))
        Y0 = np.zeros((3, lp.m))
        X0[0] = np.asarray(cold.x)[0]
        Y0[0] = np.asarray(cold.y)[0]
        mixed = solver.solve(c=C, x0=X0, y0=Y0)
        assert np.asarray(mixed.iters)[0] <= np.asarray(cold.iters)[0]
        for i in (1, 2):
            assert np.array_equal(np.asarray(mixed.x)[i],
                                  np.asarray(cold.x)[i])


# ---------------------------------------------------------------------------
# SolutionMemory: lookup grades, LRU bound, host convergence check
# ---------------------------------------------------------------------------

class TestSolutionMemory:
    def _solved(self):
        lp = _arb_lp()
        solver = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=False))
        res = solver.solve()
        return lp, solver, np.asarray(res.x), np.asarray(res.y), \
            float(res.obj)

    def test_exact_requires_data_and_tolerance_tag(self):
        lp, solver, x, y, obj = self._solved()
        mem = warmstart.SolutionMemory(max_entries=8)
        tag = warmstart.opts_tag(solver.opts)
        mem.store("sk", lp, tag, x, y, obj)
        e, kind = mem.lookup("sk", lp, tag)
        assert kind == "exact" and np.array_equal(e.x, x)
        # same data, different tolerance regime -> near (seed-only)
        loose = warmstart.opts_tag(PDHGOptions.screening())
        e2, kind2 = mem.lookup("sk", lp, loose)
        assert kind2 == "near"
        # perturbed data -> near via quantized digest / feature vector
        import copy
        lp2 = copy.copy(lp)
        lp2.c = lp.c * 1.001
        e3, kind3 = mem.lookup("sk", lp2, tag)
        assert kind3 == "near"
        # unknown structure -> miss
        e4, kind4 = mem.lookup("other", lp, tag)
        assert e4 is None and kind4 is None

    def test_lru_cap_evicts(self):
        lp, solver, x, y, obj = self._solved()
        mem = warmstart.SolutionMemory(max_entries=2)
        tag = warmstart.opts_tag(solver.opts)
        import copy
        for i in range(5):
            lpi = copy.copy(lp)
            lpi.c = lp.c * (1.0 + 0.1 * i)
            mem.store("sk", lpi, tag, x, y, obj)
        snap = mem.snapshot()
        assert snap["entries"] == 2
        assert snap["evictions"] == 3
        # lookups still work after eviction
        e, kind = mem.lookup("sk", lp, tag)
        assert kind in ("near", None) or e is not None

    def test_host_convergence_check(self):
        lp, solver, x, y, obj = self._solved()
        assert warmstart.check_converged_host(lp, x, y, solver.opts)
        assert not warmstart.check_converged_host(lp, x * 3 + 1, y,
                                                  solver.opts)
        # wrong shapes / non-finite are rejected, not crashed
        assert not warmstart.check_converged_host(lp, x[:-1], y,
                                                  solver.opts)
        bad = x.copy()
        bad[0] = np.nan
        assert not warmstart.check_converged_host(lp, bad, y, solver.opts)


# ---------------------------------------------------------------------------
# Dispatch-level: byte identity, kill switch, LRU under dispatch, faults
# ---------------------------------------------------------------------------

class TestWarmDispatch:
    def test_repeat_round_byte_identical_and_substituted(self):
        """The acceptance contract end to end: a repeat round ships
        byte-identical results with zero device dispatches, zero compile
        events, iters 0, 100% certification — and the ledger measures
        the win against the cold baseline."""
        cases = synthetic_sensitivity_cases(2, months=1)
        cache = SolverCache(pad_grid=True, warm_start=True)
        s1, led1 = _run_round(cases, cache)
        s2, led2 = _run_round(cases, cache)
        _assert_solutions_equal(s1, s2)
        w = led2["warm_start"]
        assert w["substituted"] == w["seeded"] == 2
        assert w["iters_p50_seeded"] == 0
        assert w["iters_saved"] > 0
        g = [g for g in led2["groups"] if g.get("rung") == "initial"][0]
        assert g["warm"]["baseline_cold_p50"] > 0
        assert g["dispatches"] == 0 and g["compile_events"] == 0
        # the acceptance gate: >=30% median iteration reduction
        cold_p50 = led1["warm_start"]["iters_p50_cold"]
        assert w["iters_p50_seeded"] <= 0.7 * cold_p50
        # every substituted window still carries a full certificate
        for s in s2:
            cert = s.certification
            assert cert["certified"] + cert["certified_loose"] == \
                len(s.windows)
            assert cert["rejected"] == 0

    def test_warm_first_round_equals_cold_round(self):
        """An empty memory's first round is the cold path bit for bit."""
        cases = synthetic_sensitivity_cases(2, months=1)
        s_warm, _ = _run_round(cases, SolverCache(pad_grid=True,
                                                  warm_start=True))
        s_cold, led = _run_round(cases, SolverCache(pad_grid=True))
        _assert_solutions_equal(s_warm, s_cold)
        assert led.get("warm_start") is None      # no memory, no claims

    def test_kill_switch_forces_cold_path(self, monkeypatch):
        """DERVET_TPU_WARMSTART=0 read live: an existing warm cache
        stops seeding (no ``warm`` ledger section), and a cache built
        under the switch never creates a memory at all."""
        cases = synthetic_sensitivity_cases(1, months=1)
        cache = SolverCache(pad_grid=True, warm_start=True)
        _run_round(cases, cache)
        monkeypatch.setenv(warmstart.WARMSTART_ENV, "0")
        s2, led2 = _run_round(cases, cache)
        assert led2.get("warm_start") is None
        g = [g for g in led2["groups"] if g.get("rung") == "initial"][0]
        assert "warm" not in g and g["iters_p50"] > 0   # genuinely cold
        assert SolverCache(warm_start=True).memory is None

    def test_tiny_lru_cap_never_crashes_a_round(self, monkeypatch):
        monkeypatch.setenv(warmstart.CAP_ENV, "1")
        cases = synthetic_sensitivity_cases(2, months=1)
        cache = SolverCache(pad_grid=True, warm_start=True)
        assert cache.memory.max_entries == 1
        s1, _ = _run_round(cases, cache)
        s2, led2 = _run_round(cases, cache)
        assert cache.memory.snapshot()["evictions"] >= 1
        # the round completes and certifies; the one surviving entry may
        # still substitute its member
        for s in s2:
            assert s.quarantine is None
            cert = s.certification
            assert cert["certified"] + cert["certified_loose"] == \
                len(s.windows)

    def test_partial_substitution_keeps_compiled_shapes(self):
        """A warm round where SOME members substitute pads the device
        subset to the FULL group's bucket — the shape the cold round
        compiled — so substitution never mints a new program shape
        (zero compile events even on a mixed repeat)."""
        fam = synthetic_sensitivity_cases(3, months=1)
        cache = SolverCache(pad_grid=True, warm_start=True)
        _run_round(fam[:2], cache)                    # cold: bucket 8
        s2, led2 = _run_round([fam[0], fam[2]], cache)
        w = led2["warm_start"]
        # the new member near-matches the stored neighbors (same
        # structure), so it is iterate-seeded rather than cold
        assert w["substituted"] == 1 and w["near"] >= 1
        for s in s2:
            assert s.quarantine is None
        # the shape contract itself (single-device serving): a shrunken
        # subset pads to the FULL group's bucket, never a smaller one or
        # the single-instance family.  (This 8-virtual-device platform
        # rides the sharded path, which does mesh-multiple padding — so
        # the decision is pinned directly.)
        from dervet_tpu.scenario.scenario import _subset_pad_to
        assert _subset_pad_to(cache, 2, 1, multi_dev=False) == 8
        assert _subset_pad_to(cache, 10, 5, multi_dev=False) == 32
        assert _subset_pad_to(cache, 10, 5, multi_dev=True) is None
        assert _subset_pad_to(SolverCache(), 10, 5,
                              multi_dev=False) is None   # pad_grid off

    def test_cert_rejection_invalidates_memory_entry(self):
        """A certificate rejection drops the memory entry that vouched
        for the data: without invalidation, a wrong-but-KKT-passing
        entry would be re-substituted, re-rejected, and re-escalated on
        every exact repeat forever.  Driven with the corrupt_solution
        fault: the substituted answer is corrupted post-solve, the
        certifier rejects it, the entry is invalidated, the ladder
        recovers, and the NEXT round goes cold and re-stores."""
        cases = synthetic_sensitivity_cases(1, months=1)
        cache = SolverCache(pad_grid=True, warm_start=True)
        _run_round(cases, cache)                      # populate
        with faultinject.inject(corrupt={"all"}, rungs={"solve"}):
            s2, _ = _run_round(cases, cache)
        assert cache.memory.snapshot()["invalidated"] >= 1
        for s in s2:
            assert s.quarantine is None
            assert s.certification["rejected_then_recovered"] >= 1
        # the repeat after invalidation runs cold (no stale substitution
        # loop) and repopulates the memory
        s3, led3 = _run_round(cases, cache)
        w = led3["warm_start"]
        assert w["substituted"] == 0 and w["cold"] >= 1
        for s in s3:
            assert s.quarantine is None
        s4, led4 = _run_round(cases, cache)
        assert led4["warm_start"]["substituted"] == 1   # healthy again

    def test_stale_seed_costs_iterations_never_correctness(self):
        """The stale_seed fault corrupts the warm seed: the member is
        demoted from substitution to iterate seeding, converges anyway,
        certifies, and the ledger attributes the extra iterations to a
        seeded member with the fault counted."""
        cases = synthetic_sensitivity_cases(1, months=1)
        cache = SolverCache(pad_grid=True, warm_start=True)
        s1, _ = _run_round(cases, cache)
        with faultinject.inject(stale_seed={"all"}) as plan:
            s2, led2 = _run_round(cases, cache)
        assert any(ev == faultinject.EVENT_STALE_SEED
                   for ev, _ in plan.fired)
        w = led2["warm_start"]
        assert w["stale_seed_faults"] >= 1
        assert w["substituted"] == 0          # demoted to iterate seeding
        assert w["seeded"] == 1
        assert w["iters_p50_seeded"] > 0      # the corruption's cost
        for s in s2:
            assert s.quarantine is None
            cert = s.certification
            assert cert["certified"] + cert["certified_loose"] == \
                len(s.windows)
            assert cert["rejected"] == 0
        # correctness untouched: same answers as the clean first round
        # to solver tolerance
        for a, b in zip(s1, s2):
            for k, av in a.objective_values.items():
                assert av["Total Objective"] == pytest.approx(
                    b.objective_values[k]["Total Objective"], rel=1e-4)


# ---------------------------------------------------------------------------
# Service-level: warm vs cold byte identity across the full CSV surface
# ---------------------------------------------------------------------------

class TestServiceWarmRepeat:
    def test_repeat_request_csv_surface_identical_to_cold_service(
            self, tmp_path, monkeypatch):
        """Two identical requests through a WARM service vs a COLD
        (kill-switched) service: every results CSV byte-identical in
        both rounds, the warm repeat fully substituted with zero compile
        events, and the warm pass 100% certified."""
        from dervet_tpu.service import ScenarioService
        cases = {i: c for i, c in
                 enumerate(synthetic_sensitivity_cases(2, months=1))}

        def two_rounds(svc):
            f1 = svc.submit(cases, request_id="r1")
            assert svc.run_once() == 1
            f2 = svc.submit(cases, request_id="r2")
            assert svc.run_once() == 1
            return f1.result(0), f2.result(0)

        monkeypatch.setenv(warmstart.WARMSTART_ENV, "0")
        cold_svc = ScenarioService(backend="jax", max_wait_s=0.0)
        try:
            c1, c2 = two_rounds(cold_svc)
            assert cold_svc.metrics()["warm_start"] is None
        finally:
            cold_svc.close()
        monkeypatch.delenv(warmstart.WARMSTART_ENV)
        warm_svc = ScenarioService(backend="jax", max_wait_s=0.0)
        try:
            w1, w2 = two_rounds(warm_svc)
            led = warm_svc.last_round_ledger
            m = warm_svc.metrics()
        finally:
            warm_svc.close()
        assert led["warm_start"]["substituted"] == 2
        assert led["totals"]["compile_events"] == 0
        assert m["rounds"]["substituted_windows"] == 2
        assert m["warm_start"]["substituted"] == 2
        for res, sub in ((c1, "c1"), (c2, "c2"), (w1, "w1"), (w2, "w2")):
            res.save_as_csv(tmp_path / sub)
        for cold_dir, warm_dir in (("c1", "w1"), ("c2", "w2")):
            names = sorted(p.name for p in
                           (tmp_path / cold_dir).glob("*.csv"))
            assert names == sorted(p.name for p in
                                   (tmp_path / warm_dir).glob("*.csv"))
            assert names
            for name in names:
                a = (tmp_path / cold_dir / name).read_bytes()
                b = (tmp_path / warm_dir / name).read_bytes()
                assert a == b, f"{warm_dir}/{name} differs from cold"
        # the warm repeat is certified end to end
        cert = w2.run_health["certification"]
        assert cert["enabled"] and cert["windows"]["rejected_final"] == 0


# ---------------------------------------------------------------------------
# Escalation-ladder retry rung: seeded from the failed member's iterate
# ---------------------------------------------------------------------------

class _Ctx:
    def __init__(self, label=5):
        self.label = label


class _Scn:
    def __init__(self):
        self.health = {"clean": 0, "inaccurate": 0, "retried": 0,
                       "cpu_fallback": 0, "quarantined": 0,
                       "retry_seconds": 0.0}

    class case:
        case_id = 0


class TestRetryRungSeeded:
    def test_retry_converges_in_fewer_iterations_than_original(self):
        """Regression for the cold-retry bug: the boosted-budget retry
        now seeds from the failed member's last iterate instead of
        restarting from zero — with an injected forced non-convergence
        (the iterate actually converged), the retry accepts within its
        first convergence check instead of re-paying the full count."""
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        s = _Scn()
        ledger = []
        with faultinject.inject(nonconverge={"5"}, rungs={"solve"}):
            xs, objs, ok, diags = resolve_group(
                [(s, _Ctx(5), lp)], "jax", opts, ledger=ledger)
        assert ok == [True]
        assert s.health["retried"] == 1
        initial = [e for e in ledger if e.get("rung") == "initial"][0]
        retry = [e for e in ledger if e.get("rung") == "retry"][0]
        assert retry["warm"]["source"] == "failed_iterate"
        assert retry["warm"]["seeded"] == 1
        assert retry["iters_p50"] < initial["iters_p50"]

    def test_retry_cold_with_kill_switch(self, monkeypatch):
        monkeypatch.setenv(warmstart.WARMSTART_ENV, "0")
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        s = _Scn()
        ledger = []
        with faultinject.inject(nonconverge={"5"}, rungs={"solve"}):
            xs, objs, ok, diags = resolve_group(
                [(s, _Ctx(5), lp)], "jax", opts, ledger=ledger)
        assert ok == [True]
        retry = [e for e in ledger if e.get("rung") == "retry"][0]
        assert "warm" not in retry
        initial = [e for e in ledger if e.get("rung") == "initial"][0]
        assert retry["iters_p50"] >= initial["iters_p50"]  # genuinely cold


# ---------------------------------------------------------------------------
# Design screen: tiers seed each other; repeat design reproduces frontier
# ---------------------------------------------------------------------------

def _design_case():
    from dervet_tpu.benchlib import synthetic_case
    c = synthetic_case()
    c.scenario["allow_partial_year"] = True
    c.datasets.time_series = c.datasets.time_series.iloc[: 24 * 3]
    return c


class TestDesignTierSeeding:
    def test_refinement_tiers_seed_from_prior_tier(self):
        """Tier i+1 re-screens the same candidates: its members
        near-match tier i's stored iterates through the shared memory
        (the tolerance tag differs, so they can only SEED — a loose
        tier's answer never substitutes at a tighter tier)."""
        from dervet_tpu.design.population import (DERBounds, DesignSpec,
                                                  generate_population)
        from dervet_tpu.design.screen import (ScreeningCaches,
                                              screen_candidates)
        spec = DesignSpec(bounds={("Battery", "1"):
                                  DERBounds(kw=(200.0, 1000.0),
                                            kwh=(400.0, 4000.0))},
                          population=8, top_k=2, refine_rounds=1)
        caches = ScreeningCaches(pad_grid=True)
        assert caches.memory is not None
        cands = generate_population(spec)
        report = screen_candidates(_design_case(), cands, caches=caches,
                                   refine_rounds=1, top_k=2)
        assert report.converged
        snap = caches.memory.snapshot()
        assert snap["stores"] > 0
        assert snap["hits_near"] > 0       # the refinement round seeded
        # tier caches share ONE memory object
        assert caches.tier(0).memory is caches.tier(1).memory

    def test_repeat_design_request_reproduces_certified_frontier(self):
        """A repeat design request against warm caches reproduces the
        certified frontier: same finalists, byte-identical certified
        totals (the finalists' exact-match entries substitute)."""
        from dervet_tpu.design.frontier import run_design
        from dervet_tpu.design.population import DERBounds, DesignSpec
        from dervet_tpu.design.screen import ScreeningCaches
        from dervet_tpu.scenario.scenario import SolverCache
        spec = DesignSpec(bounds={("Battery", "1"):
                                  DERBounds(kw=(200.0, 1000.0),
                                            kwh=(400.0, 4000.0))},
                          population=6, top_k=2, refine_rounds=0)
        caches = ScreeningCaches(pad_grid=True)
        final_cache = SolverCache(pad_grid=True, memory=caches.memory)
        f1 = run_design(_design_case(), spec, caches=caches,
                        final_cache=final_cache)
        f2 = run_design(_design_case(), spec, caches=caches,
                        final_cache=final_cache)
        assert list(f1.frontier["candidate"]) == \
            list(f2.frontier["candidate"])
        assert list(f1.frontier["total"]) == list(f2.frontier["total"])
        assert f1.all_finalists_certified and f2.all_finalists_certified


# ---------------------------------------------------------------------------
# Variant x seeding interaction (solver-core PR): the seeded init program
# and every step variant compose without perturbing cold members
# ---------------------------------------------------------------------------

class TestVariantSeeding:
    @pytest.mark.parametrize("variant", ["vanilla", "reflected", "halpern"])
    def test_zero_seed_is_cold_start_bitwise(self, variant):
        lp = _arb_lp()
        solver = CompiledLPSolver(
            lp, PDHGOptions(pallas_chunk=False, variant=variant))
        C = np.stack([lp.c, lp.c * 1.01, lp.c * 0.99])
        cold = solver.solve(c=C)
        zero = solver.solve(c=C, x0=np.zeros((3, lp.n)),
                            y0=np.zeros((3, lp.m)))
        assert np.array_equal(np.asarray(cold.x), np.asarray(zero.x))
        assert np.array_equal(np.asarray(cold.iters),
                              np.asarray(zero.iters))

    @pytest.mark.parametrize("variant", ["vanilla", "reflected", "halpern"])
    def test_partial_seed_leaves_cold_members_bitwise(self, variant):
        lp = _arb_lp()
        solver = CompiledLPSolver(
            lp, PDHGOptions(pallas_chunk=False, variant=variant))
        C = np.stack([lp.c, lp.c * 1.02, lp.c * 0.98])
        cold = solver.solve(c=C)
        X0 = np.zeros((3, lp.n))
        Y0 = np.zeros((3, lp.m))
        X0[0] = np.asarray(cold.x)[0]
        Y0[0] = np.asarray(cold.y)[0]
        mixed = solver.solve(c=C, x0=X0, y0=Y0)
        assert np.asarray(mixed.iters)[0] <= np.asarray(cold.iters)[0]
        for i in (1, 2):
            assert np.array_equal(np.asarray(mixed.x)[i],
                                  np.asarray(cold.x)[i])

    def test_kill_switch_restores_vanilla_seeded_bitwise(self, monkeypatch):
        """The env kill switch restores vanilla for the SEEDED program
        too — seeding and the variant knob are orthogonal."""
        lp = _arb_lp()
        base = CompiledLPSolver(
            lp, PDHGOptions(pallas_chunk=False, variant="vanilla"))
        cold = base.solve()
        ref = base.solve(x0=np.asarray(cold.x), y0=np.asarray(cold.y))
        monkeypatch.setenv("DERVET_TPU_PDHG_VARIANT", "vanilla")
        killed = CompiledLPSolver(
            lp, PDHGOptions(pallas_chunk=False, variant="halpern"))
        warm = killed.solve(x0=np.asarray(cold.x), y0=np.asarray(cold.y))
        assert np.array_equal(np.asarray(warm.x), np.asarray(ref.x))
        assert int(warm.iters) == int(ref.iters)

    @pytest.mark.parametrize("variant", ["reflected", "halpern"])
    def test_own_solution_seed_converges_fast(self, variant):
        lp = _arb_lp()
        solver = CompiledLPSolver(
            lp, PDHGOptions(pallas_chunk=False, variant=variant))
        cold = solver.solve()
        warm = solver.solve(x0=np.asarray(cold.x), y0=np.asarray(cold.y))
        assert bool(warm.converged)
        assert int(warm.iters) < int(cold.iters)
