"""Design requests through the scenario service: admission, screening
fidelity isolation, finalist co-batching, load-shed degraded frontiers,
spool serving.

The integration contract under test:

* a design request rides the SAME admission queue (priority, deadline,
  backpressure, duplicate-id, draining) as scenario requests and
  delivers a :class:`DesignFrontier` through its future;
* fidelity isolation: a design request CO-BATCHED with a certified
  scenario request leaves the scenario answer 100% certified while the
  screening answers are never certificate-stamped — the PR-6
  thread-local policy drill extended to the design path;
* finalists genuinely coalesce with scenario windows in the certified
  round (a shared ledger group tagged with both request ids);
* a load-SHED design request is answered with the screening-only
  DEGRADED frontier (explicit mark + resubmit hint, zero certificates)
  and the shed accounting is visible PER REQUEST TYPE in metrics();
* ``design.json`` files in the spool's incoming/ serve end to end.
"""
import json
import time

import numpy as np
import pytest

from dervet_tpu.benchlib import synthetic_case, synthetic_sensitivity_cases
from dervet_tpu.design import DERBounds, DesignSpec, DesignFrontier
from dervet_tpu.service import (DeadlineExpiredError, ScenarioClient,
                                ScenarioService, ServiceClosedError)
from dervet_tpu.utils.errors import ParameterError


def _case(hours: int = 72, seed: int = 0):
    c = synthetic_case(seed=seed)
    c.scenario["allow_partial_year"] = True
    c.datasets.time_series = c.datasets.time_series.iloc[:hours]
    return c


def _scen_cases(n: int = 1, hours: int = 72):
    out = {}
    for i, c in enumerate(synthetic_sensitivity_cases(n, months=0,
                                                      seed=1)):
        c.datasets.time_series = c.datasets.time_series.iloc[:hours]
        c.scenario["allow_partial_year"] = True
        out[i] = c
    return out


def _spec(**over):
    base = dict(bounds={("Battery", "1"): DERBounds(kw=(500.0, 2500.0),
                                                    kwh=(1000.0, 9000.0))},
                population=8, top_k=2, refine_rounds=0)
    base.update(over)
    return DesignSpec(**base)


# ---------------------------------------------------------------------------
# End-to-end request
# ---------------------------------------------------------------------------

class TestDesignRequest:
    def test_design_request_end_to_end(self):
        svc = ScenarioService(backend="jax", max_wait_s=0.0)
        fut = svc.submit_design(_case(), _spec(), request_id="d1")
        assert svc.run_once() == 1
        fr = fut.result(0)
        assert isinstance(fr, DesignFrontier)
        assert fr.request_id == "d1"
        assert fr.fidelity == "certified"
        assert fr.all_finalists_certified
        assert fr.request_latency_s is not None
        # per-request observability: health + ledger slices exist
        assert fr.run_health["certification"]["enabled"]
        assert fr.run_health["design"]["candidates"] == 8
        assert fr.solve_ledger["request_id"] == "d1"
        m = svc.metrics()
        assert m["design"]["requests"] == 1
        assert m["design"]["candidates"] == 8
        assert m["design"]["finalists"] == 2
        assert m["requests"]["completed"] == 1
        svc.close()

    def test_invalid_spec_rejected_at_admission(self):
        svc = ScenarioService(backend="cpu")
        with pytest.raises(ParameterError):
            svc.submit_design(_case(), _spec(top_k=0))
        with pytest.raises(ParameterError):
            svc.submit_design(_case(), None, bounds={})
        svc.close()

    def test_draining_service_rejects_design(self):
        svc = ScenarioService(backend="cpu")
        svc.request_stop()
        with pytest.raises(ServiceClosedError):
            svc.submit_design(_case(), _spec())
        svc.close()

    def test_expired_design_request_answered_typed(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        fut = svc.submit_design(_case(), _spec(), request_id="late",
                                deadline_s=0.0)
        time.sleep(0.01)
        svc.run_once()
        with pytest.raises(DeadlineExpiredError):
            fut.result(0)
        svc.close()

    def test_client_design_blocks_for_frontier(self):
        svc = ScenarioService(backend="jax", max_wait_s=0.0).start()
        client = ScenarioClient(svc)
        fr = client.design(_case(), _spec(), request_id="viaclient",
                           timeout=600)
        assert fr.all_finalists_certified
        svc.close()


# ---------------------------------------------------------------------------
# Fidelity isolation + co-batching (the PR-6 drill, extended)
# ---------------------------------------------------------------------------

class TestFidelityIsolation:
    def test_design_cobatch_leaves_scenario_fully_certified(self):
        """One cycle, one design + one certified scenario request: the
        scenario answer must be 100% certified, the screening answers
        must never be certificate-stamped, and the design finalists must
        co-batch with the scenario windows in the certified round."""
        svc = ScenarioService(backend="jax", max_wait_s=0.0)
        f_design = svc.submit_design(_case(), _spec(top_k=3),
                                     request_id="dsg")
        f_scen = svc.submit(_scen_cases(2), request_id="scn")
        assert svc.run_once() == 2
        res = f_scen.result(0)
        fr = f_design.result(0)
        # scenario side: full certification, untouched by the screen
        assert res.fidelity == "certified"
        cert = res.run_health["certification"]
        n_win = sum(len(inst.scenario.windows)
                    for inst in res.instances.values())
        assert cert["enabled"] and cert["windows_certified"] == n_win
        assert cert["windows"]["rejected_final"] == 0
        # design side: ordinal screen never stamped, finalists certified
        assert fr.screen["certification_stamped"] is False
        assert fr.all_finalists_certified
        # co-batching observable: a certified-round device group carried
        # windows from BOTH requests
        shared = [g for g in fr.solve_ledger["groups"]
                  if {"dsg", "scn"} <= set(g.get("requests") or ())]
        assert shared, fr.solve_ledger["groups"]
        assert fr.solve_ledger["coalesced_groups"] >= 1
        svc.close()

    def test_ambient_policy_unchanged_after_screen(self):
        """The thread-local certification override must not leak out of
        the screening dispatch into the service thread's ambient
        policy."""
        from dervet_tpu.ops import certify
        svc = ScenarioService(backend="jax", max_wait_s=0.0)
        fut = svc.submit_design(_case(), _spec(), request_id="leakcheck")
        svc.run_once()
        fut.result(0)
        assert certify.policy_from_env().enabled
        svc.close()


# ---------------------------------------------------------------------------
# Load shedding: the degraded design tier + per-type accounting
# ---------------------------------------------------------------------------

class TestDesignShedding:
    def _overloaded(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0,
                              max_queue_depth=8, max_batch_requests=2,
                              shed_threshold_frac=0.5,
                              shed_sustain_rounds=1)
        f_design = svc.submit_design(
            _case(), _spec(population=6, top_k=2), request_id="shedme",
            priority=0)
        futs = [svc.submit(_scen_cases(1), request_id=f"s{i}",
                           priority=(1 if i % 2 else 0))
                for i in range(5)]
        while svc.queue.depth():
            svc.run_once()
        return svc, f_design, futs

    def test_shed_design_gets_degraded_frontier(self):
        svc, f_design, futs = self._overloaded()
        fr = f_design.result(0)
        assert fr.fidelity == "degraded"
        assert "resubmit" in fr.resubmit_hint
        # screening-only: ranked frontier, zero certificates anywhere
        assert len(fr.frontier) == 2
        assert not fr.frontier["certified"].any()
        assert fr.run_health["fidelity"] == "degraded"
        svc.close()

    def test_shed_counts_split_by_request_type(self):
        svc, f_design, futs = self._overloaded()
        shed = svc.metrics()["resilience"]["load_shedding"]
        by_kind = shed["degraded_by_kind"]
        assert by_kind.get("design", 0) >= 1
        assert by_kind.get("scenario", 0) >= 1
        assert shed["degraded_requests"] == sum(by_kind.values())
        # design screening load is its own metrics section, separate
        # from scenario rounds
        m = svc.metrics()
        assert m["design"]["degraded_answers"] >= 1
        assert m["design"]["candidates"] >= 6
        svc.close()


# ---------------------------------------------------------------------------
# Warm service: persistent screening caches
# ---------------------------------------------------------------------------

class TestWarmDesign:
    def test_warm_repeat_screen_compiles_nothing(self):
        svc = ScenarioService(backend="jax", max_wait_s=0.0)
        f1 = svc.submit_design(_case(), _spec(), request_id="cold")
        svc.run_once()
        f1.result(0)
        f2 = svc.submit_design(_case(), _spec(), request_id="warm")
        svc.run_once()
        fr = f2.result(0)
        assert fr.screen["compile_events"] == 0
        assert svc.last_screen_stats["request_id"] == "warm"
        assert svc.last_screen_stats["compile_events"] == 0
        svc.close()


# ---------------------------------------------------------------------------
# Spool front end: design.json
# ---------------------------------------------------------------------------

def _write_design_spool(tmp_path, population=6, top_k=2):
    """A spool-shaped design request on disk: a reference-format
    model-parameters CSV + its time series + the design.json that
    references them.  Returns the design.json path."""
    import pandas as pd
    ts = _case().datasets.time_series
    ts_path = tmp_path / "ts.csv"
    # the loader expects hour-ENDING stamps (it shifts back by dt)
    ts.set_axis(ts.index + pd.Timedelta(hours=1)).rename_axis(
        "Datetime (he)").to_csv(ts_path)
    rows = [
        ("Scenario", "", "dt", "1", "float"),
        ("Scenario", "", "opt_years", "[2017]", "list/int"),
        ("Scenario", "", "n", "month", "string/int"),
        ("Scenario", "", "start_year", "2017", "period"),
        ("Scenario", "", "end_year", "2017", "period"),
        ("Scenario", "", "allow_partial_year", "1", "bool"),
        ("Scenario", "", "incl_site_load", "1", "bool"),
        ("Scenario", "", "time_series_filename", str(ts_path), "string"),
        ("Finance", "", "npv_discount_rate", "7", "float"),
        ("Finance", "", "inflation_rate", "3", "float"),
        ("Battery", "1", "ch_max_rated", "1000", "float"),
        ("Battery", "1", "dis_max_rated", "1000", "float"),
        ("Battery", "1", "ene_max_rated", "4000", "float"),
        ("Battery", "1", "rte", "85", "float"),
        ("Battery", "1", "llsoc", "5", "float"),
        ("Battery", "1", "ulsoc", "100", "float"),
        ("Battery", "1", "soc_target", "50", "float"),
        ("PV", "1", "rated_capacity", "3000", "float"),
        ("PV", "1", "curtail", "1", "bool"),
        ("DA", "", "growth", "0", "float"),
    ]
    df = pd.DataFrame(rows, columns=["Tag", "ID", "Key", "Value", "Type"])
    df["Active"] = "yes"
    params_path = tmp_path / "params.csv"
    df.to_csv(params_path, index=False)
    design_path = tmp_path / "design.json"
    design_path.write_text(json.dumps({"design": {
        "parameters": str(params_path),
        "der": "Battery", "kw": [500, 2000], "kwh": [1000, 8000],
        "population": population, "top_k": top_k,
        "refine_rounds": 0}}))
    return design_path


class TestDesignSpool:
    def test_parse_design_request_shapes(self):
        from dervet_tpu.design.service import (is_design_payload,
                                               parse_design_request)
        assert is_design_payload({"design": {}})
        assert not is_design_payload({"Scenario": {}})
        assert not is_design_payload([1, 2])
        with pytest.raises(ParameterError, match="parameters"):
            parse_design_request({"design": {}})
        with pytest.raises(ParameterError, match="pair"):
            parse_design_request({"design": {"parameters": "x.csv",
                                             "kw": [1, 2, 3]}})

    def test_submit_design_file(self, tmp_path):
        """The spool admission path: a design.json referencing a real
        model-parameters file parses at admission and serves."""
        design_path = _write_design_spool(tmp_path)
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        fut = svc.submit_design_file(design_path, request_id="spool1")
        svc.run_once()
        fr = fut.result(0)
        assert fr.all_finalists_certified
        fr.save_as_csv(tmp_path / "out")
        assert (tmp_path / "out" / "design_frontier.csv").exists()
        saved = json.loads((tmp_path / "out" / "design_frontier.json")
                           .read_text())
        assert saved["request_id"] == "spool1"
        svc.close()

    def test_design_json_serves_through_spool_loop(self, tmp_path):
        """End to end through ``dervet-tpu serve --once``: a design.json
        drop becomes a served request with frontier artifacts under
        results/<rid>/ and the input moved to done/."""
        from dervet_tpu.service.server import serve_main
        design_path = _write_design_spool(tmp_path)
        incoming = tmp_path / "spool" / "incoming"
        incoming.mkdir(parents=True)
        design_path.replace(incoming / "mydesign.json")
        rc = serve_main([str(tmp_path / "spool"), "--once",
                         "--backend", "cpu"])
        assert rc == 0
        out = tmp_path / "spool" / "results" / "mydesign"
        assert (out / "design_frontier.csv").exists()
        assert (out / "design_population.csv").exists()
        assert (out / "run_health.mydesign.json").exists()
        assert (tmp_path / "spool" / "done" / "mydesign.json").exists()
        metrics = json.loads(
            (tmp_path / "spool" / "service_metrics.json").read_text())
        assert metrics["requests"]["completed"] == 1
        assert metrics["design"]["requests"] == 1
