"""Monte-Carlo uncertainty product: seeded sampler, distribution math,
the two-tier batched valuation engine, the serving surface, and the
risk-aware design frontier.

The contract under test:

* the sampler is a PURE function of (seed, sample index) — same draws
  across runs, processes, and generation order — and shares every
  reference frame except ``time_series`` across the population;
* quantiles and CVaR are float64 HOST math, re-derivable to 1e-9 from
  the published per-sample vector by an independent implementation;
* a fixed seed yields a byte-identical ``mc_distribution.json`` across
  reruns AND across solve-batch orderings, with zero compile events
  once the caches are warm;
* the quantile-pinning samples re-solve fully certified while the
  screening mass is never certificate-stamped; a load-shed (degraded)
  answer carries no certificates and says so;
* the ``bad_sample`` fault kind quarantines exactly the poisoned
  sample — labeled by sample index — while the rest of the batch
  completes;
* MC requests fold their sampler identity into the request-cache key,
  ride the service front door end to end, and serve from the spool;
* ``DesignSpec.risk`` adds per-finalist MC columns and a (capex,
  E[value], CVaR) Pareto axis to the certified design frontier.
"""
import json
import math
from concurrent.futures import Future

import numpy as np
import pytest

from dervet_tpu.benchlib import synthetic_case
from dervet_tpu.design import DERBounds, DesignSpec, dominated_mask, \
    run_design
from dervet_tpu.design.screen import ScreeningCaches
from dervet_tpu.scenario.scenario import SolverCache
from dervet_tpu.service import (QueueFullError, ScenarioClient,
                                ScenarioService)
from dervet_tpu.service.queue import QueuedRequest
from dervet_tpu.stochastic import (MCDistribution, MCSpec, cvar,
                                   distribution_stats, run_montecarlo,
                                   sample_case, sample_seed)
from dervet_tpu.stochastic.distribution import pinning_positions
from dervet_tpu.stochastic.sampler import mc_spec_from_dict
from dervet_tpu.stochastic.service import (MonteCarloRound,
                                           is_montecarlo_payload,
                                           montecarlo_fingerprint,
                                           parse_montecarlo_request)
from dervet_tpu.utils import faultinject
from dervet_tpu.utils.errors import ParameterError


def _case(hours: int = 72, seed: int = 0):
    c = synthetic_case(seed=seed)
    c.scenario["allow_partial_year"] = True
    c.datasets.time_series = c.datasets.time_series.iloc[:hours]
    return c


def _spec(**over):
    base = dict(n_samples=8, seed=3, alpha=0.75, quantiles=(0.5,),
                screen_tier=0)
    base.update(over)
    return MCSpec(**base)


# ---------------------------------------------------------------------------
# Sampler: determinism + frame sharing
# ---------------------------------------------------------------------------

class TestSampler:
    def test_sample_seed_is_pure_and_independent(self):
        assert sample_seed(0, 7) == sample_seed(0, 7)
        assert sample_seed(0, 7) != sample_seed(0, 8)
        assert sample_seed(0, 7) != sample_seed(1, 7)

    def test_samples_deterministic_across_generation_order(self):
        case = _case()
        spec = _spec()
        a = sample_case(case, spec, 5).datasets.time_series
        # generate other samples in between: no sequential RNG state
        sample_case(case, spec, 0)
        sample_case(case, spec, 11)
        b = sample_case(case, spec, 5).datasets.time_series
        assert a.equals(b)
        c = sample_case(case, spec, 6).datasets.time_series
        assert not a.equals(c)

    def test_perturbation_model_touches_the_right_columns(self):
        case = _case()
        base = case.datasets.time_series
        s = sample_case(case, _spec(seed=9), 0).datasets.time_series
        assert not np.allclose(s["DA Price ($/kWh)"],
                               base["DA Price ($/kWh)"])
        assert not np.allclose(s["Site Load (kW)"], base["Site Load (kW)"])
        # solar availability is one multiplicative factor in [0, 1]
        gen_b = base["PV Gen (kW/rated kW)"].to_numpy()
        gen_s = s["PV Gen (kW/rated kW)"].to_numpy()
        nz = gen_b > 0
        ratios = gen_s[nz] / gen_b[nz]
        assert np.allclose(ratios, ratios[0])
        assert 0.0 <= ratios[0] <= 1.0
        # nothing goes negative
        assert (s["DA Price ($/kWh)"] >= 0).all()
        assert (s["Site Load (kW)"] >= 0).all()

    def test_frames_shared_except_time_series(self):
        case = _case()
        s = sample_case(case, _spec(), 0)
        assert s.datasets.time_series is not case.datasets.time_series
        assert s.datasets.monthly is case.datasets.monthly
        assert s.datasets.tariff is case.datasets.tariff
        # the base frame is never mutated
        assert case.datasets.time_series.equals(
            _case().datasets.time_series)

    def test_spec_validation(self):
        with pytest.raises(ParameterError, match="n_samples"):
            _spec(n_samples=1).validate()
        with pytest.raises(ParameterError, match="alpha"):
            _spec(alpha=1.0).validate()
        with pytest.raises(ParameterError, match="quantile"):
            _spec(quantiles=(0.5, 1.5)).validate()
        with pytest.raises(ParameterError, match="price_sigma"):
            _spec(price_sigma=-0.1).validate()
        with pytest.raises(ParameterError, match="screen_tier"):
            _spec(screen_tier=99).validate()

    def test_sample_cap_env(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_MC_MAX_SAMPLES", "16")
        with pytest.raises(ParameterError, match="cap"):
            _spec(n_samples=17).validate()
        _spec(n_samples=16).validate()

    def test_spec_from_dict_surface(self):
        spec = mc_spec_from_dict({"samples": 64, "seed": 2,
                                  "quantiles": [0.1, 0.9]})
        assert spec.n_samples == 64 and spec.seed == 2
        assert spec.quantiles == (0.1, 0.9)
        with pytest.raises(ParameterError, match="unknown field"):
            mc_spec_from_dict({"sample_count": 64})
        with pytest.raises(ParameterError, match="object"):
            mc_spec_from_dict("64")

    def test_normalized_includes_seed_and_count(self):
        a = _spec(seed=1).normalized()
        b = _spec(seed=2).normalized()
        assert a != b
        assert a["seed"] == 1 and a["n_samples"] == 8


# ---------------------------------------------------------------------------
# Distribution math: float64 host recompute to 1e-9
# ---------------------------------------------------------------------------

def _manual_quantile(values, q):
    """Independent linear-interpolation quantile (pure python float)."""
    s = sorted(float(v) for v in values)
    pos = q * (len(s) - 1)
    lo, hi = int(math.floor(pos)), int(math.ceil(pos))
    frac = pos - lo
    return s[lo] * (1.0 - frac) + s[hi] * frac


def _manual_cvar(values, alpha):
    s = sorted(float(v) for v in values)
    k = max(1, int(math.ceil(round((1.0 - alpha) * len(s), 12))))
    tail = s[-k:]
    return sum(tail) / len(tail)


class TestDistributionMath:
    def test_stats_match_independent_recompute(self):
        rng = np.random.default_rng(42)
        v = rng.normal(1e4, 2e3, size=257)
        stats = distribution_stats(v, 0.95, (0.05, 0.5, 0.95))
        for q in (0.05, 0.5, 0.95):
            assert stats["quantiles"][f"p{100 * q:g}"] == pytest.approx(
                _manual_quantile(v, q), rel=1e-9)
        assert stats["var_alpha"] == pytest.approx(
            _manual_quantile(v, 0.95), rel=1e-9)
        assert stats["cvar_alpha"] == pytest.approx(
            _manual_cvar(v, 0.95), rel=1e-9)
        assert stats["mean"] == pytest.approx(sum(v) / v.size, rel=1e-9)
        assert stats["n"] == 257

    def test_cvar_tail_definition(self):
        v = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 9.0, 10.0]
        # alpha=0.8 over 10 samples: worst ceil(2) = {9, 10}
        assert cvar(v, 0.8) == pytest.approx(9.5)
        # alpha=0.95 of 10 -> ceil(0.5) = 1 worst sample
        assert cvar(v, 0.95) == pytest.approx(10.0)
        # the decimal-rounding guard: 0.95 of 1024 must be 52, not 51
        n = 1024
        k = max(1, int(math.ceil(round((1.0 - 0.95) * n, 12))))
        assert k == 52

    def test_pinning_positions_cover_quantiles_and_tail(self):
        rng = np.random.default_rng(7)
        v = rng.normal(size=100)
        picks = pinning_positions(v, (0.5,), 0.9)
        order = np.argsort(v, kind="stable")
        # the median's bracketing order statistics are pinned
        assert int(order[49]) in picks and int(order[50]) in picks
        # the whole CVaR tail (worst 10) is pinned
        for i in order[-10:]:
            assert int(i) in picks
        assert picks == sorted(picks)


# ---------------------------------------------------------------------------
# Request-cache key material folds the sampler identity
# ---------------------------------------------------------------------------

class TestRequestCacheKeys:
    def test_mc_spec_distinguishes_cache_keys(self):
        from dervet_tpu.service import reqcache
        cases = {0: _case()}
        m0 = reqcache.key_material(cases)
        m_seed1 = reqcache.key_material(cases, mc_spec=_spec(seed=1))
        m_seed2 = reqcache.key_material(cases, mc_spec=_spec(seed=2))
        m_n16 = reqcache.key_material(cases,
                                      mc_spec=_spec(seed=1, n_samples=16))
        # a plain scenario request's material is UNCHANGED (no mc field
        # -> existing cache entries stay addressable)
        assert "mc" not in m0
        assert {k: v for k, v in m_seed1.items() if k != "mc"} == m0
        # seed and sample count each produce a distinct key
        keys = {reqcache.material_key(m)
                for m in (m0, m_seed1, m_seed2, m_n16)}
        assert len(keys) == 4

    def test_montecarlo_fingerprint_keys_on_seed(self):
        case = _case()
        assert montecarlo_fingerprint(case, _spec(seed=1)) != \
            montecarlo_fingerprint(case, _spec(seed=2))
        assert montecarlo_fingerprint(case, _spec(seed=1)) == \
            montecarlo_fingerprint(case, _spec(seed=1))


# ---------------------------------------------------------------------------
# Engine: determinism, tiering, faults (cpu XLA dispatches)
# ---------------------------------------------------------------------------

class TestEngine:
    def test_distribution_deterministic_and_order_invariant(self):
        """Fixed seed => byte-identical mc_distribution.json across
        reruns AND across solve-batch orderings; warm reruns on shared
        caches compile nothing."""
        case = _case()
        spec = _spec()
        caches = ScreeningCaches(pad_grid=True)
        final = SolverCache(pad_grid=True, memory=caches.memory)

        def run(**kw):
            return run_montecarlo(case, spec, backend="jax",
                                  caches=caches, final_cache=final,
                                  request_id="det", **kw)

        r1 = run()
        r2 = run()
        r3 = run(sample_order=list(reversed(range(spec.n_samples))))
        assert r1.to_json() == r2.to_json() == r3.to_json()
        # compiles amortize to zero on the shared caches
        assert r2.engine["compile_events"] == 0
        assert r3.engine["compile_events"] == 0
        # the pinning samples all certified; the screening mass never
        # got a certificate stamped
        assert r1.pinning_all_certified
        assert not r1.engine["certification_stamped_screening"]
        assert r1.fidelity == "certified"
        assert r1.tier_mix["certified"] >= 2
        assert r1.tier_mix["screening"] + r1.tier_mix["certified"] == \
            spec.n_samples
        # exactly one dispatch round per tier
        assert [r["tier"] for r in r1.engine["rounds"]] == \
            ["screening", "certified"]
        # health + ledger ride the result contract
        assert r1.run_health["monte_carlo"]["tier_mix"] == r1.tier_mix
        assert r1.solve_ledger is not None

    def test_byte_identity_survives_tight_warmstart_cap(
            self, monkeypatch):
        """A warm-start LRU smaller than the batch must not break the
        fixed-seed replay contract: the engine raises the cap so every
        window of the batch stays resident (an evicted window would
        re-converge near-grade on the repeat, landing on a slightly
        different objective within the screening tolerance)."""
        monkeypatch.setenv("DERVET_TPU_WARMSTART_CAP", "2")
        case = _case()
        spec = _spec()
        caches = ScreeningCaches(pad_grid=True)
        final = SolverCache(pad_grid=True, memory=caches.memory)
        r1 = run_montecarlo(case, spec, backend="jax", caches=caches,
                            final_cache=final, request_id="cap")
        r2 = run_montecarlo(case, spec, backend="jax", caches=caches,
                            final_cache=final, request_id="cap")
        assert caches.memory.max_entries >= 2 * spec.n_samples
        assert r1.to_json() == r2.to_json()

    def test_stats_recompute_from_published_samples(self):
        """The published stats re-derive to 1e-9 from the published
        per-sample objectives alone (float64 host math, no hidden
        state)."""
        case = _case()
        r = run_montecarlo(case, _spec(seed=5), backend="jax")
        v = [row["objective"]
             for row in r.as_dict()["samples"]
             if row["objective"] is not None]
        assert len(v) == r.stats["n"]
        assert r.stats["quantiles"]["p50"] == pytest.approx(
            _manual_quantile(v, 0.5), rel=1e-9)
        assert r.stats["var_alpha"] == pytest.approx(
            _manual_quantile(v, 0.75), rel=1e-9)
        assert r.stats["cvar_alpha"] == pytest.approx(
            _manual_cvar(v, 0.75), rel=1e-9)
        assert r.stats["mean"] == pytest.approx(sum(v) / len(v),
                                                rel=1e-9)

    def test_degraded_contract(self, monkeypatch):
        """certify_tier=False: reduced sample count, degraded mark,
        resubmit hint, and NOTHING certificate-stamped."""
        monkeypatch.setenv("DERVET_TPU_MC_DEGRADED_SAMPLES", "4")
        case = _case()
        r = run_montecarlo(case, _spec(n_samples=8), backend="jax",
                           certify_tier=False)
        assert r.fidelity == "degraded"
        assert r.stats["n"] == 4
        assert "resubmit" in r.resubmit_hint
        assert not r.samples["certified"].any()
        assert (r.samples["tier"] == "screening").all()
        assert not r.pinning_all_certified
        assert r.tier_mix["certified"] == 0
        assert not r.engine["certification_stamped_screening"]

    def test_bad_sample_fault_quarantines_exactly_that_sample(
            self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FAULT_BAD_SAMPLE", "1")
        monkeypatch.setenv("DERVET_TPU_FAULT_BAD_SAMPLE_IDX", "3")
        case = _case()
        r = run_montecarlo(case, _spec(n_samples=6), backend="jax")
        bad = r.samples[r.samples["sample"] == 3].iloc[0]
        assert bool(bad.quarantined)
        assert "sample 3" in bad.reason
        # the rest of the batch completed and published
        good = r.samples[r.samples["sample"] != 3]
        assert not good["quarantined"].any()
        assert np.isfinite(good["objective"]).all()
        assert r.stats["n"] == 5
        assert r.tier_mix["quarantined"] == 1
        assert r.pinning_all_certified

    def test_sample_order_must_be_permutation(self):
        with pytest.raises(ParameterError, match="permutation"):
            run_montecarlo(_case(), _spec(n_samples=4), backend="jax",
                           sample_order=[0, 1, 2, 2])


class TestBadSampleFaultPlan:
    def test_env_parsing_and_injection(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FAULT_BAD_SAMPLE", "1")
        monkeypatch.setenv("DERVET_TPU_FAULT_BAD_SAMPLE_IDX", "2,5")
        plan = faultinject.get_plan()
        assert plan.bad_sample_due(2) and plan.bad_sample_due(5)
        assert not plan.bad_sample_due(0)
        import pandas as pd
        frame = pd.DataFrame({"x": np.ones(32)})
        assert faultinject.maybe_bad_sample(2, frame)
        assert frame["x"].isna().any()
        clean = pd.DataFrame({"x": np.ones(32)})
        assert not faultinject.maybe_bad_sample(0, clean)
        assert not clean["x"].isna().any()

    def test_plain_boolean_targets_sample_zero(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FAULT_BAD_SAMPLE", "1")
        plan = faultinject.get_plan()
        assert plan.bad_sample_due(0)
        assert not plan.bad_sample_due(1)


# ---------------------------------------------------------------------------
# Serving surface: service round, shed tier, spool, client, CLI
# ---------------------------------------------------------------------------

class TestMonteCarloService:
    def test_submit_montecarlo_end_to_end(self, tmp_path):
        svc = ScenarioService(backend="jax", max_wait_s=0.0)
        fut = svc.submit_montecarlo(_case(), _spec(), request_id="m1")
        assert svc.run_once() == 1
        res = fut.result(0)
        assert isinstance(res, MCDistribution)
        assert res.request_id == "m1"
        assert res.fidelity == "certified"
        assert res.pinning_all_certified
        assert res.request_latency_s is not None
        m = svc.metrics()["monte_carlo"]
        assert m["requests"] == 1 and m["samples"] == 8
        assert m["certified_samples"] == res.tier_mix["certified"]
        assert m["last"]["request_id"] == "m1"
        # warm repeat of the SAME request id is byte-identical
        fut2 = svc.submit_montecarlo(_case(), _spec(), request_id="m1")
        svc.run_once()
        assert fut2.result(0).to_json() == res.to_json()
        assert svc.metrics()["monte_carlo"]["last"]["compile_events"] == 0
        # artifacts serialize atomically and round-trip
        res.save_as_csv(tmp_path)
        payload = json.loads(
            (tmp_path / "mc_distribution.json").read_text())
        assert payload == res.as_dict()
        assert (tmp_path / "mc_samples.csv").exists()
        svc.close()

    def test_spec_kwargs_submission_and_validation(self):
        svc = ScenarioService(backend="jax", max_wait_s=0.0)
        with pytest.raises(ParameterError, match="n_samples"):
            svc.submit_montecarlo(_case(), n_samples=1)
        fut = svc.submit_montecarlo(_case(), n_samples=8, seed=3,
                                    alpha=0.75, quantiles=(0.5,),
                                    request_id="kw")
        svc.run_once()
        assert fut.result(0).spec["n_samples"] == 8
        svc.close()

    def test_shed_montecarlo_degraded_never_stamped(self, monkeypatch):
        """A load-shed MC request answers from a reduced screening-only
        sample set, marked degraded, zero certificates."""
        monkeypatch.setenv("DERVET_TPU_MC_DEGRADED_SAMPLES", "4")
        req = QueuedRequest("shed1", {}, kind="montecarlo")
        req.mc_case = _case()
        req.mc_spec = _spec(n_samples=8)
        mr = MonteCarloRound([req], backend="jax",
                             degraded_ids={"shed1"})
        mr.run()
        res = req.future.result(0)
        assert res.fidelity == "degraded"
        assert res.stats["n"] == 4
        assert not res.samples["certified"].any()
        assert "resubmit" in res.resubmit_hint
        assert mr.stats["degraded"] == 1

    def test_round_answers_failed_request_and_continues(self):
        """One poisoned request must not leak its future or take the
        round down — the next request still answers."""
        bad = QueuedRequest("bad", {}, kind="montecarlo")
        bad.mc_case = _case()
        # a spec that fails validation inside the engine
        bad.mc_spec = MCSpec(n_samples=1)
        ok = QueuedRequest("ok", {}, kind="montecarlo")
        ok.mc_case = _case()
        ok.mc_spec = _spec()
        mr = MonteCarloRound([bad, ok], backend="jax")
        mr.run()
        with pytest.raises(ParameterError):
            bad.future.result(0)
        assert ok.future.result(0).fidelity == "certified"

    def test_spool_payload_detection_and_parse_errors(self):
        assert is_montecarlo_payload({"montecarlo": {"samples": 8}})
        assert not is_montecarlo_payload({"design": {}})
        assert not is_montecarlo_payload([1, 2])
        with pytest.raises(ParameterError, match="parameters"):
            parse_montecarlo_request({"montecarlo": {"samples": 8}})
        with pytest.raises(ParameterError, match="object"):
            parse_montecarlo_request({"montecarlo": 3})

    def test_client_retry_surface(self):
        class _Stub:
            def __init__(self):
                self.calls = 0

            def submit_montecarlo(self, case, spec=None, **kw):
                self.calls += 1
                if self.calls == 1:
                    raise QueueFullError("full", retry_after_s=0.0)
                f = Future()
                f.set_result("dist")
                return f

        stub = _Stub()
        client = ScenarioClient(stub, jitter_seed=0)
        assert client.montecarlo(None) == "dist"
        assert stub.calls == 2

    def test_cli_parser_maps_flags(self):
        from dervet_tpu.stochastic.cli import _quantiles, build_parser
        args = build_parser().parse_args(
            ["case.csv", "--samples", "64", "--seed", "9",
             "--alpha", "0.9", "--quantiles", "0.1,0.9",
             "--screen-tier", "1", "--backend", "cpu",
             "--screening-only"])
        assert args.samples == 64 and args.seed == 9
        assert args.screen_tier == 1 and args.screening_only
        assert _quantiles(args.quantiles) == (0.1, 0.9)
        with pytest.raises(ParameterError):
            _quantiles("a,b")


# ---------------------------------------------------------------------------
# Risk-aware design frontier
# ---------------------------------------------------------------------------

class TestRiskAwareDesign:
    def _dspec(self, **over):
        base = dict(
            bounds={("Battery", "1"): DERBounds(kw=(500.0, 2500.0),
                                                kwh=(1000.0, 9000.0))},
            population=4, top_k=2, refine_rounds=0)
        base.update(over)
        return DesignSpec(**base)

    def test_risk_block_validates_lazily(self):
        with pytest.raises(ParameterError, match="unknown field"):
            self._dspec(risk={"bogus": 1}).validate()
        with pytest.raises(ParameterError, match="object"):
            self._dspec(risk="yes").validate()
        spec = self._dspec(risk={}).validate()
        # design risk defaults to a 256-draw cloud per finalist
        assert spec.normalized()["risk"]["n_samples"] == 256
        assert self._dspec().normalized()["risk"] is None

    def test_cvar_axis_changes_dominance(self):
        capex = [1.0, 2.0]
        value = [1.0, 2.0]
        # without risk, design 1 is dominated outright ...
        assert dominated_mask(capex, value).tolist() == [False, True]
        # ... but buying tail-risk protection keeps it on the frontier
        assert dominated_mask(capex, value,
                              cvar=[2.0, 1.0]).tolist() == [False, False]
        # a strictly-worse-everywhere design stays dominated
        assert dominated_mask([1.0, 1.0], [1.0, 1.0],
                              cvar=[1.0, 2.0]).tolist() == [False, True]

    def test_risk_mode_one_shot_frontier(self):
        spec = self._dspec(
            risk={"samples": 3, "seed": 1, "alpha": 0.75}).validate()
        fr = run_design(_case(), spec, backend="jax")
        for col in ("mc_mean", "mc_cvar", "mc_samples", "mc_alpha",
                    "mc_quarantined"):
            assert col in fr.frontier.columns
        assert fr.all_finalists_certified
        assert (fr.frontier["mc_samples"] == 3).all()
        assert np.isfinite(fr.frontier["mc_mean"]).all()
        assert np.isfinite(fr.frontier["mc_cvar"]).all()
        # CVaR is an upper-tail cost statistic: never below the mean tail
        assert (fr.frontier["mc_cvar"] >=
                fr.frontier["mc_mean"] - 1e-9).all()
        assert fr.spec["risk"]["n_samples"] == 3

    def test_risk_mode_through_the_service(self):
        spec = self._dspec(
            risk={"samples": 2, "seed": 1, "alpha": 0.75}).validate()
        svc = ScenarioService(backend="jax", max_wait_s=0.0)
        fut = svc.submit_design(_case(), spec, request_id="dr1")
        assert svc.run_once() == 1
        fr = fut.result(0)
        assert fr.fidelity == "certified"
        assert fr.all_finalists_certified
        assert (fr.frontier["mc_samples"] == 2).all()
        assert np.isfinite(fr.frontier["mc_cvar"]).all()
        svc.close()
