"""Elastic multi-device dispatch: the mesh-wide group scheduler
(parallel/elastic.py) with per-device in-flight rounds and work stealing.

The contract under test:

* elastic results are BYTE-IDENTICAL across 1/2/8-device schedules,
  placements, and steals — the scheduler changes WHERE a window solves,
  never what it solves to.  (The legacy serial path never had this
  property: its shard_map program's per-device batch width — and with
  it the XLA reduction order of dense-op matmuls — changes with the
  visible device count, so its bits depend on the host.  Elastic solves
  every group with the same single-device batched program regardless of
  mesh size, so its bits do not.)  Against the serial global scheduler
  (``DERVET_TPU_ELASTIC=0``) results agree within certification
  tolerance, with banded-op groups typically bit-equal;
* the ``straggler`` fault (one slow device) is recovered by work
  stealing: healthy devices take the straggler's queued groups and the
  round finishes correct;
* a SIGTERM mid-elastic-round drains exactly like the serial path:
  checkpoints + manifest flush, and a resume run completes with
  identical outputs;
* the solve ledger grows a per-device elastic slice whose entries
  account for each device's busy wall (the PR-3 ``accounted_fraction``
  gate, per device), plus the chosen-kernel observable per group;
* ``parallel.mesh.warmup_devices`` warms EVERY device with a tiny
  bucket-shaped solve and reports per-device timings.
"""
import os

import jax
import numpy as np
import pytest

from dervet_tpu.benchlib import synthetic_sensitivity_cases
from dervet_tpu.parallel import elastic
from dervet_tpu.scenario.scenario import (MicrogridScenario, SolverCache,
                                          run_dispatch)
from dervet_tpu.utils import faultinject

ELASTIC_ENVS = (elastic.ELASTIC_ENV, elastic.ELASTIC_DEVICES_ENV)


def _clear_env():
    for k in ELASTIC_ENVS:
        os.environ.pop(k, None)


def _mixed_cases(lengths=(96, 168, 120)):
    """A workload whose window LENGTHS differ across requests: distinct
    window hours -> distinct structure groups (plus their tail-window
    remainders), so a multi-device round has several groups to
    place/steal (synthetic month cases alone collapse to a couple of
    month-length groups)."""
    import dataclasses
    cases = []
    for i, n in enumerate(lengths):
        for c in synthetic_sensitivity_cases(1, months=1, n=n, seed=i):
            cases.append(dataclasses.replace(c, case_id=f"w{n}.{c.case_id}"))
    return cases


def _dispatch(env=None, lengths=(96, 168, 120)):
    prev = {k: os.environ.get(k) for k in ELASTIC_ENVS}
    _clear_env()
    try:
        for k, v in (env or {}).items():
            os.environ[k] = v
        scens = [MicrogridScenario(c) for c in _mixed_cases(lengths)]
        run_dispatch(scens, backend="jax")
        return scens
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.fixture(scope="module")
def elastic_run():
    """The mixed workload through the DEFAULT elastic scheduler (8
    virtual devices, conftest)."""
    return _dispatch()


@pytest.fixture(scope="module")
def single_run():
    """The identical workload on a SINGLE-device elastic schedule — the
    scheduler-invariance bit reference."""
    return _dispatch({elastic.ELASTIC_DEVICES_ENV: "1"})


@pytest.fixture(scope="module")
def serial_run():
    """The identical workload through the legacy serial global
    scheduler (one shard_map stream over the whole mesh)."""
    return _dispatch({elastic.ELASTIC_ENV: "0"})


def _assert_identical(a_scens, b_scens):
    for sa, sb in zip(a_scens, b_scens):
        assert sa.quarantine is None and sb.quarantine is None
        assert sa.objective_values == sb.objective_values
        assert set(sa._solution) == set(sb._solution)
        for name in sa._solution:
            assert np.array_equal(sa._solution[name], sb._solution[name]), \
                (sa.case.case_id, name)


def _assert_close(a_scens, b_scens, obj_rtol=1e-5, x_atol=0.05):
    for sa, sb in zip(a_scens, b_scens):
        for w in sa.objective_values:
            oa = sa.objective_values[w]["Total Objective"]
            ob = sb.objective_values[w]["Total Objective"]
            assert abs(oa - ob) <= obj_rtol * max(1.0, abs(ob)), (w, oa, ob)
        for name in sa._solution:
            assert np.allclose(sa._solution[name], sb._solution[name],
                               atol=x_atol, rtol=1e-3), name


# ---------------------------------------------------------------------------
# Scheduler invariance: bits never depend on the schedule
# ---------------------------------------------------------------------------

class TestSchedulerInvariance:
    def test_eight_devices_available(self):
        assert len(jax.devices()) >= 8

    def test_eight_vs_single_device_schedule_bitwise(self, elastic_run,
                                                     single_run):
        _assert_identical(elastic_run, single_run)

    def test_two_device_schedule_bitwise(self, single_run):
        scens = _dispatch({elastic.ELASTIC_DEVICES_ENV: "2"})
        _assert_identical(scens, single_run)

    def test_serial_scheduler_within_certification_tolerance(
            self, elastic_run, serial_run):
        """The legacy sharded path's bits vary with per-device batch
        width (dense-op XLA reduction order), so cross-SCHEDULER
        equality is tolerance-level; every window on both sides holds
        an accepted float64 certificate."""
        _assert_close(elastic_run, serial_run)

    def test_elastic_run_fully_certified(self, elastic_run):
        for s in elastic_run:
            cert = s.certification
            assert cert["rejected_final"] == 0
            assert cert["certified"] + cert["certified_loose"] \
                == len(s.windows)

    def test_serial_run_has_no_elastic_section(self, serial_run):
        led = serial_run[0].solve_metadata["solve_ledger"]
        assert "elastic" not in led


# ---------------------------------------------------------------------------
# The elastic ledger slice: placement, occupancy, per-device accounting
# ---------------------------------------------------------------------------

class TestElasticLedger:
    def test_elastic_section_schema(self, elastic_run):
        led = elastic_run[0].solve_metadata["solve_ledger"]
        el = led["elastic"]
        assert el["n_devices"] == len(jax.devices())
        assert el["round_wall_s"] > 0
        assert el["devices_with_groups"] >= 2   # the round actually fanned out
        assert len(el["devices"]) == el["n_devices"]

    def test_group_entries_carry_device_axis(self, elastic_run):
        led = elastic_run[0].solve_metadata["solve_ledger"]
        initial = [g for g in led["groups"] if g.get("rung") == "initial"]
        assert initial
        for g in initial:
            assert isinstance(g["device"], int)

    def test_per_device_slices_account_for_busy_wall(self, elastic_run):
        """The per-device extension of the PR-3 accounted_fraction gate:
        each device's group-entry walls must explain its busy wall, and
        no device can be busier than the round."""
        led = elastic_run[0].solve_metadata["solve_ledger"]
        el = led["elastic"]
        for d, rec in el["devices"].items():
            if not rec["groups"]:
                continue
            assert rec["busy_s"] <= el["round_wall_s"] * 1.05
            assert rec["accounted_fraction"] is not None
            assert 0.5 <= rec["accounted_fraction"] <= 1.05, (d, rec)

    def test_device_windows_sum_to_round(self, elastic_run):
        led = elastic_run[0].solve_metadata["solve_ledger"]
        el = led["elastic"]
        total = sum(rec["windows"] for rec in el["devices"].values())
        assert total == led["totals"]["windows"]

    def test_kernel_choice_recorded_per_group(self, elastic_run):
        led = elastic_run[0].solve_metadata["solve_ledger"]
        initial = [g for g in led["groups"] if g.get("rung") == "initial"]
        for g in initial:
            assert g["kernel"] in ("pallas_chunk", "xla_scan")
            if g["kernel"] == "xla_scan":
                assert g.get("kernel_fallback")   # reason always named
        kern = led["kernel"]
        assert kern["pallas_chunk"] + kern["xla_scan"] >= len(initial)
        # the cpu host platform is an EXPECTED scan reason, never a
        # runtime_disabled regression
        assert not any(r.startswith("runtime_disabled")
                       for r in kern["fallback_reasons"])
        assert kern["runtime_disabled"] is False

    def test_kernel_fallback_gate(self):
        """bench.check_kernel_gate: expected scan reasons (the enums)
        pass, the FALLBACK_RUNTIME_DISABLED enum fails the leg, and the
        legacy free-form 'runtime_disabled: <detail>' prefix from older
        ledgers still fails it."""
        import bench
        from dervet_tpu.ops.pdhg import (FALLBACK_BACKEND,
                                         FALLBACK_RUNTIME_DISABLED,
                                         FALLBACK_UNSUPPORTED_SHAPE)
        bench.check_kernel_gate(None, "t")
        bench.check_kernel_gate(
            {"kernel": {"fallback_reasons":
                        {FALLBACK_BACKEND: 3,
                         FALLBACK_UNSUPPORTED_SHAPE: 1}}}, "t")
        with pytest.raises(SystemExit):
            bench.check_kernel_gate(
                {"kernel": {"fallback_reasons":
                            {FALLBACK_RUNTIME_DISABLED: 1}}}, "t")
        with pytest.raises(SystemExit):
            bench.check_kernel_gate(
                {"kernel": {"fallback_reasons":
                            {"runtime_disabled: scoped vmem": 1}}}, "t")


# ---------------------------------------------------------------------------
# Scheduler unit tests (no device work)
# ---------------------------------------------------------------------------

class TestScheduler:
    def test_env_parsing(self, monkeypatch):
        monkeypatch.setenv(elastic.ELASTIC_ENV, "0")
        assert elastic.elastic_devices("jax") is None
        monkeypatch.setenv(elastic.ELASTIC_ENV, "1")
        assert elastic.elastic_devices("cpu") is None
        devs = elastic.elastic_devices("jax")
        assert devs is not None and len(devs) == len(jax.devices())
        monkeypatch.setenv(elastic.ELASTIC_DEVICES_ENV, "2")
        assert len(elastic.elastic_devices("jax")) == 2
        monkeypatch.setenv(elastic.ELASTIC_DEVICES_ENV, "1")
        assert len(elastic.elastic_devices("jax")) == 1

    def test_cost_estimate_uses_ledger_baseline(self):
        cache = SolverCache()
        items = [(None, type("Ctx", (), {"T": 10})(), None)] * 4
        cold = elastic.estimate_group_cost("k1", items, cache)
        assert cold == 4 * 10 * elastic.DEFAULT_ITERS_BASELINE
        cache.note_iters("k1", 2000.0)
        assert elastic.estimate_group_cost("k1", items, cache) \
            == 4 * 10 * 2000.0
        # EWMA: feedback converges toward the latest measurement
        cache.note_iters("k1", 1000.0)
        assert cache.iters_hint("k1") == 1500.0

    def test_lpt_placement_balances_cost(self):
        sched = elastic.ElasticScheduler(["d0", "d1", "d2"])
        for i, cost in enumerate((100.0, 90.0, 50.0, 40.0, 30.0)):
            sched.submit(f"k{i}", [None], cost)
        assert sorted(sched.placed_cost) == [90.0, 100.0, 120.0]

    def test_affinity_overrides_balance(self):
        sched = elastic.ElasticScheduler(["d0", "d1"])
        sched.submit("k0", [None], 100.0)
        sched.submit("k1", [None], 100.0, affinity=0)
        assert sched.placed_cost == [200.0, 0.0]

    def test_workers_solve_and_steal_from_busy_straggler(self):
        """4 groups over 2 fake devices; device 0's solves are slow, so
        device 1 must steal device 0's queued group while 0 is busy —
        and every group still completes exactly once."""
        import time as _t
        sched = elastic.ElasticScheduler(["slow", "fast"])

        def solve(device, idx, task):
            _t.sleep(0.5 if device == "slow" else 0.05)
            return ("done", task.key)

        for i, cost in enumerate((100.0, 99.0, 98.0, 97.0)):
            sched.submit(f"k{i}", [None], cost)
        sched.start(solve)
        sched.close_submissions()
        done = []
        for task, result, err in sched.completions():
            assert err is None
            done.append(result[1])
        sched.shutdown()
        assert sorted(done) == ["k0", "k1", "k2", "k3"]
        st = sched.stats()
        assert st["n_steals"] >= 1
        assert st["devices"]["1"]["steals_in"] >= 1
        assert st["devices"]["0"]["steals_out"] >= 1

    def test_idle_victim_is_not_stolen_from(self):
        """A group queued on an idle device belongs to that device — a
        steal would move it off its warm compiled-program shard for
        nothing (the phantom-steal hazard that broke the hot service's
        zero-compile round)."""
        import time as _t
        sched = elastic.ElasticScheduler(["a", "b"])
        order = []

        def solve(device, idx, task):
            order.append((task.key, device))
            _t.sleep(0.02)
            return "ok"

        sched.submit("k0", [None], 10.0, affinity=0)
        sched.start(solve)
        _t.sleep(0.3)
        sched.close_submissions()
        list(sched.completions())
        sched.shutdown()
        assert order == [("k0", "a")]
        assert sched.stats()["n_steals"] == 0

    def test_worker_error_propagates(self):
        sched = elastic.ElasticScheduler(["d0"])

        def solve(device, idx, task):
            raise RuntimeError("boom")

        sched.submit("k0", [None], 1.0)
        sched.start(solve)
        sched.close_submissions()
        (task, result, err), = list(sched.completions())
        sched.shutdown()
        assert isinstance(err, RuntimeError)


# ---------------------------------------------------------------------------
# Straggler fault -> work stealing, end to end
# ---------------------------------------------------------------------------

class TestStragglerDrill:
    def test_env_knobs_parse(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FAULT_STRAGGLER", "1")
        monkeypatch.setenv("DERVET_TPU_FAULT_STRAGGLER_DEVICE", "3")
        monkeypatch.setenv("DERVET_TPU_FAULT_STRAGGLER_S", "0.25")
        plan = faultinject.get_plan()
        assert plan is not None and plan.straggler
        assert plan.straggler_device == 3
        assert plan.straggler_delay(3) == 0.25
        assert plan.straggler_delay(1) == 0.0
        assert (faultinject.EVENT_STRAGGLER, "3") in plan.fired

    def test_straggler_is_stolen_from_and_results_correct(self, single_run):
        """End to end: device 0 slowed on a 2-device schedule; the round
        must record >= 1 steal, finish every window, and stay
        byte-identical to the straggler-free single-device schedule
        (stealing moves groups, never changes results)."""
        prev = {k: os.environ.get(k) for k in ELASTIC_ENVS}
        _clear_env()
        try:
            os.environ[elastic.ELASTIC_DEVICES_ENV] = "2"
            # 1.5 s, not 0.6: the slowdown must dwarf one group's solve
            # for the steal window to open deterministically — the r14
            # reflected default cut solve times ~30% and the old margin
            # started racing the victim's own queue drain
            with faultinject.inject(straggler=True, straggler_device=0,
                                    straggler_seconds=1.5) as plan:
                scens = [MicrogridScenario(c) for c in _mixed_cases()]
                run_dispatch(scens, backend="jax")
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        assert any(ev == faultinject.EVENT_STRAGGLER
                   for ev, _ in plan.fired)
        led = scens[0].solve_metadata["solve_ledger"]
        el = led["elastic"]
        assert el["n_devices"] == 2
        assert el["n_steals"] >= 1, el
        assert el["devices"]["1"]["steals_in"] >= 1
        stolen = [g for g in led["groups"] if g.get("stolen")]
        assert stolen and all(g["device"] == 1 for g in stolen)
        _assert_identical(scens, single_run)


class TestEscalationUnderElastic:
    def test_retry_rung_runs_on_the_groups_device(self):
        """Forced non-convergence inside an elastic round: the boosted-
        budget retry re-solves the failed members on the SAME device
        (the shard that holds the group's solver), recoveries land in
        health['retried'], and retry ledger entries carry the device
        tag."""
        scens = [MicrogridScenario(c) for c in _mixed_cases((96,))]
        with faultinject.inject(nonconverge="all", rungs={"solve"}):
            run_dispatch(scens, backend="jax")
        s = scens[0]
        assert s.quarantine is None
        assert s.health["retried"] == len(s.windows)
        led = s.solve_metadata["solve_ledger"]
        retries = [g for g in led["groups"] if g.get("rung") == "retry"]
        assert retries
        # batch sizes are unique per group in this workload (7 + 1), so
        # the retry pairs with its initial rung by batch width
        by_rung = {}
        for g in led["groups"]:
            if g.get("rung") in ("initial", "retry"):
                by_rung.setdefault(g["batch"], {})[g["rung"]] = \
                    g.get("device")
        paired = [r for r in by_rung.values()
                  if "retry" in r and "initial" in r]
        assert paired
        for rungs in paired:
            assert rungs["retry"] == rungs["initial"]


# ---------------------------------------------------------------------------
# Drain mid-elastic-round -> resume
# ---------------------------------------------------------------------------

class TestDrainMidElasticRound:
    def test_preempt_flushes_and_resume_completes(self, tmp_path,
                                                  single_run):
        """SIGTERM after the first elastic batch boundary: the round
        stops, checkpoints + the resume manifest flush, and a second run
        with the same checkpoint_dir finishes with outputs identical to
        the uninterrupted elastic reference."""
        import json

        from dervet_tpu.utils import supervisor as sup
        from dervet_tpu.utils.errors import PreemptedError

        scns = [MicrogridScenario(c) for c in _mixed_cases()]
        with faultinject.inject(preempt_after=1) as plan:
            with sup.RunSupervisor() as rs:
                with pytest.raises(PreemptedError):
                    run_dispatch(scns, backend="jax",
                                 checkpoint_dir=tmp_path, supervisor=rs)
        assert ("preempt", "1") in plan.fired
        manifest = json.loads(sup.manifest_path(tmp_path).read_text())
        assert manifest["cases"]

        scns2 = [MicrogridScenario(c) for c in _mixed_cases()]
        run_dispatch(scns2, backend="jax", checkpoint_dir=tmp_path)
        _assert_identical(scns2, single_run)


# ---------------------------------------------------------------------------
# Per-device warm-up
# ---------------------------------------------------------------------------

class TestWarmupDevices:
    def test_every_device_warmed_with_timings(self):
        from dervet_tpu.parallel.mesh import warmup_devices
        info = warmup_devices()
        n = len(jax.devices())
        assert info["n_devices"] == n
        assert len(info["warmup_s"]) == n
        assert all(v > 0 for v in info["warmup_s"].values())
        assert info["warmup_total_s"] >= max(info["warmup_s"].values())

    def test_inventory_only_mode(self):
        from dervet_tpu.parallel.mesh import warmup_devices
        info = warmup_devices(per_device_solve=False)
        assert "warmup_s" not in info and info["n_devices"] >= 1


# ---------------------------------------------------------------------------
# Per-device solver-cache shards
# ---------------------------------------------------------------------------

class TestCacheShards:
    def test_shards_share_memory_and_mirror_counters(self):
        from dervet_tpu.ops.warmstart import SolutionMemory
        mem = SolutionMemory()
        root = SolverCache(pad_grid=True, memory=mem)
        d0, d1 = jax.devices()[:2]
        s0 = root.shard_for(d0, 0)
        s1 = root.shard_for(d1, 1)
        assert s0 is root.shard_for(d0, 0)      # persistent
        assert s0.memory is mem and s1.memory is mem
        assert s0.pad_grid and s1.pad_grid

    def test_shard_builds_are_sticky_and_cloned_cross_device(self):
        from tests.test_pdhg import battery_like_lp
        lp = battery_like_lp(T=16)
        root = SolverCache()
        d0, d1 = jax.devices()[:2]
        s0 = root.shard_for(d0, 0)
        solver0 = s0.get("k", lp, None)
        assert root.builds == 1
        assert root.device_index_for("k") == 0
        assert list(solver0.op.Kh.devices() if hasattr(solver0.op, "Kh")
                    else solver0.dr.devices()) == [d0]
        # second shard clones the preconditioning instead of rebuilding
        s1 = root.shard_for(d1, 1)
        solver1 = s1.get("k", lp, None)
        assert solver1 is not solver0
        assert list(solver1.dr.devices()) == [d1]
        assert root.builds == 2                 # honest count, no Ruiz rerun
        assert np.array_equal(np.asarray(solver0.dr),
                              np.asarray(solver1.dr))
        assert root.structures_cached() == 1    # one structure, two shards
        root.clear()
        assert root.structures_cached() == 0
        assert root.device_index_for("k") is None
