"""Fleet lifecycle supervisor tests (service/lifecycle.py): crash
respawn with epoch bump + warm memory import, exponential crash-loop
backoff, the typed quarantine terminal state, telemetry-driven
autoscaling up/down with clean drain, the kill switch, and the
supervisor state file the status CLI reads.

All supervision logic runs against fake replicas through the
injectable ``spawn_fn`` — no subprocesses; the real-process path is
drilled by scripts/lifecycle_smoke.py and the chaos soak's supervised
phase."""
import json
import time

import pytest

from dervet_tpu.service import FleetRouter
from dervet_tpu.service.fleet import (MEMORY_EXPORT_FILE, ReplicaHandle,
                                      SpoolReplica)
from dervet_tpu.service.lifecycle import (BACKOFF, QUARANTINED, STOPPED,
                                          UP, FleetSupervisor,
                                          ReplicaSpec, supervision_enabled)
from dervet_tpu.utils.errors import ReplicaQuarantinedError


class FakeReplica(ReplicaHandle):
    """Controllable replica: beats/liveness/load under test control."""

    def __init__(self, name, epoch=None):
        super().__init__(name)
        self.epoch = epoch
        self.beating = True
        self.alive_flag = True
        self.queue_depth = 0.0
        self.imported = []
        self.terminated = False

    def submit(self, cases, rid, **kw):
        pass

    def poll(self, rid):
        return None

    def heartbeat(self):
        if not self.beating:
            return None
        return {"t": time.time(), "name": self.name,
                **({"epoch": self.epoch} if self.epoch is not None
                   else {})}

    def alive(self):
        return self.alive_flag

    def published_load(self):
        return {"queue_depth": float(self.queue_depth),
                "drain_rate_rps": 1.0, "pending": 0.0}

    def import_memory(self, blob):
        self.imported.append(blob)

    def terminate(self, timeout=30.0):
        self.terminated = True
        self.alive_flag = False
        self.beating = False

    def die(self):
        self.beating = False
        self.alive_flag = False


class SpawnLog:
    """Injectable spawn_fn: records every call, returns FakeReplicas."""

    def __init__(self):
        self.calls = []
        self.spawned = []

    def __call__(self, spool, *, name=None, epoch=None, **kw):
        self.calls.append({"spool": spool, "name": name, "epoch": epoch,
                           **kw})
        fake = FakeReplica(name, epoch=epoch)
        self.spawned.append(fake)
        return fake


def _wait(pred, timeout=10.0, msg="condition not reached"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(msg)


def _router(tmp_path, **kw):
    kw.setdefault("heartbeat_timeout_s", 0.4)
    kw.setdefault("tick_s", 0.02)
    kw.setdefault("startup_grace_s", 5.0)
    kw.setdefault("fleet_dir", tmp_path / "fleet")
    return FleetRouter([], **kw).start()


def _supervisor(router, specs, spawn, **kw):
    kw.setdefault("backoff_base_s", 0.05)
    kw.setdefault("backoff_max_s", 0.5)
    kw.setdefault("tick_s", 0.03)
    return FleetSupervisor(router, specs, spawn_fn=spawn, **kw)


class TestKillSwitch:
    def test_disabled_supervisor_is_a_complete_noop(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FLEET_SUPERVISE", "0")
        assert not supervision_enabled()
        r = _router(tmp_path)
        spawn = SpawnLog()
        sup = _supervisor(r, [ReplicaSpec(tmp_path / "r0")], spawn)
        try:
            sup.start()
            # nothing attached, nothing spawned, no thread, no state
            assert r.supervisor is None
            assert spawn.calls == []
            assert sup._thread is None
            sup.on_replica_dead("r0", "crash")       # also a no-op
            time.sleep(0.1)
            assert spawn.calls == []
            assert not (tmp_path / "fleet" /
                        "supervisor_state.json").exists()
        finally:
            sup.stop()
            r.close(terminate_replicas=False)


class TestRespawn:
    def test_crash_respawns_with_epoch_bump_and_warm_import(
            self, tmp_path):
        spool = tmp_path / "r0"
        spool.mkdir()
        # the dead incarnation's last published warm-start export
        (spool / MEMORY_EXPORT_FILE).write_bytes(b"WARM-BLOB")
        r = _router(tmp_path)
        spawn = SpawnLog()
        sup = _supervisor(r, [ReplicaSpec(spool)], spawn,
                          rapid_crash_window_s=0.0)   # never quarantine
        try:
            sup.start()
            assert r.supervisor is sup
            _wait(lambda: "r0" in r.replicas, msg="initial spawn")
            assert spawn.calls[0]["epoch"] == 1
            first = spawn.spawned[0]
            # cold start: no warm import on the initial spawn
            assert first.imported == []
            _wait(lambda: sup.snapshot()["replicas"]["r0"]["state"]
                  == UP, msg="never reached UP")

            first.die()
            _wait(lambda: len(spawn.spawned) >= 2, msg="no respawn")
            second = spawn.spawned[1]
            assert spawn.calls[1]["epoch"] == 2       # fence bump
            _wait(lambda: r.replicas.get("r0") is second,
                  msg="router never adopted the replacement")
            # warm respawn: the dead spool's export rode along
            _wait(lambda: second.imported == [b"WARM-BLOB"],
                  msg="no warm import")
            assert second.restarts == 1
            assert second.last_restart_reason == "process exited"
            snap = sup.snapshot()
            assert snap["counters"]["restarts"] == 1
            assert snap["counters"]["warm_imports"] == 1
            assert snap["replicas"]["r0"]["epoch"] == 2
            assert snap["replicas"]["r0"]["last_restart_reason"] \
                == "process exited"
        finally:
            sup.stop()
            r.close(terminate_replicas=False)

    def test_backoff_grows_exponentially(self, tmp_path):
        r = _router(tmp_path)
        spawn = SpawnLog()
        sup = _supervisor(r, [ReplicaSpec(tmp_path / "r0")], spawn,
                          backoff_base_s=0.1, backoff_max_s=10.0,
                          rapid_crash_window_s=100.0,
                          quarantine_after=10)
        try:
            sup.start()
            _wait(lambda: len(spawn.spawned) == 1, msg="initial spawn")
            _wait(lambda: sup.snapshot()["replicas"]["r0"]["state"]
                  == UP, msg="never up")
            rec = sup._records["r0"]
            t0 = time.monotonic()
            sup.on_replica_dead("r0", "crash #1")
            assert rec.state == BACKOFF
            d1 = rec.backoff_until - t0
            # simulate the respawned incarnation crashing again, fast
            rec.state = UP
            rec.last_spawn_mono = time.monotonic()
            t1 = time.monotonic()
            sup.on_replica_dead("r0", "crash #2")
            d2 = rec.backoff_until - t1
            assert d2 > d1 * 1.5        # base * 2^k doubling
        finally:
            sup.stop()
            r.close(terminate_replicas=False)


class TestQuarantine:
    def test_rapid_crashes_reach_typed_quarantine(self, tmp_path):
        r = _router(tmp_path)
        spawn = SpawnLog()
        sup = _supervisor(r, [ReplicaSpec(tmp_path / "r0")], spawn,
                          rapid_crash_window_s=60.0, quarantine_after=3)
        try:
            sup.start()
            _wait(lambda: len(spawn.spawned) == 1, msg="initial spawn")
            _wait(lambda: sup.snapshot()["replicas"]["r0"]["state"]
                  == UP, msg="never up")
            # crash-loop: each incarnation dies as soon as it is live
            for i in range(3):
                if sup.snapshot()["replicas"]["r0"]["state"] \
                        == QUARANTINED:
                    break
                _wait(lambda: spawn.spawned[-1].alive_flag,
                      msg="no live incarnation")
                n = len(spawn.spawned)
                spawn.spawned[-1].die()
                _wait(lambda: (len(spawn.spawned) > n
                               or sup.snapshot()["replicas"]["r0"]
                               ["state"] == QUARANTINED),
                      msg="no respawn/quarantine after death")
            _wait(lambda: sup.snapshot()["replicas"]["r0"]["state"]
                  == QUARANTINED, msg="never quarantined")
            snap = sup.snapshot()["replicas"]["r0"]
            q = snap["quarantine"]
            assert q["kind"] == "replica_quarantined"
            assert q["replica"] == "r0"
            assert q["crashes"] >= 3
            assert q["retry_hint"] is None
            n_spawns = len(spawn.spawned)
            time.sleep(0.3)
            # terminal: no hot-loop respawning out of quarantine
            assert len(spawn.spawned) == n_spawns
            # the typed error round-trips like the rest of the family
            err = ReplicaQuarantinedError("x", replica="r0", crashes=3,
                                          last_reason="boom")
            assert err.as_dict()["kind"] == "replica_quarantined"

            # operator release clears it and respawns immediately
            assert sup.release("r0")
            _wait(lambda: len(spawn.spawned) > n_spawns,
                  msg="release did not respawn")
            assert sup.snapshot()["counters"]["released"] == 1
        finally:
            sup.stop()
            r.close(terminate_replicas=False)


class TestAutoscale:
    def test_scale_up_on_pressure_then_down_after_clean_drain(
            self, tmp_path):
        r = _router(tmp_path)
        spawn = SpawnLog()
        sup = _supervisor(r, [ReplicaSpec(tmp_path / "r0")], spawn,
                          min_replicas=1, max_replicas=2,
                          scale_up_backlog=4.0, scale_pressure_s=0.1,
                          scale_down_idle_s=0.15,
                          spool_root=tmp_path / "scaled")
        try:
            sup.start()
            _wait(lambda: len(spawn.spawned) == 1, msg="initial spawn")
            base = spawn.spawned[0]
            _wait(lambda: sup.snapshot()["replicas"]["r0"]["state"]
                  == UP, msg="never up")
            base.queue_depth = 50.0         # sustained backlog
            _wait(lambda: len(spawn.spawned) >= 2,
                  msg="no scale-up under sustained pressure")
            scaled = spawn.spawned[1]
            assert scaled.name.startswith("scale")
            assert spawn.calls[1]["spool"] == tmp_path / "scaled" \
                / scaled.name
            _wait(lambda: scaled.name in r.replicas,
                  msg="scaled replica not adopted")
            snap = sup.snapshot()
            assert snap["counters"]["scale_up"] == 1
            assert snap["replicas"][scaled.name]["scaled"] is True
            # bounded: pressure continues but max_replicas=2 holds
            time.sleep(0.3)
            assert len(spawn.spawned) == 2

            # idle fleet: the scaled replica drains CLEAN and goes away
            base.queue_depth = 0.0
            scaled.queue_depth = 0.0
            _wait(lambda: sup.snapshot()["replicas"][scaled.name]
                  ["state"] == STOPPED, msg="no scale-down")
            assert scaled.terminated        # polite drain, not a kill
            assert scaled.name not in r.replicas
            assert sup.snapshot()["counters"]["scale_down"] == 1
            # the baseline replica is never scaled down
            assert "r0" in r.replicas
        finally:
            sup.stop()
            r.close(terminate_replicas=False)


class TestStateAndAdoption:
    def test_state_file_published_for_status_cli(self, tmp_path):
        r = _router(tmp_path)
        spawn = SpawnLog()
        sup = _supervisor(r, [ReplicaSpec(tmp_path / "r0")], spawn)
        try:
            sup.start()
            state_path = tmp_path / "fleet" / "supervisor_state.json"
            _wait(lambda: state_path.exists(), msg="no state file")
            doc = json.loads(state_path.read_text())
            assert doc["enabled"] is True
            assert "r0" in doc["replicas"]
            assert doc["replicas"]["r0"]["restarts"] == 0
            # and the router's metrics() carries the same snapshot
            assert r.metrics()["supervisor"]["replicas"]["r0"]
        finally:
            sup.stop()
            r.close(terminate_replicas=False)

    def test_existing_spool_replicas_adopted_without_specs(
            self, tmp_path):
        spool = tmp_path / "r0"
        handle = SpoolReplica("r0", spool)    # caller-spawned, no proc
        handle.epoch = 4
        r = FleetRouter([handle], fleet_dir=tmp_path / "fleet",
                        heartbeat_timeout_s=0.4, tick_s=0.02).start()
        spawn = SpawnLog()
        sup = _supervisor(r, [], spawn)
        try:
            sup.start()
            snap = sup.snapshot()["replicas"]
            assert "r0" in snap
            assert snap["r0"]["epoch"] == 4
            # a crash of the adopted replica respawns at epoch 5
            sup.on_replica_dead("r0", "heartbeats stopped")
            _wait(lambda: spawn.calls, msg="no respawn of adopted")
            assert spawn.calls[0]["epoch"] == 5
            assert spawn.calls[0]["spool"] == spool
        finally:
            sup.stop()
            r.close(terminate_replicas=False)
