"""Scenario service: the persistent serving layer with cross-request
continuous batching.

The serving contract under test:

* coalesced cross-request solves are BYTE-IDENTICAL to solo
  ``DERVET.solve`` runs of the same cases (objectives, solution arrays,
  the full results-CSV surface), with every window certified — the
  batcher may change how windows are batched, never what is solved;
* admission is bounded (typed queue-full rejections with retry-after),
  priority-then-FIFO ordered, and deadline-aware (expiry is a typed
  error that never poisons the batch);
* SIGTERM drains gracefully: in-flight work checkpoints, per-request
  ``run_manifest.<rid>.json`` slices flush, and resubmitting the same
  request ids resumes;
* the ``overload`` fault kind drills the backpressure path end to end;
* a hot service never recompiles: the persistent solver cache plus
  bucket-grid batch padding make the second round of a different request
  mix run with zero compile events.
"""
import json

import numpy as np
import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.benchlib import synthetic_sensitivity_cases
from dervet_tpu.io.summary import run_artifact_name
from dervet_tpu.service import (AdmissionQueue, DeadlineExpiredError,
                                QueueFullError, RequestFailedError,
                                RequestPreemptedError, ScenarioClient,
                                ScenarioService, ServiceClosedError)
from dervet_tpu.service.queue import QueuedRequest
from dervet_tpu.utils import faultinject
from dervet_tpu.utils import supervisor as sup
from dervet_tpu.utils.errors import PreemptedError


def _cases(n_cases: int, months: int = 1, dict_form: bool = True):
    cs = synthetic_sensitivity_cases(n_cases, months=months)
    return {i: c for i, c in enumerate(cs)} if dict_form else cs


# ---------------------------------------------------------------------------
# Admission queue: ordering, bounds, deadlines
# ---------------------------------------------------------------------------

class TestAdmissionQueue:
    def test_fifo_within_priority(self):
        q = AdmissionQueue(max_depth=8)
        for name in ("a", "b", "c"):
            q.put(QueuedRequest(name, {0: None}))
        got = [r.request_id for r in q.take(max_batch=8, block=False)]
        assert got == ["a", "b", "c"]

    def test_priority_pops_first_fifo_breaks_ties(self):
        q = AdmissionQueue(max_depth=8)
        q.put(QueuedRequest("low1", {0: None}, priority=0))
        q.put(QueuedRequest("hi1", {0: None}, priority=5))
        q.put(QueuedRequest("low2", {0: None}, priority=0))
        q.put(QueuedRequest("hi2", {0: None}, priority=5))
        got = [r.request_id for r in q.take(max_batch=8, block=False)]
        assert got == ["hi1", "hi2", "low1", "low2"]

    def test_bounded_depth_rejects_with_retry_after(self):
        q = AdmissionQueue(max_depth=1)
        q.retry_after_s = 2.5
        q.put(QueuedRequest("a", {0: None}))
        with pytest.raises(QueueFullError) as ei:
            q.put(QueuedRequest("b", {0: None}))
        assert ei.value.retry_after_s == 2.5
        assert q.counters["rejected_full"] == 1

    def test_take_respects_max_batch(self):
        q = AdmissionQueue(max_depth=8)
        for i in range(5):
            q.put(QueuedRequest(f"r{i}", {0: None}))
        assert len(q.take(max_batch=2, block=False)) == 2
        assert q.depth() == 3

    def test_expired_request_answered_not_batched(self):
        q = AdmissionQueue(max_depth=8)
        dead = QueuedRequest("dead", {0: None}, deadline_s=1e-9)
        live = QueuedRequest("live", {0: None})
        q.put(dead)
        q.put(live)
        import time
        time.sleep(0.01)
        got = q.take(max_batch=8, block=False)
        assert [r.request_id for r in got] == ["live"]
        with pytest.raises(DeadlineExpiredError):
            dead.future.result(0)
        assert q.counters["expired"] == 1

    def test_closed_queue_rejects(self):
        q = AdmissionQueue(max_depth=8)
        q.close()
        with pytest.raises(ServiceClosedError):
            q.put(QueuedRequest("a", {0: None}))


# ---------------------------------------------------------------------------
# Coalesced cross-request solves: byte-identical to solo DERVET.solve
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def coalesced():
    """Two mixed-size requests coalesced into ONE service round (jax
    backend, bucket padding on), next to solo ``DERVET.solve`` runs of
    the identical cases."""
    solo_a = DERVET.from_cases(_cases(2)).solve(backend="jax")
    solo_b = DERVET.from_cases(_cases(3)).solve(backend="jax")
    svc = ScenarioService(backend="jax", max_wait_s=0.0)
    fa = svc.submit(_cases(2), request_id="reqA")
    fb = svc.submit(_cases(3), request_id="reqB")
    served = svc.run_once()
    yield {"svc": svc, "solo": {"reqA": solo_a, "reqB": solo_b},
           "srv": {"reqA": fa.result(0), "reqB": fb.result(0)},
           "served": served}
    svc.close()


class TestCoalescedByteIdentical:
    def test_one_round_served_both(self, coalesced):
        assert coalesced["served"] == 2

    def test_round_actually_coalesced_across_requests(self, coalesced):
        led = coalesced["svc"].last_round_ledger
        initial = [g for g in led["groups"] if g.get("rung") == "initial"]
        # both requests' windows rode shared device batches.  On this
        # 8-virtual-device test platform the sharded path pads to the
        # mesh multiple itself; bucket padding (padded_to) is the
        # single-device equivalent — see TestBatchBucketPadding.
        assert any(set(g.get("requests", ())) == {"reqA", "reqB"}
                   for g in initial)
        assert all(g["batch"] == 5 for g in initial)

    def test_objectives_and_solutions_bit_identical(self, coalesced):
        for rid in ("reqA", "reqB"):
            solo, srv = coalesced["solo"][rid], coalesced["srv"][rid]
            assert sorted(solo.instances) == sorted(srv.instances)
            for k in solo.instances:
                s = solo.instances[k].scenario
                v = srv.instances[k].scenario
                assert s.objective_values == v.objective_values
                assert set(s._solution) == set(v._solution)
                for name in s._solution:
                    assert np.array_equal(s._solution[name],
                                          v._solution[name]), (rid, k, name)

    def test_results_csv_surface_identical(self, coalesced, tmp_path):
        for rid in ("reqA", "reqB"):
            coalesced["solo"][rid].save_as_csv(tmp_path / rid / "solo")
            coalesced["srv"][rid].save_as_csv(tmp_path / rid / "srv")
            solo_files = sorted(p.name for p in
                                (tmp_path / rid / "solo").glob("*.csv"))
            srv_files = sorted(p.name for p in
                               (tmp_path / rid / "srv").glob("*.csv"))
            assert solo_files == srv_files and solo_files
            for name in solo_files:
                a = (tmp_path / rid / "solo" / name).read_bytes()
                b = (tmp_path / rid / "srv" / name).read_bytes()
                assert a == b, f"{rid}/{name} differs from solo solve"

    def test_every_window_certified(self, coalesced):
        for rid in ("reqA", "reqB"):
            res = coalesced["srv"][rid]
            cert = res.run_health["certification"]
            n_windows = sum(len(inst.scenario.windows)
                            for inst in res.instances.values())
            assert cert["enabled"]
            assert cert["windows_certified"] == n_windows
            assert cert["windows"]["rejected_final"] == 0

    def test_request_scoped_health_and_ledger_slice(self, coalesced):
        ra = coalesced["srv"]["reqA"]
        rb = coalesced["srv"]["reqB"]
        assert ra.run_health["cases_total"] == 2
        assert rb.run_health["cases_total"] == 3
        for res, n_cases in ((ra, 2), (rb, 3)):
            sl = res.solve_ledger
            assert sl["request_id"] == res.request_id
            assert sl["totals"]["windows"] == n_cases   # months=1
            assert sl["totals"]["batched_windows"] == 5  # shared batches
            assert sl["coalesced_groups"] >= 1
            assert sl["round"]["dispatch_solve_s"] is not None

    def test_namespaced_artifacts_written(self, coalesced, tmp_path):
        res = coalesced["srv"]["reqA"]
        res.save_as_csv(tmp_path)
        assert (tmp_path / "run_health.reqA.json").exists()
        assert (tmp_path / "solve_ledger.reqA.json").exists()
        health = json.loads((tmp_path / "run_health.reqA.json").read_text())
        assert health["windows"]["clean"] == 2
        # the un-namespaced single-run filename is NOT produced
        assert not (tmp_path / "run_health.json").exists()

    def test_metrics_surface(self, coalesced):
        m = coalesced["svc"].metrics()
        assert m["requests"]["completed"] == 2
        assert m["queue"]["admitted"] == 2
        assert m["latency_s"]["n"] == 2
        assert m["latency_s"]["p99"] >= m["latency_s"]["p50"] > 0
        assert m["batch_occupancy"]["cross_request_groups"] >= 1
        assert m["batch_occupancy"]["mean_windows_per_device_batch"] == 5.0
        cc = m["compile_cache"]
        assert cc["solver_builds"] >= 1
        assert cc["structures_cached"] >= 1


class TestHotServiceNeverRecompiles:
    def test_second_round_zero_compiles_different_mix(self, coalesced):
        """A DIFFERENT request mix whose coalesced width lands in the
        same bucket reuses every compiled program: zero compile events,
        solver-cache hits instead of builds."""
        svc = coalesced["svc"]
        builds_before = svc.solver_cache.builds
        f1 = svc.submit(_cases(1), request_id="mix1")
        f2 = svc.submit(_cases(4), request_id="mix2")
        assert svc.run_once() == 2
        f1.result(0), f2.result(0)
        assert svc.solver_cache.builds == builds_before   # no new builds
        assert svc.solver_cache.hits >= 1
        led = svc.last_round_ledger
        assert led["totals"]["compile_events"] == 0


# ---------------------------------------------------------------------------
# Bucket-grid batch padding (the single-device never-recompile mechanism)
# ---------------------------------------------------------------------------

class TestBatchBucketPadding:
    def test_bucket_grid_and_gating(self):
        from dervet_tpu.scenario.scenario import (SolverCache,
                                                  _batch_pad_to,
                                                  batch_bucket)
        assert [batch_bucket(n) for n in (0, 1, 2, 5, 8, 9, 32, 33)] == \
            [0, 1, 8, 8, 8, 32, 32, 128]
        cache = SolverCache(pad_grid=True)
        assert _batch_pad_to(cache, 5, multi_dev=False) == 8
        assert _batch_pad_to(cache, 8, multi_dev=False) is None
        assert _batch_pad_to(cache, 9, multi_dev=False) == 32
        # inapplicable: sharded path pads to the mesh multiple itself;
        # single instances are their own program family; one-shot runs
        # (pad_grid off) pay each width's compile exactly once anyway
        assert _batch_pad_to(cache, 5, multi_dev=True) is None
        assert _batch_pad_to(cache, 1, multi_dev=False) is None
        assert _batch_pad_to(SolverCache(), 5, multi_dev=False) is None
        assert _batch_pad_to(None, 5, multi_dev=False) is None

    def _lp_variants(self, n_var: int):
        import copy
        from tests.test_pdhg import battery_like_lp
        lp = battery_like_lp(T=48)
        rng = np.random.default_rng(11)
        out = []
        for _ in range(n_var):
            lp_i = copy.deepcopy(lp)
            lp_i.c[:] = lp.c * (1.0 + 0.1 * rng.standard_normal(lp.n))
            out.append(lp_i)
        return out

    def test_padded_stack_repeats_last_instance(self):
        from dervet_tpu.scenario.scenario import _stack_group_data
        lps = self._lp_variants(3)
        C, Q, L, U = _stack_group_data(lps, np.dtype(np.float32),
                                       multi_dev=False, pad_to=8)
        assert C.shape[0] == 8
        for i in range(3, 8):
            np.testing.assert_array_equal(C[i], C[2])
        # identical-across-group vectors still collapse to 1-D (the
        # broadcast handles the padded width on device)
        assert Q.ndim == L.ndim == U.ndim == 1

    def test_padded_solve_bit_identical_after_trim(self):
        """Bucket padding is a pure shape change: the padded batch's
        first rows are bit-equal to the unpadded batch's results."""
        from dervet_tpu.ops.pdhg import CompiledLPSolver
        lps = self._lp_variants(3)
        solver = CompiledLPSolver(lps[0])

        def stack(pad_to=None):
            from dervet_tpu.scenario.scenario import _stack_group_data
            C, Q, L, U = _stack_group_data(lps, np.dtype(np.float32),
                                           multi_dev=False, pad_to=pad_to)
            B = pad_to or len(lps)
            import jax
            import jax.numpy as jnp
            Q = jnp.broadcast_to(jax.device_put(Q), (B, Q.shape[0]))
            return C, Q, L, U

        res_pad = solver.solve(*stack(pad_to=8))
        res_raw = solver.solve(*stack())
        np.testing.assert_array_equal(np.asarray(res_pad.x)[:3],
                                      np.asarray(res_raw.x))
        np.testing.assert_array_equal(np.asarray(res_pad.obj)[:3],
                                      np.asarray(res_raw.obj))


# ---------------------------------------------------------------------------
# Service-level ordering, deadlines, isolation (cpu backend: fast+exact)
# ---------------------------------------------------------------------------

class TestServiceOrdering:
    def test_priority_served_in_earlier_round(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0,
                              max_batch_requests=1)
        f_low = svc.submit(_cases(1), request_id="low", priority=0)
        f_hi = svc.submit(_cases(1), request_id="hi", priority=5)
        assert svc.run_once() == 1
        assert f_hi.done() and not f_low.done()
        assert svc.run_once() == 1
        assert f_low.done()
        svc.close()

    def test_deadline_expiry_typed_error_without_poisoning_batch(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        dead = svc.submit(_cases(1), request_id="dead", deadline_s=1e-9)
        live = svc.submit(_cases(1), request_id="live")
        import time
        time.sleep(0.01)
        assert svc.run_once() == 1
        with pytest.raises(DeadlineExpiredError):
            dead.result(0)
        res = live.result(0)
        assert res.run_health["windows"]["clean"] == 1
        assert len(res.instances) == 1
        svc.close()

    def test_request_isolation_one_request_fails_others_complete(self):
        """A poisoned request is answered with its typed failure; the
        co-batched healthy request completes clean — case-level
        quarantine isolation, lifted to request scope."""
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        with faultinject.inject(poison_cases={"bad.0"}):
            f_bad = svc.submit(_cases(1), request_id="bad")
            f_ok = svc.submit(_cases(2), request_id="ok")
            assert svc.run_once() == 2
        with pytest.raises(RequestFailedError) as ei:
            f_bad.result(0)
        assert 0 in ei.value.failures
        res = f_ok.result(0)
        assert res.run_health["windows"]["quarantined"] == 0
        assert sorted(res.instances) == [0, 1]
        svc.close()


# ---------------------------------------------------------------------------
# Overload fault: drillable backpressure
# ---------------------------------------------------------------------------

class TestOverloadFault:
    def test_forced_rejections_then_clean_service(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        with faultinject.inject(overload=True, overload_n=2) as plan:
            with pytest.raises(QueueFullError) as e1:
                svc.submit(_cases(1))
            assert e1.value.retry_after_s > 0
            with pytest.raises(QueueFullError):
                svc.submit(_cases(1))
            fut = svc.submit(_cases(1), request_id="after")  # fault spent
        assert [k for k, _ in plan.fired] == \
            [faultinject.EVENT_OVERLOAD, faultinject.EVENT_OVERLOAD]
        assert svc.run_once() == 1
        assert fut.result(0).run_health["windows"]["clean"] == 1
        m = svc.metrics()
        assert m["queue"]["rejected_overload"] == 2
        assert m["requests"]["completed"] == 1
        svc.close()        # exit-0 analogue: drain raises nothing
        assert svc.metrics()["service"]["draining"]

    def test_env_knobs_parse(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FAULT_OVERLOAD", "1")
        monkeypatch.setenv("DERVET_TPU_FAULT_OVERLOAD_N", "1")
        plan = faultinject.get_plan()
        assert plan is not None
        assert plan.overload_due()
        assert not plan.overload_due()     # bounded to the first N

    def test_client_retry_after_handling(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        svc.queue.retry_after_s = 0.01
        client = ScenarioClient(svc, max_retries=3)
        with faultinject.inject(overload=True, overload_n=2):
            fut = client.submit(_cases(1), request_id="retried")
        assert svc.run_once() == 1
        assert fut.result(0) is not None
        svc.close()

    def test_client_gives_up_after_max_retries(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        svc.queue.retry_after_s = 0.01
        client = ScenarioClient(svc, max_retries=1)
        with faultinject.inject(overload=True):     # unbounded
            with pytest.raises(QueueFullError):
                client.submit(_cases(1))
        svc.close()


# ---------------------------------------------------------------------------
# SIGTERM drain: resumable per-request manifests
# ---------------------------------------------------------------------------

class TestDrainAndResume:
    def test_sigterm_mid_round_leaves_resumable_manifests(self, tmp_path):
        """Acceptance drill: a SIGTERM mid-dispatch answers in-flight
        requests with the typed preemption error, flushes per-request
        ``run_manifest.<rid>.json`` slices, and a fresh service with the
        same checkpoint dir + request ids completes with results
        identical to never-interrupted solo runs."""
        ref_a = DERVET.from_cases(_cases(1, months=2)).solve(backend="cpu")
        ref_b = DERVET.from_cases(_cases(2, months=2)).solve(backend="cpu")

        svc = ScenarioService(backend="cpu", max_wait_s=0.0,
                              checkpoint_dir=tmp_path)
        with svc.supervisor:       # install SIGTERM handlers (main thread)
            fa = svc.submit(_cases(1, months=2), request_id="ra")
            fb = svc.submit(_cases(2, months=2), request_id="rb")
            with faultinject.inject(preempt_after=1) as plan:
                with pytest.raises(PreemptedError):
                    svc.run_once()
        assert ("preempt", "1") in plan.fired
        for fut, rid in ((fa, "ra"), (fb, "rb")):
            err = fut.exception(0)
            assert isinstance(err, RequestPreemptedError)
            assert err.manifest_path == sup.manifest_path(tmp_path, rid)
        # per-request manifest slices + the shared sweep manifest exist
        for rid, n_cases in (("ra", 1), ("rb", 2)):
            man = json.loads(sup.manifest_path(tmp_path, rid).read_text())
            assert man["request_id"] == rid
            assert len(man["cases"]) == n_cases
            assert set(man["cases"]) == \
                {f"{rid}.{k}" for k in range(n_cases)}
            assert all(c["status"] in ("done", "partial")
                       for c in man["cases"].values())
        shared = json.loads(sup.manifest_path(tmp_path).read_text())
        assert len(shared["cases"]) == 3
        # the interrupted round made real progress somewhere (each case
        # has a window in both structure groups, so after the first
        # batch boundary every case is partial with >= 1 window done)
        assert sum(c["windows_done"]
                   for c in shared["cases"].values()) >= 1

        # -- resume: same ids + checkpoint dir on a fresh service -------
        svc2 = ScenarioService(backend="cpu", max_wait_s=0.0,
                               checkpoint_dir=tmp_path)
        fa2 = svc2.submit(_cases(1, months=2), request_id="ra")
        fb2 = svc2.submit(_cases(2, months=2), request_id="rb")
        assert svc2.run_once() == 2
        for fut, ref in ((fa2, ref_a), (fb2, ref_b)):
            res = fut.result(0)
            for k in ref.instances:
                s, v = ref.instances[k].scenario, res.instances[k].scenario
                assert s.objective_values == v.objective_values
        # delivered requests' resume material is spent and reclaimed
        # (per-request manifests + npz checkpoints); the shared sweep
        # manifest records the completed round
        for rid in ("ra", "rb"):
            assert not sup.manifest_path(tmp_path, rid).exists()
        assert not list(tmp_path.glob("case*.npz"))
        shared2 = json.loads(sup.manifest_path(tmp_path).read_text())
        assert all(c["status"] == "done"
                   for c in shared2["cases"].values())
        svc2.close()
        svc.close()

    def test_unexpected_round_error_still_answers_futures(self,
                                                          monkeypatch):
        """A dispatch crash that is neither preemption nor solver
        failure must still resolve every in-flight future — a leaked
        unresolved future hangs its client forever.  Since PR 6 the
        poison-isolation protocol attributes the repeatable crash and
        answers with the TYPED quarantine error (diagnosis attached)
        instead of leaking the raw exception; the service survives."""
        from dervet_tpu.service import PoisonRequestError
        from dervet_tpu.service import batcher as batcher_mod

        def boom(*a, **k):
            raise RuntimeError("device fell over")

        monkeypatch.setattr(batcher_mod, "run_dispatch", boom)
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        fut = svc.submit(_cases(1), request_id="crashed")
        assert svc.run_once() == 1      # isolation handled: no raise
        err = fut.exception(0)
        assert isinstance(err, PoisonRequestError)
        assert "device fell over" in err.diagnosis
        svc.close()

    def test_unsafe_request_id_rejected_at_admission(self):
        """Request ids name checkpoint/manifest/health files: path
        characters must be rejected at the API boundary, not written."""
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        for bad in ("x/../../z", "a b", "", "x" * 65):
            with pytest.raises(ValueError, match="request id"):
                svc.submit(_cases(1), request_id=bad)
        svc.close()

    def test_duplicate_request_id_rejected_while_in_flight(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        fut = svc.submit(_cases(1), request_id="dup")
        with pytest.raises(ValueError, match="still in flight"):
            svc.submit(_cases(1), request_id="dup")
        assert svc.run_once() == 1
        fut.result(0)
        # the id frees once its future resolves: resubmission is fine
        fut2 = svc.submit(_cases(1), request_id="dup")
        assert svc.run_once() == 1
        assert fut2.result(0) is not None
        svc.close()

    def test_drain_answers_queued_requests_as_not_started(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        fut = svc.submit(_cases(1), request_id="never-started")
        svc.request_stop()
        with pytest.raises(ServiceClosedError):
            svc.submit(_cases(1))          # admissions closed immediately
        svc.drain()
        with pytest.raises(ServiceClosedError):
            fut.result(0)

    def test_started_service_thread_drains_clean(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.05).start()
        fut = svc.submit(_cases(2), request_id="threaded")
        res = fut.result(timeout=120)
        assert res.run_health["windows"]["clean"] == 2
        svc.close()
        assert svc.metrics()["service"]["draining"]


# ---------------------------------------------------------------------------
# Artifact namespacing
# ---------------------------------------------------------------------------

class TestArtifactNamespacing:
    def test_run_artifact_name(self):
        assert run_artifact_name("run_health.json") == "run_health.json"
        assert run_artifact_name("run_health.json", None) == \
            "run_health.json"
        assert run_artifact_name("run_health.json", "reqA") == \
            "run_health.reqA.json"
        # unsafe characters sanitized, never path separators
        assert run_artifact_name("run_health.json", "a/b c") == \
            "run_health.a_b_c.json"
        assert run_artifact_name("manifest", "x") == "manifest.x"

    def test_manifest_path_namespacing(self, tmp_path):
        assert sup.manifest_path(tmp_path).name == "run_manifest.json"
        assert sup.manifest_path(tmp_path, "r1").name == \
            "run_manifest.r1.json"

    def test_api_request_id_threads_to_artifacts(self, tmp_path):
        res = DERVET.from_cases(_cases(1)).solve(backend="cpu",
                                                 request_id="apireq")
        res.save_as_csv(tmp_path)
        assert (tmp_path / "run_health.apireq.json").exists()
        assert not (tmp_path / "run_health.json").exists()

    def test_single_run_path_keeps_todays_filenames(self, tmp_path):
        res = DERVET.from_cases(_cases(1)).solve(backend="cpu")
        res.save_as_csv(tmp_path)
        assert (tmp_path / "run_health.json").exists()
        assert not list(tmp_path.glob("solve_ledger*"))


# ---------------------------------------------------------------------------
# `dervet-tpu serve` file-spool loop
# ---------------------------------------------------------------------------

class TestServeLoop:
    def test_serve_once_processes_spool_and_exits_zero(self, tmp_path,
                                                       monkeypatch):
        from dervet_tpu.io.params import Params
        from dervet_tpu.service.server import serve_main
        monkeypatch.setattr(
            Params, "initialize",
            classmethod(lambda cls, path, base_path=None, verbose=False:
                        _cases(1)))
        incoming = tmp_path / "incoming"
        incoming.mkdir(parents=True)
        (incoming / "caseX.csv").write_text("patched-away")
        rc = serve_main([str(tmp_path), "--once", "--backend", "cpu"])
        assert rc == 0
        out = tmp_path / "results" / "caseX"
        assert (out / "run_health.caseX.json").exists()
        assert list(out.glob("*.csv"))
        assert (tmp_path / "done" / "caseX.csv").exists()
        metrics = json.loads(
            (tmp_path / "service_metrics.json").read_text())
        assert metrics["requests"]["completed"] == 1

    def test_serve_once_retries_deferred_inputs_under_backpressure(
            self, tmp_path, monkeypatch):
        """--once must serve EVERY spool file even when an admission is
        deferred by backpressure: the deferred leftover is rescanned
        once the queue eases, not silently dropped with exit 0."""
        from dervet_tpu.io.params import Params
        from dervet_tpu.service.server import serve_main
        monkeypatch.setattr(
            Params, "initialize",
            classmethod(lambda cls, path, base_path=None, verbose=False:
                        _cases(1)))
        incoming = tmp_path / "incoming"
        incoming.mkdir(parents=True)
        (incoming / "first.csv").write_text("stub")
        (incoming / "second.csv").write_text("stub")
        with faultinject.inject(overload=True, overload_n=1):
            rc = serve_main([str(tmp_path), "--once", "--backend", "cpu",
                             "--poll-s", "0.05"])
        assert rc == 0
        assert (tmp_path / "done" / "first.csv").exists()
        assert (tmp_path / "done" / "second.csv").exists()
        metrics = json.loads(
            (tmp_path / "service_metrics.json").read_text())
        assert metrics["requests"]["completed"] == 2
        assert metrics["queue"]["rejected_overload"] == 1

    def test_serve_once_parks_unparseable_input(self, tmp_path):
        from dervet_tpu.service.server import serve_main
        incoming = tmp_path / "incoming"
        incoming.mkdir(parents=True)
        (incoming / "broken.csv").write_text("not,a,model,params,file")
        rc = serve_main([str(tmp_path), "--once", "--backend", "cpu"])
        assert rc == 0
        assert (tmp_path / "failed" / "broken.csv").exists()
        assert (tmp_path / "failed" / "broken.csv.error.txt").exists()

    def test_cli_dispatches_serve_subcommand(self, monkeypatch, tmp_path):
        import dervet_tpu.__main__ as cli
        from dervet_tpu.service import server as server_mod
        called = {}

        def fake_serve(argv):
            called["argv"] = argv
            return 0

        monkeypatch.setattr(server_mod, "serve_main", fake_serve)
        with pytest.raises(SystemExit) as ei:
            cli.main(["serve", str(tmp_path), "--once"])
        assert ei.value.code == 0
        assert called["argv"] == [str(tmp_path), "--once"]
