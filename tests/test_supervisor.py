"""Run supervisor layer: graceful SIGTERM shutdown with a sweep-level
resume manifest, the solve watchdog, and crash-safe output writes — every
path exercised deterministically through the fault-injection harness
(``hang`` / ``slow_solve`` / ``preempt`` fault kinds).

PR 1's resilience ladder covers *solver* failure inside a window; this
layer covers the *run*: a preempted sweep flushes checkpoints plus
``run_manifest.json`` and exits with a distinct code, a re-run with the
same checkpoint_dir skips fully-``done`` cases entirely, and a wedged
device call is abandoned at the ``DERVET_TPU_SOLVE_DEADLINE_S`` deadline
instead of stalling the process."""
import json
import types

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.benchlib import synthetic_case
from dervet_tpu.scenario.scenario import MicrogridScenario, run_dispatch
from dervet_tpu.utils import faultinject
from dervet_tpu.utils import supervisor as sup
from dervet_tpu.utils.errors import PreemptedError


def _small_case(case_id: int = 0, days: int = 2, n=12):
    """Days of the synthetic Battery+PV+DA case in n-hour windows — small
    enough for per-fault drills (same shape as test_resilience)."""
    case = synthetic_case()
    case.case_id = case_id
    case.scenario["allow_partial_year"] = True
    case.scenario["n"] = n
    case.datasets.time_series = \
        case.datasets.time_series.iloc[: 24 * days].copy()
    return case


# ---------------------------------------------------------------------------
# Crash-safe writes
# ---------------------------------------------------------------------------

class TestAtomicWrites:
    def test_atomic_write_round_trip(self, tmp_path):
        target = tmp_path / "out" / "health.json"
        sup.atomic_write(target, '{"ok": 1}')
        assert json.loads(target.read_text()) == {"ok": 1}
        # no tmp residue, and the tmp name is dot-prefixed so output-dir
        # globs can never pick a half-written file up
        assert [p.name for p in target.parent.iterdir()] == ["health.json"]

    def test_interrupted_write_keeps_previous_file(self, tmp_path):
        target = tmp_path / "manifest.json"
        sup.atomic_write(target, "v1")
        with pytest.raises(RuntimeError):
            with sup.atomic_output(target) as tmp:
                tmp.write_text("v2-half-wri")
                raise RuntimeError("kill mid-write")
        assert target.read_text() == "v1"          # old file intact
        assert list(tmp_path.iterdir()) == [target]  # tmp cleaned up

    def test_atomic_output_keeps_suffix_for_savez(self, tmp_path):
        # np.savez appends .npz when the target lacks it: the tmp must
        # keep the suffix so the write lands on the intended name
        target = tmp_path / "case0_windows.npz"
        with sup.atomic_output(target) as tmp:
            assert tmp.suffix == ".npz"
            np.savez(tmp, a=np.arange(3))
        assert np.array_equal(np.load(target)["a"], np.arange(3))

    def test_bytes_payload(self, tmp_path):
        sup.atomic_write(tmp_path / "b.bin", b"\x00\x01")
        assert (tmp_path / "b.bin").read_bytes() == b"\x00\x01"


# ---------------------------------------------------------------------------
# Resume manifest
# ---------------------------------------------------------------------------

def _fake_scn(cid, total, solved, quarantine=None, opt_engine=True):
    s = types.SimpleNamespace(
        case=types.SimpleNamespace(case_id=cid),
        windows=list(range(total)), _solved=set(range(solved)),
        quarantine=quarantine, opt_engine=opt_engine)
    s._checkpoint_fingerprint = lambda: f"fp{cid}"
    return s


class TestManifest:
    def test_write_statuses(self, tmp_path):
        scns = [_fake_scn(0, 4, 4),
                _fake_scn(1, 4, 2),
                _fake_scn(2, 4, 1, quarantine={"reason": "boom"}),
                _fake_scn(3, 4, 0, opt_engine=False)]
        m = sup.write_manifest(tmp_path, scns, backend="cpu")
        on_disk = json.loads(sup.manifest_path(tmp_path).read_text())
        assert on_disk == m
        assert m["version"] == sup.MANIFEST_VERSION
        assert m["backend"] == "cpu"
        cases = m["cases"]
        assert cases["0"]["status"] == "done"
        assert cases["1"]["status"] == "partial"
        assert cases["1"]["windows_done"] == 2
        assert cases["2"]["status"] == "quarantined"
        assert cases["2"]["reason"] == "boom"
        assert cases["3"]["status"] == "done"     # no dispatch needed
        assert cases["0"]["fingerprint"] == "fp0"

    def test_load_missing_corrupt_or_wrong_version(self, tmp_path):
        assert sup.load_manifest(tmp_path) is None
        path = sup.manifest_path(tmp_path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text("{ truncated")
        assert sup.load_manifest(tmp_path) is None
        path.write_text(json.dumps({"version": 999, "cases": {}}))
        assert sup.load_manifest(tmp_path) is None
        path.write_text(json.dumps({"version": sup.MANIFEST_VERSION,
                                    "cases": {"0": {"status": "done"}}}))
        m = sup.load_manifest(tmp_path)
        assert m is not None and m["cases"]["0"]["status"] == "done"


# ---------------------------------------------------------------------------
# Solve watchdog
# ---------------------------------------------------------------------------

class TestWatchdog:
    def test_call_fast_slow_and_raising(self):
        wd = sup.SolveWatchdog(0.25)
        assert wd.call(lambda: 42) == (42, False)
        import time as _t
        result, timed_out = wd.call(lambda: _t.sleep(5))
        assert timed_out and result is None
        assert wd.timeouts == 1

        def boom():
            raise ValueError("inner")

        with pytest.raises(ValueError, match="inner"):
            wd.call(boom)

    def test_from_env(self, monkeypatch):
        monkeypatch.delenv(sup.DEADLINE_ENV, raising=False)
        assert sup.SolveWatchdog.from_env() is None
        monkeypatch.setenv(sup.DEADLINE_ENV, "2.5")
        wd = sup.SolveWatchdog.from_env()
        assert wd is not None and wd.deadline_s == 2.5
        monkeypatch.setenv(sup.DEADLINE_ENV, "0")
        assert sup.SolveWatchdog.from_env() is None
        monkeypatch.setenv(sup.DEADLINE_ENV, "not-a-number")
        assert sup.SolveWatchdog.from_env() is None

    def test_hang_detected_and_escalated(self, monkeypatch):
        """Acceptance drill: an injected hang is detected within the
        configured deadline and surfaced in the health report; the hung
        call is abandoned and its windows recover down the existing
        ladder instead of stalling the process."""
        monkeypatch.setenv(sup.DEADLINE_ENV, "0.3")
        ref = MicrogridScenario(_small_case())
        with faultinject.inject(hang={1}, hang_seconds=1.5):
            s = MicrogridScenario(_small_case())
            s.optimize_problem_loop(backend="cpu")
        monkeypatch.delenv(sup.DEADLINE_ENV)
        ref.optimize_problem_loop(backend="cpu")
        assert s.quarantine is None
        # the hung group (all windows co-batched) was abandoned as ONE
        # call — one watchdog event — and every member recovered on the
        # boosted-budget retry
        assert s.health["watchdog_timeouts"] == 1
        assert s.health["retried"] == len(s.windows)
        assert s.health["clean"] == 0
        for k in ref.objective_values:
            assert s.objective_values[k]["Total Objective"] == \
                pytest.approx(ref.objective_values[k]["Total Objective"],
                              rel=1e-9)

    def test_hang_in_health_report_and_metadata(self, monkeypatch):
        from dervet_tpu.io.summary import run_health_report
        monkeypatch.setenv(sup.DEADLINE_ENV, "0.3")
        with faultinject.inject(hang={1}, hang_seconds=1.5) as plan:
            s = MicrogridScenario(_small_case())
            s.optimize_problem_loop(backend="cpu")
        assert ("hang", "1") in plan.fired
        assert s.solve_metadata["health"]["watchdog_timeouts"] > 0
        report = run_health_report({0: s.health}, {})
        assert report["watchdog_timeouts"] == 1
        assert report["per_case"]["0"]["watchdog_timeouts"] == 1

    def test_slow_solve_within_deadline_is_clean(self, monkeypatch):
        """A bounded slowdown under the deadline must NOT trip the
        watchdog — no false positives from the deadline machinery."""
        monkeypatch.setenv(sup.DEADLINE_ENV, "30")
        with faultinject.inject(slow={1}, slow_seconds=0.2) as plan:
            s = MicrogridScenario(_small_case())
            s.optimize_problem_loop(backend="cpu")
        assert ("slow_solve", "1") in plan.fired
        assert s.quarantine is None
        assert s.health["watchdog_timeouts"] == 0
        assert s.health["clean"] == len(s.windows)


# ---------------------------------------------------------------------------
# Graceful shutdown + resume
# ---------------------------------------------------------------------------

def _two_structure_sweep():
    """Two cases whose windows differ in length (12 h vs 24 h): two
    structure groups, hence two window-batch boundaries — the preempt
    point lands BETWEEN the groups, leaving one case done and one
    untouched."""
    return [MicrogridScenario(_small_case(0, n=12)),
            MicrogridScenario(_small_case(1, n=24))]


class TestPreemptResume:
    def test_sigterm_mid_sweep_then_resume(self, tmp_path):
        """Acceptance drill: an injected SIGTERM mid-sweep exits cleanly
        with a valid run_manifest.json, and a second run with the same
        checkpoint_dir completes without re-dispatching ``done`` cases,
        producing outputs identical to an uninterrupted run."""
        ref = _two_structure_sweep()
        run_dispatch(ref, backend="cpu")
        ref_ts = {s.case.case_id: s.timeseries_results() for s in ref}

        scns = _two_structure_sweep()
        with faultinject.inject(preempt_after=1) as plan:
            with sup.RunSupervisor() as rs:
                with pytest.raises(PreemptedError) as ei:
                    run_dispatch(scns, backend="cpu",
                                 checkpoint_dir=tmp_path, supervisor=rs)
        assert ("preempt", "1") in plan.fired
        assert rs.stop_signal is not None
        assert "stop requested" in str(ei.value)

        manifest = json.loads(sup.manifest_path(tmp_path).read_text())
        statuses = sorted(c["status"] for c in manifest["cases"].values())
        assert statuses == ["done", "partial"]

        # -- resume: the done case is reloaded, not re-dispatched --------
        scns2 = _two_structure_sweep()
        run_dispatch(scns2, backend="cpu", checkpoint_dir=tmp_path)
        done_id = next(cid for cid, c in manifest["cases"].items()
                       if c["status"] == "done")
        for s in scns2:
            assert s.quarantine is None
            assert len(s.objective_values) == len(s.windows)
            if str(s.case.case_id) == done_id:
                assert s.solve_metadata.get("resumed_from_manifest") is True
                assert s.solve_metadata["batched_solves"] == 0
                assert sum(s.health[k] for k in
                           ("clean", "retried", "cpu_fallback")) == 0
            else:
                assert "resumed_from_manifest" not in s.solve_metadata
        # final outputs identical to the uninterrupted run
        for s in scns2:
            pd.testing.assert_frame_equal(
                s.timeseries_results(), ref_ts[s.case.case_id])
        for r, s in zip(ref, scns2):
            for k in r.objective_values:
                assert s.objective_values[k]["Total Objective"] == \
                    pytest.approx(
                        r.objective_values[k]["Total Objective"], rel=1e-12)
        # the completed resume run marks every case done
        manifest2 = json.loads(sup.manifest_path(tmp_path).read_text())
        assert all(c["status"] == "done"
                   for c in manifest2["cases"].values())

    def test_fingerprint_mismatch_forces_full_dispatch(self, tmp_path):
        """A manifest whose fingerprint does not match the case inputs
        must NOT be trusted: the case re-dispatches from scratch."""
        scns = _two_structure_sweep()
        run_dispatch(scns, backend="cpu", checkpoint_dir=tmp_path)
        manifest = json.loads(sup.manifest_path(tmp_path).read_text())
        for c in manifest["cases"].values():
            c["fingerprint"] = "stale-inputs"
        sup.manifest_path(tmp_path).write_text(json.dumps(manifest))
        scns2 = _two_structure_sweep()
        run_dispatch(scns2, backend="cpu", checkpoint_dir=tmp_path)
        for s in scns2:
            assert "resumed_from_manifest" not in s.solve_metadata
            # the per-window checkpoint self-verifies its own fingerprint
            # (which still matches), so windows resume from it — but the
            # manifest fast path was refused
            assert s.quarantine is None

    def test_stop_flag_without_signals(self, tmp_path):
        """The supervisor works as a plain stop-flag where handlers
        cannot be installed: a pre-requested stop halts at the FIRST
        batch boundary and still flushes the manifest."""
        rs = sup.RunSupervisor(install_signals=False)
        rs.request_stop()
        scns = _two_structure_sweep()
        with pytest.raises(PreemptedError):
            run_dispatch(scns, backend="cpu", checkpoint_dir=tmp_path,
                         supervisor=rs)
        manifest = json.loads(sup.manifest_path(tmp_path).read_text())
        assert set(manifest["cases"]) == {"0", "1"}

    def test_preempt_without_checkpoint_dir_still_raises(self):
        rs = sup.RunSupervisor(install_signals=False)
        rs.request_stop()
        with pytest.raises(PreemptedError):
            run_dispatch(_two_structure_sweep(), backend="cpu",
                         supervisor=rs)

    def test_second_signal_escalates(self):
        """The first signal only requests a stop; handler bookkeeping for
        the second-signal escape hatch restores the default disposition
        (asserted without actually re-delivering, which would kill the
        test process)."""
        import signal as _signal
        with sup.RunSupervisor() as rs:
            assert not rs.stop_requested()
            rs._on_signal(_signal.SIGTERM, None)
            assert rs.stop_requested()
            assert rs.stop_signal == _signal.SIGTERM
        # context exit restored the original handlers
        assert _signal.getsignal(_signal.SIGTERM) \
            is _signal.SIG_DFL or callable(
                _signal.getsignal(_signal.SIGTERM))


class TestCLIExitCode:
    def test_preempted_maps_to_exit_75(self, monkeypatch, tmp_path):
        import dervet_tpu.api as api
        import dervet_tpu.__main__ as cli

        class FakeDERVET:
            def __init__(self, path, verbose=False, base_path=None):
                pass

            def solve(self, backend="auto", checkpoint_dir=None):
                raise PreemptedError("stop requested (signal 15)")

        monkeypatch.setattr(api, "DERVET", FakeDERVET)
        with pytest.raises(SystemExit) as ei:
            cli.main([str(tmp_path / "params.csv")])
        assert ei.value.code == sup.EXIT_PREEMPTED == 75


class TestFaultEnvKnobs:
    def test_new_env_knobs_parse(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FAULT_HANG", "1")
        monkeypatch.setenv("DERVET_TPU_FAULT_HANG_S", "7.5")
        monkeypatch.setenv("DERVET_TPU_FAULT_SLOW", "2")
        monkeypatch.setenv("DERVET_TPU_FAULT_SLOW_S", "0.5")
        monkeypatch.setenv("DERVET_TPU_FAULT_PREEMPT_AFTER", "3")
        plan = faultinject.get_plan()
        assert plan is not None
        secs, kind = plan.sleep_seconds([1], faultinject.RUNG_SOLVE)
        assert (secs, kind) == (7.5, faultinject.EVENT_HANG)
        secs, kind = plan.sleep_seconds([2], faultinject.RUNG_SOLVE)
        assert (secs, kind) == (0.5, faultinject.EVENT_SLOW)
        assert not plan.preempt_due(2)
        assert plan.preempt_due(3)
        assert not plan.preempt_due(4)     # one-shot

    def test_hang_wins_over_slow_on_same_label(self):
        plan = faultinject.FaultPlan(hang={1}, hang_seconds=2.0,
                                     slow={1}, slow_seconds=0.1)
        secs, kind = plan.sleep_seconds([1], faultinject.RUNG_SOLVE)
        assert (secs, kind) == (2.0, faultinject.EVENT_HANG)

    def test_sleep_respects_rungs(self):
        plan = faultinject.FaultPlan(hang={1}, rungs={"retry"})
        assert plan.sleep_seconds([1], faultinject.RUNG_SOLVE) == (0.0, "")
        secs, _ = plan.sleep_seconds([1], faultinject.RUNG_RETRY)
        assert secs > 0
