"""Golden-file comparators (re-implements /root/reference/test/TestingLib.py
semantics: relative-error bound in percent, cell-by-cell comparison of
proforma / size / LCPC frames against the frozen reference CSVs)."""
from pathlib import Path

import numpy as np
import pandas as pd

REF = Path("/root/reference")


def assert_within_error_bound(expected, actual, bound_pct: float, msg=""):
    """|actual - expected| / |expected| <= bound_pct %  (reference
    TestingLib.py:56-60)."""
    expected = float(expected)
    actual = float(actual)
    if expected == 0.0:
        assert abs(actual) < 1e-6 or abs(actual) <= bound_pct, \
            f"{msg} expected 0, got {actual}"
        return
    err = abs(actual - expected) / abs(expected) * 100.0
    assert err <= bound_pct, \
        f"{msg} expected {expected}, got {actual} ({err:.2f}% > {bound_pct}%)"


def _ci_lookup(columns, name: str):
    low = {str(c).strip().lower(): c for c in columns}
    return low.get(str(name).strip().lower())


def compare_proforma_results(inst, frozen_path, bound_pct: float):
    """Cell-by-cell vs the frozen proforma (reference TestingLib.py:78-96).
    Columns matched case-insensitively; expected all-zero columns may be
    absent from the result."""
    expected = pd.read_csv(frozen_path, index_col=0)
    got = inst.proforma_df.copy()
    got.index = [str(i) for i in got.index]
    # column superset: every golden column with any non-zero value must be
    # present in the result (all-zero columns may be absent — the reference
    # emits a zero column where the repo omits the stream entirely)
    missing = [c for c in expected.columns
               if _ci_lookup(got.columns, c) is None
               and not np.allclose(np.nan_to_num(
                   expected[c].to_numpy(dtype=float)), 0.0)]
    assert not missing, f"missing non-zero proforma columns {missing}"
    for col in expected.columns:
        gcol = _ci_lookup(got.columns, col)
        if gcol is None:
            continue
        for idx in expected.index:
            exp = expected.loc[idx, col]
            if pd.isna(exp):
                continue
            assert str(idx) in got.index, f"missing proforma row {idx}"
            assert_within_error_bound(
                exp, got.loc[str(idx), gcol], bound_pct,
                f"proforma[{idx}, {col}]:")


def compare_size_results(inst, frozen_path, bound_pct: float):
    """Size frame vs frozen CSV (reference TestingLib.py:119-135)."""
    expected = pd.read_csv(frozen_path, index_col="DER")
    got = inst.sizing_df
    for der in expected.index:
        gder = _ci_lookup(got.index, der)
        if gder is None:
            row = expected.loc[der]
            assert not row.notna().any() or \
                np.allclose(row.dropna().to_numpy(dtype=float), 0.0), \
                f"missing sized DER {der!r}"
            continue
        for col in expected.columns:
            exp = expected.loc[der, col]
            if pd.isna(exp):
                continue
            gcol = _ci_lookup(got.columns, col)
            # a column the golden populates must exist and hold a value in
            # the result (reference TestingLib.py:131-135 raises KeyError on
            # a missing column; a NaN where the golden has a number is the
            # same defect)
            assert gcol is not None, f"missing size column {col!r}"
            assert not pd.isna(got.loc[gder, gcol]), \
                f"size[{der}, {col}] is NaN, expected {exp}"
            assert_within_error_bound(exp, got.loc[gder, gcol], bound_pct,
                                      f"size[{der}, {col}]:")


def compare_lcpc_results(inst, frozen_path, bound_pct: float):
    """LCPC curve vs frozen CSV (reference TestingLib.py:138-148)."""
    test_df = inst.drill_down_dict.get("load_coverage_prob")
    assert test_df is not None
    actual = pd.read_csv(frozen_path)
    got = test_df.reset_index()
    for i in actual.index:
        exp = actual.loc[i, "Load Coverage Probability (%)"]
        val = got.loc[i, "Load Coverage Probability (%)"]
        assert_within_error_bound(exp, val, bound_pct, f"lcpc[{i}]:")
