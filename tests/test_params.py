"""Params loader regression: every reference input must load (or fail with
the reference's error semantics).

Reference inputs are the spec (VERDICT r1 item 3): this sweeps every
model-parameters CSV in the reference snapshot through ``Params.initialize``.
Files whose referenced datasets are absent from the snapshot (large 5-min
blobs listed in /root/reference/.MISSING_LARGE_BLOBS, paths under the
never-checked-out storagevet submodule, .xlsx inputs) must raise
``ModelParameterError`` — the reference's own failure mode for a missing
referenced file (dervet/DERVETParams.py:93-130).
"""
import glob
from pathlib import Path

import pytest

from dervet_tpu.io.params import Params, convert_value, normalize_path
from dervet_tpu.utils.errors import ModelParameterError

REF = Path("/root/reference")

# inputs whose referenced data files do not exist anywhere in the snapshot
# (or that only disabled xtest_ reference tests consume)
KNOWN_UNLOADABLE = {
    "002-catch_wrong_length.csv",                # reference expects this to
                                                 # raise: evaluation list vs
                                                 # sensitivity length mismatch
    "109-carrying_cost_d_is_e_error.csv",        # reference expects a raise
                                                 # (analysis_horizon_mode=4 is
                                                 # outside allowed 1|2|3)
    "004-cba_valuation_coupled_dt.csv",          # 000-011-timeseries_5min_2017.csv missing
    "Model_Parameters_Template_DER_PoSD.csv",    # .\Testing\... datasets absent
    "Model_Parameters_Template_DER_PoSD_deferral.csv",
    "Model_Parameters_Template_DER_PoSD_service_error.csv",
    "Model_Parameters_Template_ENEA_S1_8_12_UC1_DAETS.csv",
    "Model_Parameters_Template_ENEA_S1_8_12_UC1_DAETS_doesnt_reach_eol_during_opt.csv",
    "shortest_lifetime_linear_salvage.csv",      # swapped cols; only xtest_ uses it
    "017-bat_timeseries_dt_sensitivity_couples.csv",  # .xlsx input absent
    "018-DA_battery_month_5min.csv",             # .MISSING_LARGE_BLOBS
    "020-coupled_dt_timseries_error.csv",        # .MISSING_LARGE_BLOBS
}

ALL_INPUTS = sorted(
    set(glob.glob(str(REF / "test/**/model_params/*.csv"), recursive=True))
    | {str(REF / "Model_Parameters_Template_DER.csv")}
)


@pytest.mark.parametrize("path", ALL_INPUTS, ids=lambda p: Path(p).name)
def test_reference_input_loads(path):
    name = Path(path).name
    if name in KNOWN_UNLOADABLE:
        with pytest.raises(ModelParameterError):
            Params.initialize(path, base_path=REF)
        return
    cases = Params.initialize(path, base_path=REF)
    assert len(cases) >= 1
    case = cases[0]
    assert case.scenario and case.finance


def test_canonical_template_monthly_data_case_mismatch():
    """The canonical template references 'monthly_Data.csv'; on-disk file is
    'monthly_data.csv' — resolution must be case-insensitive (ADVICE r1)."""
    cases = Params.initialize(REF / "Model_Parameters_Template_DER.csv", base_path=REF)
    assert cases[0].datasets.monthly is not None


def test_posix_absolute_path(tmp_path):
    f = tmp_path / "ts.csv"
    f.write_text("a,b\n1,2\n")
    assert normalize_path(str(f), tmp_path) == f


def test_sensitivity_fanout():
    """009-bat_energy_sensitivity sweeps ene_max_rated -> multiple cases
    (reference: test_1params.py:51-62 semantics)."""
    path = REF / "test/test_storagevet_features/model_params/009-bat_energy_sensitivity.csv"
    cases = Params.initialize(path, base_path=REF)
    # the reference's own count for this input (test_1params.py:51-56)
    assert len(cases) == 4
    vals = set()
    for c in cases.values():
        bat = next(keys for tag, _, keys in c.ders if tag == "Battery")
        vals.add(bat["ene_max_rated"])
    assert len(vals) == len(cases)
    assert not cases[0].sensitivity_df.empty


def test_multiyear_opt_years_whitespace_list():
    path = REF / "test/test_storagevet_features/model_params/007-nsr_battery_multiyr.csv"
    cases = Params.initialize(path, base_path=REF)
    assert cases[0].scenario["opt_years"] == [2017, 2018]


def test_convert_value_types():
    assert convert_value("1.5", "float") == 1.5
    assert convert_value("2017, 2018", "list/int") == [2017, 2018]
    assert convert_value("2017 2018", "list/int") == [2017, 2018]
    assert convert_value("month", "string/int") == "month"
    assert convert_value("744", "string/int") == 744
    assert convert_value("linear salvage value", "string/float") == "linear salvage value"
    assert convert_value("500", "string/float") == 500.0
    assert convert_value("yes", "bool") is True
    assert convert_value("nan", "bool") is False


def test_opt_years_not_in_timeseries_data():
    """Reference test_1params.py:97-101: an opt_year with no rows in the
    referenced time series is REJECTED, not growth-filled."""
    from dervet_tpu.api import DERVET
    from dervet_tpu.utils.errors import TimeseriesDataError
    path = (REF / "test/test_storagevet_features/model_params/"
            "025-opt_year_more_than_timeseries_data.csv")
    with pytest.raises(TimeseriesDataError):
        DERVET(path, base_path=REF).solve(backend="cpu")


def test_opt_years_not_in_monthly_data():
    """Reference test_1params.py:117-124: an opt_year missing from the
    monthly data raises MonthlyDataError."""
    from dervet_tpu.api import DERVET
    from dervet_tpu.utils.errors import MonthlyDataError
    path = (REF / "test/test_storagevet_features/model_params/"
            "039-mutli_opt_years_not_in_monthly_data.csv")
    with pytest.raises(MonthlyDataError):
        DERVET(path, base_path=REF).solve(backend="cpu")


# ---------------------------------------------------------------------------
# Allowed-Values enforcement (VERDICT r2 #5): out-of-range / out-of-set
# inputs are rejected like the reference's per-key Schema validation
# (reference: dervet/Schema.json allowed_values/min/max metadata enforced
# through DERVETParams' validation path).
# ---------------------------------------------------------------------------

def _template_with(tmp_path, tag, key, value):
    import pandas as pd
    df = pd.read_csv(REF / "Model_Parameters_Template_DER.csv")
    sel = (df.Tag == tag) & (df.Key == key)
    assert sel.any(), (tag, key)
    df.loc[sel, "Optimization Value"] = value
    out = tmp_path / "mp.csv"
    df.to_csv(out, index=False)
    return out


@pytest.mark.parametrize("tag,key,value", [
    ("Scenario", "binary", "2"),            # bool outside {0,1}
    ("Scenario", "ownership", "shared"),    # not in customer|utility|3rd party
    ("Battery", "rte", "110"),              # % above max 100
    ("Battery", "macrs_term", "6"),         # not an IRS MACRS term
    ("Finance", "analysis_horizon_mode", "4"),   # allowed 1|2|3
    ("Battery", "salvage_value", "bogus words"),  # not a number or mode
])
def test_allowed_values_rejected(tmp_path, tag, key, value):
    with pytest.raises(ModelParameterError):
        Params.initialize(_template_with(tmp_path, tag, key, value),
                          base_path=REF)


def test_allowed_values_accepted(tmp_path):
    """In-range edits still load: numeric salvage, allowed ownership."""
    path = _template_with(tmp_path, "Battery", "salvage_value", "5000")
    cases = Params.initialize(path, base_path=REF)
    assert len(cases) == 1


# ---------------------------------------------------------------------------
# bad_active_combo (VERDICT r3 #8): Params-time rejection of active-tag
# combinations that cannot produce a solvable run, before any window is
# assembled (reference: dervet/DERVETParams.py:143-155).
# ---------------------------------------------------------------------------

def _template_with_active(tmp_path, activate=(), deactivate=()):
    import pandas as pd
    df = pd.read_csv(REF / "Model_Parameters_Template_DER.csv")
    for tag in activate:
        sel = df.Tag == tag
        assert sel.any(), tag
        df.loc[sel, "Active"] = "yes"
    for tag in deactivate:
        df.loc[df.Tag == tag, "Active"] = "no"
    out = tmp_path / "mp.csv"
    df.to_csv(out, index=False)
    return out


class TestBadActiveCombo:
    # template baseline active tags: Battery + DA (+ Scenario/Finance)

    def test_no_der_active(self, tmp_path):
        path = _template_with_active(tmp_path, deactivate=("Battery",))
        with pytest.raises(ModelParameterError, match="technology"):
            Params.initialize(path, base_path=REF)

    def test_no_stream_active(self, tmp_path):
        path = _template_with_active(tmp_path, deactivate=("DA",))
        with pytest.raises(ModelParameterError, match="value stream"):
            Params.initialize(path, base_path=REF)

    def test_ra_and_dr_conflict(self, tmp_path):
        path = _template_with_active(tmp_path, activate=("RA", "DR"))
        with pytest.raises(ModelParameterError, match="Resource Adequacy"):
            Params.initialize(path, base_path=REF)

    def test_market_without_dispatchable_der(self, tmp_path):
        path = _template_with_active(tmp_path, activate=("FR", "PV"),
                                     deactivate=("Battery",))
        with pytest.raises(ModelParameterError, match="dispatchable"):
            Params.initialize(path, base_path=REF)

    def test_good_combo_untouched(self, tmp_path):
        path = _template_with_active(tmp_path)     # Battery + DA baseline
        assert len(Params.initialize(path, base_path=REF)) == 1
