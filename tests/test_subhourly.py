"""Sub-hourly (5-minute) dispatch: SOE dt scaling, hour-ending billing
masks, window partitioning (the reference ships 5-min datasets —
test/datasets/000-004-timeseries_5min*.csv — but they were dropped from
the snapshot, so this synthesizes an equivalent)."""
import numpy as np
import pandas as pd
import pytest

from dervet_tpu.io.params import CaseParams, Datasets
from dervet_tpu.scenario.scenario import MicrogridScenario


def _case_5min(days=2):
    dt = 1.0 / 12.0
    idx = pd.date_range("2017-01-01", periods=days * 288, freq="5min")
    rng = np.random.default_rng(11)
    price = 0.03 + 0.05 * (idx.hour >= 17) + 0.01 * rng.random(len(idx))
    ts = pd.DataFrame({"DA Price ($/kWh)": price,
                       "Site Load (kW)": 500.0}, index=idx)
    tariff = pd.DataFrame({
        "Billing Period": [1, 2], "Start Month": [1, 1], "End Month": [12, 12],
        "Start Time": [1, 18], "End Time": [24, 21],
        "Excluding Start Time": [None] * 2, "Excluding End Time": [None] * 2,
        "Weekday?": [2, 2], "Value": [0.05, 15.0],
        "Charge": ["Energy", "Demand"]}).set_index("Billing Period")
    scenario = {"dt": dt, "n": 12, "opt_years": [2017],
                "start_year": 2017, "end_year": 2021, "incl_site_load": True,
                "allow_partial_year": True}
    ders = [("Battery", "1", {
        "name": "b5", "ene_max_rated": 400, "ch_max_rated": 200,
        "dis_max_rated": 200, "rte": 90, "ulsoc": 100, "llsoc": 0,
        "soc_target": 50, "ccost_kw": 100, "ccost_kwh": 100})]
    return CaseParams(
        case_id=0, scenario=scenario,
        finance={"npv_discount_rate": 7, "inflation_rate": 2,
                 "customer_tariff_filename": "x"},
        results={}, ders=ders, streams={"DA": {"growth": 0}},
        datasets=Datasets(time_series=ts, tariff=tariff))


def test_5min_dispatch_physics():
    case = _case_5min()
    # drop tariff streams; DA only for physics
    case.finance.pop("customer_tariff_filename")
    case.datasets.tariff = None
    s = MicrogridScenario(case)
    # year-completeness check must accept partial synthetic horizons, so
    # run the loop directly on the windows
    s.optimize_problem_loop(backend="cpu")
    ts = s.timeseries_results()
    dt = 1.0 / 12.0
    ch = ts["BATTERY: b5 Charge (kW)"].to_numpy()
    dis = ts["BATTERY: b5 Discharge (kW)"].to_numpy()
    ene = ts["BATTERY: b5 State of Energy (kWh)"].to_numpy()
    # begin-of-step dynamics with dt = 5 min; windows are 12h = 144 steps
    n_win = len(s.windows)
    step = 144
    for w in range(n_win):
        sl = slice(w * step, (w + 1) * step)
        e, c, d = ene[sl], ch[sl], dis[sl]
        resid = e[1:] - e[:-1] - 0.90 * dt * c[:-1] + dt * d[:-1]
        assert np.abs(resid).max() < 1e-4
        assert e[0] == pytest.approx(200.0, abs=1e-3)   # 50% of 400
    assert dis.sum() > 0   # arbitrage happened


def test_5min_hour_ending_masks():
    """he labels for 5-min steps: all 12 steps of hour h belong to he h+1
    (reference: 'Times are in units of hour-ending')."""
    from dervet_tpu.financial.tariff import TariffEngine
    case = _case_5min()
    eng = TariffEngine(case.datasets.tariff)
    idx = pd.date_range("2017-01-02", periods=288, freq="5min")
    mask = eng.period_mask(2, idx)   # he 18..21 -> hb hours 17..20
    hours = np.asarray(idx.hour)
    assert (mask == ((hours >= 17) & (hours <= 20))).all()
    # demand charge on the 5-min peak
    load = pd.Series(100.0, index=idx)
    load.iloc[17 * 12 + 3] = 400.0
    _, simple = eng.monthly_bill(load, load, dt=1 / 12)
    assert float(simple["Demand Charge ($)"].iloc[0]) == pytest.approx(
        15.0 * 400.0)
    # energy charge integrates dt
    expected = 0.05 * (100.0 * 287 + 400.0) / 12.0
    assert float(simple["Energy Charge ($)"].iloc[0]) == pytest.approx(expected)


def test_5min_window_partitioning():
    case = _case_5min()
    case.finance.pop("customer_tariff_filename")
    case.datasets.tariff = None
    s = MicrogridScenario(case)
    assert len(s.windows) == 4          # 2 days / 12h windows
    assert all(w.T == 144 for w in s.windows)
