"""Portfolio co-optimization: dual-decomposed coupled-site LPs.

The portfolio contract under test:

* decomposition CORRECTNESS — a 2-site toy portfolio with a binding
  shared export cap matches a monolithic HiGHS solve of the full
  coupled LP to 1e-6 objective agreement (exact cpu inner solves +
  finite column-generation convergence);
* coupling-row FEASIBILITY of the blended answer at termination,
  certified in float64 against the unscaled aggregate;
* BYTE-DETERMINISM of a repeated portfolio solve;
* dual-iterate WARM SEEDING: iteration k+1 reseeds every window from
  its iteration-k iterate even though the price shift moves every
  float16-quantized digest feature (the PR-13 warm-start fix), with
  measurably fewer inner iterations than round 0;
* typed INFEASIBILITY (``PortfolioInfeasibleError`` with violated-row
  diagnosis) instead of a non-converging dual loop, and the
  ``diverging_duals`` fault drill (detect, rescale, still certify);
* SERVICE integration: submit/metrics/spool round-trips, and a
  load-shed degraded portfolio answer that is NEVER cert-stamped.
"""
import dataclasses
import json

import numpy as np
import pytest

from dervet_tpu.ops import warmstart
from dervet_tpu.ops.certify import validate_portfolio_certification
from dervet_tpu.portfolio import (COUPLING_LABEL, PortfolioInfeasibleError,
                                  PortfolioSpec, monolithic_reference,
                                  solve_portfolio,
                                  validate_portfolio_section)
from dervet_tpu.portfolio.service import (PortfolioRound,
                                          parse_portfolio_request,
                                          synthetic_portfolio_members)
from dervet_tpu.utils import faultinject
from dervet_tpu.utils.errors import ParameterError


def _members(n=2, hours=48, window=24, seed=0, pv_kw=9000.0):
    return synthetic_portfolio_members(n, hours=hours, window=window,
                                       seed=seed, pv_kw=pv_kw)


def _binding_cap(n=2, hours=48, window=24, margin=800.0):
    """A shared export cap strictly below the fleet's unconstrained
    aggregate peak — guaranteed binding."""
    probe = solve_portfolio(
        PortfolioSpec(members=_members(n, hours, window),
                      export_cap_kw=1e9, max_outer=1), backend="cpu")
    return float(probe.aggregate["net_export"].max()) - margin


# ---------------------------------------------------------------------------
# Spec validation
# ---------------------------------------------------------------------------

class TestSpec:
    def test_requires_coupling(self):
        with pytest.raises(ParameterError, match="no coupling"):
            PortfolioSpec(members=_members(2)).validate()

    def test_requires_two_sites(self):
        m = _members(2)
        one = {k: v for k, v in list(m.items())[:1]}
        with pytest.raises(ParameterError, match=">= 2 sites"):
            PortfolioSpec(members=one, export_cap_kw=1.0).validate()

    def test_mismatched_horizons_rejected(self):
        m = _members(2, hours=48)
        m2 = _members(2, hours=72)
        mixed = {"a": m["site000"], "b": m2["site001"]}
        spec = PortfolioSpec(members=mixed, export_cap_kw=1e9)
        with pytest.raises(ParameterError, match="horizon"):
            solve_portfolio(spec, backend="cpu")

    def test_profile_length_validated(self):
        spec = PortfolioSpec(members=_members(2, hours=48),
                             export_cap_kw=[1.0] * 7)
        with pytest.raises(ParameterError, match="profile has length"):
            solve_portfolio(spec, backend="cpu")


# ---------------------------------------------------------------------------
# Decomposition correctness vs the monolithic coupled LP
# ---------------------------------------------------------------------------

class TestDecomposition:
    def test_two_site_matches_monolithic_to_1e6(self):
        cap = _binding_cap()
        spec = PortfolioSpec(members=_members(), export_cap_kw=cap,
                             gap_tol=1e-9, feas_tol=1e-7, max_outer=60)
        res = solve_portfolio(spec, backend="cpu")
        mono = monolithic_reference(
            PortfolioSpec(members=_members(), export_cap_kw=cap))
        assert mono["status"] == 0
        assert res.converged
        rel = abs(res.primal_objective - mono["objective_cx"]) \
            / (1.0 + abs(mono["objective_cx"]))
        assert rel < 1e-6, (res.primal_objective, mono["objective_cx"])
        # the cap genuinely binds (otherwise this test proves nothing)
        assert res.certification["coupling_rows"]["export_cap"][
            "binding"] > 0
        # and the coupled optimum is strictly worse than uncoupled
        probe = solve_portfolio(
            PortfolioSpec(members=_members(), export_cap_kw=1e9,
                          max_outer=1), backend="cpu")
        assert res.primal_objective > probe.primal_objective + 1.0

    def test_coupling_feasible_at_termination_float64(self):
        cap = _binding_cap()
        res = solve_portfolio(
            PortfolioSpec(members=_members(), export_cap_kw=cap,
                          gap_tol=1e-9, feas_tol=1e-7, max_outer=60),
            backend="cpu")
        # float64 re-check of the blended aggregate, independent of the
        # engine's own bookkeeping
        viol = np.maximum(res.aggregate["net_export"] - cap, 0.0)
        assert float(viol.max()) <= 1e-6 * (1.0 + abs(cap))
        cert = res.certification
        validate_portfolio_certification(cert)
        assert cert["verdict"] in ("certified", "certified_loose")
        assert cert["inner_exact"] is True
        assert cert["gap_rel"] <= 1e-9 * 10

    def test_demand_charge_epigraph(self):
        # a portfolio demand charge prices the PEAK aggregate import;
        # the monolithic reference carries the same epigraph variable
        spec = PortfolioSpec(members=_members(), export_cap_kw=1e9,
                             demand_charge_per_kw=2.0,
                             gap_tol=1e-6, max_outer=60)
        res = solve_portfolio(spec, backend="cpu")
        assert res.converged
        peak_import = float(np.maximum(
            -res.aggregate["net_export"], 0.0).max())
        assert res.demand_charge_cost == pytest.approx(
            2.0 * peak_import, rel=1e-4)
        mono = monolithic_reference(
            PortfolioSpec(members=_members(), export_cap_kw=1e9,
                          demand_charge_per_kw=2.0))
        rel = abs(res.primal_objective - mono["objective_cx"]) \
            / (1.0 + abs(mono["objective_cx"]))
        assert rel < 1e-5

    def test_repeat_solve_byte_deterministic(self):
        cap = _binding_cap()

        def run():
            return solve_portfolio(
                PortfolioSpec(members=_members(), export_cap_kw=cap,
                              gap_tol=1e-9, feas_tol=1e-7,
                              max_outer=60), backend="cpu")

        a, b = run(), run()
        assert repr(a.primal_objective) == repr(b.primal_objective)
        assert a.outer_rounds == b.outer_rounds
        assert a.aggregate["net_export"].tobytes() == \
            b.aggregate["net_export"].tobytes()
        for kind in a.duals:
            assert a.duals[kind].tobytes() == b.duals[kind].tobytes()
        for key in a.site_solutions:
            for name, arr in a.site_solutions[key].items():
                assert arr.tobytes() == \
                    b.site_solutions[key][name].tobytes(), (key, name)


# ---------------------------------------------------------------------------
# Dual-iterate warm seeding (jax backend)
# ---------------------------------------------------------------------------

class TestDualWarmSeeding:
    @pytest.fixture(scope="class")
    def coupled(self):
        probe = solve_portfolio(
            PortfolioSpec(members=_members(4, hours=336, window=168),
                          export_cap_kw=1e9, max_outer=1),
            backend="jax")
        cap = float(probe.aggregate["net_export"].max()) - 2000.0
        res = solve_portfolio(
            PortfolioSpec(members=_members(4, hours=336, window=168),
                          export_cap_kw=cap, max_outer=10),
            backend="jax")
        return probe, res

    def test_rounds_after_first_are_dual_seeded(self, coupled):
        _, res = coupled
        assert res.converged
        assert len(res.rounds) >= 2
        for r in res.rounds[1:]:
            assert r["seeded"] == r["windows"]
            assert r["dual_iterate"] + r["substituted"] == r["windows"]

    def test_seeded_rounds_cut_iterations(self, coupled):
        probe, res = coupled
        cold = probe.rounds[0]["iters_p50"]
        late = [r["iters_p50"] for r in res.rounds[1:]]
        assert min(late) < cold / 1.5
        assert res.rounds[-1]["iters_p50"] < cold

    def test_zero_compiles_after_round_one(self, coupled):
        _, res = coupled
        assert sum(r["compile_events"] for r in res.rounds[1:]) == 0

    def test_all_site_windows_certified(self, coupled):
        _, res = coupled
        ps = res.certification["per_site"]
        assert ps["all_certified"] and ps["windows_total"] > 0
        validate_portfolio_section(res.run_health["portfolio"])
        assert res.solve_ledger["portfolio"]["converged"]


class TestDualIterateGrade:
    """The PR-13 warm-start fix: a dual update's uniform price shift
    moves every float16-quantized digest feature, so the near grade
    degrades — the dedicated ``dual_iterate`` hint grade must carry the
    reseeding instead."""

    def _lp(self, shift=0.0):
        from dervet_tpu.benchlib import synthetic_case
        from dervet_tpu.scenario.scenario import MicrogridScenario
        c = synthetic_case()
        ts = c.datasets.time_series
        c.datasets.time_series = ts.iloc[:48]
        c.scenario["allow_partial_year"] = True
        c.scenario["n"] = 24
        s = MicrogridScenario(c)
        ctx = s.windows[0]
        lp = s.build_window_lp(ctx)
        if shift:
            # the dual update's signature: every power-term cost entry
            # shifts by the (per-timestep) price — far past the float16
            # digest's ~3-significant-digit resolution
            lp.c = lp.c + shift
        return s, lp

    def test_price_shift_defeats_quant_digest_but_not_hint(self):
        from dervet_tpu.ops.pdhg import PDHGOptions
        s, lp0 = self._lp()
        mem = warmstart.SolutionMemory(max_entries=16)
        opts = PDHGOptions()
        skey = ("struct",)
        tag = warmstart.opts_tag(opts)
        x = np.linspace(0.0, 1.0, lp0.n)
        y = np.linspace(0.0, 0.5, lp0.m)
        mem.store(skey, lp0, tag, x, y, 1.0)
        mem.store_hint(("portfolio", "rid", "siteA", 0), x, y, 1.0)

        _, lp1 = self._lp(shift=0.02)
        # WITHOUT the hint: the quantized digest moved — no near hit
        entry, kind, _, _ = mem.probe(skey, lp1, tag)
        assert kind != "near"   # feature-fallback or miss, never near
        # WITH the hint: the dedicated grade carries the reseed
        lp1.seed_hint = ("portfolio", "rid", "siteA", 0)
        plans = warmstart.plan_group(mem, skey, [lp1], opts, [0])
        assert plans[0].kind == "dual_iterate"
        assert plans[0].entry is not None
        assert np.array_equal(plans[0].entry.x, x)
        assert mem.stats["hits_dual"] >= 1

    def test_exact_hit_outranks_hint(self):
        from dervet_tpu.ops.pdhg import PDHGOptions
        s, lp0 = self._lp()
        mem = warmstart.SolutionMemory(max_entries=16)
        opts = PDHGOptions()
        tag = warmstart.opts_tag(opts)
        x = np.zeros(lp0.n)
        y = np.zeros(lp0.m)
        mem.store(("k",), lp0, tag, x, y, 0.0)
        mem.store_hint(("h",), x + 1.0, y, 0.0)
        lp0.seed_hint = ("h",)
        plans = warmstart.plan_group(mem, ("k",), [lp0], opts, [0])
        assert plans[0].kind == "exact"

    def test_kill_switch_restores_cold(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_WARMSTART", "0")
        assert not warmstart.enabled()

    def test_hint_table_bounded(self):
        mem = warmstart.SolutionMemory(max_entries=4)
        for i in range(12):
            mem.store_hint(("h", i), np.zeros(3), np.zeros(2), 0.0)
        assert mem.snapshot()["hint_entries"] <= 4
        assert mem.lookup_hint(("h", 11)) is not None
        assert mem.lookup_hint(("h", 0)) is None


# ---------------------------------------------------------------------------
# Fault matrix: coupling_infeasible + diverging_duals
# ---------------------------------------------------------------------------

class TestFaults:
    def test_coupling_infeasible_typed_error(self):
        # an aggregate import cap far below the fleet's must-serve load
        spec = PortfolioSpec(members=_members(), import_cap_kw=500.0,
                             max_outer=8)
        with pytest.raises(PortfolioInfeasibleError) as ei:
            solve_portfolio(spec, backend="cpu")
        err = ei.value
        assert err.kind == "portfolio_infeasible"
        assert err.violations
        worst = err.violations[0]
        assert worst["kind"] == "import_cap"
        assert worst["shortfall_kw"] > 0
        assert "import_cap" in str(err)
        # the typed record serializes for spool .error.json files
        assert json.dumps(err.as_dict())

    def test_infeasible_terminates_before_dual_loop(self):
        spec = PortfolioSpec(members=_members(), import_cap_kw=500.0,
                             max_outer=8)
        calls = []
        import dervet_tpu.portfolio.solve as psolve
        orig = psolve.run_dispatch

        def counting(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        psolve.run_dispatch = counting
        try:
            with pytest.raises(PortfolioInfeasibleError):
                solve_portfolio(spec, backend="cpu")
        finally:
            psolve.run_dispatch = orig
        assert not calls    # pre-flight fired before any dispatch

    def test_diverging_duals_detected_rescaled_certified(self):
        probe = solve_portfolio(
            PortfolioSpec(members=_members(4, hours=336, window=168),
                          export_cap_kw=1e9, max_outer=1),
            backend="jax")
        cap = float(probe.aggregate["net_export"].max()) - 2000.0
        with faultinject.inject(diverge_duals_round=1,
                                diverge_duals_scale=25.0) as plan:
            res = solve_portfolio(
                PortfolioSpec(members=_members(4, hours=336,
                                               window=168),
                              export_cap_kw=cap, max_outer=14),
                backend="jax")
        assert ("diverging_duals", "1") in plan.fired
        assert res.dual_rescales >= 1
        assert any(r["regressed"] for r in res.rounds)
        assert res.converged
        assert res.certification["verdict"] in ("certified",
                                                "certified_loose")


# ---------------------------------------------------------------------------
# Service integration
# ---------------------------------------------------------------------------

class TestService:
    def test_submit_portfolio_round_trip_and_metrics(self):
        from dervet_tpu.service import ScenarioService
        svc = ScenarioService(backend="jax", max_wait_s=0.0)
        try:
            probe = svc.submit_portfolio(
                PortfolioSpec(members=_members(4, hours=336,
                                               window=168),
                              export_cap_kw=1e9, max_outer=1),
                request_id="pf-probe")
            svc.run_once()
            cap = float(probe.result(0).aggregate["net_export"].max()) \
                - 2000.0
            fut = svc.submit_portfolio(
                PortfolioSpec(members=_members(4, hours=336,
                                               window=168),
                              export_cap_kw=cap, max_outer=10),
                request_id="pf-bind")
            served = svc.run_once()
            res = fut.result(0)
            assert served == 1 and res.converged
            assert res.fidelity == "certified"
            m = svc.metrics()["portfolio"]
            assert m["requests"] == 2
            assert m["dual_iterate_seeds"] > 0
            validate_portfolio_section(m["last"])
        finally:
            svc.close()

    def test_infeasible_request_answers_typed(self):
        from dervet_tpu.service import ScenarioService
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        try:
            fut = svc.submit_portfolio(
                PortfolioSpec(members=_members(), import_cap_kw=500.0,
                              max_outer=5),
                request_id="pf-bad")
            svc.run_once()
            with pytest.raises(PortfolioInfeasibleError):
                fut.result(0)
            assert svc.metrics()["portfolio"]["infeasible"] == 1
        finally:
            svc.close()

    def test_shed_degraded_portfolio_never_cert_stamped(self):
        from dervet_tpu.service.queue import QueuedRequest
        spec = PortfolioSpec(members=_members(4, hours=336, window=168),
                             export_cap_kw=-800.0 * 4, max_outer=4)
        req = QueuedRequest("pf-shed", {}, kind="portfolio")
        req.portfolio_spec = spec
        rnd = PortfolioRound([req], backend="jax",
                             degraded_ids={"pf-shed"})
        rnd.run()
        res = req.future.result(0)
        assert res.fidelity == "degraded"
        assert res.resubmit_hint
        cert = res.certification
        assert cert["enabled"] is False
        assert cert["verdict"] == "not_certified"
        assert res.run_health["fidelity"] == "degraded"
        assert rnd.stats["degraded"] == 1

    def test_spool_round_trip(self, tmp_path):
        from dervet_tpu.service.server import serve_main
        spool = tmp_path / "spool"
        (spool / "incoming").mkdir(parents=True)
        payload = {"portfolio": {
            "synthetic_members": {"sites": 2, "hours": 48,
                                  "window": 24},
            "export_cap_kw": _binding_cap(),
            "gap_tol": 5e-3,
            "max_outer": 40,
        }}
        (spool / "incoming" / "pfreq.json").write_text(
            json.dumps(payload))
        rc = serve_main([str(spool), "--backend", "cpu", "--once",
                         "--heartbeat-s", "0",
                         "--memory-export-s", "0"])
        assert rc == 0
        out = spool / "results" / "pfreq" / "portfolio.json"
        assert out.exists()
        rec = json.loads(out.read_text())
        assert rec["converged"]
        assert rec["certification"]["verdict"] in ("certified",
                                                   "certified_loose")
        assert (spool / "results" / "pfreq"
                / "portfolio_aggregate.csv").exists()
        assert (spool / "done" / "pfreq.json").exists()

    def test_parse_portfolio_request_validation(self):
        with pytest.raises(ParameterError, match="members"):
            parse_portfolio_request({"portfolio": {}})
        spec = parse_portfolio_request({"portfolio": {
            "synthetic_members": {"sites": 2, "hours": 48,
                                  "window": 24},
            "export_cap_kw": 100.0}})
        assert len(spec.members) == 2
        assert spec.export_cap_kw == 100.0


# ---------------------------------------------------------------------------
# Objective-component integrity under the price shift
# ---------------------------------------------------------------------------

class TestCouplingComponent:
    def test_breakdown_carries_coupling_label_and_reconciles(self):
        cap = _binding_cap()
        res = solve_portfolio(
            PortfolioSpec(members=_members(), export_cap_kw=cap,
                          gap_tol=1e-9, feas_tol=1e-7, max_outer=60),
            backend="cpu")
        assert any(res.duals["export_cap"] > 0)
        # true cost excludes the coupling-price component: the blend's
        # true cost must match the master objective exactly
        assert res.objective_cx == pytest.approx(
            res.primal_objective, abs=1e-9)
