"""End-to-end scenario runtime tests: the Battery+DA slice (VERDICT r1 #1).

Spec: a reference model-params CSV runs end-to-end (params -> DER models ->
LP -> batched solve -> results), dispatch respects the physics, and the
PDHG backend matches the HiGHS CPU reference within 1%
(reference behavior: dervet/MicrogridScenario.py:281-346 window loop).
"""
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.io.params import Params
from dervet_tpu.scenario.scenario import MicrogridScenario
from dervet_tpu.scenario.window import build_optimization_levels

REF = Path("/root/reference")
CASE_000 = REF / "test/test_storagevet_features/model_params/000-DA_battery_month.csv"


@pytest.fixture(scope="module")
def solved_cpu():
    d = DERVET(CASE_000, base_path=REF)
    return d.solve(backend="cpu")


def test_end_to_end_runs(solved_cpu):
    inst = solved_cpu.instances[0]
    ts = inst.time_series_data
    assert len(ts) == 8760
    for col in ["BATTERY: battery Charge (kW)", "BATTERY: battery Discharge (kW)",
                "BATTERY: battery State of Energy (kWh)", "BATTERY: battery SOC (%)",
                "Net Load (kW)", "Total Storage Power (kW)", "DA Price ($/kWh)"]:
        assert col in ts.columns, col


def test_battery_physics(solved_cpu):
    inst = solved_cpu.instances[0]
    ts = inst.time_series_data
    ch = ts["BATTERY: battery Charge (kW)"].to_numpy()
    dis = ts["BATTERY: battery Discharge (kW)"].to_numpy()
    ene = ts["BATTERY: battery State of Energy (kWh)"].to_numpy()
    tol = 1e-4
    assert (ch >= -tol).all() and (ch <= 1000 + tol).all()
    assert (dis >= -tol).all() and (dis <= 1000 + tol).all()
    assert (ene >= -tol).all() and (ene <= 2000 + tol).all()
    # begin-of-step SOE convention (matches the reference goldens):
    # ene[t+1] = ene[t] + .85*ch[t] - dis[t] within each monthly window
    idx = ts.index
    same_month = (idx.month[1:] == idx.month[:-1])
    resid = ene[1:] - ene[:-1] - 0.85 * ch[:-1] + dis[:-1]
    assert np.abs(resid[same_month]).max() < 1e-3
    # every window enters at the SOC target
    first_of_month = np.concatenate([[True], ~same_month])
    assert np.abs(ene[first_of_month] - 1000.0).max() < 1e-3
    # windows also EXIT at the target (post-last-step SOE pinned), so the
    # year conserves energy: rte * charge == discharge
    assert abs(0.85 * ch.sum() - dis.sum()) / max(dis.sum(), 1) < 1e-3


def test_objective_negative_value_possible(solved_cpu):
    """DA arbitrage must produce nonzero dispatch with these prices."""
    inst = solved_cpu.instances[0]
    dis = inst.time_series_data["BATTERY: battery Discharge (kW)"]
    assert dis.sum() > 0


def test_financials_present(solved_cpu):
    inst = solved_cpu.instances[0]
    assert inst.proforma_df is not None
    assert "Yearly Net Value" in inst.proforma_df.columns
    assert "BATTERY: battery Capital Cost" in inst.proforma_df.columns
    # construction_year == start_year (2017): capex lands on 2017 and the
    # all-zero CAPEX Year row is dropped (reference CBA.py:316-318 +
    # put_capital_cost_on_construction_year)
    assert "CAPEX Year" not in inst.proforma_df.index
    assert inst.proforma_df.loc[2017, "BATTERY: battery Capital Cost"] \
        == pytest.approx(-(100 * 1000 + 800 * 2000))
    assert inst.npv_df is not None and "DA ETS" in inst.npv_df.columns
    assert float(inst.npv_df["DA ETS"].iloc[0]) > 0


def test_save_as_csv(solved_cpu, tmp_path):
    solved_cpu.save_as_csv(tmp_path)
    for name in ["timeseries_results", "pro_forma", "npv", "payback",
                 "cost_benefit", "size", "technology_summary"]:
        assert (tmp_path / f"{name}.csv").exists(), name


@pytest.mark.slow
def test_pdhg_matches_cpu_objective():
    """PDHG batched backend vs HiGHS per-window: <1% on every window
    (BASELINE.md accuracy gate; here to 0.1%)."""
    d = DERVET(CASE_000, base_path=REF)
    res_jax = d.solve(backend="jax")
    d2 = DERVET(CASE_000, base_path=REF)
    res_cpu = d2.solve(backend="cpu")
    oj = res_jax.instances[0].scenario.objective_values
    oc = res_cpu.instances[0].scenario.objective_values
    assert set(oj) == set(oc) and len(oj) == 12
    for k in oj:
        a, b = oj[k]["Total Objective"], oc[k]["Total Objective"]
        assert abs(a - b) / max(abs(b), 1.0) < 1e-3, (k, a, b)


def test_optimization_levels_month():
    idx = pd.date_range("2017-01-01", periods=8760, freq="h")
    lv = build_optimization_levels(idx, "month", 1.0)
    assert lv.nunique() == 12
    assert (lv.iloc[:744] == lv.iloc[0]).all()


def test_optimization_levels_hours():
    idx = pd.date_range("2017-01-01", periods=8760, freq="h")
    lv = build_optimization_levels(idx, 12, 1.0)
    assert lv.nunique() == 730


def test_scenario_window_grouping():
    cases = Params.initialize(CASE_000, base_path=REF)
    s = MicrogridScenario(cases[0])
    lengths = sorted({w.T for w in s.windows})
    assert lengths == [672, 720, 744]
    assert len(s.windows) == 12
