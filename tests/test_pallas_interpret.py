"""Interpret-mode equivalence suite for the fused Pallas chunk kernel.

``DERVET_TPU_PALLAS_INTERPRET=1`` runs every ``pl.pallas_call`` with
``interpret=True`` (the kernel body executed as plain jax ops) and lifts
the TPU-backend requirement in ``pallas_chunk.supports`` — so CPU CI
executes the REAL kernel, for all three step variants, and asserts
equivalence against the ``lax.scan`` reference path that production
falls back to.  Before this harness existed the kernel was untestable
without a chip (BENCH_r03's silent-fallback era).

Contract (mirrors the bench acceptance gates):

* ``vanilla``: kernel == scan **bitwise** (the kernel implements
  ``one_iter`` verbatim; both paths lower to the same op sequence);
* ``reflected`` / ``halpern``: kernel == scan to certification
  tolerance (the relaxation reorders a handful of elementwise ops);
* padding rows (batch not a multiple of BLK) never leak into real rows;
* the eq/ge mixed ``fl`` row mask (-inf floor on equality rows) matches
  the scan path's ``where(eq_mask, ...)`` projection;
* both the dense and the banded kernels (incl. the low-rank wide-row
  pair) are exercised.
"""
import numpy as np
import pytest
import scipy.sparse as sp

from dervet_tpu.ops import CompiledLPSolver, LPBuilder, PDHGOptions
from dervet_tpu.ops import pallas_chunk
from dervet_tpu.ops.pdhg import (BandedOp, DenseOp, KERNEL_PALLAS,
                                 KERNEL_SCAN, kernel_selection)

VARIANTS = ("vanilla", "reflected", "halpern")
# certification-grade tolerance for the variant paths: the kernel
# reorders the relaxation's elementwise ops, so low-order bits may
# differ; anything above this is a real divergence
VARIANT_ATOL = 1e-4


def mixed_lp(T=48, seed=0):
    """Battery-like LP with BOTH eq rows (SOE recursion) and ge rows
    (a requirement row), so the kernel's fl mask carries -inf and 0."""
    rng = np.random.default_rng(seed)
    b = LPBuilder()
    ch = b.var("ch", T, 0.0, 10.0)
    dis = b.var("dis", T, 0.0, 10.0)
    ene = b.var("ene", T, 0.0, 40.0)
    price = rng.uniform(10, 50, T)
    b.add_cost(ch, price)
    b.add_cost(dis, -price)
    D = sp.diags([np.ones(T), -np.ones(T - 1)], [0, -1])
    b.add_rows("soe", [(ene, D), (ch, -0.9 * sp.eye(T)),
                       (dis, (1 / 0.9) * sp.eye(T))], "eq",
               np.r_[20.0, np.zeros(T - 1)])
    b.add_rows("req", [(dis, np.ones((1, T)))], "ge", 5.0)
    return b.build()


def banded_lp(T=300):
    """Large enough that make_op picks the banded decomposition (bands
    need >= max(256, m // 64) entries)."""
    rng = np.random.default_rng(2)
    b = LPBuilder()
    ch = b.var("ch", T, 0.0, 250.0)
    dis = b.var("dis", T, 0.0, 250.0)
    ene = b.var("ene", T, 0.0, 1000.0)
    price = rng.uniform(10, 80, T) / 1000
    b.add_cost(ch, price)
    b.add_cost(dis, -price)
    D = np.eye(T) - np.eye(T, k=-1)
    rhs = np.zeros(T)
    rhs[0] = 500.0
    b.add_rows("soe", [(ene, D), (ch, -0.85), (dis, 1.0)], "eq", rhs)
    return b.build()


def solve_pair(lp, variant, C, monkeypatch, opts_kw=None):
    """(kernel result, scan result) for the same batch: the kernel leg
    runs under the interpret knob, the scan leg with pallas_chunk=False
    (the production fallback trace)."""
    kw = dict(opts_kw or {})
    monkeypatch.setenv(pallas_chunk.INTERPRET_ENV, "1")
    sk = CompiledLPSolver(lp, PDHGOptions(variant=variant, **kw))
    kern, why, _ = kernel_selection(sk, batched=True)
    assert kern == KERNEL_PALLAS, (variant, why)
    rk = sk.solve(c=C)
    monkeypatch.delenv(pallas_chunk.INTERPRET_ENV)
    ss = CompiledLPSolver(
        lp, PDHGOptions(variant=variant, pallas_chunk=False, **kw))
    rs = ss.solve(c=C)
    return rk, rs


def batch_prices(lp, B):
    return np.stack([lp.c * (1 + 0.01 * i) for i in range(B)])


class TestDenseInterpretEquivalence:
    def test_vanilla_bitwise(self, monkeypatch):
        lp = mixed_lp()
        C = batch_prices(lp, 5)         # non-multiple of BLK: 123 pad rows
        rk, rs = solve_pair(lp, "vanilla", C, monkeypatch)
        assert np.array_equal(np.asarray(rk.x), np.asarray(rs.x))
        assert np.array_equal(np.asarray(rk.y), np.asarray(rs.y))
        assert np.array_equal(np.asarray(rk.iters), np.asarray(rs.iters))
        assert np.array_equal(np.asarray(rk.restarts),
                              np.asarray(rs.restarts))

    @pytest.mark.parametrize("variant", ["reflected", "halpern"])
    def test_variant_certification_tolerance(self, variant, monkeypatch):
        lp = mixed_lp()
        C = batch_prices(lp, 5)
        rk, rs = solve_pair(lp, variant, C, monkeypatch)
        assert int(np.asarray(rk.converged).sum()) == 5
        assert int(np.asarray(rs.converged).sum()) == 5
        np.testing.assert_allclose(np.asarray(rk.x), np.asarray(rs.x),
                                   atol=VARIANT_ATOL, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(rk.obj), np.asarray(rs.obj),
                                   atol=VARIANT_ATOL, rtol=1e-5)

    @pytest.mark.parametrize("B", [3, 12])
    def test_padding_rows_any_batch(self, B, monkeypatch):
        """Padded rows (grid*BLK - B of them) must never perturb real
        rows — vanilla stays bitwise vs scan at every batch width.
        (B=1 is excluded from the BITWISE cross-path check only because
        XLA lowers the single-row SCAN side as a matvec with a different
        reduction order; the kernel-vs-kernel independence test below
        covers B=1.)"""
        lp = mixed_lp(T=24)
        C = batch_prices(lp, B)
        rk, rs = solve_pair(lp, "vanilla", C, monkeypatch)
        assert np.array_equal(np.asarray(rk.x), np.asarray(rs.x))

    def test_batch_width_independence_incl_b1(self, monkeypatch):
        """Kernel rows are independent of both the padding rows and the
        co-batched rows: solving the first B instances alone reproduces
        the corresponding rows of the 12-wide solve bit for bit (every
        width pads to the same 128-row grid step, so any difference
        would be leakage)."""
        lp = mixed_lp(T=24)
        monkeypatch.setenv(pallas_chunk.INTERPRET_ENV, "1")
        full = CompiledLPSolver(lp, PDHGOptions(variant="vanilla")) \
            .solve(c=batch_prices(lp, 12))
        for B in (1, 3):
            sub = CompiledLPSolver(lp, PDHGOptions(variant="vanilla")) \
                .solve(c=batch_prices(lp, B))
            assert np.array_equal(np.asarray(sub.x),
                                  np.asarray(full.x)[:B])
            assert np.array_equal(np.asarray(sub.iters),
                                  np.asarray(full.iters)[:B])

    @pytest.mark.parametrize("variant", VARIANTS)
    def test_small_shape_grid(self, variant, monkeypatch):
        """A second (m, n) point so the equivalence is not a one-shape
        accident."""
        lp = mixed_lp(T=16, seed=5)
        C = batch_prices(lp, 7)
        rk, rs = solve_pair(lp, variant, C, monkeypatch)
        assert int(np.asarray(rk.converged).sum()) == 7
        np.testing.assert_allclose(np.asarray(rk.x), np.asarray(rs.x),
                                   atol=VARIANT_ATOL, rtol=1e-4)

    def test_mixed_eq_ge_mask_duals(self, monkeypatch):
        """The fl mask drives the dual projection: ge-row duals must be
        nonnegative on both paths, eq-row duals free — and equal."""
        lp = mixed_lp()
        assert 0 < lp.n_eq < lp.m       # genuinely mixed
        C = batch_prices(lp, 4)
        rk, rs = solve_pair(lp, "vanilla", C, monkeypatch)
        y = np.asarray(rk.y)
        assert np.all(y[:, lp.n_eq:] >= -1e-9)
        assert np.array_equal(y, np.asarray(rs.y))


class TestBandedInterpretEquivalence:
    def test_op_is_banded(self):
        lp = banded_lp()
        solver = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=False))
        assert isinstance(solver.op, BandedOp)
        assert solver.op.ell is None    # kernel-eligible decomposition

    def test_vanilla_bitwise_banded(self, monkeypatch):
        lp = banded_lp()
        C = batch_prices(lp, 3)
        rk, rs = solve_pair(lp, "vanilla", C, monkeypatch)
        assert np.array_equal(np.asarray(rk.x), np.asarray(rs.x))
        assert np.array_equal(np.asarray(rk.iters), np.asarray(rs.iters))

    @pytest.mark.parametrize("variant", ["reflected", "halpern"])
    def test_variant_banded_tolerance(self, variant, monkeypatch):
        lp = banded_lp()
        C = batch_prices(lp, 3)
        rk, rs = solve_pair(lp, variant, C, monkeypatch)
        assert int(np.asarray(rk.converged).sum()) == 3
        np.testing.assert_allclose(np.asarray(rk.x), np.asarray(rs.x),
                                   atol=VARIANT_ATOL, rtol=1e-4)


class TestInterpretGating:
    """supports()/kernel_selection semantics of the interpret knob."""

    def test_supports_requires_interpret_off_tpu(self, monkeypatch):
        lp = mixed_lp()
        solver = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=False))
        monkeypatch.delenv(pallas_chunk.INTERPRET_ENV, raising=False)
        import jax
        if jax.default_backend() != "tpu":
            assert not pallas_chunk.supports(
                solver.op, solver.opts.dtype, solver.opts.precision)
        monkeypatch.setenv(pallas_chunk.INTERPRET_ENV, "1")
        for v in VARIANTS:
            assert pallas_chunk.supports(
                solver.op, solver.opts.dtype, solver.opts.precision,
                variant=v)

    @pytest.mark.parametrize("variant", ["reflected", "halpern"])
    def test_variant_selects_kernel_under_interpret(self, variant,
                                                    monkeypatch):
        """Regression (the PR-11 shape): a variant solve must select the
        kernel, not report an expected-variant fallback — the 'variant'
        reason class no longer exists."""
        monkeypatch.setenv(pallas_chunk.INTERPRET_ENV, "1")
        lp = mixed_lp()
        solver = CompiledLPSolver(lp, PDHGOptions(variant=variant))
        kern, why, detail = kernel_selection(solver, batched=True)
        assert kern == KERNEL_PALLAS
        assert why is None and detail is None

    def test_halpern_vmem_accounting_counts_anchors(self):
        """The halpern anchor blocks are charged against the per-step
        envelope: its admitted footprint must exceed vanilla's at the
        same shape."""
        assert pallas_chunk._block_vmem_bytes(100, 300, 128, "halpern") \
            > pallas_chunk._block_vmem_bytes(100, 300, 128, "vanilla")
        assert pallas_chunk._block_vmem_bytes(100, 300, 128, "reflected") \
            == pallas_chunk._block_vmem_bytes(100, 300, 128, "vanilla")

    def test_selection_reason_is_enum_on_plain_cpu(self, monkeypatch):
        import jax
        if jax.default_backend() == "tpu":
            pytest.skip("TPU backend: no fallback to classify")
        monkeypatch.delenv(pallas_chunk.INTERPRET_ENV, raising=False)
        from dervet_tpu.ops.pdhg import (FALLBACK_BACKEND,
                                         KERNEL_FALLBACK_REASONS)
        lp = mixed_lp()
        for v in VARIANTS:
            solver = CompiledLPSolver(lp, PDHGOptions(variant=v))
            kern, why, _ = kernel_selection(solver, batched=True)
            assert kern == KERNEL_SCAN
            assert why == FALLBACK_BACKEND
            assert why in KERNEL_FALLBACK_REASONS
