"""Battery cycle degradation: rainflow counting, damage, SOH coupling.

Spec: storagevet battery degradation surface driven from
dervet/MicrogridDER/Battery.py:69-179 (rainflow cycle counting via the
``rainflow`` dependency, depth-binned cycle-life table, replacement reset
at the state-of-health threshold); reference input
010-degradation_test.csv exercises the end-to-end path.
"""
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.models.der.degradation import (CycleDegradation, rainflow,
                                               turning_points)

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"


def test_turning_points():
    x = np.array([0, 1, 2, 3, 2, 1, 2, 2, 2, 5, 0])
    np.testing.assert_allclose(turning_points(x), [0, 3, 1, 5, 0])


def test_rainflow_astm_example():
    """The ASTM E1049 worked example: peaks [-2,1,-3,5,-1,3,-4,4,-2]
    yields ranges {3:0.5, 4:1.5, 6:0.5, 8:1.0, 9:0.5} (range:count)."""
    x = np.array([-2, 1, -3, 5, -1, 3, -4, 4, -2], float)
    counts = {}
    for rng, c in rainflow(x):
        counts[rng] = counts.get(rng, 0) + c
    assert counts == {3.0: 0.5, 4.0: 1.5, 6.0: 0.5, 8.0: 1.0, 9.0: 0.5}


def test_cycle_damage_lookup():
    table = pd.DataFrame({"Cycle Depth Upper Limit": [0.1, 0.5, 1.0],
                          "Cycle Life Value": [10000, 2000, 500]})
    model = CycleDegradation(table)
    assert model.life_at(0.05) == 10000
    assert model.life_at(0.3) == 2000
    assert model.life_at(1.0) == 500
    # one full 100%-depth cycle consumes 1/500 of life
    profile = np.array([1.0, 0.0, 1.0])
    assert model.damage(profile) == pytest.approx(1 / 500, rel=1e-6)


def test_reference_cycle_life_table_loads():
    table = pd.read_csv(REF / "data/battery_cycle_life.csv")
    model = CycleDegradation(table)
    assert model.life_at(0.1) == 63000
    assert model.life_at(0.95) > 0


@pytest.fixture(scope="module")
def solved_degradation():
    d = DERVET(MP / "010-degradation_test.csv", base_path=REF)
    return d.solve(backend="cpu")


def test_degradation_case_runs(solved_degradation):
    inst = solved_degradation.instances[0]
    s = inst.scenario
    bat = next(d for d in s.ders if d.tag == "Battery")
    assert bat.incl_cycle_degrade
    assert bat.degradation_log, "no degradation windows recorded"
    # SOH decreases monotonically absent replacement
    soh = [rec["State of Health (%)"] for rec in bat.degradation_log
           if not rec["Replaced"]]
    assert all(b <= a + 1e-9 for a, b in zip(soh, soh[1:]))
    assert soh[-1] < 100.0


def test_degradation_drilldown(solved_degradation):
    inst = solved_degradation.instances[0]
    keys = [k for k in inst.drill_down_dict if k.startswith("degradation")]
    assert keys
    df = inst.drill_down_dict[keys[0]]
    assert {"Cycle Degradation", "Calendar Degradation",
            "State of Health (%)"} <= set(df.columns)


def test_sequential_solve_when_degrading(solved_degradation):
    """Degradation forces the sequential window path: as many solves as
    windows."""
    inst = solved_degradation.instances[0]
    meta = inst.scenario.solve_metadata
    assert meta["batched_solves"] == meta["n_windows"]


class TestDegradationCrossCaseBatching:
    """VERDICT r2 #7: degradation keeps windows time-sequential within a
    case (SOH feeds the next window's bounds) but window step t of N
    sensitivity cases solves as ONE batched call carrying per-case SOH."""

    @pytest.fixture(scope="class")
    def swept_input(self, tmp_path_factory):
        df = pd.read_csv(MP / "010-degradation_test.csv")
        sel = (df.Tag == "Battery") & (df.Key == "ene_max_rated")
        df.loc[sel, "Sensitivity Parameters"] = "[10000, 20000]"
        df.loc[sel, "Sensitivity Analysis"] = "yes"
        out = tmp_path_factory.mktemp("deg") / "mp.csv"
        df.to_csv(out, index=False)
        return out

    def test_batched_equals_serial_with_per_case_soh(self, swept_input,
                                                     monkeypatch):
        import dervet_tpu.scenario.scenario as scn
        calls = []
        real = scn.solve_group

        def counting(lp0, lps, backend, opts, **kw):
            calls.append(len(lps))
            return real(lp0, lps, backend, opts, **kw)

        monkeypatch.setattr(scn, "solve_group", counting)
        batched = DERVET(swept_input, base_path=REF).solve(backend="cpu")
        # every degradation step solved BOTH cases in one call: ~n_windows
        # calls of size 2, not 2 x n_windows of size 1
        assert max(calls) == 2
        assert sum(1 for c in calls if c == 2) >= 11, calls
        monkeypatch.setattr(scn, "solve_group", real)

        from dervet_tpu.io.params import Params
        from dervet_tpu.scenario.scenario import MicrogridScenario
        cases = Params.initialize(swept_input, base_path=REF)
        for key, inst in batched.instances.items():
            serial = MicrogridScenario(cases[key])
            serial.optimize_problem_loop(backend="cpu")
            oj = inst.scenario.objective_values
            oc = serial.objective_values
            assert set(oj) == set(oc)
            for k in oj:
                a = oj[k]["Total Objective"]
                b = oc[k]["Total Objective"]
                assert abs(a - b) / max(abs(b), 1.0) < 1e-6, (key, k, a, b)
            # per-case SOH trajectories differ (different ratings degrade
            # differently) and the batched run carried each one
            bat_b = inst.scenario.ders[0]
            bat_s = serial.ders[0]
            assert bat_b.soh == pytest.approx(bat_s.soh, rel=1e-9)
        sohs = [i.scenario.ders[0].soh for i in batched.instances.values()]
        assert sohs[0] != sohs[1]


@pytest.mark.slow
def test_solver_cache_one_precondition_per_structure():
    """VERDICT r3 #2: phase-2 degradation stepping re-solves the same LP
    structure once per window — the compiled solver (Ruiz + power
    iteration + jit wrappers) must be built ONCE per structure and reused
    from the dispatch-level cache, not rebuilt per window step."""
    import dervet_tpu.ops.pdhg as pdhg

    builds = []
    real_init = pdhg.CompiledLPSolver.__init__

    def counting_init(self, lp, opts=None):
        builds.append(lp.m)
        real_init(self, lp, opts)

    pdhg.CompiledLPSolver.__init__ = counting_init
    try:
        res = DERVET(MP / "010-degradation_test.csv", base_path=REF) \
            .solve(backend="jax")
    finally:
        pdhg.CompiledLPSolver.__init__ = real_init
    meta = res.instances[0].scenario.solve_metadata
    # a year of monthly windows has exactly 3 structures (28/30/31 days)
    assert meta["n_windows"] == 12
    assert meta["dispatch_solver_builds"] == 3, meta
    assert meta["dispatch_solver_hits"] == 9, meta
    assert len(builds) == 3, builds
