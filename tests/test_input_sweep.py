"""End-to-end smoke sweep over the reference's storagevet-features inputs
(SURVEY §4: the reference's dominant test pattern is input-permutation
coverage through the full pipeline).  Inputs whose referenced datasets were
dropped from the snapshot, or that the reference expects to FAIL, are
declared as such.
"""
from pathlib import Path

import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.utils.errors import (ModelParameterError, MonthlyDataError,
                                     ParameterError, TimeseriesDataError)

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"

# inputs whose referenced data files are absent from the snapshot
MISSING_DATA = {
    "017-bat_timeseries_dt_sensitivity_couples.csv",   # .xlsx dataset
    "018-DA_battery_month_5min.csv",                   # 5-min CSV dropped
    "020-coupled_dt_timseries_error.csv",              # 5-min CSV dropped
    "021-DR_program_end_nan.csv",                      # 5-min CSV dropped?
    "022-DR_length_nan.csv",
    "023-DR_weekends.csv",
    "026-DA_FR_sensitivity_analysis.csv",
}
# inputs the REFERENCE expects to error (error-path fixtures)
EXPECT_ERROR = {
    "024-DR_nan_length_prgramd_end_hour.csv": ParameterError,
    # test_1params.py:97-124: user opt_years must exist in the data
    "025-opt_year_more_than_timeseries_data.csv": TimeseriesDataError,
    "039-mutli_opt_years_not_in_monthly_data.csv": MonthlyDataError,
}


def all_csvs():
    return sorted(p.name for p in MP.glob("*.csv"))


@pytest.mark.slow
@pytest.mark.parametrize("name", all_csvs())
def test_input_runs_end_to_end(name):
    path = MP / name
    if name in EXPECT_ERROR:
        with pytest.raises(EXPECT_ERROR[name]):
            DERVET(path, base_path=REF).solve(backend="cpu")
        return
    try:
        res = DERVET(path, base_path=REF).solve(backend="cpu")
    except (ModelParameterError, TimeseriesDataError) as e:
        # only the curated allowlist may skip — a path-resolution
        # regression must fail the sweep, not silently skip it
        if name in MISSING_DATA:
            pytest.skip(f"referenced data missing from snapshot: {e}")
        raise
    inst = res.instances[0]
    assert inst.time_series_data is not None
    assert len(inst.time_series_data)


# ---------------------------------------------------------------------------
# CPU-vs-jax parity across the feature matrix (VERDICT r3 #3): for every
# runnable reference input, the TPU-path solver (PDHG, backend="jax") must
# agree with the exact CPU solver (HiGHS) at the NPV and proforma level —
# converting "the two blessed golden cases prove the jax path" into "the
# jax path is proven wherever the CPU path is".
# ---------------------------------------------------------------------------

def runnable_csvs():
    return [n for n in all_csvs()
            if n not in EXPECT_ERROR and n not in MISSING_DATA]


# Inputs whose OPTIMUM is degenerate across value streams, so per-column
# proforma attribution is non-unique — HiGHS returns a vertex, PDHG a
# face point, with window-objective totals and NPV agreeing (verified at
# triage, r4).  For these, parity is asserted on NPV and on each year's
# NET proforma row instead of per column.
DEGENERATE_SPLIT = {
    # SR and NSR priced identically: reserve-capacity split (and the ICE
    # energy/reserve allocation feeding DA ETS) is a face of optima;
    # totals agree to 5e-5
    "027-DA_FR_SR_NSR_pv_ice_month.csv",
    # DA energy vs SR reserve marginal-value ties shift ~1.6% of DA ETS
    # between the two streams; objective totals agree to 2e-5
    "008-sr_battery_multiyr.csv",
    # FR/SR/NSR capacity all priced: CPU assigns the capacity revenue to
    # one stream, PDHG splits it; 'DA ETS' differs by $15 ABSOLUTE on a
    # $15-scale column; objective totals agree to 1e-8
    "029-DA_FR_SR_NSR_battery_month_ts_constraints.csv",
}


# Default-suite parity slice (VERDICT r5 #6): small inputs spanning DA,
# FR, deferral, retail+DCM, and degradation run cpu-vs-jax NPV/proforma
# parity WITHOUT --runslow, so a solver-numerics regression fails the
# default local gate.  The full feature-matrix sweep below stays slow.
FAST_PARITY_SLICE = [
    "000-DA_battery_month.csv",
    "001-DA_FR_battery_month.csv",
    "003-DA_Deferral_battery_month.csv",
    "004-fixed_size_battery_retailets_dcm.csv",
    "010-degradation_test.csv",
]


@pytest.mark.parametrize("name", FAST_PARITY_SLICE)
def test_backend_parity_default_slice(name):
    _check_backend_parity(name)


@pytest.mark.slow
@pytest.mark.parametrize(
    "name", [n for n in runnable_csvs() if n not in FAST_PARITY_SLICE])
def test_backend_parity_cpu_vs_jax(name):
    _check_backend_parity(name)


def _check_backend_parity(name):
    import numpy as np

    path = MP / name
    try:
        res_c = DERVET(path, base_path=REF).solve(backend="cpu")
    except (ModelParameterError, TimeseriesDataError) as e:
        pytest.skip(f"input not runnable here: {e}")
    res_j = DERVET(path, base_path=REF).solve(backend="jax")
    assert res_c.instances.keys() == res_j.instances.keys()
    for key in res_c.instances:
        ic, ij = res_c.instances[key], res_j.instances[key]
        npv_c = float(ic.npv_df["Lifetime Present Value"].iloc[0])
        npv_j = float(ij.npv_df["Lifetime Present Value"].iloc[0])
        scale = max(1.0, abs(npv_c))
        assert abs(npv_j - npv_c) / scale < 1e-2, \
            (name, key, npv_c, npv_j)
        # proforma: every shared numeric column agrees to 1% of its own
        # magnitude (alternate optima can shuffle pennies between value
        # streams; the 1% bound is the reference's own golden tolerance)
        pc, pj = ic.proforma_df, ij.proforma_df
        assert list(pc.columns) == list(pj.columns), (name, key)
        if name in DEGENERATE_SPLIT:
            num_c = pc.select_dtypes("number")
            a = np.asarray(num_c.sum(axis=1), float)
            b = np.asarray(pj[num_c.columns].sum(axis=1), float)
            row_scale = max(1.0, np.nanmax(np.abs(a)))
            assert np.nanmax(np.abs(a - b)) / row_scale < 1e-2, (name, key)
            continue
        for col in pc.columns:
            a = np.asarray(pc[col], float)
            b = np.asarray(pj[col], float)
            col_scale = max(1.0, np.nanmax(np.abs(a)) if a.size else 1.0)
            worst = np.nanmax(np.abs(a - b)) / col_scale if a.size else 0.0
            assert worst < 1e-2, (name, key, col, worst)
