"""End-to-end smoke sweep over the reference's storagevet-features inputs
(SURVEY §4: the reference's dominant test pattern is input-permutation
coverage through the full pipeline).  Inputs whose referenced datasets were
dropped from the snapshot, or that the reference expects to FAIL, are
declared as such.
"""
from pathlib import Path

import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.utils.errors import (ModelParameterError, MonthlyDataError,
                                     ParameterError, TimeseriesDataError)

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"

# inputs whose referenced data files are absent from the snapshot
MISSING_DATA = {
    "017-bat_timeseries_dt_sensitivity_couples.csv",   # .xlsx dataset
    "018-DA_battery_month_5min.csv",                   # 5-min CSV dropped
    "020-coupled_dt_timseries_error.csv",              # 5-min CSV dropped
    "021-DR_program_end_nan.csv",                      # 5-min CSV dropped?
    "022-DR_length_nan.csv",
    "023-DR_weekends.csv",
    "026-DA_FR_sensitivity_analysis.csv",
}
# inputs the REFERENCE expects to error (error-path fixtures)
EXPECT_ERROR = {
    "024-DR_nan_length_prgramd_end_hour.csv": ParameterError,
    # test_1params.py:97-124: user opt_years must exist in the data
    "025-opt_year_more_than_timeseries_data.csv": TimeseriesDataError,
    "039-mutli_opt_years_not_in_monthly_data.csv": MonthlyDataError,
}


def all_csvs():
    return sorted(p.name for p in MP.glob("*.csv"))


@pytest.mark.slow
@pytest.mark.parametrize("name", all_csvs())
def test_input_runs_end_to_end(name):
    path = MP / name
    if name in EXPECT_ERROR:
        with pytest.raises(EXPECT_ERROR[name]):
            DERVET(path, base_path=REF).solve(backend="cpu")
        return
    try:
        res = DERVET(path, base_path=REF).solve(backend="cpu")
    except (ModelParameterError, TimeseriesDataError) as e:
        # only the curated allowlist may skip — a path-resolution
        # regression must fail the sweep, not silently skip it
        if name in MISSING_DATA:
            pytest.skip(f"referenced data missing from snapshot: {e}")
        raise
    inst = res.instances[0]
    assert inst.time_series_data is not None
    assert len(inst.time_series_data)


# ---------------------------------------------------------------------------
# CPU-vs-jax parity across the feature matrix (VERDICT r3 #3): for every
# runnable reference input, the TPU-path solver (PDHG, backend="jax") must
# agree with the exact CPU solver (HiGHS) at the NPV and proforma level —
# converting "the two blessed golden cases prove the jax path" into "the
# jax path is proven wherever the CPU path is".
# ---------------------------------------------------------------------------

def runnable_csvs():
    return [n for n in all_csvs()
            if n not in EXPECT_ERROR and n not in MISSING_DATA]


# r4 carved out three inputs here (027/008/029: co-priced reserve
# streams made per-column revenue attribution non-unique).  r5 closed
# 008 and 029: MarketService tilts each service's optimization price by
# TIEBREAK_EPS x rank (markets.py) so the split is unique, and this
# check runs the jax backend at eps_rel=1e-6 so the first-order solver
# actually lands on the tilted vertex.  027 (PV+ICE+FR/SR/NSR, 8
# streams) remains: its NSR column is ~$0 on the exact vertex, and
# pinning a near-zero column to 1% of its own scale needs ~1e-7-of-
# objective allocation accuracy on a near-degenerate face — beyond a
# first-order method's practical resolution (measured $728 absolute on
# a ~$1M NPV at eps_rel=1e-6).  For it, parity is asserted on NPV and
# each year's NET proforma row.
DEGENERATE_SPLIT = {"027-DA_FR_SR_NSR_pv_ice_month.csv"}


# Default-suite parity slice (VERDICT r5 #6): small inputs spanning DA,
# FR, deferral, retail+DCM, and degradation run cpu-vs-jax NPV/proforma
# parity WITHOUT --runslow, so a solver-numerics regression fails the
# default local gate.  The full feature-matrix sweep below stays slow.
FAST_PARITY_SLICE = [
    "000-DA_battery_month.csv",
    "001-DA_FR_battery_month.csv",
    "003-DA_Deferral_battery_month.csv",
    "004-fixed_size_battery_retailets_dcm.csv",
    "010-degradation_test.csv",
]


@pytest.mark.parametrize("name", FAST_PARITY_SLICE)
def test_backend_parity_default_slice(name):
    # product-default tolerance: this is the default suite's regression
    # gate on the REAL product path (the slice inputs have no co-priced
    # degeneracy, so default accuracy passes the per-column check)
    _check_backend_parity(name, tight=False)


# Only the formerly-degenerate co-priced inputs need the tighter solver
# tolerance to pin their per-column splits (see _check_backend_parity);
# running the whole sweep tight costs minutes PER INPUT (the jax path
# iterates ~10x longer at eps_rel 1e-6) for no added evidence elsewhere.
TIGHT_TOLERANCE = {
    "008-sr_battery_multiyr.csv",
    "029-DA_FR_SR_NSR_battery_month_ts_constraints.csv",
}


@pytest.mark.slow
@pytest.mark.parametrize(
    "name", [n for n in runnable_csvs() if n not in FAST_PARITY_SLICE])
def test_backend_parity_cpu_vs_jax(name):
    _check_backend_parity(name, tight=name in TIGHT_TOLERANCE)


def _check_backend_parity(name, tight):
    import numpy as np

    from dervet_tpu.ops.pdhg import PDHGOptions

    path = MP / name
    try:
        res_c = DERVET(path, base_path=REF).solve(backend="cpu")
    except (ModelParameterError, TimeseriesDataError) as e:
        pytest.skip(f"input not runnable here: {e}")
    # tight: the per-column 1% gate on a small ($10-scale) proforma
    # column demands ~1e-7 of the window objective — beyond the product
    # default eps_rel=1e-4.  The market tie-break (markets.py
    # TIEBREAK_EPS) makes the optimum unique; the tighter tolerance
    # makes the first-order solver land on it closely enough to compare
    # columns (VERDICT r5 #8).
    opts = PDHGOptions(eps_rel=1e-6, eps_abs=1e-8) if tight else None
    res_j = DERVET(path, base_path=REF).solve(
        backend="jax", solver_opts=opts)
    assert res_c.instances.keys() == res_j.instances.keys()
    for key in res_c.instances:
        ic, ij = res_c.instances[key], res_j.instances[key]
        npv_c = float(ic.npv_df["Lifetime Present Value"].iloc[0])
        npv_j = float(ij.npv_df["Lifetime Present Value"].iloc[0])
        scale = max(1.0, abs(npv_c))
        assert abs(npv_j - npv_c) / scale < 1e-2, \
            (name, key, npv_c, npv_j)
        # proforma: every shared numeric column agrees to 1% of its own
        # magnitude (alternate optima can shuffle pennies between value
        # streams; the 1% bound is the reference's own golden tolerance)
        pc, pj = ic.proforma_df, ij.proforma_df
        assert list(pc.columns) == list(pj.columns), (name, key)
        if name in DEGENERATE_SPLIT:
            num_c = pc.select_dtypes("number")
            a = np.asarray(num_c.sum(axis=1), float)
            b = np.asarray(pj[num_c.columns].sum(axis=1), float)
            row_scale = max(1.0, np.nanmax(np.abs(a)))
            assert np.nanmax(np.abs(a - b)) / row_scale < 1e-2, (name, key)
            continue
        for col in pc.columns:
            a = np.asarray(pc[col], float)
            b = np.asarray(pj[col], float)
            col_scale = max(1.0, np.nanmax(np.abs(a)) if a.size else 1.0)
            worst = np.nanmax(np.abs(a - b)) / col_scale if a.size else 0.0
            assert worst < 1e-2, (name, key, col, worst)
