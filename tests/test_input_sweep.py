"""End-to-end smoke sweep over the reference's storagevet-features inputs
(SURVEY §4: the reference's dominant test pattern is input-permutation
coverage through the full pipeline).  Inputs whose referenced datasets were
dropped from the snapshot, or that the reference expects to FAIL, are
declared as such.
"""
from pathlib import Path

import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.utils.errors import (ModelParameterError, MonthlyDataError,
                                     ParameterError, TimeseriesDataError)

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"

# inputs whose referenced data files are absent from the snapshot
MISSING_DATA = {
    "017-bat_timeseries_dt_sensitivity_couples.csv",   # .xlsx dataset
    "018-DA_battery_month_5min.csv",                   # 5-min CSV dropped
    "020-coupled_dt_timseries_error.csv",              # 5-min CSV dropped
    "021-DR_program_end_nan.csv",                      # 5-min CSV dropped?
    "022-DR_length_nan.csv",
    "023-DR_weekends.csv",
    "026-DA_FR_sensitivity_analysis.csv",
}
# inputs the REFERENCE expects to error (error-path fixtures)
EXPECT_ERROR = {
    "024-DR_nan_length_prgramd_end_hour.csv": ParameterError,
    # test_1params.py:97-124: user opt_years must exist in the data
    "025-opt_year_more_than_timeseries_data.csv": TimeseriesDataError,
    "039-mutli_opt_years_not_in_monthly_data.csv": MonthlyDataError,
}


def all_csvs():
    return sorted(p.name for p in MP.glob("*.csv"))


@pytest.mark.slow
@pytest.mark.parametrize("name", all_csvs())
def test_input_runs_end_to_end(name):
    path = MP / name
    if name in EXPECT_ERROR:
        with pytest.raises(EXPECT_ERROR[name]):
            DERVET(path, base_path=REF).solve(backend="cpu")
        return
    try:
        res = DERVET(path, base_path=REF).solve(backend="cpu")
    except (ModelParameterError, TimeseriesDataError) as e:
        # only the curated allowlist may skip — a path-resolution
        # regression must fail the sweep, not silently skip it
        if name in MISSING_DATA:
            pytest.skip(f"referenced data missing from snapshot: {e}")
        raise
    inst = res.instances[0]
    assert inst.time_series_data is not None
    assert len(inst.time_series_data)
