"""Growth fill/drop of referenced data + XML model-parameters input
(reference: storagevet Library.fill_extra_data/drop_extra_data surface and
the Params XML tree, SURVEY §2.8)."""
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.io.growth import (column_growth_rates, fill_extra_data,
                                  fill_extra_monthly)
from dervet_tpu.io.params import Params

REF = Path("/root/reference")


def test_fill_extra_data_growth_rates():
    idx = pd.date_range("2017-01-01", periods=8760, freq="h")
    ts = pd.DataFrame({"Site Load (kW)": 100.0,
                       "DA Price ($/kWh)": 0.05,
                       "PV Gen (kW/rated kW)": 0.5}, index=idx)
    rates = column_growth_rates({"def_growth": 10}, {"DA": {"growth": 5}},
                                ts.columns)
    assert rates["Site Load (kW)"] == pytest.approx(0.10)
    assert rates["DA Price ($/kWh)"] == pytest.approx(0.05)
    assert rates["PV Gen (kW/rated kW)"] == 0.0
    out = fill_extra_data(ts, [2017, 2019], rates)
    y19 = out[out.index.year == 2019]
    assert len(y19) == 8760
    assert y19["Site Load (kW)"].iloc[0] == pytest.approx(100 * 1.1 ** 2)
    assert y19["DA Price ($/kWh)"].iloc[0] == pytest.approx(0.05 * 1.05 ** 2)
    assert y19["PV Gen (kW/rated kW)"].iloc[0] == pytest.approx(0.5)


def test_fill_from_leap_year_drops_feb29():
    idx = pd.date_range("2020-01-01", periods=8784, freq="h")   # leap
    ts = pd.DataFrame({"Site Load (kW)": 1.0}, index=idx)
    out = fill_extra_data(ts, [2021], {"Site Load (kW)": 0.0})
    y21 = out[out.index.year == 2021]
    assert len(y21) == 8760


def test_fill_extra_monthly():
    m = pd.DataFrame({"Backup Energy (kWh)": range(12)},
                     index=pd.MultiIndex.from_tuples(
                         [(2017, i) for i in range(1, 13)],
                         names=["Year", "Month"]))
    out = fill_extra_monthly(m, [2017, 2019])
    assert (2019, 6) in out.index
    assert out.loc[(2019, 6), "Backup Energy (kWh)"] == \
        out.loc[(2017, 6), "Backup Energy (kWh)"]


def test_xml_input_round_trip(tmp_path):
    """A minimal XML model-parameters file loads through the same pipeline
    as CSV (reference XML surface, DERVETParams.py:200-260)."""
    ts_path = REF / "data/hourly_timeseries.csv"
    xml = f"""<input>
  <Scenario active="yes" id=".">
    <time_series_filename analysis="no"><Value>{ts_path}</Value><Type>string</Type></time_series_filename>
    <dt analysis="no"><Value>1</Value><Type>float</Type></dt>
    <opt_years analysis="no"><Value>2017</Value><Type>list/int</Type></opt_years>
    <start_year analysis="no"><Value>2017</Value><Type>Period</Type></start_year>
    <end_year analysis="no"><Value>2020</Value><Type>Period</Type></end_year>
    <n analysis="no"><Value>month</Value><Type>string</Type></n>
    <incl_site_load analysis="no"><Value>1</Value><Type>bool</Type></incl_site_load>
  </Scenario>
  <Finance active="yes" id=".">
    <npv_discount_rate analysis="no"><Value>7</Value><Type>float</Type></npv_discount_rate>
    <inflation_rate analysis="no"><Value>3</Value><Type>float</Type></inflation_rate>
  </Finance>
  <Battery active="yes" id="1">
    <name analysis="no"><Value>xbat</Value><Type>string</Type></name>
    <ene_max_rated analysis="no"><Value>2000</Value><Type>float</Type></ene_max_rated>
    <ch_max_rated analysis="no"><Value>1000</Value><Type>float</Type></ch_max_rated>
    <dis_max_rated analysis="no"><Value>1000</Value><Type>float</Type></dis_max_rated>
    <rte analysis="no"><Value>85</Value><Type>float</Type></rte>
    <ccost_kwh analysis="no"><Value>100</Value><Type>float</Type>
      <Evaluation active="yes">0</Evaluation></ccost_kwh>
  </Battery>
  <DA active="yes" id=".">
    <growth analysis="no"><Value>0</Value><Type>float</Type></growth>
  </DA>
</input>"""
    p = tmp_path / "case.xml"
    p.write_text(xml)
    cases = Params.initialize(p, base_path=REF)
    case = cases[0]
    assert case.scenario["dt"] == 1.0
    bat = next(keys for tag, _, keys in case.ders if tag == "Battery")
    assert bat["ene_max_rated"] == 2000.0
    assert case.cba_overrides[("Battery", "1", "ccost_kwh")] == 0.0
    assert "DA" in case.streams
