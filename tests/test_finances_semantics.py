"""Proforma semantics mirrored from the reference's test_2finances.py:
degradation lowers later optimized years' energy value; non-optimized
years fill forward at the STREAM's growth rate (flat when growth=0)."""
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.api import DERVET

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"


@pytest.fixture(scope="module")
def degradation_proforma():
    d = DERVET(MP / "040-Degradation_Test_MP.csv", base_path=REF)
    return d.solve(backend="cpu").instances[0].proforma_df


class TestProformaWithDegradation:
    """Reference TestProformaWithDegradation (040: degradation on,
    retailETS growth 0, inflation 3%)."""

    def test_all_project_years_present(self, degradation_proforma):
        years = {i for i in degradation_proforma.index if i != "CAPEX Year"}
        assert years == set(range(2017, 2031))

    def test_all_years_filled(self, degradation_proforma):
        assert np.all(degradation_proforma["Yearly Net Value"].to_numpy()
                      != 0)

    def test_degraded_year_earns_less(self, degradation_proforma):
        ec = degradation_proforma["Avoided Energy Charge"]
        assert ec[2017] > ec[2022]

    def test_non_opt_years_flat_at_zero_growth(self, degradation_proforma):
        ec = degradation_proforma["Avoided Energy Charge"]
        for yr in range(2023, 2031):
            assert ec[yr] == pytest.approx(ec[2022], rel=1e-9)


class TestProformaWithoutDegradation:
    """Reference TestProformaWithNoDegradation (041: degradation off)."""

    @pytest.fixture(scope="class")
    def proforma(self):
        d = DERVET(MP / "041-no_Degradation_Test_MP.csv", base_path=REF)
        return d.solve(backend="cpu").instances[0].proforma_df

    def test_opt_years_equal_without_degradation(self, proforma):
        ec = proforma["Avoided Energy Charge"]
        assert ec[2017] == pytest.approx(ec[2022], rel=1e-6)


class TestPaybackMetrics:
    """Payback metrics stay meaningful when capex moves to the
    construction-year row and the CAPEX Year row is dropped (reference
    computes capex from the technologies, CBA.py:479-523)."""

    @pytest.fixture(scope="class")
    def result(self):
        d = DERVET(MP / "041-no_Degradation_Test_MP.csv", base_path=REF)
        return d.solve(backend="cpu").instances[0]

    def test_capital_cost_counted_once(self, result):
        pf = result.proforma_df
        ders = result.scenario.ders
        cap_cols = [c for c in pf.columns if c.endswith(" Capital Cost")]
        total_cap = float(pf[cap_cols].to_numpy().sum())
        expected = -sum(d.get_capex() for d in ders)
        assert total_cap == pytest.approx(expected, rel=1e-9)
        # and each column carries its capex in exactly one row
        for col in cap_cols:
            assert int((pf[col] != 0).sum()) == 1

    def test_payback_not_nan_with_positive_net(self, result):
        pb = result.payback_df
        row = pb.set_index("Unit")
        payback = float(row.loc["Years", "Payback Period"])
        assert np.isfinite(payback) and payback > 0

    def test_lifetime_npv_matches_npv_report(self, result):
        pb = result.payback_df.set_index("Unit")
        lifetime = float(pb.loc["$", "Lifetime Net Present Value"])
        assert lifetime == pytest.approx(
            float(result.npv_df["Lifetime Present Value"].iloc[0]), rel=1e-9)

    def test_benefit_cost_ratio_is_benefit_over_cost(self, result):
        pb = result.payback_df.set_index("Unit")
        cb = result.cost_benefit_df
        ben = float(cb.loc["Lifetime Present Value", "Benefit ($)"])
        cost = float(cb.loc["Lifetime Present Value", "Cost ($)"])
        assert float(pb.loc["-", "Benefit-Cost Ratio"]) == pytest.approx(
            ben / cost, rel=1e-9)
