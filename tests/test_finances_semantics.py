"""Proforma semantics mirrored from the reference's test_2finances.py:
degradation lowers later optimized years' energy value; non-optimized
years fill forward at the STREAM's growth rate (flat when growth=0)."""
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.api import DERVET

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"


@pytest.fixture(scope="module")
def degradation_proforma():
    d = DERVET(MP / "040-Degradation_Test_MP.csv", base_path=REF)
    return d.solve(backend="cpu").instances[0].proforma_df


class TestProformaWithDegradation:
    """Reference TestProformaWithDegradation (040: degradation on,
    retailETS growth 0, inflation 3%)."""

    def test_all_project_years_present(self, degradation_proforma):
        years = {i for i in degradation_proforma.index if i != "CAPEX Year"}
        assert years == set(range(2017, 2031))

    def test_all_years_filled(self, degradation_proforma):
        assert np.all(degradation_proforma["Yearly Net Value"].to_numpy()
                      != 0)

    def test_degraded_year_earns_less(self, degradation_proforma):
        ec = degradation_proforma["Avoided Energy Charge"]
        assert ec[2017] > ec[2022]

    def test_non_opt_years_flat_at_zero_growth(self, degradation_proforma):
        ec = degradation_proforma["Avoided Energy Charge"]
        for yr in range(2023, 2031):
            assert ec[yr] == pytest.approx(ec[2022], rel=1e-9)


class TestProformaWithoutDegradation:
    """Reference TestProformaWithNoDegradation (041: degradation off)."""

    @pytest.fixture(scope="class")
    def proforma(self):
        d = DERVET(MP / "041-no_Degradation_Test_MP.csv", base_path=REF)
        return d.solve(backend="cpu").instances[0].proforma_df

    def test_opt_years_equal_without_degradation(self, proforma):
        ec = proforma["Avoided Energy Charge"]
        assert ec[2017] == pytest.approx(ec[2022], rel=1e-6)
