"""Market value streams FR/SR/NSR/LF: joint headroom + SOE reservation.

Spec: storagevet market-stream surface (SURVEY.md §2.8) — capacity bids
priced from the reference's price columns, all concurrent services share DER
headroom, storage reserves duration-hours of energy per awarded kW.
Reference input 001-DA_FR_SR_NSR_battery_month_ts_constraints.csv runs
end-to-end (the reference's own test only asserts completion,
test_3battery.py).
"""
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.api import DERVET

REF = Path("/root/reference")
CASE_001 = REF / ("test/model_params/"
                  "001-DA_FR_SR_NSR_battery_month_ts_constraints.csv")


@pytest.fixture(scope="module")
def solved():
    d = DERVET(CASE_001, base_path=REF)
    return d.solve(backend="cpu")


def test_market_case_runs(solved):
    inst = solved.instances[0]
    ts = inst.time_series_data
    for col in ["FR Awarded Up (kW)", "FR Awarded Down (kW)",
                "SR Awarded Up (kW)", "NSR Awarded Up (kW)"]:
        assert col in ts.columns, col
    assert (ts["FR Awarded Up (kW)"] >= -1e-6).all()


def test_headroom_respected(solved):
    """Sum of up awards can never exceed battery discharge headroom +
    charge-cut headroom."""
    inst = solved.instances[0]
    ts = inst.time_series_data
    s = inst.scenario
    bat = next(d for d in s.ders if d.tag == "Battery")
    dis_cap = bat.discharge_capacity()
    ch = ts[bat.col("Charge (kW)")].to_numpy()
    dis = ts[bat.col("Discharge (kW)")].to_numpy()
    up = (ts["FR Awarded Up (kW)"] + ts["SR Awarded Up (kW)"]
          + ts["NSR Awarded Up (kW)"]).to_numpy()
    headroom = (dis_cap - dis) + ch
    assert (up <= headroom + 1e-4).all()
    down = ts["FR Awarded Down (kW)"].to_numpy()
    ch_cap = bat.charge_capacity()
    assert (down <= (ch_cap - ch) + dis + 1e-4).all()


def test_soe_reservation(solved):
    """With duration d, SOE must stay >= e_min + d*up_awards."""
    inst = solved.instances[0]
    s = inst.scenario
    ts = inst.time_series_data
    bat = next(d for d in s.ders if d.tag == "Battery")
    durations = {tag: float(vs.duration) for tag, vs in s.streams.items()
                 if hasattr(vs, "duration")}
    up_reserved = np.zeros(len(ts))
    for tag, dur in durations.items():
        col = f"{tag} Awarded Up (kW)"
        if dur and col in ts.columns:
            up_reserved += dur * ts[col].to_numpy()
    if up_reserved.any():
        ene = ts[bat.col("State of Energy (kWh)")].to_numpy()
        assert (ene >= bat.operational_min_energy() + up_reserved - 1e-3).all()


def test_user_ts_constraint_columns(solved):
    """Mirrors the reference's test_technology_features.py:51-60: the
    applied user TS limits are echoed into the output timeseries and the
    optimized dispatch respects them."""
    inst = solved.instances[0]
    ts = inst.time_series_data
    bat = next(d for d in inst.scenario.ders if d.tag == "Battery")
    dis_max = ts[bat.col("User Discharge Max (kW)")]
    ch_max = ts[bat.col("User Charge Max (kW)")]
    assert not dis_max.isna().any() and not ch_max.isna().any()
    assert np.all(ts[bat.col("Discharge (kW)")] <= dis_max + 1e-6)
    assert np.all(ts[bat.col("Charge (kW)")] <= ch_max + 1e-6)


def test_market_revenue_in_proforma(solved):
    inst = solved.instances[0]
    pf = inst.proforma_df
    market_cols = [c for c in pf.columns
                   if c.startswith(("FR ", "SR ", "NSR "))]
    assert market_cols, pf.columns.tolist()
    # battery earns regulation revenue with these prices
    assert sum(pf.loc[2017, c] for c in market_cols) > 0


def test_ts_bid_bounds():
    """With u/d_ts_constraints on, awards respect the reference's
    FR Reg Up/Down Max columns (001 ships them at 200 kW)."""
    import dervet_tpu.io.params as p
    cases = p.Params.initialize(CASE_001, base_path=REF)
    case = cases[0]
    for key in ("u_ts_constraints", "d_ts_constraints"):
        case.streams["FR"][key] = True
    from dervet_tpu.scenario.scenario import MicrogridScenario
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    ts = s.timeseries_results()
    from dervet_tpu.scenario.window import grab_column
    raw = case.datasets.time_series.loc[ts.index]
    for award_col, max_col in [("FR Awarded Up (kW)", "FR Reg Up Max (kW)"),
                               ("FR Awarded Down (kW)", "FR Reg Down Max (kW)")]:
        cap = grab_column(raw, max_col)
        assert cap is not None
        assert (ts[award_col].to_numpy() <= cap + 1e-4).all(), award_col
