"""Market value streams FR/SR/NSR/LF: joint headroom + SOE reservation.

Spec: storagevet market-stream surface (SURVEY.md §2.8) — capacity bids
priced from the reference's price columns, all concurrent services share DER
headroom, storage reserves duration-hours of energy per awarded kW.
Reference input 001-DA_FR_SR_NSR_battery_month_ts_constraints.csv runs
end-to-end (the reference's own test only asserts completion,
test_3battery.py).
"""
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.api import DERVET

REF = Path("/root/reference")
CASE_001 = REF / ("test/model_params/"
                  "001-DA_FR_SR_NSR_battery_month_ts_constraints.csv")


@pytest.fixture(scope="module")
def solved():
    d = DERVET(CASE_001, base_path=REF)
    return d.solve(backend="cpu")


def test_market_case_runs(solved):
    inst = solved.instances[0]
    ts = inst.time_series_data
    for col in ["FR Awarded Up (kW)", "FR Awarded Down (kW)",
                "SR Awarded Up (kW)", "NSR Awarded Up (kW)"]:
        assert col in ts.columns, col
    assert (ts["FR Awarded Up (kW)"] >= -1e-6).all()


def test_headroom_respected(solved):
    """Sum of up awards can never exceed battery discharge headroom +
    charge-cut headroom."""
    inst = solved.instances[0]
    ts = inst.time_series_data
    s = inst.scenario
    bat = next(d for d in s.ders if d.tag == "Battery")
    dis_cap = bat.discharge_capacity()
    ch = ts[bat.col("Charge (kW)")].to_numpy()
    dis = ts[bat.col("Discharge (kW)")].to_numpy()
    up = (ts["FR Awarded Up (kW)"] + ts["SR Awarded Up (kW)"]
          + ts["NSR Awarded Up (kW)"]).to_numpy()
    headroom = (dis_cap - dis) + ch
    assert (up <= headroom + 1e-4).all()
    down = ts["FR Awarded Down (kW)"].to_numpy()
    ch_cap = bat.charge_capacity()
    assert (down <= (ch_cap - ch) + dis + 1e-4).all()


def test_soe_reservation(solved):
    """With duration d, SOE must stay >= e_min + d*up_awards."""
    inst = solved.instances[0]
    s = inst.scenario
    ts = inst.time_series_data
    bat = next(d for d in s.ders if d.tag == "Battery")
    durations = {tag: float(vs.duration) for tag, vs in s.streams.items()
                 if hasattr(vs, "duration")}
    up_reserved = np.zeros(len(ts))
    for tag, dur in durations.items():
        col = f"{tag} Awarded Up (kW)"
        if dur and col in ts.columns:
            up_reserved += dur * ts[col].to_numpy()
    if up_reserved.any():
        ene = ts[bat.col("State of Energy (kWh)")].to_numpy()
        assert (ene >= bat.operational_min_energy() + up_reserved - 1e-3).all()


def test_user_ts_constraint_columns(solved):
    """Mirrors the reference's test_technology_features.py:51-60: the
    applied user TS limits are echoed into the output timeseries and the
    optimized dispatch respects them."""
    inst = solved.instances[0]
    ts = inst.time_series_data
    bat = next(d for d in inst.scenario.ders if d.tag == "Battery")
    dis_max = ts[bat.col("User Discharge Max (kW)")]
    ch_max = ts[bat.col("User Charge Max (kW)")]
    assert not dis_max.isna().any() and not ch_max.isna().any()
    assert np.all(ts[bat.col("Discharge (kW)")] <= dis_max + 1e-6)
    assert np.all(ts[bat.col("Charge (kW)")] <= ch_max + 1e-6)


def test_market_revenue_in_proforma(solved):
    inst = solved.instances[0]
    pf = inst.proforma_df
    market_cols = [c for c in pf.columns
                   if c.startswith(("FR ", "SR ", "NSR "))]
    assert market_cols, pf.columns.tolist()
    # battery earns regulation revenue with these prices
    assert sum(pf.loc[2017, c] for c in market_cols) > 0


def test_ts_bid_bounds():
    """With u/d_ts_constraints on, awards respect the reference's
    FR Reg Up/Down Max columns (001 ships them at 200 kW)."""
    import dervet_tpu.io.params as p
    cases = p.Params.initialize(CASE_001, base_path=REF)
    case = cases[0]
    for key in ("u_ts_constraints", "d_ts_constraints"):
        case.streams["FR"][key] = True
    from dervet_tpu.scenario.scenario import MicrogridScenario
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    ts = s.timeseries_results()
    from dervet_tpu.scenario.window import grab_column
    raw = case.datasets.time_series.loc[ts.index]
    for award_col, max_col in [("FR Awarded Up (kW)", "FR Reg Up Max (kW)"),
                               ("FR Awarded Down (kW)", "FR Reg Down Max (kW)")]:
        cap = grab_column(raw, max_col)
        assert cap is not None
        assert (ts[award_col].to_numpy() <= cap + 1e-4).all(), award_col


def test_component_sum_equals_total_with_tilt():
    """Regression (ADVICE r5 medium, closed by the numerical trust PR):
    the tiebreak tilt used to ride as an UNLABELED cost, so the labeled
    per-stream revenue components summed to the tilted total minus an
    invisible residual.  Now the tilt is its own explicit objective
    column and "Total Objective" subtracts it — labeled components must
    sum to the reported total to 1e-9.  Synthetic FR+SR market case: no
    reference data needed."""
    import numpy as np
    from dervet_tpu.benchlib import synthetic_case
    from dervet_tpu.models.streams.markets import TILT_LABEL
    from dervet_tpu.scenario.scenario import MicrogridScenario

    case = synthetic_case()
    case.scenario["allow_partial_year"] = True
    case.scenario["n"] = 12
    ts = case.datasets.time_series.iloc[:48].copy()
    rng = np.random.default_rng(0)
    ts["Reg Up Price ($/kW)"] = 0.010 + 0.005 * rng.random(len(ts))
    ts["Reg Down Price ($/kW)"] = 0.008 + 0.004 * rng.random(len(ts))
    ts["SR Price ($/kW)"] = 0.006 + 0.003 * rng.random(len(ts))
    case.datasets.time_series = ts
    case.streams["FR"] = {"duration": 0.25, "eou": 0.3, "eod": 0.3,
                          "growth": 0}
    case.streams["SR"] = {"duration": 0.25, "growth": 0}
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    assert s.quarantine is None
    saw_tilt = False
    for label, bd in s.objective_values.items():
        total = bd["Total Objective"]
        comp = sum(v for k, v in bd.items()
                   if k not in ("Total Objective", TILT_LABEL))
        assert comp == pytest.approx(total, rel=1e-9, abs=1e-9), label
        saw_tilt = saw_tilt or abs(bd.get(TILT_LABEL, 0.0)) > 0
    # the tilt term must be REPORTED (nonzero with market awards), not
    # silently folded away
    assert saw_tilt
