"""Output file-set audit (VERDICT r2 #8): the CSV set written for a case
must cover the reference's frozen results directory file-for-file, and
multi-case runs must write sensitivity_summary.csv (reference:
storagevet.Result.sensitivity_summary written from dervet/DERVET.py:85)."""
from pathlib import Path

import pandas as pd
import pytest

from dervet_tpu.api import DERVET

REF = Path("/root/reference")


def _stems(directory, suffix):
    return {p.name[: -len(suffix) - 4] for p in directory.glob(f"*{suffix}.csv")}


def test_file_set_covers_reference_load_shedding(tmp_path):
    """The reference's wo_ls1 sizing frozen dir is the checklist: every
    file name it contains must be produced (with our label) for the same
    input."""
    res = DERVET(REF / "test/test_load_shedding/mp/Sizing/"
                 "Model_Parameters_Template_DER_wo_ls1.csv",
                 base_path=REF).solve(backend="cpu")
    res.save_as_csv(tmp_path)
    expected = _stems(
        REF / "test/test_load_shedding/results/Sizing/wo_ls1", "_2mw_5hr")
    got = {p.stem for p in tmp_path.glob("*.csv")}
    missing = expected - got
    assert not missing, f"missing output files: {sorted(missing)}"


def test_sensitivity_summary_csv_written(tmp_path):
    """A 4-case sensitivity run writes one summary row per case with the
    swept parameter and the lifetime NPV."""
    res = DERVET(REF / "test/test_storagevet_features/model_params/"
                 "009-bat_energy_sensitivity.csv",
                 base_path=REF).solve(backend="cpu")
    res.save_as_csv(tmp_path)
    f = tmp_path / "sensitivity_summary.csv"
    assert f.exists()
    df = pd.read_csv(f, index_col="Case")
    assert len(df) == 4
    assert "Battery/ene_max_rated" in df.columns
    assert "Lifetime Net Present Value" in df.columns
    assert df["Lifetime Net Present Value"].notna().all()


def test_single_case_writes_no_sensitivity_summary(tmp_path):
    res = DERVET(REF / "test/test_storagevet_features/model_params/"
                 "000-DA_battery_month.csv", base_path=REF).solve(
        backend="cpu")
    res.save_as_csv(tmp_path)
    assert not (tmp_path / "sensitivity_summary.csv").exists()
