"""Reliability stream: vectorized outage walk, min-SOE schedule, LCPC.

Spec: dervet/MicrogridValueStreams/Reliability.py — the greedy SOE walk
(:489-570), min-SOE-iterative schedule (:685-732), LCPC accounting
(:876-966) and contribution waterfall (:806-874).  The vectorized
scan/vmap walk is cross-validated here against a direct scalar
re-simulation of the reference semantics.
"""
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.io.params import Params
from dervet_tpu.models.streams.reliability import (
    Reliability, _simulate_all_outages, rolling_forward_sum)
from dervet_tpu.scenario.scenario import MicrogridScenario

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"


def scalar_walk(rc, dl, ec, init_soe, ch_max, dis_max, e_min, e_max, rte,
                dt, L, start):
    """Direct reading of the reference simulate_outage semantics
    (Reliability.py:489-570) for one outage start."""
    soe = init_soe
    profile = []
    for j in range(L):
        i = start + j
        if i >= len(rc):
            break
        if rc[i] <= 0:
            if e_max >= soe:
                charge = min((e_max - soe) / (rte * dt), -dl[i], ch_max)
                charge = max(charge, 0.0)
                soe = soe + charge * rte * dt
        else:
            if round(ec[i] * dt - soe, 2) <= 0:
                discharge = min((soe - e_min) / dt, dl[i], dis_max)
                if round(dl[i] - discharge, 2) > 0:
                    break
                soe = soe - discharge * dt
            else:
                break
        profile.append(soe)
    return profile


def test_walk_matches_scalar_reference():
    rng = np.random.default_rng(7)
    T, L = 200, 12
    crit = rng.uniform(0, 100, T)
    gen = np.full(T, 30.0)
    pv = rng.uniform(0, 60, T)
    rc = np.around(crit - gen - pv, 5)
    dl = np.around(crit - gen - pv, 5)
    ec = rc.copy()
    params = dict(ch_max=40.0, dis_max=50.0, e_min=10.0, e_max=200.0,
                  rte=0.85, dt=1.0)
    init = np.full(T, 120.0)
    cov, prof = _simulate_all_outages(
        crit, gen, pv, pv, 1.0, np.ones(L), init,
        params["ch_max"], params["dis_max"],
        params["e_min"], params["e_max"], params["rte"], params["dt"], L)
    cov = np.asarray(cov)
    prof = np.asarray(prof)
    for start in range(0, T, 17):
        expect = scalar_walk(rc, dl, ec, 120.0, L=L, start=start, **params)
        assert cov[start] == len(expect), start
        got = prof[start, :len(expect)]
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-4)


def test_rolling_forward_sum():
    arr = np.array([1.0, 2.0, 3.0, 4.0])
    np.testing.assert_allclose(rolling_forward_sum(arr, 2), [3, 5, 7, 4])
    np.testing.assert_allclose(rolling_forward_sum(arr, 10), [10, 9, 7, 4])


def _case_with_reliability(**rel_keys):
    cases = Params.initialize(MP / "000-DA_battery_month.csv", base_path=REF)
    case = cases[0]
    keys = {"target": 2, "post_facto_initial_soc": 100,
            "post_facto_only": 0, "max_outage_duration": 8, "n-2": 0,
            "load_shed_percentage": 0}
    keys.update(rel_keys)
    case.streams["Reliability"] = keys
    return case


@pytest.fixture(scope="module")
def solved_rel():
    case = _case_with_reliability()
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    return s


def test_min_soe_requirement_enforced(solved_rel):
    s = solved_rel
    rel = s.streams["Reliability"]
    assert rel.min_soe_df is not None
    ts = s.timeseries_results()
    soe = ts["Aggregated State of Energy (kWh)"].to_numpy()
    need = rel.min_soe_df["soe"].to_numpy()
    assert (soe >= need - 1e-3).all()
    assert "Total Critical Load (kWh)" in ts.columns
    assert "Critical Load (kW)" in ts.columns


def test_lcpc_shape_and_monotonicity(solved_rel):
    s = solved_rel
    rel = s.streams["Reliability"]
    ts = s.timeseries_results()
    lcpc = rel.load_coverage_probability(s.ders, ts)
    assert len(lcpc) == 8
    p = lcpc["Load Coverage Probability (%)"].to_numpy()
    assert (p >= 0).all() and (p <= 1).all()
    assert (np.diff(p) <= 1e-12).all()   # longer outages never more coverable


def test_lcpc_with_huge_battery_is_certain():
    case = _case_with_reliability()
    for tag, der_id, keys in case.ders:
        if tag == "Battery":
            keys["ene_max_rated"] = 1e7
            keys["dis_max_rated"] = 1e5
            keys["ch_max_rated"] = 1e5
    s = MicrogridScenario(case)
    rel = s.streams["Reliability"]
    rel._prepare(s.index)
    results = pd.DataFrame(index=s.index)
    lcpc = rel.load_coverage_probability(s.ders, results)
    assert (lcpc["Load Coverage Probability (%)"] == 1.0).all()


def test_contribution_waterfall(solved_rel):
    s = solved_rel
    rel = s.streams["Reliability"]
    ts = s.timeseries_results()
    contrib = rel.contribution_summary(s.ders, ts)
    assert "Storage Outage Contribution (kWh)" in contrib.columns
    assert (contrib["Storage Outage Contribution (kWh)"] >= -1e-9).all()


def test_post_facto_only_skips_requirements():
    case = _case_with_reliability(post_facto_only=1)
    s = MicrogridScenario(case)
    reqs = s.service_agg.identify_system_requirements(
        s.ders, s.opt_years, s.index)
    assert [r for r in reqs if r.source == "Reliability"] == []


def test_drill_down_reports(solved_rel):
    s = solved_rel
    rel = s.streams["Reliability"]
    ts = s.timeseries_results()
    dd = rel.drill_down_reports(s.ders, ts)
    assert "load_coverage_prob" in dd
    assert "lcp_outage_soe_profiles" in dd
    assert "outage_energy_contributions" in dd


class TestExactMinSoe:
    """min_soe_exact=1: the exact per-start minimal-SOE schedule (the
    reference's min_soe_opt mode, Reliability.py:572-683) computed as a
    vmapped backward recursion.  Exactness is proven against the forward
    outage simulator: the schedule is sufficient (a walk starting AT the
    requirement survives the target at every start) and minimal (starting
    just below it fails at binding starts)."""

    @pytest.fixture(scope="class")
    def rel_pair(self):
        import jax.numpy as jnp
        from dervet_tpu.models.streams.reliability import _min_soe_required
        case = _case_with_reliability(min_soe_exact=1)
        s = MicrogridScenario(case)
        s.sizing_module()
        rel = s.streams["Reliability"]
        rel._prepare(s.index)
        mix = rel._der_mix(s.ders)
        req = rel.min_soe_schedule(s.ders, s.index)["soe"].to_numpy()
        p = mix["props"]
        L = rel.coverage_steps
        raw = np.asarray(_min_soe_required(
            jnp.asarray(rel.critical_load.to_numpy()),
            jnp.asarray(mix["gen"]), jnp.asarray(mix["pv_max"]),
            jnp.asarray(mix["pv_vari"]), mix["gamma"],
            jnp.asarray(rel._shed_curve(L)),
            p["charge max"], p["discharge max"], p["soe min"],
            p["soe max"], p["rte"], rel.dt, L))
        # starts whose raw requirement exceeds the energy cap are not
        # coverable at ANY state of energy (fixed undersized battery)
        coverable = raw <= p["soe max"] + 1e-6
        return rel, mix, req, coverable

    def test_sufficient(self, rel_pair):
        rel, mix, req, coverable = rel_pair
        assert coverable.any() and not coverable.all()
        L = rel.coverage_steps
        cov, _ = rel._walk(mix, req, L)
        T = len(req)
        horizon_cap = np.minimum(L, T - np.arange(T))
        bad = coverable & (cov < horizon_cap)
        assert not bad.any(), \
            f"{int(bad.sum())} coverable starts uncovered at the exact " \
            "requirement"

    def test_minimal_at_binding_starts(self, rel_pair):
        rel, mix, req, coverable = rel_pair
        L = rel.coverage_steps
        e_min = mix["props"]["soe min"]
        binding = coverable & (req > e_min + 1.0)
        assert binding.any()
        lower = np.where(binding, req - 1.0, req)
        cov, _ = rel._walk(mix, lower, L)
        T = len(req)
        horizon_cap = np.minimum(L, T - np.arange(T))
        # every binding start must now fail (the requirement was tight)
        assert (cov[binding] < horizon_cap[binding]).all()

    def test_exact_no_looser_than_iterative(self, rel_pair):
        rel, _, ex_req, coverable = rel_pair
        case = _case_with_reliability(min_soe_exact=0)
        s = MicrogridScenario(case)
        s.sizing_module()
        rel_it = s.streams["Reliability"]
        rel_it._prepare(s.index)
        it_req = rel_it.min_soe_schedule(s.ders, s.index)["soe"].to_numpy()
        # on COVERABLE starts the exact schedule never demands more energy
        # than the iterative swing heuristic (it is the true per-start
        # minimum); on uncoverable starts the heuristic underreports (its
        # simulation dies early and the surviving prefix has a small
        # swing) while exact honestly caps at the fleet energy limit
        assert (ex_req[coverable] <= it_req[coverable] + 1e-3).all()
        assert ex_req.max() > 0
