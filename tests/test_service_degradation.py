"""Self-healing scenario service: degradation & recovery drills.

The resilience contract under test (PR 6):

* **circuit breakers** — sliding-window failure rates trip a rung's
  breaker; the escalation ladder skips the sick rung (serving from the
  healthy ones), half-opens on a probe schedule, and the whole board is
  visible in run_health, the solve ledger, and service metrics;
* **load shedding** — sustained overload answers low-priority requests
  with a loose-tolerance short-budget screening solve explicitly marked
  ``fidelity: "degraded"`` and NEVER certificate-stamped; higher
  priorities stay certified;
* **backend-loss recovery** — a device death mid-round re-initializes
  the backend and replays from checkpoints; N consecutive re-init
  failures fail the round over to the exact CPU backend;
* **poison quarantine** — a request that crashes the dispatch twice is
  answered with a typed ``PoisonRequestError`` (diagnosis attached) and
  its content fingerprint blocklisted, while co-batched innocents
  complete undamaged;
* **service journal** — the serve loop's append-only fsync'd journal
  reconciles a SIGKILLed spool on restart.
"""
import json
import time

import pytest

from dervet_tpu.benchlib import synthetic_sensitivity_cases
from dervet_tpu.service import (PoisonRequestError, ScenarioClient,
                                ScenarioService, ServiceJournal)
from dervet_tpu.service.queue import QueuedRequest
from dervet_tpu.service.resilience import (LoadShedder, PoisonRegistry,
                                           is_backend_loss,
                                           request_fingerprint)
from dervet_tpu.utils import faultinject
from dervet_tpu.utils.breaker import BreakerBoard, CircuitBreaker
from dervet_tpu.utils.errors import (BreakerOpenError,
                                     DeadlineExpiredError,
                                     DeviceLossError, QueueFullError,
                                     RequestFailedError, TypedError)


def _cases(n_cases: int, months: int = 1, bump: float = 0.0):
    cs = synthetic_sensitivity_cases(n_cases, months=months)
    if bump:
        # distinct content => distinct poison fingerprint
        for c in cs:
            for tag, _, keys in c.ders:
                if tag == "Battery":
                    keys["ene_max_rated"] = \
                        float(keys["ene_max_rated"]) + bump
    return {i: c for i, c in enumerate(cs)}


# ---------------------------------------------------------------------------
# Circuit breakers
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_trip_open_halfopen_close_cycle(self):
        clock = [0.0]
        br = CircuitBreaker("t", window=8, min_samples=3,
                            failure_threshold=0.5, cooldown_s=10.0,
                            clock=lambda: clock[0])
        assert br.allow() and br.state == "closed"
        br.record(True)
        br.record(False)
        br.record(False)                    # 2/3 failures >= 0.5: trip
        assert br.state == "open" and br.trips == 1
        assert not br.allow()
        assert br.probe_in_s() == pytest.approx(10.0)
        clock[0] = 10.5
        assert br.allow()                   # half-open: one probe
        assert not br.allow()               # probe in flight
        br.record(False)                    # probe failed: re-open
        assert br.state == "open"
        clock[0] = 21.0
        assert br.allow()
        br.record(True)                     # probe ok: closed, fresh
        assert br.state == "closed"
        assert br.snapshot()["samples"] == 0

    def test_lost_probe_reaped_not_wedged(self):
        """A probe whose guarded path RAISES never reports an outcome;
        after a cooldown of silence it is declared lost (a failure) and
        the breaker re-opens — instead of wedging half-open-and-
        refusing forever."""
        clock = [0.0]
        br = CircuitBreaker("t", min_samples=2, failure_threshold=1.0,
                            cooldown_s=5.0, clock=lambda: clock[0])
        br.record(False)
        br.record(False)
        clock[0] = 6.0
        assert br.allow()                   # probe consumed…
        # …and the path crashes: no record() ever arrives
        clock[0] = 12.0
        assert not br.allow()               # reaped -> OPEN, cooling
        clock[0] = 18.0
        assert br.allow()                   # a FRESH probe is possible
        br.record(True)
        assert br.state == "closed"

    def test_record_only_caller_heals_after_cooldown(self):
        """The service's backend breaker never calls allow(): the first
        outcome recorded past the cooldown is treated as the probe."""
        clock = [0.0]
        br = CircuitBreaker("b", min_samples=2, failure_threshold=1.0,
                            cooldown_s=5.0, clock=lambda: clock[0])
        br.record(False)
        br.record(False)
        assert br.state == "open"
        br.record(True)                     # inside cooldown: ignored
        assert br.state == "open"
        clock[0] = 6.0
        br.record(True)                     # past cooldown: probe, heal
        assert br.state == "closed"

    def test_board_autocreate_and_snapshot(self):
        board = BreakerBoard(min_samples=2, failure_threshold=1.0)
        assert board.allow("anything")
        board.record("anything", False)
        board.record("anything", False)
        assert board.is_open("anything")
        snap = board.snapshot()
        assert snap["anything"]["state"] == "open"

    def test_retry_rung_breaker_skips_to_cpu_fallback(self):
        """When the retry rung's failure rate trips its breaker, failed
        windows skip the boosted retry and recover on the CPU rung —
        and the breaker state is visible in ledger + run_health."""
        svc = ScenarioService(
            backend="cpu", max_wait_s=0.0,
            breaker_opts={"min_samples": 2, "failure_threshold": 0.5,
                          "cooldown_s": 300.0})
        # nonconverge at solve AND retry rungs: every window fails rung
        # 1, feeding the retry breaker failures until it trips
        with faultinject.inject(nonconverge="all",
                                rungs={"solve", "retry"}):
            f1 = svc.submit(_cases(2), request_id="t1")
            svc.run_once()
            res1 = f1.result(0)
            assert svc.breakers.get("retry_rung").state == "open"
            # next round: retry rung skipped entirely, CPU rung recovers
            fired_before = svc.breakers.get("retry_rung").snapshot()
            f2 = svc.submit(_cases(1), request_id="t2")
            svc.run_once()
            res2 = f2.result(0)
        assert res1.run_health["windows"]["cpu_fallback"] > 0
        # round 2 recovered every window WITHOUT the retry rung: no new
        # samples on the tripped breaker, all recoveries on cpu rung
        assert res2.run_health["windows"]["cpu_fallback"] == \
            sum(len(i.scenario.windows) for i in res2.instances.values())
        assert res2.run_health["windows"]["retried"] == 0
        assert svc.breakers.get("retry_rung").snapshot()["samples"] == \
            fired_before["samples"]
        # breaker states ride run_health and the round ledger
        assert res2.run_health["breakers"]["retry_rung"]["state"] == \
            "open"
        assert svc.last_round_ledger["breakers"]["retry_rung"][
            "state"] == "open"
        svc.close()

    def test_drain_while_breaker_open(self):
        """Satellite drill: a drain with a tripped breaker must still
        answer queued requests typed and exit clean."""
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        svc.breakers.configure("retry_rung", min_samples=1,
                               failure_threshold=0.5, cooldown_s=300.0)
        svc.breakers.record("retry_rung", False)
        assert svc.breakers.is_open("retry_rung")
        fut = svc.submit(_cases(1), request_id="queued")
        svc.request_stop()
        svc.drain()
        from dervet_tpu.service import ServiceClosedError
        assert isinstance(fut.exception(0), ServiceClosedError)
        assert svc.metrics()["resilience"]["breakers"]["retry_rung"][
            "state"] == "open"

    def test_backend_breaker_rejects_admissions_typed(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        svc.breakers.configure("backend", min_samples=1,
                               failure_threshold=0.5, cooldown_s=300.0)
        svc.breakers.record("backend", False)
        with pytest.raises(BreakerOpenError) as ei:
            svc.submit(_cases(1))
        assert ei.value.kind == "breaker_open"
        assert ei.value.retry_hint == pytest.approx(300.0, abs=5.0)
        svc.close()


# ---------------------------------------------------------------------------
# Load shedding: the degraded-fidelity tier
# ---------------------------------------------------------------------------

class TestLoadShedding:
    def _overloaded_service(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0,
                              max_queue_depth=8, max_batch_requests=4,
                              shed_threshold_frac=0.5,
                              shed_sustain_rounds=1)
        futs = {}
        for i in range(8):
            futs[i] = svc.submit(_cases(1), request_id=f"r{i}",
                                 priority=(1 if i % 2 else 0))
        while svc.queue.depth():
            svc.run_once()
        return svc, futs

    def test_low_priority_degraded_high_priority_certified(self):
        svc, futs = self._overloaded_service()
        for i, fut in futs.items():
            res = fut.result(0)
            if i % 2:
                assert res.fidelity == "certified", i
            else:
                assert res.fidelity == "degraded", i
        svc.close()

    def test_degraded_marked_never_certified_stamped(self):
        svc, futs = self._overloaded_service()
        degraded = [f.result(0) for i, f in futs.items() if not i % 2]
        assert degraded
        for res in degraded:
            cert = res.run_health["certification"]
            assert not cert["enabled"]
            assert cert["windows_certified"] == 0
            assert res.run_health["fidelity"] == "degraded"
            assert "resubmit" in res.resubmit_hint
        # the certified tier in the SAME storm stays fully certified
        certified = [f.result(0) for i, f in futs.items() if i % 2]
        for res in certified:
            cert = res.run_health["certification"]
            assert cert["enabled"]
            n_win = sum(len(inst.scenario.windows)
                        for inst in res.instances.values())
            assert cert["windows_certified"] == n_win
        svc.close()

    def test_shed_metrics_and_release(self):
        svc, futs = self._overloaded_service()
        shed = svc.metrics()["resilience"]["load_shedding"]
        assert shed["degraded_requests"] >= 1
        assert svc.metrics()["rounds"]["degraded_rounds"] >= 1
        # pressure gone: the next request is served certified again
        fut = svc.submit(_cases(1), request_id="calm", priority=0)
        while not fut.done():
            svc.run_once()
        assert fut.result(0).fidelity == "certified"
        svc.close()

    def test_degraded_round_writes_no_checkpoints(self, tmp_path):
        """A checkpoint records case content, not solver fidelity: a
        screening solution persisted under the certified namespace
        would be reloaded verbatim by a later certified resume of the
        same request id.  Degraded rounds therefore get NO checkpoint
        namespace at all."""
        svc = ScenarioService(backend="cpu", max_wait_s=0.0,
                              max_queue_depth=4, max_batch_requests=2,
                              shed_threshold_frac=0.5,
                              shed_sustain_rounds=1,
                              checkpoint_dir=tmp_path)
        futs = [svc.submit(_cases(1, bump=0.001 * i),
                           request_id=f"d{i}", priority=0)
                for i in range(4)]
        while any(not f.done() for f in futs):
            svc.run_once()
        degraded = [f.result(0) for f in futs
                    if f.result(0).fidelity == "degraded"]
        assert degraded                     # the drill actually shed
        assert not list(tmp_path.glob("case*.npz"))
        assert not list(tmp_path.glob("run_manifest*"))
        svc.close()

    def test_failed_first_round_answers_second_tier_typed(self,
                                                          monkeypatch):
        """When the degraded round dies hard, the certified tier taken
        in the same cycle (already out of the queue) must still be
        answered — not leaked as a forever-pending future."""
        from dervet_tpu.service import ServiceClosedError
        from dervet_tpu.service import batcher as batcher_mod

        real_run = batcher_mod.BatchRound.run

        def exploding_run(self):
            if self.degraded:
                raise RuntimeError("degraded round exploded")
            return real_run(self)

        monkeypatch.setattr(batcher_mod.BatchRound, "run", exploding_run)
        svc = ScenarioService(backend="cpu", max_wait_s=0.0,
                              max_queue_depth=4, max_batch_requests=4,
                              shed_threshold_frac=0.5,
                              shed_sustain_rounds=1)
        futs = {i: svc.submit(_cases(1, bump=0.001 * i),
                              request_id=f"m{i}", priority=i % 2)
                for i in range(4)}
        with pytest.raises(RuntimeError, match="degraded round"):
            # depth past threshold -> shed engaged -> degraded round
            # (priority 0) raises before the certified round runs
            svc.run_once()
        # the CERTIFIED tier was popped from the queue but never
        # dispatched: its futures must be answered typed, not leaked
        # (the exploding patch bypasses the round's own answer-before-
        # raise contract, so only the later tier is asserted here)
        for i in (1, 3):
            err = futs[i].exception(0)
            assert isinstance(err, ServiceClosedError), (i, err)
            assert "not dispatched" in str(err)

    def test_shedder_requires_sustained_pressure(self):
        sh = LoadShedder(threshold_frac=0.5, sustain_rounds=2)
        assert not sh.observe(8, 8, 0)      # first pressured round
        assert sh.observe(8, 8, 0)          # second: engaged
        assert not sh.observe(0, 8, 0)      # released immediately
        assert not sh.observe(8, 8, 0)      # needs sustain again

    def test_screening_options_are_loose_and_bounded(self):
        from dervet_tpu.ops.pdhg import PDHGOptions
        opts = PDHGOptions.screening()
        base = PDHGOptions()
        assert opts.eps_rel > base.eps_rel
        assert opts.max_iters < base.max_iters
        assert opts.cpu_rescue_after is None


# ---------------------------------------------------------------------------
# Backend-loss recovery
# ---------------------------------------------------------------------------

class TestBackendLossRecovery:
    def test_classification(self):
        assert is_backend_loss(DeviceLossError("x"))
        assert not is_backend_loss(RuntimeError("some bug"))
        assert not is_backend_loss(ValueError("bad input"))

    def test_device_loss_reinit_and_replay(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        with faultinject.inject(device_loss=True, device_loss_n=1) as p:
            fut = svc.submit(_cases(2), request_id="dl")
            assert svc.run_once() == 1
        assert [k for k, _ in p.fired].count(
            faultinject.EVENT_DEVICE_LOSS) == 1
        res = fut.result(0)
        n_win = sum(len(i.scenario.windows)
                    for i in res.instances.values())
        assert res.run_health["windows"]["clean"] == n_win
        rec = svc.metrics()["resilience"]["backend_recovery"]
        assert rec["losses"] == 1 and rec["reinits"] == 1
        assert rec["failovers"] == 0
        svc.close()

    def test_consecutive_reinit_failures_fail_over_to_cpu(self):
        """3 consecutive device losses (solve + two re-init probes) on
        the jax backend exhaust the re-init budget; the round fails
        over to the exact CPU backend and still completes."""
        svc = ScenarioService(backend="jax", max_wait_s=0.0,
                              backend_max_reinits=2)
        with faultinject.inject(device_loss=True, device_loss_n=3):
            fut = svc.submit(_cases(2), request_id="fo")
            assert svc.run_once() == 1
        res = fut.result(0)
        assert res.fidelity == "certified"
        rec = svc.metrics()["resilience"]["backend_recovery"]
        assert rec["failovers"] == 1
        assert rec["reinit_failures"] == 2
        svc.close()

    def test_replay_reuses_checkpoints(self, tmp_path):
        """The replay after a device loss reloads already-solved windows
        from the PR-2 checkpoints instead of re-solving everything."""
        svc = ScenarioService(backend="cpu", max_wait_s=0.0,
                              checkpoint_dir=tmp_path)
        # fire the loss after 2 solve calls: the first groups' windows
        # are checkpointed before the crash
        with faultinject.inject(device_loss=True, device_loss_after=2,
                                device_loss_n=1):
            fut = svc.submit(_cases(1, months=3), request_id="ck")
            assert svc.run_once() == 1
        res = fut.result(0)
        assert res.run_health["windows"]["clean"] == 3
        meta = res.instances[0].scenario.solve_metadata
        # the replayed dispatch solved FEWER windows than the case has:
        # the checkpointed ones were reloaded, not re-dispatched
        assert meta["batched_solves"] < 3
        svc.close()

    def test_env_knobs_parse(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FAULT_DEVICE_LOSS", "1")
        monkeypatch.setenv("DERVET_TPU_FAULT_DEVICE_LOSS_AFTER", "1")
        monkeypatch.setenv("DERVET_TPU_FAULT_DEVICE_LOSS_N", "2")
        plan = faultinject.get_plan()
        assert plan is not None
        assert not plan.device_loss_due()       # call 1: armed after 1
        assert plan.device_loss_due()           # call 2 dies
        assert plan.device_loss_due()           # call 3 dies (n=2)
        assert not plan.device_loss_due()       # spent

    def test_exit_zero_recovery_via_serve_drain(self):
        """Acceptance shape: a service that lost its backend mid-round
        still drains clean (the exit-0 contract)."""
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        with faultinject.inject(device_loss=True, device_loss_n=1):
            fut = svc.submit(_cases(1), request_id="x")
            svc.run_once()
        assert fut.result(0) is not None
        svc.drain()                              # raises nothing
        assert svc.metrics()["service"]["draining"]


# ---------------------------------------------------------------------------
# Poison-request quarantine
# ---------------------------------------------------------------------------

class TestPoisonQuarantine:
    def test_two_strikes_typed_error_and_no_collateral_damage(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        with faultinject.inject(crash_cases={"bad.0"}):
            f_bad = svc.submit(_cases(1), request_id="bad")
            f_ok = svc.submit(_cases(2, bump=7.0), request_id="ok")
            assert svc.run_once() == 2
        err = f_bad.exception(0)
        assert isinstance(err, PoisonRequestError)
        assert err.kind == "poison_request"
        assert "poison request crash" in err.diagnosis
        # co-batched innocents complete clean — no collateral damage
        res = f_ok.result(0)
        assert res.run_health["windows"]["quarantined"] == 0
        assert sorted(res.instances) == [0, 1]
        assert svc.metrics()["resilience"]["poison_quarantine"][
            "quarantined"] == 1
        svc.close()

    def test_blocklisted_resubmission_rejected_fast_at_admission(self):
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        with faultinject.inject(crash_cases={"bad.0"}):
            f_bad = svc.submit(_cases(1), request_id="bad")
            svc.run_once()
        assert isinstance(f_bad.exception(0), PoisonRequestError)
        # identical content, new id, NO fault active: rejected at the
        # admission boundary in microseconds, never dispatched
        t0 = time.monotonic()
        with pytest.raises(PoisonRequestError) as ei:
            svc.submit(_cases(1), request_id="bad-again")
        assert time.monotonic() - t0 < 1.0
        assert ei.value.diagnosis
        # DIFFERENT content sails through
        fut = svc.submit(_cases(1, bump=3.0), request_id="fine")
        assert svc.run_once() == 1
        assert fut.result(0) is not None
        svc.close()

    def test_registry_two_strike_threshold(self):
        reg = PoisonRegistry(threshold=2)
        fp = "f" * 64
        assert reg.blocked(fp) is None
        assert reg.strike(fp, "r1", "boom") == 1
        assert reg.blocked(fp) is None          # one strike: not yet
        assert reg.strike(fp, "r2", "boom again") == 2
        assert reg.blocked(fp) == "boom again"
        assert reg.snapshot()["quarantined"] == 1

    def test_fingerprint_tracks_content_not_request_id(self):
        a1 = _cases(1)
        a2 = _cases(1)
        b = _cases(1, bump=1.0)
        assert request_fingerprint(a1) == request_fingerprint(a2)
        assert request_fingerprint(a1) != request_fingerprint(b)

    def test_isolation_crash_answers_futures_typed(self, monkeypatch):
        """Any repeatable unexpected round crash resolves every future
        with a TYPED error (no raw leak, no hang) and quarantines the
        crashing content after two strikes."""
        from dervet_tpu.service import batcher as batcher_mod

        def boom(*a, **k):
            raise RuntimeError("device fell over")

        monkeypatch.setattr(batcher_mod, "run_dispatch", boom)
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        fut = svc.submit(_cases(1), request_id="crashed")
        assert svc.run_once() == 1
        err = fut.exception(0)
        assert isinstance(err, PoisonRequestError)
        assert "device fell over" in err.diagnosis
        svc.close()

    def test_env_knob_parses(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FAULT_POISON", "case7")
        plan = faultinject.get_plan()
        assert plan is not None
        assert plan.should_crash("case7")
        assert not plan.should_crash("case8")


# ---------------------------------------------------------------------------
# Typed-error family (satellite)
# ---------------------------------------------------------------------------

class TestTypedErrorFamily:
    def test_kinds_and_uniform_serialization(self):
        from dervet_tpu.utils.errors import (RequestPreemptedError,
                                             ServiceClosedError)
        samples = [
            (QueueFullError("q", retry_after_s=2.5), "queue_full", 2.5),
            (DeadlineExpiredError("d"), "deadline_expired", None),
            (ServiceClosedError("c"), "service_closed", None),
            (RequestFailedError({"a": "why"}), "request_failed", None),
            (PoisonRequestError("p", diagnosis="d"), "poison_request",
             None),
            (BreakerOpenError("b", probe_in_s=7.0), "breaker_open", 7.0),
            (RequestPreemptedError("r"), "request_preempted", 0.0),
        ]
        kinds = set()
        for err, kind, hint in samples:
            assert isinstance(err, TypedError)
            assert err.kind == kind
            assert err.retry_hint == hint
            d = err.as_dict()
            assert set(d) == {"error", "kind", "message", "retry_hint"}
            assert d["kind"] == kind
            kinds.add(kind)
        assert len(kinds) == len(samples)   # kinds are distinct

    def test_historical_import_path_still_works(self):
        from dervet_tpu.service.queue import (  # noqa: F401
            QueueFullError as Q, ServiceError)
        assert issubclass(Q, ServiceError)


# ---------------------------------------------------------------------------
# Queue: drain-rate hint, fairness floor, deadline race (satellites)
# ---------------------------------------------------------------------------

class TestQueueSatellites:
    def test_retry_hint_tracks_observed_drain_rate(self):
        from dervet_tpu.service import AdmissionQueue
        q = AdmissionQueue(max_depth=2)
        q.retry_after_s = 9.9               # static fallback
        q.put(QueuedRequest("a", {0: None}))
        q.put(QueuedRequest("b", {0: None}))
        with pytest.raises(QueueFullError) as e0:
            q.put(QueuedRequest("c", {0: None}))
        assert e0.value.retry_after_s == 9.9    # no history yet
        # observed drain: 4 requests per second of round wall
        q.note_round(requests_served=8, round_s=2.0)
        with pytest.raises(QueueFullError) as e1:
            q.put(QueuedRequest("d", {0: None}))
        # depth 2 + the retry itself, at 4 req/s -> 0.75 s
        assert e1.value.retry_after_s == pytest.approx(0.75)

    def test_fairness_floor_prevents_priority_starvation(self):
        from dervet_tpu.service import AdmissionQueue
        q = AdmissionQueue(max_depth=64, fairness_after_s=0.05)
        q.put(QueuedRequest("starved", {0: None}, priority=0))
        time.sleep(0.06)
        for i in range(6):                  # sustained hi-pri load
            q.put(QueuedRequest(f"hi{i}", {0: None}, priority=9))
        got = [r.request_id for r in q.take(max_batch=2, block=False)]
        # the starved low-priority request is served FIRST, ahead of
        # the high-priority stream, once past the fairness threshold
        assert got[0] == "starved"
        assert q.counters["fairness_promotions"] == 1

    def test_fairness_floor_off_within_threshold(self):
        from dervet_tpu.service import AdmissionQueue
        q = AdmissionQueue(max_depth=64, fairness_after_s=30.0)
        q.put(QueuedRequest("low", {0: None}, priority=0))
        q.put(QueuedRequest("hi", {0: None}, priority=9))
        got = [r.request_id for r in q.take(max_batch=2, block=False)]
        assert got == ["hi", "low"]

    def test_deadline_expiry_racing_batch_assembly(self):
        """A request that expires AFTER take() but BEFORE its scenarios
        assemble is answered typed at assembly time and never rides the
        batch."""
        from dervet_tpu.service.batcher import BatchRound
        dead = QueuedRequest("race", _cases(1), deadline_s=0.02)
        live = QueuedRequest("live", _cases(1))
        time.sleep(0.03)                     # expires post-take
        rnd = BatchRound([dead, live], backend="cpu")
        rnd.run()
        assert isinstance(dead.future.exception(0), DeadlineExpiredError)
        assert live.future.result(0) is not None
        assert dead in rnd.answered_early

    def test_client_backoff_capped_and_jittered(self):
        class _Svc:
            pass
        client = ScenarioClient(_Svc(), backoff_cap_s=2.0,
                                jitter_frac=0.25, jitter_seed=7)
        waits = {client._backoff_s(100.0) for _ in range(16)}
        assert all(1.5 <= w <= 2.5 for w in waits)   # capped ±25%
        assert len(waits) > 1                        # jittered

    def test_client_jitter_deterministic_with_seed(self):
        class _Svc:
            pass
        a = ScenarioClient(_Svc(), jitter_seed=3)
        b = ScenarioClient(_Svc(), jitter_seed=3)
        assert [a._backoff_s(1.0) for _ in range(5)] == \
            [b._backoff_s(1.0) for _ in range(5)]


# ---------------------------------------------------------------------------
# Service journal
# ---------------------------------------------------------------------------

class TestServiceJournal:
    def test_admitted_completed_replay(self, tmp_path):
        j = ServiceJournal(tmp_path / "j.jsonl")
        j.admitted("a", file="a.csv")
        j.admitted("b", file="b.csv")
        j.completed("a")
        j.failed("c", error={"kind": "request_failed"})
        assert j.replay()["a"]["state"] == "completed"
        assert j.unfinished() == [("b", "b.csv")]
        j.close()

    def test_torn_tail_tolerated(self, tmp_path):
        path = tmp_path / "j.jsonl"
        j = ServiceJournal(path)
        j.admitted("a", file="a.csv")
        j.close()
        with open(path, "a") as fh:         # simulate SIGKILL mid-append
            fh.write('{"event": "comple')
        j2 = ServiceJournal(path)
        assert j2.unfinished() == [("a", "a.csv")]
        j2.close()

    def test_recover_spool_moves_completed_reserves_admitted(
            self, tmp_path):
        incoming = tmp_path / "incoming"
        done = tmp_path / "done"
        failed = tmp_path / "failed"
        for d in (incoming, done, failed):
            d.mkdir()
        (incoming / "x.csv").write_text("x")
        (incoming / "y.csv").write_text("y")
        (incoming / "z.csv").write_text("z")
        j = ServiceJournal(tmp_path / "j.jsonl")
        j.admitted("x", file="x.csv")
        j.admitted("y", file="y.csv")
        j.admitted("z", file="z.csv")
        j.completed("x")                    # killed before the move
        j.failed("z", error={"kind": "request_failed"})
        rec = j.recover_spool(incoming, done, failed)
        assert rec["reserve"] == ["y"]
        assert sorted(rec["moved"]) == ["x", "z"]
        assert (done / "x.csv").exists()
        assert (incoming / "y.csv").exists()
        # a journaled FAILURE is finished into failed/, never done/
        assert (failed / "z.csv").exists()
        assert not (done / "z.csv").exists()
        j.close()
