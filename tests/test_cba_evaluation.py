"""CBA Evaluation-column machinery (VERDICT r1 component #5).

Spec: reference DERVETParams.py:157-467 + test_cba_validation/test_cba.py —
the CBA re-prices the SAME dispatch with the Evaluation values; coupled
sensitivity/evaluation lists must match lengths; mismatches raise
ModelParameterError.
"""
from pathlib import Path

import numpy as np
import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.utils.errors import ModelParameterError

REF = Path("/root/reference")
DIR = REF / "test/test_cba_validation/model_params"


@pytest.fixture(scope="module")
def zeroed():
    d = DERVET(DIR / "001-cba_valuation.csv", base_path=REF)
    return d.solve(backend="cpu").instances[0]


class TestEvaluateBatteryICECostsToZero:
    """Reference TestEvaluateBatteryICECostsToZero: evaluation zeroes every
    battery and ICE cost in the proforma while dispatch stays priced."""

    def test_battery_capital_cost(self, zeroed):
        col = [c for c in zeroed.proforma_df.columns
               if c.startswith("BATTERY:") and "Capital Cost" in c]
        assert col and np.all(zeroed.proforma_df[col[0]].values == 0)

    def test_battery_oms(self, zeroed):
        pf = zeroed.proforma_df
        for pat in ("Variable O&M", "Fixed O&M"):
            col = [c for c in pf.columns
                   if c.startswith("BATTERY:") and pat in c]
            assert col and np.all(pf[col[0]].values == 0), pat

    def test_ice_costs(self, zeroed):
        pf = zeroed.proforma_df
        for pat in ("Capital Cost", "Variable O&M Costs", "Fixed O&M",
                    "Diesel Fuel Costs"):
            col = [c for c in pf.columns
                   if c.startswith("ICE:") and pat in c]
            assert col and np.all(pf[col[0]].values == 0), pat

    def test_dispatch_not_zeroed(self, zeroed):
        """The optimization itself used the real (nonzero) prices."""
        s = zeroed.scenario
        bat = next(d for d in s.ders if d.tag == "Battery")
        assert bat.get_capex() > 0     # original DER keeps its costs


def test_sensitivity_evaluation_runs():
    d = DERVET(DIR / "003-cba_valuation_sensitivity.csv", base_path=REF)
    res = d.solve(backend="cpu")
    assert len(res.instances) > 1


@pytest.mark.skip(reason="input references test/datasets/000-011-timeseries_"
                  "5min_2017.csv, dropped from the reference snapshot "
                  "(.MISSING_LARGE_BLOBS)")
def test_coupled_evaluation_runs():
    d = DERVET(DIR / "004-cba_valuation_coupled_dt.csv", base_path=REF)
    assert d.solve(backend="cpu").instances


def test_catch_wrong_length():
    with pytest.raises(ModelParameterError):
        DERVET(DIR / "002-catch_wrong_length.csv", base_path=REF)


def test_monthly_evaluation_runs():
    d = DERVET(DIR / "005-cba_monthly_timseries.csv", base_path=REF)
    assert d.solve(backend="cpu").instances


CBA_MP = REF / "test/test_cba_validation/model_params"


class TestLifetimeHorizons:
    """Mirrors the reference's active lifetime-horizon assertions
    (test_cba_validation/test_cba.py:127-229): with analysis_horizon_mode
    3 the proforma spans the LONGEST DER lifetime (+ the CAPEX Year row),
    with mode 2 the SHORTEST; under mode 2 replaceable and
    non-replaceable DERs produce the same proforma; sizing combined with
    either mode errors."""

    def _proforma(self, name):
        return DERVET(CBA_MP / name,
                      base_path=REF).solve(backend="cpu").instances[0] \
            .proforma_df

    def test_longest_lifetime_proforma_length(self):
        pf = self._proforma("longest_lifetime.csv")
        assert len(pf.index) == 14 + 1  # longest lifetime + CAPEX Year row

    def test_longest_lifetime_replaceable_proforma_length(self):
        pf = self._proforma("longest_lifetime_replaceble.csv")
        assert len(pf.index) == 14 + 1

    def test_shortest_replacements_same_proforma(self):
        no_rep = self._proforma("shortest_lifetime.csv")
        rep = self._proforma("shortest_lifetime_replaceble.csv")
        assert no_rep.shape == rep.shape
        import numpy as np
        assert np.allclose(no_rep.to_numpy(dtype=float),
                           rep.to_numpy(dtype=float), rtol=1e-9)

    @pytest.mark.parametrize("name", ["shortest_lifetime_sizing_error.csv",
                                      "longest_lifetime_sizing_error.csv"])
    def test_horizon_mode_with_sizing_errors(self, name):
        from dervet_tpu.utils.errors import ParameterError
        with pytest.raises(ParameterError):
            DERVET(CBA_MP / name, base_path=REF).solve(backend="cpu")


def test_ppa_payment():
    """PV PPA (reference xtest_ppa + IntermittentResourceSizing.py:262-316):
    the proforma carries a PPA column priced on MAXIMUM production,
    escalated at the PPA inflation rate, and the non-owned panels have no
    MACRS/replacement/decommissioning/salvage entries."""
    inst = DERVET(CBA_MP / "ppa_payment.csv",
                  base_path=REF).solve(backend="cpu").instances[0]
    pf = inst.proforma_df
    ppa_cols = [c for c in pf.columns if c.endswith(" PPA")]
    assert len(ppa_cols) == 1
    ppa = pf[ppa_cols[0]]
    pv = next(d for d in inst.scenario.ders if d.tag == "PV")
    assert not pv.owns_asset()
    # pays for production every operating year (zeroed after EOL like any
    # dead DER, reference zero_out_dead_der_costs)
    years = [y for y in pf.index
             if y != "CAPEX Year" and y <= pv.last_operation_year]
    assert years and (ppa[years] < 0).all()
    # escalation at the PPA inflation rate year over year (equal annual
    # production profile -> constant ratio)
    ratios = (ppa[years].to_numpy()[1:] / ppa[years].to_numpy()[:-1])
    assert np.allclose(ratios, 1 + pv.ppa_inflation, rtol=1e-6)
    uid = pv.unique_tech_id
    for stem in ("MACRS Depreciation", "Replacement Costs",
                 "Decommissioning Cost", "Salvage Value"):
        col = f"{uid} {stem}"
        if col in pf.columns:
            assert (pf[col] == 0).all(), col
