"""Tariff engine + retailTimeShift/DCM value streams (VERDICT r1 #5).

Spec: billing-period semantics from the reference tariff format
(/root/reference/data/tariff.csv header comments: inclusive ranges, times in
hour-ending units, Weekday? 0/1/2) and the frozen billing outputs
(test_validation_report_sept1 adv/simple_monthly_bill columns); a
bill-reduction case reproduces the billing-period structure and reduces the
bill vs the original load.
"""
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.financial.tariff import TariffEngine
from dervet_tpu.utils.errors import TariffError

REF = Path("/root/reference")
CASE_004 = REF / ("test/test_storagevet_features/model_params/"
                  "004-fixed_size_battery_retailets_dcm.csv")


def _tariff(rows):
    df = pd.DataFrame(rows, columns=[
        "Billing Period", "Start Month", "End Month", "Start Time",
        "End Time", "Excluding Start Time", "Excluding End Time",
        "Weekday?", "Value", "Charge"])
    return df.set_index("Billing Period")


@pytest.fixture
def engine():
    return TariffEngine(_tariff([
        [1, 1, 5, 1, 24, None, None, 2, 0.05, "Energy"],
        [2, 6, 9, 12, 18, None, None, 1, 0.10, "energy"],
        [3, 6, 9, 1, 24, 12, 18, 2, 0.04, "energy"],
        [4, 1, 12, 1, 24, None, None, 2, 10.0, "Demand"],
        [5, 6, 9, 13, 19, None, None, 1, 25.0, "demand"],
    ]))


def test_energy_price_stacks_and_masks(engine):
    # Jan 1 2018 is a Monday
    idx = pd.date_range("2018-01-01", periods=48, freq="h")
    p = engine.energy_price(idx)
    assert np.allclose(p, 0.05)          # period 1 only, all hours
    idx7 = pd.date_range("2018-07-02", periods=24, freq="h")  # Monday
    p7 = engine.energy_price(idx7)
    # he 12..18 -> hb hours 11..17: period 2 (weekday); others period 3
    assert p7[11] == pytest.approx(0.10)
    assert p7[17] == pytest.approx(0.10)
    assert p7[10] == pytest.approx(0.04)
    assert p7[18] == pytest.approx(0.04)
    # weekend in July: period 2 off, period 3 excludes he 12-18 -> zero there
    idx7s = pd.date_range("2018-07-07", periods=24, freq="h")  # Saturday
    p7s = engine.energy_price(idx7s)
    assert p7s[11] == pytest.approx(0.0)
    assert p7s[3] == pytest.approx(0.04)


def test_hour_ending_semantics(engine):
    # he 12 means the hour beginning at 11:00
    idx = pd.date_range("2018-07-02 10:00", periods=2, freq="h")
    mask = engine.period_mask(2, idx)
    assert not mask[0] and mask[1]


def test_monthly_bill_hand_check(engine):
    idx = pd.date_range("2018-01-01", periods=31 * 24, freq="h")
    load = pd.Series(100.0, index=idx)
    load.iloc[40] = 500.0              # single peak
    adv, simple = engine.monthly_bill(load, load * 2, dt=1.0)
    jan = simple.loc["2018-01"]
    expected_energy = 0.05 * (100.0 * (31 * 24 - 1) + 500)
    assert float(jan["Energy Charge ($)"]) == pytest.approx(expected_energy)
    assert float(jan["Demand Charge ($)"]) == pytest.approx(10.0 * 500)
    assert float(jan["Original Demand Charge ($)"]) == pytest.approx(10.0 * 1000)
    dem = adv.dropna(subset=["Demand Charge ($)"])
    assert list(dem["Billing Period"]) == [4]


def test_demand_charge_floor_at_zero(engine):
    idx = pd.date_range("2018-01-01", periods=24, freq="h")
    exporting = pd.Series(-50.0, index=idx)
    _, simple = engine.monthly_bill(exporting, exporting, dt=1.0)
    assert float(simple["Demand Charge ($)"].iloc[0]) == 0.0


def test_missing_tariff_raises():
    with pytest.raises(TariffError):
        TariffEngine(None)
    with pytest.raises(TariffError):
        TariffEngine(pd.DataFrame({"Billing Period": []}).set_index("Billing Period"))


# ---------------------------------------------------------------------------
# end-to-end bill-reduction case (reference input 004)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def solved_004():
    d = DERVET(CASE_004, base_path=REF)
    return d.solve(backend="cpu")


def test_bill_reduction_runs(solved_004):
    inst = solved_004.instances[0]
    ts = inst.time_series_data
    assert "Tariff Energy Price ($/kWh)" in ts.columns
    assert "Demand Charge Billing Periods" in ts.columns
    assert (ts["Tariff Energy Price ($/kWh)"] > 0).all()


def test_bill_reduced_vs_original(solved_004):
    inst = solved_004.instances[0]
    adv = inst.drill_down_dict["adv_monthly_bill"]
    simple = inst.drill_down_dict["simple_monthly_bill"]
    assert len(simple) == 12
    with_der = simple["Energy Charge ($)"].sum() + simple["Demand Charge ($)"].sum()
    original = simple["Original Energy Charge ($)"].sum() + \
        simple["Original Demand Charge ($)"].sum()
    assert with_der < original
    assert set(adv.columns) >= {"Energy Charge ($)", "Original Energy Charge ($)",
                                "Billing Period", "Demand Charge ($)",
                                "Original Demand Charge ($)"}


def test_avoided_charges_in_proforma(solved_004):
    inst = solved_004.instances[0]
    pf = inst.proforma_df
    assert "Avoided Energy Charge" in pf.columns
    assert "Avoided Demand Charge" in pf.columns
    # avoided charges in optimized year are positive (battery shifts load)
    assert pf.loc[2017, "Avoided Energy Charge"] > 0
    assert pf.loc[2017, "Avoided Demand Charge"] > 0
    # fill-forward escalates each stream column at that STREAM's growth
    # rate (reference test_2finances semantics: growth=0 stays flat)
    s = inst.scenario
    growth = s.streams["retailTimeShift"].growth
    assert pf.loc[2025, "Avoided Energy Charge"] == pytest.approx(
        pf.loc[2017, "Avoided Energy Charge"] * (1 + growth) ** 8)


def test_objective_breakdown_labels(solved_004):
    inst = solved_004.instances[0]
    obj = inst.objective_values
    assert "retailETS" in obj.columns
    assert "DCM" in obj.columns
    assert "demand_charges" in inst.drill_down_dict


def test_dcm_peak_shaved(solved_004):
    """Monthly demand-charge peaks with the battery must not exceed the
    original peaks (the battery can only help)."""
    inst = solved_004.instances[0]
    adv = inst.drill_down_dict["adv_monthly_bill"]
    dem = adv.dropna(subset=["Demand Charge ($)"])
    assert (dem["Demand Charge ($)"] <=
            dem["Original Demand Charge ($)"] + 1e-6).all()


@pytest.mark.slow
def test_retail_pdhg_matches_cpu():
    d = DERVET(CASE_004, base_path=REF)
    res_jax = d.solve(backend="jax")
    d2 = DERVET(CASE_004, base_path=REF)
    res_cpu = d2.solve(backend="cpu")
    oj = res_jax.instances[0].scenario.objective_values
    oc = res_cpu.instances[0].scenario.objective_values
    for k in oj:
        a, b = oj[k]["Total Objective"], oc[k]["Total Objective"]
        assert abs(a - b) / max(abs(b), 1.0) < 1e-2, (k, a, b)
