"""Tax semantics mirrored from the reference's TestAssetDepreciation
(test_cba_validation/test_cba.py:328-358): exact MACRS depreciation
schedule, the capex 'disregard' zeroing taxable income in the CAPEX year,
and state/federal burdens opposing the sign of taxable income — plus
end-of-life salvage coverage on the reference's cba-validation inputs.
"""
from pathlib import Path

import numpy as np
import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.utils.errors import ModelParameterError

REF = Path("/root/reference")
MP = REF / "test/model_params"
CBA_MP = REF / "test/test_cba_validation/model_params"


@pytest.fixture(scope="module")
def tax_case():
    res = DERVET(MP / "002-tax_scenario.csv", base_path=REF).solve(
        backend="cpu")
    return res.instances[0]


class TestAssetDepreciation:
    """Reference TestAssetDepreciation on 002-tax_scenario.csv (federal
    23%, state 10%, battery capex 825k on a 3-year MACRS schedule)."""

    def test_macrs_depreciation(self, tax_case):
        expected = [0, -274972.5, -366712.5, -122182.5, -61132.5, 0, 0, 0,
                    0, 0, 0, 0, 0, 0, 0]
        actual = tax_case.tax_breakdown_df[
            "BATTERY: es MACRS Depreciation"].values
        assert list(actual) == pytest.approx(expected)

    def test_zero_tax_in_capex(self, tax_case):
        assert tax_case.tax_breakdown_df.loc[
            "CAPEX Year", "Taxable Yearly Net"] == pytest.approx(0.0)

    def test_sign_of_state_tax(self, tax_case):
        df = tax_case.tax_breakdown_df
        rows = df[df.index != "CAPEX Year"]
        taxable = rows["Taxable Yearly Net"].values
        state = rows["State Tax Burden"].values
        assert np.all(np.sign(taxable) != np.sign(state))

    def test_sign_of_federal_tax(self, tax_case):
        df = tax_case.tax_breakdown_df
        rows = df[df.index != "CAPEX Year"]
        taxable = rows["Taxable Yearly Net"].values
        federal = rows["Federal Tax Burden"].values
        assert np.all(np.sign(taxable) != np.sign(federal))

    def test_burdens_in_proforma(self, tax_case):
        pf = tax_case.proforma_df
        for col in ("State Tax Burden", "Federal Tax Burden",
                    "Overall Tax Burden"):
            assert col in pf.columns
        rows = pf[pf.index != "CAPEX Year"]
        assert rows["Overall Tax Burden"].values == pytest.approx(
            rows["State Tax Burden"].values
            + rows["Federal Tax Burden"].values)


def test_macrs_15_year_table_matches_reference(tmp_path):
    """Deliberate parity pin (VERDICT r3 #7): the reference's 15-year
    MACRS table carries 6.83% at year 5 (dervet/CBA.py:88) where IRS Pub
    946 says 6.93; we follow the REFERENCE so fixed-size tax rows agree
    by construction.  This runs macrs_term=15 end-to-end and asserts the
    year-5 depreciation against the 6.83 value exactly — if someone
    "fixes" the table to the IRS number, this fails loudly."""
    import pandas as pd

    from dervet_tpu.financial.cba import MACRS_TABLES

    assert MACRS_TABLES[15][4] == 6.83     # reference CBA.py:88, not 6.93

    df = pd.read_csv(MP / "002-tax_scenario.csv")
    sel = (df.Tag == "Battery") & (df.Key == "macrs_term")
    assert sel.any()
    df.loc[sel, "Optimization Value"] = "15"
    mp = tmp_path / "mp15.csv"
    df.to_csv(mp, index=False)
    inst = DERVET(mp, base_path=REF).solve(backend="cpu").instances[0]
    dep = inst.tax_breakdown_df["BATTERY: es MACRS Depreciation"]
    rows = dep[dep.index != "CAPEX Year"].values
    # battery capex 825k (002 fixture): year-5 depreciation at 6.83%
    assert rows[4] == pytest.approx(-825000 * 0.0683)
    assert rows[0] == pytest.approx(-825000 * 0.05)


def test_linear_salvage_value_runs():
    """006-linear_salvage_value runs end-to-end (its battery life exactly
    spans the analysis window and salvage_value=0, so no salvage lands —
    reference calculate_salvage_value returns 0 when the equipment does
    not outlive the project)."""
    res = DERVET(CBA_MP / "006-linear_salvage_value.csv",
                 base_path=REF).solve(backend="cpu")
    pf = res.instances[0].proforma_df
    salvage_cols = [c for c in pf.columns if "Salvage" in c]
    assert salvage_cols
    assert sum(abs(pf[c]).sum() for c in salvage_cols) == 0


def test_linear_salvage_semantics():
    """Linear salvage = capex * years-beyond-project / lifetime, gated on
    the equipment outliving the analysis (reference
    DERExtension.calculate_salvage_value)."""
    from dervet_tpu.financial.cba import CostBenefitAnalysis
    from dervet_tpu.models.der.base import DER

    cba = CostBenefitAnalysis({}, 2017, 2030, [2017], 1.0)

    class Dummy(DER):
        def __init__(self, keys):
            super().__init__("Battery", "1", keys, {})

    # lifetime 20 from 2017 -> outlives 2030 by 6 years: 6/20 of capex
    d = Dummy({"name": "b", "salvage_value": "Linear Salvage Value",
               "expected_lifetime": 20, "operation_year": 2017})
    d.set_failure_years(2030, 2017)
    assert cba._salvage_value(d, 1000.0) == pytest.approx(1000.0 * 6 / 20)

    # life ends exactly at the analysis end: no salvage
    d2 = Dummy({"name": "b", "salvage_value": "Linear Salvage Value",
                "expected_lifetime": 14, "operation_year": 2017})
    d2.set_failure_years(2030, 2017)
    assert cba._salvage_value(d2, 1000.0) == 0.0

    # replaceable short-lived equipment: the last replacement outlives the
    # project, so salvage applies (reference: "if it has a life shorter
    # than the analysis window but is replaced, a salvage value applies")
    d3 = Dummy({"name": "b", "salvage_value": "Linear Salvage Value",
                "expected_lifetime": 10, "operation_year": 2017,
                "replaceable": 1})
    d3.set_failure_years(2030, 2017)
    assert d3.last_operation_year == 2036
    assert cba._salvage_value(d3, 1000.0) == pytest.approx(1000.0 * 6 / 10)

    # user-specified $ salvage: the reference's gate is strictly
    # last_op + 1 <= end, so a life ending exactly at the analysis end
    # still earns the $ amount, but dying a year earlier does not
    d4 = Dummy({"name": "b", "salvage_value": 500,
                "expected_lifetime": 14, "operation_year": 2017})
    d4.set_failure_years(2030, 2017)
    assert cba._salvage_value(d4, 1000.0) == 500.0
    d5 = Dummy({"name": "b", "salvage_value": 500,
                "expected_lifetime": 13, "operation_year": 2017})
    d5.set_failure_years(2030, 2017)
    assert cba._salvage_value(d5, 1000.0) == 0.0


def test_degradation_not_replaceable_runs():
    """043: cycle degradation with a non-replaceable battery runs through
    the full pipeline."""
    res = DERVET(CBA_MP / "043-Degradation_Test_MP_not_replaceable.csv",
                 base_path=REF).solve(backend="cpu")
    assert res.instances[0].proforma_df is not None


def test_ecc_requires_reliability_or_deferral():
    """ecc_checks: ECC mode without a Reliability/Deferral service raises
    (reference CBA.py:132-158)."""
    from dervet_tpu.io.params import Params
    from dervet_tpu.scenario.scenario import MicrogridScenario
    cases = Params.initialize(
        REF / "test/test_storagevet_features/model_params/"
              "000-DA_battery_month.csv", base_path=REF)
    case = cases[0]
    case.finance["ecc_mode"] = 1
    with pytest.raises(ModelParameterError):
        MicrogridScenario(case)
