"""DER lifecycle / DERExtension surface: failure years, replacements,
equipment lifetimes, dead-DER zero-out, ECC substitution.

Spec: dervet/MicrogridDER/DERExtension.py:86-306 + CBA.py:348-438; the
Usecase1 equipment_lifetimes golden fixes the report semantics
(Beginning of Life = construction year, End of Life = operation year +
expected lifetime - 1 for non-replaceable equipment).
"""
from pathlib import Path

import pandas as pd
import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.financial.cba import CostBenefitAnalysis
from dervet_tpu.models.der.ess import Battery

REF = Path("/root/reference")
UC1 = REF / "test/test_validation_report_sept1/Model_params/Usecase1"


def _battery(**keys):
    base = {"name": "bat", "ene_max_rated": 100, "ch_max_rated": 50,
            "dis_max_rated": 50, "rte": 85, "ulsoc": 100, "llsoc": 0}
    base.update(keys)
    return Battery(base, {"dt": 1})


def test_failure_years_non_replaceable():
    b = _battery(operation_year=2017, expected_lifetime=5, replaceable=0)
    assert b.set_failure_years(2030) == [2021]
    assert b.last_operation_year == 2021
    assert b.operational(2021) and not b.operational(2022)


def test_failure_years_replaceable():
    b = _battery(operation_year=2017, expected_lifetime=5, replaceable=1)
    assert b.set_failure_years(2030) == [2021, 2026]
    # the final replacement (installed 2027) operates through 2031 — one
    # year beyond the analysis end (reference DERExtension.py:106-112)
    assert b.last_operation_year == 2031
    assert b.operational(2030)


def test_replacement_cost_components():
    b = _battery(rcost=1000, rcost_kW=10, rcost_kWh=2)
    assert b.replacement_cost() == 1000 + 10 * 50 + 2 * 100


def test_replacement_rows_in_proforma():
    b = _battery(operation_year=2017, expected_lifetime=5, replaceable=1,
                 rcost_kW=100, ter=7, ccost_kw=100)
    cba = CostBenefitAnalysis({"npv_discount_rate": 7, "inflation_rate": 3},
                              2017, 2030, [2017])
    cols = cba._der_columns(b, [2017], pd.DataFrame())
    rep = cols["BATTERY: bat Replacement Costs"]
    # failure 2021 -> paid 2021+1-1(construction time)=2021, escalated at ter
    assert rep[2021] == pytest.approx(-100 * 50 * 1.07 ** 4)
    assert rep[2026] == pytest.approx(-100 * 50 * 1.07 ** 9)


def test_equipment_lifetimes_golden_semantics():
    """Battery in Usecase1: construction 2016, operation 2017,
    lifetime 100 -> EoL 2116 (golden equipment_lifetimesuc3.csv)."""
    b = _battery(construction_year=2016, operation_year=2017,
                 expected_lifetime=100, replaceable=0)
    row = b.equipment_lifetime_row(2037)
    assert row == {"Beginning of Life": 2016, "Operation Begins": 2017,
                   "End of Life": 2116}


def test_ecc_substitution():
    b = _battery(operation_year=2017, expected_lifetime=4, ccost_kw=100,
                 **{"ecc%": 10})
    cba = CostBenefitAnalysis({"npv_discount_rate": 7, "inflation_rate": 0,
                               "ecc_mode": 1}, 2017, 2026, [2017])
    pf = pd.DataFrame(0.0, index=["CAPEX Year"] + list(range(2017, 2027)),
                      columns=["BATTERY: bat Capital Cost"])
    pf.loc["CAPEX Year"] = -5000.0
    out = cba._ecc_substitution(pf, [b])
    assert (out["BATTERY: bat Capital Cost"] == 0).all()
    cc = out["BATTERY: bat Carrying Cost"]
    assert cc[2017] == pytest.approx(-b.get_capex() * 0.10)
    assert cc[2020] != 0 and cc[2021] == 0


def test_analysis_horizon_modes():
    """Mode 2 = shortest DER lifetime, mode 3 = longest (reference
    CBA.py:94-130 wired through scenario init)."""
    from dervet_tpu.io.params import Params
    from dervet_tpu.scenario.scenario import MicrogridScenario
    MP = REF / "test/test_storagevet_features/model_params"
    cases = Params.initialize(MP / "000-DA_battery_month.csv", base_path=REF)
    case = cases[0]
    for tag, _, keys in case.ders:
        keys["operation_year"] = 2017
        keys["expected_lifetime"] = 6
    case.finance["analysis_horizon_mode"] = 2
    s = MicrogridScenario(case)
    assert s.end_year == 2022


def test_equipment_lifetimes_saved(tmp_path):
    d = DERVET(UC1 / "Model_Parameters_Template_Usecase1_UnPlanned_ES.csv",
               base_path=REF)
    res = d.solve(backend="cpu")
    res.save_as_csv(tmp_path)
    el = pd.read_csv(tmp_path / "equipment_lifetimes.csv", index_col=0)
    assert "BATTERY: es" in el.columns
    assert int(el.loc["End of Life", "BATTERY: es"]) == 2116
