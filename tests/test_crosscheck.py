"""Independent-formulation cross-check (VERDICT r5 #5).

For stream families with no reference golden (FR/SR/NSR/LF, DR, User,
EV1, VoltVar — their executable spec lives in the missing StorageVET
layer), every
window's LP is re-assembled by a SECOND, independent stack
(``scripts/crosscheck_formulation.py``: flat-index scipy COO + linprog,
no LPBuilder) and the optimal window objectives must agree.  Two
equivalent LPs share their optimum even at degenerate argmins, so the
gate is tight (1e-5 relative; measured <=6e-11 across all families).
"""
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))

from crosscheck_formulation import CASES, crosscheck_case  # noqa: E402


@pytest.mark.slow
@pytest.mark.parametrize("family", sorted(CASES))
def test_independent_formulation_agrees(family):
    worst = crosscheck_case(family)
    assert worst < 1e-5, (family, worst)
