"""Fleet serving tests: structure-affinity routing, health-probed
failover, exactly-once recovery, hedging, and the retry-after redirect
discipline (service/fleet.py + service/router.py).

Three tiers:

* stub-replica tests — a minimal in-memory :class:`ReplicaHandle` gives
  precise control over answers/heartbeats, so routing, failover,
  watchdog, hedging, and duplicate-suppression logic are exercised in
  milliseconds;
* local-replica tests — real :class:`ScenarioService` instances behind
  :class:`LocalReplica` handles prove the routed path end-to-end in
  process (cpu backend);
* subprocess tests — a real ``serve`` replica process under the
  ``replica_crash`` fault drills the death-detection + journal-failover
  path against a genuinely unclean exit.
"""
import json
import pickle
import threading
import time

import numpy as np
import pytest

from dervet_tpu.benchlib import synthetic_sensitivity_cases
from dervet_tpu.ops.lp import LP
from dervet_tpu.ops.warmstart import SolutionMemory
from dervet_tpu.service import (FleetRouter, FleetUnavailableError,
                                LocalReplica, QueueFullError,
                                ScenarioClient, ScenarioService,
                                ServiceJournal)
from dervet_tpu.service.fleet import ReplicaHandle, structure_fingerprint
from dervet_tpu.utils import faultinject
from dervet_tpu.utils.breaker import CircuitBreaker


def _cases(n=1, window=None, months=1, variant=0):
    kwargs = {"months": months}
    if window is not None:
        kwargs["n"] = window
    cases = synthetic_sensitivity_cases(n, **kwargs)
    for c in cases:
        for tag, _, keys in c.ders:
            if tag == "Battery":
                keys["ene_max_rated"] = \
                    float(keys["ene_max_rated"]) + 0.5 * variant
    return {i: c for i, c in enumerate(cases)}


# ---------------------------------------------------------------------------
# Structure fingerprint (the affinity key)
# ---------------------------------------------------------------------------

class TestStructureFingerprint:
    def test_content_invariant(self):
        # different prices/ratings, same structure -> same fingerprint
        assert structure_fingerprint(_cases(variant=0)) == \
            structure_fingerprint(_cases(variant=7))

    def test_window_scheme_changes_it(self):
        assert structure_fingerprint(_cases(window=72)) != \
            structure_fingerprint(_cases(window=96))

    def test_der_set_changes_it(self):
        a = _cases()
        b = _cases()
        b[0].ders.pop()             # drop the PV
        assert structure_fingerprint(a) != structure_fingerprint(b)

    def test_horizon_changes_it(self):
        assert structure_fingerprint(_cases(months=1)) != \
            structure_fingerprint(_cases(months=2))


# ---------------------------------------------------------------------------
# Warm-start memory export/import (the failover handoff)
# ---------------------------------------------------------------------------

def _lp(seed=0, n=6, m=4):
    import scipy.sparse as sp
    rng = np.random.default_rng(seed)
    return LP(c=rng.normal(size=n),
              K=sp.csr_matrix(rng.normal(size=(m, n))),
              q=rng.normal(size=m), n_eq=2, l=np.full(n, -10.0),
              u=np.full(n, 10.0), var_refs={}, row_groups={})


class _Opts:
    eps_abs = 1e-4
    eps_rel = 1e-4
    max_iters = 1000
    inaccurate_factor = 10.0
    dtype = np.float32


class TestMemoryHandoff:
    def test_export_import_roundtrip_exact_grade(self):
        from dervet_tpu.ops.warmstart import data_digest, opts_tag
        mem = SolutionMemory(max_entries=16)
        lp = _lp()
        tag = opts_tag(_Opts)
        mem.store("s1", lp, tag, np.ones(lp.n), np.ones(lp.m), 1.0)
        blob = pickle.dumps(mem.export_entries())

        other = SolutionMemory(max_entries=16)
        assert other.import_entries(pickle.loads(blob)) == 1
        assert other.snapshot()["imported"] == 1
        entry, kind = other.lookup("s1", lp, tag)
        assert kind == "exact"
        np.testing.assert_array_equal(entry.x, np.ones(lp.n))
        # and the key carries the same digest the donor computed
        assert entry.exact == data_digest(lp, np.float32)

    def test_exact_only_import_invisible_to_near(self):
        from dervet_tpu.ops.warmstart import opts_tag
        mem = SolutionMemory(max_entries=16)
        tag = opts_tag(_Opts)
        mem.store("s1", _lp(seed=0), tag, np.ones(6), np.ones(4), 1.0)
        other = SolutionMemory(max_entries=16)
        other.import_entries(mem.export_entries())
        # a NEARBY (not byte-exact) instance must come back cold: a
        # near-grade seed from imported foreign data would shift the
        # re-solve's iterate path and break byte-identical failover
        entry, kind = other.lookup("s1", _lp(seed=1), tag)
        assert entry is None and kind is None
        # the donor itself WOULD near-seed it (its own entries are
        # fully indexed)
        _, kind_donor = mem.lookup("s1", _lp(seed=1), tag)
        assert kind_donor == "near"

    def test_import_skips_existing_and_malformed(self):
        from dervet_tpu.ops.warmstart import opts_tag
        mem = SolutionMemory(max_entries=16)
        tag = opts_tag(_Opts)
        mem.store("s1", _lp(), tag, np.ones(6), np.ones(4), 1.0)
        payload = mem.export_entries()
        assert mem.import_entries(payload) == 0       # already present
        assert SolutionMemory(max_entries=16).import_entries(
            [("garbage", {"nope": 1})] + payload) == 1

    def test_eviction_unlinks_imported(self):
        from dervet_tpu.ops.warmstart import opts_tag
        mem = SolutionMemory(max_entries=16)
        tag = opts_tag(_Opts)
        mem.store("s1", _lp(), tag, np.ones(6), np.ones(4), 1.0)
        tiny = SolutionMemory(max_entries=1)
        tiny.import_entries(mem.export_entries())
        assert tiny.snapshot()["imported_live"] == 1
        tiny.store("s2", _lp(seed=3), tag, np.ones(6), np.ones(4), 2.0)
        assert tiny.snapshot()["imported_live"] == 0   # evicted cleanly


# ---------------------------------------------------------------------------
# replica_crash / replica_hang fault kinds
# ---------------------------------------------------------------------------

class TestReplicaFaults:
    def test_env_knobs_parse_and_one_shot(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FAULT_REPLICA_CRASH", "3")
        monkeypatch.setenv("DERVET_TPU_FAULT_REPLICA_HANG", "2")
        monkeypatch.setenv("DERVET_TPU_FAULT_REPLICA_HANG_S", "0.01")
        plan = faultinject.get_plan()
        assert plan.replica_crash_after == 3
        assert plan.replica_hang_after == 2
        assert not plan.replica_crash_due(2)
        assert plan.replica_crash_due(3)
        assert not plan.replica_crash_due(4)          # one-shot
        assert plan.replica_hang_seconds_due(1) == 0.0
        assert plan.replica_hang_seconds_due(2) == 0.01
        assert plan.replica_hang_seconds_due(5) == 0.0  # one-shot
        # env-plan memo: the same plan object (with its latches) comes
        # back on the next hook call
        assert faultinject.get_plan() is plan
        assert [e for e, _ in plan.fired] == ["replica_crash",
                                              "replica_hang"]

    def test_hang_hook_sleeps(self):
        with faultinject.inject(replica_hang_after=1,
                                replica_hang_seconds=0.05):
            t0 = time.monotonic()
            assert faultinject.maybe_replica_hang(0) == 0.0
            assert faultinject.maybe_replica_hang(1) == 0.05
            assert time.monotonic() - t0 >= 0.05
        assert faultinject.maybe_replica_hang(9) == 0.0   # plan closed


# ---------------------------------------------------------------------------
# Journal: racing recoveries stay idempotent (the satellite drill)
# ---------------------------------------------------------------------------

class TestJournalRecoveryRace:
    def _spool(self, tmp_path):
        incoming = tmp_path / "incoming"
        done = tmp_path / "done"
        failed = tmp_path / "failed"
        for d in (incoming, done, failed):
            d.mkdir()
        return incoming, done, failed

    def test_concurrent_recover_spool_idempotent(self, tmp_path):
        """Router failover firing while the replica restarts: both replay
        the same journal concurrently.  The interrupted result move must
        finish exactly once and no request may be re-served twice."""
        incoming, done, failed = self._spool(tmp_path)
        jpath = tmp_path / "service_journal.jsonl"
        seed = ServiceJournal(jpath)
        # killed between journaling 'completed' and moving the file:
        seed.admitted("rid-done", "rid-done.pkl")
        seed.completed("rid-done")
        (incoming / "rid-done.pkl").write_bytes(b"payload")
        # killed mid-flight (admitted, no terminal): must be re-served
        seed.admitted("rid-open", "rid-open.pkl")
        (incoming / "rid-open.pkl").write_bytes(b"payload")
        seed.close()

        journals = [ServiceJournal(jpath) for _ in range(2)]
        outcomes = [None, None]
        barrier = threading.Barrier(2)

        def recover(i):
            barrier.wait()
            outcomes[i] = journals[i].recover_spool(incoming, done,
                                                    failed)

        threads = [threading.Thread(target=recover, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for j in journals:
            j.close()
        assert all(o is not None for o in outcomes), "a recovery crashed"
        # the interrupted move finished exactly once
        assert (done / "rid-done.pkl").exists()
        assert not (incoming / "rid-done.pkl").exists()
        assert sum("rid-done" in o["moved"] for o in outcomes) == 1
        # the in-flight request is re-servable (file untouched), and
        # both recoveries agree on that — re-serving is idempotent by
        # the atomic-rewrite contract, never a double answer
        assert (incoming / "rid-open.pkl").exists()
        assert all("rid-open" in o["reserve"] for o in outcomes)
        # a third recovery after the dust settles is a no-op move-wise
        j3 = ServiceJournal(jpath)
        assert j3.recover_spool(incoming, done, failed)["moved"] == []
        j3.close()

    def test_cancelled_state_removal_replayed(self, tmp_path):
        incoming, done, failed = self._spool(tmp_path)
        j = ServiceJournal(tmp_path / "service_journal.jsonl")
        j.admitted("hedge-loser", "hedge-loser.pkl")
        j.note("cancelled", "hedge-loser", file="hedge-loser.pkl")
        (incoming / "hedge-loser.pkl").write_bytes(b"payload")
        out = j.recover_spool(incoming, done, failed)
        j.close()
        # the kill landed between journaling the cancel and unlinking:
        # recovery finishes the removal instead of re-serving the loser
        assert not (incoming / "hedge-loser.pkl").exists()
        assert "hedge-loser" not in out["reserve"]

    def test_note_events_and_replay_path(self, tmp_path):
        jpath = tmp_path / "j.jsonl"
        j = ServiceJournal(jpath)
        j.note("routed", "r1", replica="a")
        j.note("rerouted", "r1", to="b")
        j.completed("r1")
        j.close()
        states = ServiceJournal.replay_path(jpath)
        assert states["r1"]["state"] == "completed"
        lines = [json.loads(ln) for ln in
                 jpath.read_text().splitlines()]
        assert [ln["event"] for ln in lines] == ["routed", "rerouted",
                                                 "completed"]
        assert lines[1]["to"] == "b"


# ---------------------------------------------------------------------------
# Router logic against stub replicas
# ---------------------------------------------------------------------------

class StubReplica(ReplicaHandle):
    """Scripted replica: answers/heartbeats under test control."""

    def __init__(self, name, reject_with=None):
        super().__init__(name)
        self.reqs = {}
        self.answers = {}
        self.beating = True
        self.reject_with = reject_with      # raise on submit when set
        self.reject_count = 0
        self.cancelled = []
        self.retracted = []
        self.probes = []
        self.imported = []
        self.export = None

    def submit(self, cases, rid, *, priority=0, deadline_epoch=None,
               payload=None, trace_ctx=None):
        if self.reject_with is not None:
            self.reject_count += 1
            raise QueueFullError("stub full",
                                 retry_after_s=self.reject_with)
        self.reqs[rid] = cases

    def poll(self, rid):
        return self.answers.get(rid)

    def heartbeat(self):
        if not self.beating:
            return None
        hb = {"t": time.time(), "name": self.name}
        if self.probes:
            hb["probe_nonce"] = self.probes[-1]
        return hb

    def probe(self, nonce, trace=None):
        self.probes.append(nonce)

    def cancel(self, rid):
        self.cancelled.append(rid)

    def retract(self, rid):
        self.retracted.append(rid)
        self.reqs.pop(rid, None)

    def read_memory_export(self):
        return self.export

    def import_memory(self, blob):
        self.imported.append(blob)


def _router(reps, **kw):
    kw.setdefault("heartbeat_timeout_s", 0.4)
    kw.setdefault("tick_s", 0.02)
    kw.setdefault("startup_grace_s", 5.0)
    return FleetRouter(reps, **kw).start()


def _wait(pred, timeout=10.0, msg="condition not reached"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(msg)


CASES = None


def _stub_cases():
    # one shared case dict: fingerprinting only reads it, and building
    # synthetic frames per test is the slow part
    global CASES
    if CASES is None:
        CASES = _cases()
    return CASES


class TestRouterRouting:
    def test_affinity_sticks_and_counts(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _router([a, b])
        try:
            r.submit(_stub_cases(), request_id="x1")
            first = "a" if "x1" in a.reqs else "b"
            r.submit(_stub_cases(), request_id="x2")
            # same structure fingerprint -> same replica, even though
            # the other one is now less loaded
            assert ("x2" in (a if first == "a" else b).reqs)
            m = r.metrics()["routing"]
            assert m["affinity_hits"] == 1
            assert m["affinity_misses"] == 1
        finally:
            r.close(terminate_replicas=False)

    def test_least_loaded_fallback_when_affinity_full(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _router([a, b], max_inflight_per_replica=1)
        try:
            r.submit(_stub_cases(), request_id="x1")
            loaded = a if "x1" in a.reqs else b
            other = b if loaded is a else a
            # affinity replica at its inflight bound -> least-loaded
            r.submit(_stub_cases(), request_id="x2")
            assert "x2" in other.reqs
        finally:
            r.close(terminate_replicas=False)

    def test_queue_full_redirects_to_next_replica(self):
        a = StubReplica("a", reject_with=3.0)
        b = StubReplica("b")
        r = _router([a, b])
        try:
            r.submit(_stub_cases(), request_id="x1")
            assert "x1" in b.reqs
            assert r.metrics()["routing"]["redirects"] >= 1
        finally:
            r.close(terminate_replicas=False)

    def test_all_full_propagates_min_retry_hint(self):
        a = StubReplica("a", reject_with=7.0)
        b = StubReplica("b", reject_with=3.0)
        r = _router([a, b])
        try:
            with pytest.raises(FleetUnavailableError) as ei:
                r.submit(_stub_cases(), request_id="x1")
            # the hint survives the routing hop: the SMALLEST per-
            # replica drain-rate hint, and the typed error is still a
            # QueueFullError so client backoff discipline applies
            assert ei.value.retry_after_s == 3.0
            assert isinstance(ei.value, QueueFullError)
        finally:
            r.close(terminate_replicas=False)

    def test_client_retry_discipline_through_router(self):
        a = StubReplica("a", reject_with=0.02)
        b = StubReplica("b", reject_with=0.02)
        r = _router([a, b])
        try:
            client = ScenarioClient(r, max_retries=8, jitter_seed=7)

            def release():
                time.sleep(0.03)
                a.reject_with = None

            threading.Thread(target=release).start()
            fut = client.submit(_stub_cases(), request_id="x1")
            assert "x1" in a.reqs and fut is not None
            # and the backoff the client slept was the router's hint,
            # capped + jittered within +/-25%
            hint = 0.02
            w = ScenarioClient(r, jitter_seed=7)._backoff_s(hint)
            assert 0.75 * hint <= w <= 1.25 * hint
            # seeded determinism
            assert w == ScenarioClient(r, jitter_seed=7)._backoff_s(hint)
        finally:
            r.close(terminate_replicas=False)

    def test_rid_reuse_rejected(self):
        a = StubReplica("a")
        r = _router([a])
        try:
            r.submit(_stub_cases(), request_id="x1")
            a.answers["x1"] = ("done", object())
            with pytest.raises(ValueError, match="already routed"):
                r.submit(_stub_cases(), request_id="x1")
        finally:
            r.close(terminate_replicas=False)

    def test_no_healthy_replica_is_typed(self):
        a = StubReplica("a")
        a.state = "dead"
        a.beating = False       # a beating "dead" replica resurrects
        r = _router([a])
        try:
            with pytest.raises(FleetUnavailableError):
                r.submit(_stub_cases(), request_id="x1")
        finally:
            r.close(terminate_replicas=False)


class TestRouterFailover:
    def test_heartbeat_death_reroutes_exactly_once(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _router([a, b])
        try:
            fut = r.submit(_stub_cases(), request_id="x1")
            victim = a if "x1" in a.reqs else b
            other = b if victim is a else a
            victim.export = b"fake-memory-blob"
            victim.beating = False
            _wait(lambda: "x1" in other.reqs, msg="not rerouted")
            # fencing + memory handoff happened
            assert "x1" in victim.retracted
            assert other.imported == [b"fake-memory-blob"]
            other.answers["x1"] = ("done", object())
            res = fut.result(timeout=5)
            assert res.recovered and res.replica == other.name
            m = r.metrics()
            assert m["routing"]["failovers"] == 1
            assert m["routing"]["rerouted"] == 1
            assert m["routing"]["memory_handoffs"] == 1
            assert m["replicas"][victim.name]["state"] == "dead"
            assert m["replicas"][victim.name]["breaker"]["state"] == \
                "open"
            assert m["failover_latency_s"]["n"] == 1
        finally:
            r.close(terminate_replicas=False)

    def test_dead_replicas_completed_answer_is_harvested(self):
        """Kill between answering and the router noticing: the journal/
        spool already holds the result — harvest it, never re-solve."""
        a, b = StubReplica("a"), StubReplica("b")
        r = _router([a, b])
        try:
            fut = r.submit(_stub_cases(), request_id="x1")
            victim = a if "x1" in a.reqs else b
            answer = object()
            victim.answers["x1"] = ("done", answer)
            victim.beating = False
            res = fut.result(timeout=5)
            assert res.result is answer
            m = r.metrics()["routing"]
            # harvested (if death won the race) or plainly completed (if
            # the poller read the answer first) — never both, never zero
            assert m["completed"] == 1
            assert m["rerouted"] == 0
        finally:
            r.close(terminate_replicas=False)

    def test_watchdog_reroutes_wedged_request(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _router([a, b], request_timeout_s=0.2)
        try:
            fut = r.submit(_stub_cases(), request_id="x1")
            primary = a if "x1" in a.reqs else b
            other = b if primary is a else a
            # primary heartbeats happily but never answers: only the
            # per-request watchdog can see this
            _wait(lambda: "x1" in other.reqs, msg="watchdog never fired")
            assert primary.beating
            other.answers["x1"] = ("done", object())
            res = fut.result(timeout=5)
            assert res.replica == other.name
            m = r.metrics()
            assert m["routing"]["watchdog_reroutes"] == 1
            # the wedged replica took a breaker failure sample
            assert m["replicas"][primary.name]["breaker"]["samples"] >= 1
        finally:
            r.close(terminate_replicas=False)

    def test_late_duplicate_suppressed_first_answer_wins(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _router([a, b], request_timeout_s=0.2)
        try:
            fut = r.submit(_stub_cases(), request_id="x1")
            primary = a if "x1" in a.reqs else b
            other = b if primary is a else a
            _wait(lambda: "x1" in other.reqs, msg="watchdog never fired")
            first = object()
            other.answers["x1"] = ("done", first)
            res = fut.result(timeout=5)
            assert res.result is first
            # the wedged primary finally answers: suppressed, counted
            primary.answers["x1"] = ("done", object())
            _wait(lambda: r.metrics()["routing"][
                "duplicates_suppressed"] == 1,
                msg="late duplicate not counted")
            assert fut.result(timeout=0).result is first
        finally:
            r.close(terminate_replicas=False)

    def test_probe_closes_breaker_after_flap(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _router([a, b], request_timeout_s=0.1,
                    breaker_opts={"min_samples": 1,
                                  "failure_threshold": 0.5,
                                  "cooldown_s": 0.2})
        try:
            fut = r.submit(_stub_cases(), request_id="x1")
            primary = a if "x1" in a.reqs else b
            other = b if primary is a else a
            _wait(lambda: "x1" in other.reqs, msg="watchdog never fired")
            other.answers["x1"] = ("done", object())
            fut.result(timeout=5)
            # the flapping replica's breaker opened on the watchdog
            # failure; it keeps heartbeating, so after the cooldown the
            # router probes it (nonce echo, no solve) and closes
            br = r.breakers.get(primary.name)
            _wait(lambda: br.state == CircuitBreaker.CLOSED,
                  msg="probe never closed the breaker")
            assert primary.probes, "no probe nonce was sent"
            assert r.metrics()["routing"]["probes_ok"] >= 1
        finally:
            r.close(terminate_replicas=False)


class EpochStubReplica(StubReplica):
    """Stub whose heartbeats carry a controllable incarnation epoch —
    the handle's `epoch` is what it was spawned with, `hb_epoch` is
    what its beats claim (split so tests can play a zombie process
    beating with a fenced epoch, then a replacement beating above it)."""

    def __init__(self, name, epoch=None):
        super().__init__(name)
        self.epoch = epoch
        self.hb_epoch = epoch

    def heartbeat(self):
        hb = super().heartbeat()
        if hb is not None and self.hb_epoch is not None:
            hb["epoch"] = self.hb_epoch
        return hb


class TestEpochFence:
    """The satellite drill: `_declare_dead` re-route racing a
    concurrent same-named respawn.  The heartbeat-epoch fence must
    reject the stale incarnation's late beats and answers while the
    replacement (strictly higher epoch) earns routing back — exactly
    one answer ever reaches the caller."""

    def test_zombie_beats_at_fence_epoch_never_resurrect(self):
        a = EpochStubReplica("a", epoch=1)
        b = EpochStubReplica("b", epoch=1)
        r = _router([a, b])
        try:
            fut = r.submit(_stub_cases(), request_id="x1")
            victim = a if "x1" in a.reqs else b
            other = b if victim is a else a
            victim.beating = False
            _wait(lambda: "x1" in other.reqs, msg="not rerouted")
            assert victim.state == "dead"
            # declare-dead armed the fence at the corpse's incarnation
            assert victim.fence_epoch == 1
            # the zombie wakes up and resumes beating with its OWN
            # (fenced) epoch: the beats are discredited wholesale — no
            # liveness credit, no resurrection, routing stays closed
            victim.beating = True
            time.sleep(0.2)
            assert victim.state == "dead"
            assert r._hb_cache[victim.name] is None
            # its late answer is inert (the route was resolved at
            # failover) — only the re-routed sibling delivers
            victim.answers["x1"] = ("done", object())
            real = object()
            other.answers["x1"] = ("done", real)
            res = fut.result(timeout=5)
            assert res.result is real and res.replica == other.name
            m = r.metrics()["routing"]
            assert m["rerouted"] == 1
            # a replacement incarnation beating ABOVE the fence is the
            # only thing that resurrects the name — and it disarms it
            victim.hb_epoch = 2
            _wait(lambda: victim.state == "up",
                  msg="replacement epoch never resurrected the name")
            assert victim.fence_epoch is None
        finally:
            r.close(terminate_replicas=False)

    def test_respawn_race_no_double_delivery(self):
        """Replacement handle adopted DURING the failover window, same
        name, epoch bumped past the fence: the stale process's answer
        can never be delivered and the caller sees exactly one result."""
        a = EpochStubReplica("a", epoch=1)
        b = EpochStubReplica("b", epoch=1)
        r = _router([a, b])
        try:
            fut = r.submit(_stub_cases(), request_id="x1")
            victim = a if "x1" in a.reqs else b
            other = b if victim is a else a
            victim.beating = False
            _wait(lambda: "x1" in other.reqs, msg="not rerouted")
            # supervisor respawn lands mid-flight: same name, epoch+1
            repl = EpochStubReplica(victim.name, epoch=2)
            r.adopt_replica(repl)
            assert r.replicas[victim.name] is repl
            # replacement re-proves liveness from scratch (fresh grace)
            assert r._first_seen[victim.name] is None
            # the zombie answers late through its orphaned handle: it
            # is no longer registered or polled — no double delivery
            victim.beating = True
            victim.answers["x1"] = ("done", object())
            real = object()
            other.answers["x1"] = ("done", real)
            res = fut.result(timeout=5)
            assert res.result is real and res.replica == other.name
            m = r.metrics()["routing"]
            assert m["rerouted"] == 1
            assert m["duplicates_suppressed"] == 0
            # the replacement's own fresh beats earn it back into the
            # routable set
            _wait(lambda: r._first_seen[repl.name] is not None,
                  msg="replacement's beats never credited")
            assert r.replicas[repl.name].state == "up"
        finally:
            r.close(terminate_replicas=False)

    def test_spool_epoch_filter_discredits_stale_beats(self, tmp_path):
        """SpoolReplica path: a heartbeat.json written by an older
        incarnation over the shared spool (epoch below the handle's) is
        discredited entirely; the matching epoch restores credit."""
        from dervet_tpu.service.fleet import HEARTBEAT_FILE, SpoolReplica
        spool = tmp_path / "r0"
        h = SpoolReplica("r0", spool)
        h.epoch = 2
        r = _router([h])
        try:
            def beat(epoch):
                tmp = spool / f".{HEARTBEAT_FILE}.tmp"
                tmp.write_text(json.dumps(
                    {"t": time.time(), "name": "r0", "epoch": epoch}))
                tmp.replace(spool / HEARTBEAT_FILE)

            beat(1)                 # the fenced predecessor's late write
            time.sleep(0.2)
            assert r._hb_cache["r0"] is None
            assert r._first_seen["r0"] is None
            beat(2)                 # the real incarnation announces
            _wait(lambda: r._first_seen["r0"] is not None,
                  msg="current-epoch beat never credited")
            assert r._hb_cache["r0"]["epoch"] == 2
        finally:
            r.close(terminate_replicas=False)


class TestRouterHedging:
    def test_deadline_pressure_hedges_first_answer_wins(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _router([a, b], hedge_min_wait_s=0.1, hedge_wait_frac=0.01)
        try:
            fut = r.submit(_stub_cases(), request_id="x1",
                           deadline_s=30.0)
            primary = a if "x1" in a.reqs else b
            other = b if primary is a else a
            _wait(lambda: "x1" in other.reqs, msg="hedge never fired")
            m = r.metrics()["routing"]
            assert m["hedged"] == 1
            # hedge answers first -> it wins, the loser gets a cancel
            other.answers["x1"] = ("done", object())
            res = fut.result(timeout=5)
            assert res.hedged and res.replica == other.name
            _wait(lambda: "x1" in primary.cancelled,
                  msg="loser never cancelled")
            assert r.metrics()["routing"]["hedge_wins"] == 1
            # loser answers anyway at its round boundary: suppressed
            primary.answers["x1"] = ("done", object())
            _wait(lambda: r.metrics()["routing"][
                "duplicates_suppressed"] == 1,
                msg="hedge loser's answer not suppressed")
        finally:
            r.close(terminate_replicas=False)

    def test_no_hedge_without_deadline(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _router([a, b], hedge_min_wait_s=0.05, hedge_wait_frac=0.01)
        try:
            r.submit(_stub_cases(), request_id="x1")
            time.sleep(0.3)
            assert r.metrics()["routing"]["hedged"] == 0
            assert len(a.reqs) + len(b.reqs) == 1
        finally:
            r.close(terminate_replicas=False)


# ---------------------------------------------------------------------------
# Local-replica (real ScenarioService) end-to-end
# ---------------------------------------------------------------------------

class TestLocalFleet:
    def _fleet(self, n=2, **router_kw):
        services = [ScenarioService(backend="cpu", max_wait_s=0.0)
                    for _ in range(n)]
        for s in services:
            s.start()
        reps = [LocalReplica(f"n{i}", s)
                for i, s in enumerate(services)]
        router = _router(reps, heartbeat_timeout_s=1.0, **router_kw)
        return router, reps, services

    def test_routed_solve_end_to_end(self):
        router, reps, services = self._fleet()
        try:
            fut = router.submit(_cases(), request_id="e1")
            res = fut.result(timeout=300)
            assert res.result is not None
            cert = res.load_run_health()["certification"]
            assert cert["enabled"] and cert["windows_certified"] > 0
            assert res.latency_s > 0
        finally:
            router.close(terminate_replicas=False)
            for s in services:
                s.close()

    def test_kill_mid_flight_recovers_on_sibling(self):
        router, reps, services = self._fleet()
        try:
            fut = router.submit(_cases(), request_id="e1")
            victim = next(rep for rep in reps if "e1" in rep._futures)
            # the service keeps solving (a hung-not-dead replica), but
            # its heartbeats stop: the router must not wait for it
            victim.kill()
            res = fut.result(timeout=300)
            assert res.result is not None
            m = router.metrics()["routing"]
            assert m["completed"] == 1
            # either the sibling solved it (reroute) or the victim's
            # answer landed before death was declared (harvest-or-
            # normal) — exactly one delivery either way
            assert m["failovers"] >= 1
        finally:
            router.close(terminate_replicas=False)
            for s in services:
                s.close()

    def test_overload_redirect_with_real_services(self):
        router, reps, services = self._fleet()
        try:
            # the first admission is rejected by the overload fault
            # (queue-full shape, real drain-rate hint); the router
            # redirects to the sibling and the request still completes
            with faultinject.inject(overload=True, overload_n=1):
                fut = router.submit(_cases(), request_id="e1")
            res = fut.result(timeout=300)
            assert res.result is not None
            assert router.metrics()["routing"]["redirects"] == 1
        finally:
            router.close(terminate_replicas=False)
            for s in services:
                s.close()


# ---------------------------------------------------------------------------
# Subprocess spool replicas: the replica_crash fault drill
# ---------------------------------------------------------------------------

class TestSpoolFleet:
    def test_replica_crash_failover_exactly_once(self, tmp_path):
        """A real serve process hard-exits (os._exit — the SIGKILL
        analogue) right after journaling its first admission.  The
        router must detect the death, replay the journal, re-route the
        orphaned request to the healthy replica, and deliver exactly
        one certified answer."""
        from dervet_tpu.service import spawn_replica
        logs = [open(tmp_path / f"r{i}.log", "w") for i in range(2)]
        victim = spawn_replica(
            tmp_path / "victim", name="victim", backend="cpu",
            stdout=logs[0], stderr=logs[0],
            env={"DERVET_TPU_FAULT_REPLICA_CRASH": "1"})
        healthy = spawn_replica(
            tmp_path / "healthy", name="healthy", backend="cpu",
            stdout=logs[1], stderr=logs[1])
        router = FleetRouter(
            [victim, healthy], fleet_dir=tmp_path / "fleet",
            heartbeat_timeout_s=3.0, tick_s=0.05,
            # force the primary route onto the crashing replica
            max_inflight_per_replica=32).start()
        try:
            # two DISTINCT structures so affinity cannot pile both onto
            # one replica: least-loaded puts c0 on 'healthy' (name
            # order), c1 on 'victim' — whose first admission crashes it
            futs = {
                "c0": router.submit(_cases(), request_id="c0",
                                    deadline_s=300.0),
                "c1": router.submit(_cases(window=96, variant=1),
                                    request_id="c1", deadline_s=300.0),
            }
            results = {rid: fut.result(timeout=280)
                       for rid, fut in futs.items()}
            m = router.metrics()
            r = m["routing"]
            assert r["completed"] == 2 and r["failed"] == 0
            assert r["failovers"] == 1, r
            assert r["harvested"] + r["rerouted"] >= 1, r
            assert m["replicas"]["victim"]["state"] == "dead"
            assert m["replicas"]["victim"]["breaker"]["state"] == "open"
            recovered = [rid for rid, res in results.items()
                         if res.recovered]
            assert recovered, "crash produced no recovered request"
            for rid, res in results.items():
                cert = res.load_run_health()["certification"]
                assert cert["enabled"]
                assert cert["windows"]["rejected_final"] == 0
            # the victim's journal shows the orphaned admission the
            # failover recovered
            states = ServiceJournal.replay_path(
                tmp_path / "victim" / "service_journal.jsonl")
            assert any(e["state"] == "admitted"
                       for e in states.values())
            # failover-drill trace contract: the harvested/re-routed
            # request yields ONE stitched trace (router slice + both
            # replicas' exports merge under the router root) carrying
            # the fence event plus harvest or re-route on the timeline
            from dervet_tpu.telemetry import trace as ttrace
            from dervet_tpu.telemetry.ops import load_stitched_trace
            rid = recovered[0]
            spans = load_stitched_trace(rid, [tmp_path])
            report = ttrace.validate_trace(spans)
            assert report["n_spans"] >= 3, spans
            assert report["root"]["name"] == "fleet_request"
            events = [e["name"] for s in spans
                      for e in s.get("events") or ()]
            assert "fence" in events, events
            assert "harvest" in events or "reroute" in events, events
            # the un-recovered request's trace must NOT carry failover
            # events — fencing is attributed per request, not fleet-wide
            other = next(r for r in results if r != rid)
            other_events = [
                e["name"] for s in load_stitched_trace(other, [tmp_path])
                for e in s.get("events") or ()]
            assert "reroute" not in other_events, other_events
        finally:
            router.close()
            for lg in logs:
                lg.close()

    @pytest.mark.slow
    def test_replica_hang_detected_by_missed_heartbeats(self, tmp_path):
        """The serve scan loop wedges (heartbeats stop, process alive):
        the router's staleness watchdog must fail over just like a
        crash."""
        from dervet_tpu.service import spawn_replica
        logs = [open(tmp_path / f"r{i}.log", "w") for i in range(2)]
        hanger = spawn_replica(
            tmp_path / "hanger", name="hanger", backend="cpu",
            stdout=logs[0], stderr=logs[0],
            env={"DERVET_TPU_FAULT_REPLICA_HANG": "1",
                 "DERVET_TPU_FAULT_REPLICA_HANG_S": "3600"})
        healthy = spawn_replica(
            tmp_path / "healthy", name="healthy", backend="cpu",
            stdout=logs[1], stderr=logs[1])
        router = FleetRouter(
            [hanger, healthy], fleet_dir=tmp_path / "fleet",
            heartbeat_timeout_s=2.0, tick_s=0.05).start()
        try:
            futs = {f"h{i}": router.submit(_cases(variant=i),
                                           request_id=f"h{i}",
                                           deadline_s=300.0)
                    for i in range(2)}
            results = {rid: fut.result(timeout=280)
                       for rid, fut in futs.items()}
            assert router.metrics()["routing"]["completed"] == 2
            assert all(res.results_dir is not None
                       for res in results.values())
            # the hanger's BATCHER thread may outrace the 2s staleness
            # window and answer before death is declared (the scan
            # thread is what wedged) — the drill's claim is that the
            # wedged replica is EVENTUALLY declared dead and fenced
            _wait(lambda: router.metrics()["replicas"]["hanger"][
                "state"] == "dead", timeout=30,
                msg="hung replica never declared dead")
            # SIGKILL fencing reaped the hung-but-alive process
            _wait(lambda: hanger.process.poll() is not None, timeout=30,
                  msg="hung replica process never fenced")
        finally:
            router.close()
            for lg in logs:
                lg.close()
