"""Multi-device sharding tests on the 8-virtual-device CPU mesh.

Validates the SURVEY.md §2.10 commitment: scenario-axis shard_map over a
device mesh with psum'd convergence stats, results identical to the
unsharded vmap path.
"""
import jax
import numpy as np
import pytest

from dervet_tpu.ops import CompiledLPSolver, LPBuilder, PDHGOptions
from dervet_tpu.parallel import scenario_mesh, solve_batch_sharded
from tests.test_pdhg import battery_like_lp


@pytest.fixture(scope="module")
def solver():
    return CompiledLPSolver(battery_like_lp(T=48))


def _price_batch(lp, B, seed=11):
    rng = np.random.default_rng(seed)
    prices = rng.uniform(5, 100, (B, 48)) / 1000
    c_b = np.zeros((B, lp.n))
    for i in range(B):
        c_b[i, lp.var_refs["ch"].sl] = prices[i]
        c_b[i, lp.var_refs["dis"].sl] = -prices[i]
    return c_b


def test_eight_devices_available():
    assert len(jax.devices()) >= 8


def test_sharded_matches_unsharded(solver):
    lp = solver.lp
    B = 16
    c_b = _price_batch(lp, B)
    mesh = scenario_mesh(8)
    res_sh, stats = solve_batch_sharded(solver, mesh, c=c_b)
    res_un = solver.solve(c=c_b)
    assert res_sh.x.shape == (B, lp.n)
    np.testing.assert_allclose(np.asarray(res_sh.obj), np.asarray(res_un.obj),
                               rtol=1e-5, atol=1e-4)
    assert int(stats.n_converged) == B
    assert bool(np.all(np.asarray(res_sh.converged)))


def test_sharded_pads_uneven_batch(solver):
    lp = solver.lp
    B = 11  # not a multiple of 8
    c_b = _price_batch(lp, B, seed=5)
    mesh = scenario_mesh(8)
    res_sh, stats = solve_batch_sharded(solver, mesh, c=c_b)
    assert res_sh.x.shape == (B, lp.n)
    res_un = solver.solve(c=c_b)
    np.testing.assert_allclose(np.asarray(res_sh.obj), np.asarray(res_un.obj),
                               rtol=1e-5, atol=1e-4)


def test_smaller_mesh(solver):
    lp = solver.lp
    c_b = _price_batch(lp, 4, seed=9)
    mesh = scenario_mesh(2)
    res_sh, _ = solve_batch_sharded(solver, mesh, c=c_b)
    res_un = solver.solve(c=c_b)
    np.testing.assert_allclose(np.asarray(res_sh.obj), np.asarray(res_un.obj),
                               rtol=1e-5, atol=1e-4)


class TestTimeSharding:
    """Row(time)-axis sharding of ONE large LP (SURVEY §2.10 TP/SP row):
    sharded solve must match the unsharded solver and HiGHS."""

    @pytest.fixture(scope="class")
    def lp(self):
        return battery_like_lp(T=96)

    def test_time_sharded_matches_unsharded(self, lp):
        from dervet_tpu.parallel.timeshard import (TimeShardedLPSolver,
                                                   time_mesh)
        mesh = time_mesh(8)
        res_sh = TimeShardedLPSolver(lp, mesh).solve()
        assert bool(np.asarray(res_sh.converged))
        res = CompiledLPSolver(lp).solve()
        obj_sh = float(np.asarray(res_sh.obj))
        obj = float(np.asarray(res.obj))
        scale = max(1.0, abs(obj))
        assert abs(obj_sh - obj) / scale < 5e-4
        # primal iterates agree (both converged to tolerance)
        x_sh = np.asarray(res_sh.x)
        x = np.asarray(res.x)
        assert np.max(np.abs(x_sh - x)) / max(1.0, np.abs(x).max()) < 5e-3
        # dual vector has the original (unpadded) length
        assert res_sh.y.shape == (lp.m,)

    def test_time_sharded_vs_highs(self, lp):
        from dervet_tpu.ops.cpu_ref import solve_lp_cpu
        from dervet_tpu.parallel.timeshard import (TimeShardedLPSolver,
                                                   time_mesh)
        res_sh = TimeShardedLPSolver(lp, time_mesh(8)).solve()
        ref = solve_lp_cpu(lp)
        obj_sh = float(np.asarray(res_sh.obj))
        assert abs(obj_sh - ref.obj) / max(1.0, abs(ref.obj)) < 2e-3


def test_monte_carlo_multi_der_sharded():
    """BASELINE config 5 shape (virtualized): Monte-Carlo price draws x
    multi-DER microgrid (Battery+PV+ICE+CHP, thermal balance), sharded
    over the 8-device mesh; stats psum across devices and every draw
    solves the same LP the unsharded path solves."""
    from dervet_tpu.benchlib import (build_window_lps,
                                     scenario_price_batch, synthetic_case)

    case = synthetic_case(multi_der=True)
    scen, groups = build_window_lps(case)
    T = min(groups)                       # smallest month for speed
    lp = groups[T][0]
    C = scenario_price_batch(lp, 16, seed=23)
    solver = CompiledLPSolver(lp, PDHGOptions())
    mesh = scenario_mesh(8)
    res_sh, stats = solve_batch_sharded(solver, mesh, c=C)
    res = solver.solve(c=C)
    assert int(stats.n_converged) == 16
    np.testing.assert_allclose(np.asarray(res_sh.obj), np.asarray(res.obj),
                               rtol=2e-4, atol=1e-3)


class TestCrossCaseBatching:
    """VERDICT r2 #3: sensitivity cases batch their same-structure windows
    into shared device calls (sharded over the 8-device CPU mesh here), and
    the batched results equal the serial per-case path."""

    @pytest.fixture(scope="class")
    def batched(self):
        from pathlib import Path
        from dervet_tpu.api import DERVET
        REF = Path("/root/reference")
        d = DERVET(REF / "test/test_storagevet_features/model_params/"
                   "009-bat_energy_sensitivity.csv", base_path=REF)
        return d.solve(backend="jax")

    def test_four_cases_batch_into_shared_groups(self, batched):
        insts = batched.instances
        assert len(insts) == 4
        for inst in insts.values():
            meta = inst.scenario.solve_metadata
            # 4 cases x 12 monthly windows collapse into the 3 month-length
            # structure groups (31/30/28 days) DISPATCH-WIDE — if cross-case
            # sharing broke (e.g. the swept parameter started entering K),
            # this would read 12 per-case groups instead
            assert meta["dispatch_groups_total"] == 3, meta
            assert meta["structure_groups_total"] == 3, meta
            assert meta["n_windows"] == 12

    def test_batched_matches_serial_cpu(self, batched):
        from pathlib import Path
        from dervet_tpu.io.params import Params
        from dervet_tpu.scenario.scenario import MicrogridScenario
        REF = Path("/root/reference")
        cases = Params.initialize(
            REF / "test/test_storagevet_features/model_params/"
            "009-bat_energy_sensitivity.csv", base_path=REF)
        for key, inst in batched.instances.items():
            serial = MicrogridScenario(cases[key])
            serial.optimize_problem_loop(backend="cpu")
            oj = inst.scenario.objective_values
            oc = serial.objective_values
            assert set(oj) == set(oc)
            for k in oj:
                a = oj[k]["Total Objective"]
                b = oc[k]["Total Objective"]
                assert abs(a - b) / max(abs(b), 1.0) < 1e-3, (key, k, a, b)


@pytest.mark.slow
class TestLargeShardedDispatch:
    """VERDICT r5 weak #5: the serialized-sharded-solves x dispatch-
    pipeline interaction (scenario.py solve_group -> solve_batch_sharded
    under the pipeline's single in-flight worker) stressed at HUNDREDS of
    window-LP instances over multiple structure groups on the 8-device
    mesh — not the 4-case touch the default suite gives it."""

    N_CASES = 26     # x 12 monthly windows = 312 window-LPs, 3 groups

    def test_hundreds_of_instances_through_pipeline(self, monkeypatch):
        from dervet_tpu.benchlib import (synthetic_sensitivity_cases,
                                         validate_solve_ledger)
        from dervet_tpu.scenario.scenario import (MicrogridScenario,
                                                  run_dispatch)
        # this test exercises the SHARDED solve x pipeline interaction
        # specifically — the elastic scheduler (its own test file) would
        # route these groups to per-device solves instead
        monkeypatch.setenv("DERVET_TPU_ELASTIC", "0")
        scens = [MicrogridScenario(c)
                 for c in synthetic_sensitivity_cases(self.N_CASES)]
        run_dispatch(scens, backend="jax")
        meta = scens[0].solve_metadata
        assert meta["dispatch_groups_total"] == 3
        led = validate_solve_ledger(meta["solve_ledger"])
        assert led["pipeline"] is True
        initial = [g for g in led["groups"] if g.get("rung") == "initial"]
        assert sum(g["batch"] for g in initial) == self.N_CASES * 12
        # every batched group actually rode the sharded path
        assert all(g["sharded"] for g in initial if g["batch"] > 1)
        # spot-check three cases against the serial exact CPU path
        fresh = synthetic_sensitivity_cases(self.N_CASES)
        for i in (0, self.N_CASES // 2, self.N_CASES - 1):
            serial = MicrogridScenario(fresh[i])
            serial.optimize_problem_loop(backend="cpu")
            oj = scens[i].objective_values
            oc = serial.objective_values
            assert set(oj) == set(oc) and len(oj) == 12
            for k in oj:
                a = oj[k]["Total Objective"]
                b = oc[k]["Total Objective"]
                assert abs(a - b) / max(abs(b), 1.0) < 1e-3, (i, k, a, b)


@pytest.mark.slow
def test_dervet_solve_large_sharded_fanout(tmp_path):
    """The same stress through the PRODUCT entry point: a 32-case
    Sensitivity-Parameters fan-out (384 window-LPs, 3 groups) through
    ``DERVET.solve(backend="jax")`` on the mesh, NPV-gated per case
    against the serial CPU path and publishing a valid solve ledger."""
    from pathlib import Path

    REF = Path("/root/reference")
    src = REF / ("test/test_storagevet_features/model_params/"
                 "000-DA_battery_month.csv")
    if not src.exists():
        pytest.skip("reference input not available")
    from dervet_tpu.api import DERVET
    from dervet_tpu.benchlib import (validate_solve_ledger,
                                     widen_sensitivity_csv)

    n_cases = 32
    mp = widen_sensitivity_csv(src, tmp_path / "mp_large_fanout.csv",
                               n_cases)
    res_j = DERVET(mp, base_path=REF).solve(backend="jax")
    assert len(res_j.instances) == n_cases
    led = validate_solve_ledger(res_j.solve_ledger)
    assert led["totals"]["windows"] >= n_cases * 12
    res_c = DERVET(mp, base_path=REF).solve(backend="cpu")
    for key in res_c.instances:
        nc = float(res_c.instances[key].npv_df[
            "Lifetime Present Value"].iloc[0])
        nj = float(res_j.instances[key].npv_df[
            "Lifetime Present Value"].iloc[0])
        assert abs(nj - nc) / max(1.0, abs(nc)) < 1e-2, key
