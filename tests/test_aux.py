"""Auxiliary subsystems: checkpoint/resume, drill-down maps, input echo
(SURVEY §5: checkpointing is an addition over the reference; drill-down
CSVs match the reference output set §2.7)."""
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.io.params import Params
from dervet_tpu.scenario.scenario import MicrogridScenario

REF = Path("/root/reference")
CASE_000 = REF / "test/test_storagevet_features/model_params/000-DA_battery_month.csv"


def test_checkpoint_resume(tmp_path):
    cases = Params.initialize(CASE_000, base_path=REF)
    s = MicrogridScenario(cases[0])
    s.optimize_problem_loop(backend="cpu", checkpoint_dir=tmp_path)
    full = s.timeseries_results()
    assert (tmp_path / "case0_windows.npz").exists()
    n_first = s.solve_metadata["batched_solves"]
    assert n_first > 0

    # resume: no windows left to solve, identical results
    cases2 = Params.initialize(CASE_000, base_path=REF)
    s2 = MicrogridScenario(cases2[0])
    s2.optimize_problem_loop(backend="cpu", checkpoint_dir=tmp_path)
    assert s2.solve_metadata["batched_solves"] == 0
    resumed = s2.timeseries_results()
    pd.testing.assert_frame_equal(full, resumed)
    assert set(s2.objective_values) == set(s.objective_values)
    for k in s.objective_values:
        assert s2.objective_values[k] == pytest.approx(s.objective_values[k])


def test_drill_down_maps():
    inst = DERVET(CASE_000, base_path=REF).solve(backend="cpu").instances[0]
    dd = inst.drill_down_dict
    assert "peak_day_load" in dd
    pk = dd["peak_day_load"]
    assert {"Timestep Beginning", "Date", "Load (kW)",
            "Net Load (kW)"} <= set(pk.columns)
    maps = [k for k in dd if k.endswith("_dispatch_map")]
    assert maps
    dm = dd[maps[0]]
    assert list(dm.index) == list(range(1, 25))     # hour-ending rows
    assert "energyp_map" in dd


def test_class_summary_echo(caplog):
    import logging
    from dervet_tpu.io.summary import class_summary
    cases = Params.initialize(CASE_000, base_path=REF)
    with caplog.at_level(logging.INFO, logger="dervet_tpu"):
        class_summary(cases)
    joined = " ".join(r.message for r in caplog.records)
    assert "INPUT SUMMARY" in joined
    assert "Battery" in joined or "ene_max_rated" in joined


class TestAutoBackendRouting:
    """backend='auto' (VERDICT r3 #9): small dispatches must NOT pay the
    XLA compile bill — they route to the exact CPU solver with an info
    log; large dispatches route to jax; explicit choices are honored."""

    @staticmethod
    def _captured_backend(dervet, monkeypatch, **solve_kw):
        import dervet_tpu.api as api
        seen = {}

        def capture(scenarios, backend="jax", **kw):
            seen["backend"] = backend
            raise _Routed()

        class _Routed(Exception):
            pass

        import dervet_tpu.scenario.scenario as scn
        monkeypatch.setattr(scn, "run_dispatch", capture)
        with pytest.raises(_Routed):
            dervet.solve(**solve_kw)
        return seen["backend"]

    def test_small_run_routes_to_cpu(self, monkeypatch, caplog):
        d = DERVET(CASE_000, base_path=REF)     # one case, one month window
        assert self._captured_backend(d, monkeypatch) == "cpu"

    def test_large_run_routes_to_jax(self, monkeypatch):
        d = DERVET(CASE_000, base_path=REF)
        monkeypatch.setattr(DERVET, "AUTO_JAX_MIN_WINDOWS", 1)
        assert self._captured_backend(d, monkeypatch) == "jax"

    def test_explicit_backend_honored(self, monkeypatch):
        d = DERVET(CASE_000, base_path=REF)
        assert self._captured_backend(
            d, monkeypatch, backend="jax") == "jax"
