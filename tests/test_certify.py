"""Numerical trust layer: independent float64 solution certification,
the corrupt_solution fault drill, deterministic shadow-solve sampling,
and the physical-invariant audit.

The certifier (``ops/certify.py``) re-derives every accepted window
solution's quality from the UNSCALED float64 LP data — independently of
the solver's own (scaled, float32) residual bookkeeping — and rejected
windows re-enter the PR-1 escalation ladder instead of shipping.  The
``corrupt_solution`` fault perturbs a returned solution AFTER the solver
declares success: the exact silent-wrong-answer shape only this layer
can catch."""
import json

import numpy as np
import pytest

from dervet_tpu.benchlib import synthetic_case
from dervet_tpu.ops import certify, cpu_ref
from dervet_tpu.ops.lp import LPBuilder
from dervet_tpu.scenario.scenario import MicrogridScenario, run_dispatch
from dervet_tpu.utils import faultinject


def _tiny_lp():
    """min x0 + 2 x1  s.t.  x0 + x1 == 4,  x0 >= 1,  0 <= x <= 10.
    Optimum x = (4, 0), obj = 4; optimal duals y = (1, 0)."""
    b = LPBuilder()
    x = b.var("x", 2, lb=0.0, ub=10.0)
    b.add_cost(x, [1.0, 2.0])
    b.add_rows("balance_row", [(x, np.array([[1.0, 1.0]]))], "eq", 4.0)
    b.add_rows("req_row", [(x, np.array([[1.0, 0.0]]))], "ge", 1.0)
    return b.build()


def _small_case(case_id: int = 0, days: int = 2):
    """Two days of the synthetic Battery+PV+DA case in 12-hour windows
    (4 small window-LPs) — the same drill shape as test_resilience."""
    case = synthetic_case()
    case.case_id = case_id
    case.scenario["allow_partial_year"] = True
    case.scenario["n"] = 12
    case.datasets.time_series = case.datasets.time_series.iloc[: 24 * days]
    return case


class TestCertifySolution:
    def test_accepts_exact_cpu_solution(self):
        lp = _tiny_lp()
        res = cpu_ref.solve_lp_cpu(lp)
        cert = certify.certify_solution(lp, res.x, res.obj)
        assert cert.verdict == "certified"
        assert cert.accepted
        assert max(cert.rel_viol.values()) < 1e-6
        assert cert.obj_rel_err < 1e-9

    def test_rejects_perturbed_solution(self):
        lp = _tiny_lp()
        res = cpu_ref.solve_lp_cpu(lp)
        bad = faultinject.corrupt_array(res.x.copy(), label=7, scale=0.25)
        cert = certify.certify_solution(lp, bad, res.obj)
        assert cert.verdict == "rejected"
        assert not cert.accepted
        assert cert.reason

    def test_balance_class_and_worst_group(self):
        lp = _tiny_lp()
        cert = certify.certify_solution(lp, np.array([3.0, 0.0]), 3.0)
        assert cert.verdict == "rejected"
        assert cert.worst_class == "balance"
        assert cert.worst_group == "balance_row"

    def test_requirement_class(self):
        # x0 + x1 == 4 holds, x0 >= 1 violated by 0.5
        lp = _tiny_lp()
        cert = certify.certify_solution(lp, np.array([0.5, 3.5]), 7.5)
        assert cert.verdict == "rejected"
        assert cert.worst_class == "requirement"
        assert cert.worst_group == "req_row"
        assert cert.abs_viol["requirement"] == pytest.approx(0.5)

    def test_bounds_class(self):
        # balance + requirement hold (5 - 1 = 4, 5 >= 1) but x1 < 0
        lp = _tiny_lp()
        cert = certify.certify_solution(lp, np.array([5.0, -1.0]), 3.0)
        assert cert.verdict == "rejected"
        assert cert.worst_class == "bounds"

    def test_objective_disagreement_alone_rejects(self):
        lp = _tiny_lp()
        res = cpu_ref.solve_lp_cpu(lp)
        cert = certify.certify_solution(lp, res.x, res.obj + 1.0)
        assert cert.verdict == "rejected"
        assert "objective" in cert.reason
        assert max(cert.rel_viol.values()) < 1e-6  # primal was fine

    def test_loose_band(self):
        # eq violation 0.03 on row scale 9 => ~3.3e-3 rel: between
        # eps_rel (1e-3) and the loose cut (1e-2) => certified_loose
        lp = _tiny_lp()
        x = np.array([4.03, 0.0])
        cert = certify.certify_solution(lp, x, float(lp.c @ x))
        assert cert.verdict == "certified_loose"
        assert cert.accepted
        assert "primal" in cert.reason

    def test_dual_certificate(self):
        lp = _tiny_lp()
        res = cpu_ref.solve_lp_cpu(lp)
        good = certify.certify_solution(lp, res.x, res.obj,
                                        y=np.array([1.0, 0.0]))
        assert good.verdict == "certified"
        assert good.gap_rel == pytest.approx(0.0, abs=1e-9)
        assert good.dual_rel_viol == pytest.approx(0.0, abs=1e-9)
        bad = certify.certify_solution(lp, res.x, res.obj,
                                       y=np.array([5.0, 0.0]))
        assert bad.verdict == "rejected"
        assert "gap" in bad.reason

    def test_policy_env_knobs(self, monkeypatch):
        lp = _tiny_lp()
        x = np.array([4.0 + 1e-6, 0.0])   # ~1e-7 rel: fine by default
        assert certify.certify_solution(lp, x, float(lp.c @ x)).accepted
        monkeypatch.setenv("DERVET_TPU_CERT_EPS_REL", "1e-9")
        monkeypatch.setenv("DERVET_TPU_CERT_LOOSE_FACTOR", "2")
        policy = certify.policy_from_env()
        assert policy.eps_rel == 1e-9
        assert policy.loose_factor == 2
        cert = certify.certify_solution(lp, x, float(lp.c @ x), policy)
        assert cert.verdict == "rejected"

    def test_nonfinite_solution_rejected(self):
        lp = _tiny_lp()
        cert = certify.certify_solution(
            lp, np.array([np.nan, 0.0]), 4.0)
        assert cert.verdict == "rejected"
        assert "non-finite" in cert.reason

    def test_certificate_json_serializable(self):
        lp = _tiny_lp()
        res = cpu_ref.solve_lp_cpu(lp)
        cert = certify.certify_solution(lp, res.x, res.obj)
        json.dumps(cert.as_dict())   # must not raise


class TestCorruptSolutionFault:
    def test_corrupt_array_deterministic(self):
        x = np.linspace(0.0, 5.0, 16)
        a = faultinject.corrupt_array(x.copy(), label=3)
        b = faultinject.corrupt_array(x.copy(), label=3)
        c = faultinject.corrupt_array(x.copy(), label=4)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)
        assert not np.array_equal(a, x)

    def test_env_knobs_parse(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FAULT_CORRUPT", "3")
        monkeypatch.setenv("DERVET_TPU_FAULT_CORRUPT_SCALE", "0.1")
        plan = faultinject.get_plan()
        assert plan is not None
        assert plan.corrupt_scale == 0.1
        assert plan.corrupt_due(3, "solve")
        assert not plan.corrupt_due(4, "solve")
        assert not plan.corrupt_due(3, "retry")    # rungs default: solve
        assert plan.fired == [("corrupt_solution", "3")]

    def test_corrupt_rejected_escalated_recovered_cpu(self):
        """Acceptance drill (cpu backend): the corrupted window is
        rejected by the float64 certifier, escalated down the existing
        ladder, recovered on the boosted retry, re-certified — and the
        final objectives match an uninjected run exactly."""
        ref = MicrogridScenario(_small_case())
        ref.optimize_problem_loop(backend="cpu")
        with faultinject.inject(corrupt={1}) as plan:
            s = MicrogridScenario(_small_case())
            s.optimize_problem_loop(backend="cpu")
        assert ("corrupt_solution", "1") in plan.fired
        assert s.quarantine is None
        cert = s.certification
        assert cert["rejected"] == 1
        assert cert["rejected_then_recovered"] == 1
        assert cert["rejected_final"] == 0
        assert cert["certified"] + cert["certified_loose"] == len(s.windows)
        assert "1" in cert["windows"]          # rejected-window record
        assert s.health["retried"] == 1
        assert s.health["clean"] == len(s.windows) - 1
        for k in ref.objective_values:
            assert s.objective_values[k]["Total Objective"] == \
                pytest.approx(ref.objective_values[k]["Total Objective"],
                              rel=1e-9)

    def test_corrupt_rejected_recovered_jax(self):
        """Same drill through the batched PDHG path: only the corrupted
        member re-solves, and every window ends certified."""
        with faultinject.inject(corrupt={2}) as plan:
            s = MicrogridScenario(_small_case())
            s.optimize_problem_loop(backend="jax")
        assert ("corrupt_solution", "2") in plan.fired
        assert s.quarantine is None
        cert = s.certification
        assert cert["rejected"] == 1
        assert cert["rejected_then_recovered"] == 1
        assert cert["certified"] + cert["certified_loose"] == len(s.windows)
        assert s.health["retried"] == 1

    def test_corrupt_at_retry_falls_to_cpu_fallback(self):
        """Corruption at BOTH the solve and retry rungs: the retry's
        solution is re-certified, rejected again, and the window lands on
        the exact CPU fallback — rungs climbed in order, recovery still
        counted."""
        with faultinject.inject(corrupt={1},
                                rungs={"solve", "retry"}) as plan:
            s = MicrogridScenario(_small_case())
            s.optimize_problem_loop(backend="cpu")
        fired = [f for f in plan.fired if f[0] == "corrupt_solution"]
        assert fired == [("corrupt_solution", "1")] * 2
        assert s.quarantine is None
        cert = s.certification
        assert cert["rejected"] == 2               # solve + retry rejections
        assert cert["rejected_then_recovered"] == 1
        assert s.health["cpu_fallback"] == 1
        assert s.health["retried"] == 0            # disjoint final buckets

    def test_certifier_disabled_lets_corruption_through(self, monkeypatch):
        """DERVET_TPU_CERT=0 is the kill switch: with the certifier off,
        the corrupted solution ships (proving the certifier — not some
        other guard — is what catches it when on)."""
        monkeypatch.setenv("DERVET_TPU_CERT", "0")
        assert not certify.policy_from_env().enabled
        with faultinject.inject(corrupt={1}) as plan:
            s = MicrogridScenario(_small_case())
            s.optimize_problem_loop(backend="cpu")
        assert ("corrupt_solution", "1") in plan.fired
        cert = s.certification
        assert cert["rejected"] == 0
        assert cert["certified"] + cert["certified_loose"] == 0
        assert s.health["retried"] == 0        # nothing caught, no ladder


class TestShadowSolve:
    def test_sample_deterministic_across_runs(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_CERT_SHADOW_K", "2")
        picked = []
        for _ in range(2):
            s = MicrogridScenario(_small_case())
            run_dispatch([s], backend="jax")
            sh = s.certification["shadow"]
            assert sh["n"] == 2
            assert sh["rel_diff_max"] < 1e-3   # PDHG vs HiGHS drift
            assert sh["shadow_s"] > 0
            picked.append(tuple(sorted(sh["windows"])))
        assert picked[0] == picked[1]

    def test_shadow_skipped_on_cpu_backend(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_CERT_SHADOW_K", "2")
        s = MicrogridScenario(_small_case())
        run_dispatch([s], backend="cpu")
        assert s.certification["shadow"]["n"] == 0

    def test_pick_shadow_sample_ranks(self):
        pairs = [(0, lbl) for lbl in range(20)]
        a = certify.pick_shadow_sample(pairs, 3)
        b = certify.pick_shadow_sample(list(reversed(pairs)), 3)
        assert a == b       # order-independent, rank-determined
        assert len(a) == 3


class TestRunHealthSection:
    def test_certification_section_schema(self):
        from dervet_tpu.io.summary import (log_health_report,
                                           run_health_report)
        s = MicrogridScenario(_small_case())
        run_dispatch([s], backend="cpu")
        rep = run_health_report({0: s.health}, {},
                                certification_by_case={0: s.certification})
        cert = certify.validate_certification(rep["certification"])
        assert cert["windows_certified"] == len(s.windows)
        assert cert["windows"]["rejected"] == 0
        log_health_report(rep)     # must not raise
        json.dumps(rep)            # persisted form is JSON

    def test_ledger_carries_certification(self):
        s = MicrogridScenario(_small_case())
        run_dispatch([s], backend="jax")
        ledger = s.solve_metadata["solve_ledger"]
        cert = certify.validate_certification(ledger["certification"])
        assert cert["cert_s"] >= 0
        assert cert["windows_certified"] == len(s.windows)
        assert s.solve_metadata["certification"]["certified"] \
            + s.solve_metadata["certification"]["certified_loose"] \
            == len(s.windows)


class TestInvariantAudit:
    def test_clean_run_passes(self):
        s = MicrogridScenario(_small_case())
        s.optimize_problem_loop(backend="cpu")
        audit = certify.audit_case(s)
        assert audit["ok"], audit
        checks = audit["checks"]
        assert checks["soe_recurrence"]["transitions"] > 0
        assert checks["soe_recurrence"]["rel_max"] < 1e-6   # exact CPU
        assert checks["soe_seams"]["rel_max"] < 1e-6
        assert checks["objective_components"]["rel_max"] < 1e-9

    def test_scrambled_scatter_caught(self):
        """A post-solve corruption of the assembled solution arrays —
        the window-mixup / scatter-race shape — trips the SOE recurrence
        even though every per-window certificate passed."""
        s = MicrogridScenario(_small_case())
        s.optimize_problem_loop(backend="cpu")
        ene = s._solution["Battery-1/ene"]
        ene[5:15] = ene[5:15][::-1].copy()     # scramble a stretch
        audit = certify.audit_case(s)
        assert not audit["ok"]
        assert not audit["checks"]["soe_recurrence"]["ok"]

    def test_bound_violation_caught(self):
        s = MicrogridScenario(_small_case())
        s.optimize_problem_loop(backend="cpu")
        bat = next(d for d in s.ders if d.tag == "Battery")
        s._solution["Battery-1/dis"][3] = bat.discharge_capacity() * 1.5
        audit = certify.audit_case(s)
        assert not audit["checks"]["dispatch_bounds"]["ok"]

    def test_aggregate_audits(self):
        good = {"ok": True, "checks": {}}
        bad = {"ok": False, "checks": {"soe_seams": {"ok": False}}}
        agg = certify.aggregate_audits({0: good, 1: bad, 2: None})
        assert not agg["ok"]
        assert agg["cases_audited"] == 2
        assert list(agg["failing"]) == ["1"]
