"""Grid sizing sweep on the batch axis (VERDICT r1 item 8: the 20x20
sweep IS the batch; chosen candidate's dispatch cross-checks vs HiGHS)."""
from pathlib import Path

import numpy as np
import pytest

from dervet_tpu.io.params import Params
from dervet_tpu.ops import cpu_ref
from dervet_tpu.sizing import sizing_sweep, _candidate_scenario
from dervet_tpu.utils.errors import ParameterError

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"


@pytest.fixture(scope="module")
def case():
    c = Params.initialize(MP / "000-DA_battery_month.csv", base_path=REF)[0]
    c.scenario["allow_partial_year"] = True
    c.scenario["binary"] = 0
    # one week keeps the batched solve quick on the CPU test backend
    c.datasets.time_series = c.datasets.time_series.iloc[: 24 * 7]
    return c


def test_sweep_returns_surface_and_best(case):
    kw = [500, 1000, 2000]
    kwh = [1000, 4000, 8000]
    out = sizing_sweep(case, kw, kwh)
    assert len(out) == 9
    assert out.converged.all()
    ov = out.set_index(["kW", "kWh"])["operating_value"]
    # the sweep actually senses size: candidates differ, and net of the
    # size-scaled fixed O&M constant the bigger battery dispatches at
    # least as much arbitrage benefit
    assert ov.nunique() == len(ov)
    hours = len(case.datasets.time_series)   # windows cover one week
    fom = {(kw, kwh): next(
        d for d in _candidate_scenario(case, "Battery", "1", kw, kwh).ders
        if d.tag == "Battery").fixed_om_per_kw * kw * hours / 8760.0
        for kw, kwh in [(2000, 8000), (500, 1000)]}
    big = ov[(2000, 8000)] - fom[(2000, 8000)]
    small = ov[(500, 1000)] - fom[(500, 1000)]
    assert big <= small + 1e-6
    # capex grows with size, so the argmin of total is an interior
    # tradeoff the caller reads off the surface
    assert np.isfinite(out["total"]).all()


def test_best_candidate_cross_checks_vs_highs(case):
    out = sizing_sweep(case, [500, 1000], [1000, 4000])
    best = out.loc[out["total"].idxmin()]
    s = _candidate_scenario(case, "Battery", "1",
                            float(best["kW"]), float(best["kWh"]))
    total = 0.0
    for ctx in s.windows:
        lp = s.build_window_lp(ctx)
        res = cpu_ref.solve_lp_cpu(lp)
        assert res.status == 0
        total += res.obj + lp.c0
    scale = max(1.0, abs(total))
    assert abs(total - float(best["operating_value"])) / scale < 2e-3


def test_sweep_rejects_sizing_cases(case):
    import copy
    c = copy.deepcopy(case)
    for tag, _id, keys in c.ders:
        if tag == "Battery":
            keys["ene_max_rated"] = 0   # would add a size variable
    with pytest.raises(ParameterError):
        sizing_sweep(c, [500], [0])


def _synthetic_week_case():
    from dervet_tpu.benchlib import synthetic_case
    c = synthetic_case()
    c.scenario["allow_partial_year"] = True
    c.datasets.time_series = c.datasets.time_series.iloc[: 24 * 3]
    return c


def test_sweep_dedupes_and_sorts_duplicate_candidates():
    """Duplicate (kW, kWh) pairs used to solve twice and could make
    ``best`` tie-dependent on grid order; the shim deduplicates and
    sorts before solving.  Synthetic case: no reference data needed."""
    out = sizing_sweep(_synthetic_week_case(),
                       [1000, 500, 500, 1000], [1000, 4000, 1000])
    # 2 distinct kW x 2 distinct kWh -> 4 rows, sorted, no duplicates
    pairs = list(zip(out["kW"], out["kWh"]))
    assert pairs == [(500.0, 1000.0), (500.0, 4000.0),
                     (1000.0, 1000.0), (1000.0, 4000.0)]
    assert out.converged.all()
    # legacy column surface preserved by the design-engine shim
    assert list(out.columns) == ["kW", "kWh", "operating_value", "capex",
                                 "total", "converged", "lifetime_npv"]
    assert np.isfinite(out["total"]).all()


def test_sweep_order_invariant():
    """The same grid in a different order returns the same surface (the
    dedupe/sort contract: the winner can never be tie-dependent)."""
    a = sizing_sweep(_synthetic_week_case(), [500, 1000], [1000, 4000])
    b = sizing_sweep(_synthetic_week_case(), [1000, 500], [4000, 1000])
    assert list(zip(a["kW"], a["kWh"])) == list(zip(b["kW"], b["kWh"]))
    best_a = a.loc[a["total"].idxmin()]
    best_b = b.loc[b["total"].idxmin()]
    assert (best_a["kW"], best_a["kWh"]) == (best_b["kW"], best_b["kWh"])
    scale = max(1.0, abs(float(best_a["total"])))
    assert abs(float(best_a["total"])
               - float(best_b["total"])) / scale < 1e-6


def test_sweep_hard_errors_on_binary_formulation():
    """binary=1 + sizing sweep is a hard error, matching the reference's
    binary+sizing prohibition (MicrogridPOI.py:132-147) — the former
    warning let a 400-candidate sweep silently rank candidates on LP-
    relaxation objectives the binary formulation never attains
    (VERDICT r5 weak #3).  Synthetic case: no reference data needed."""
    from dervet_tpu.benchlib import synthetic_case
    c = synthetic_case()
    c.scenario["binary"] = 1
    c.scenario["allow_partial_year"] = True
    ts = c.datasets.time_series
    c.datasets.time_series = ts.iloc[: 24 * 7]
    with pytest.raises(ParameterError, match="binary"):
        sizing_sweep(c, [500], [1000])
