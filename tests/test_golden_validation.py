"""Frozen-golden regression vs the reference's Sept-1 validation report
(VERDICT r1 #7/#8): run the reference's own Usecase1 model-parameter files
end-to-end and compare size/proforma/LCPC against the frozen CSVs with the
reference's own error bounds (test_beta_release_validation_report.py:
MAX_PERCENT_ERROR=3; size at MAX-1, proforma at MAX+2 for the ES case).
"""
from pathlib import Path

import pytest

from dervet_tpu.api import DERVET
from tests.goldenlib import (compare_lcpc_results, compare_proforma_results,
                             compare_size_results)

REF = Path("/root/reference")
UC1 = REF / "test/test_validation_report_sept1/Model_params/Usecase1"
RES1 = REF / "test/test_validation_report_sept1/Results/Usecase1"

MAX_PERCENT_ERROR = 3


@pytest.fixture(scope="module")
def es_case():
    d = DERVET(UC1 / "Model_Parameters_Template_Usecase1_UnPlanned_ES.csv",
               base_path=REF)
    return d.solve(backend="cpu").instances[0]


class TestUsecase1EsSizing:
    """1 ESS sizing — BTM with post-facto reliability (reference:
    TestUseCase1EssSizing4Btm)."""

    def test_size_within_bound(self, es_case):
        compare_size_results(es_case, RES1 / "es/sizeuc3.csv",
                             MAX_PERCENT_ERROR - 1)

    def test_proforma_within_bound(self, es_case):
        compare_proforma_results(es_case, RES1 / "es/pro_formauc3.csv",
                                 MAX_PERCENT_ERROR + 2)

    def test_lcpc_exists(self, es_case):
        assert "load_coverage_prob" in es_case.drill_down_dict


UC2 = REF / "test/test_validation_report_sept1/Model_params/Usecase2"
RES2 = REF / "test/test_validation_report_sept1/Results/Usecase2"


class TestUsecase2ReliabilitySizing:
    """1 ESS sized for reliability only — planned outage (reference:
    TestUseCase2EssSizing4Reliability, step1 goldens)."""

    @pytest.fixture(scope="class")
    def case(self):
        d = DERVET(UC2 / "Model_Parameters_Template_Usecase3_Planned_ES.csv",
                   base_path=REF)
        return d.solve(backend="cpu").instances[0]

    def test_size_within_bound(self, case):
        compare_size_results(case, RES2 / "es/step1/sizeuc3_es_step1.csv",
                             MAX_PERCENT_ERROR)

    def test_step2_proforma_exact(self):
        """Step2 (fixed size from step1, retail + DCM + User min-SOE floor):
        the dispatch-dependent proforma reproduces the golden exactly —
        avoided demand AND energy charges match to the cent."""
        d = DERVET(UC2 / "Model_Parameters_Template_Usecase3_Planned_ES_Step2.csv",
                   base_path=REF)
        inst = d.solve(backend="cpu").instances[0]
        compare_proforma_results(
            inst, RES2 / "es/step2/pro_formauc3_es_step2.csv", 0.1)

    def test_lcpc_within_bound(self, case):
        """LCPC from the min-SOE schedule is deterministic and matches the
        frozen curve (the dispatch-SOE-seeded Usecase1 LCPC is not
        comparable: equally-optimal dispatches differ, and the reference's
        own value check is disabled — xtest_lcpc_meets_target)."""
        compare_lcpc_results(
            case, RES2 / "es/step1/load_coverage_probuc3_es_step1.csv",
            MAX_PERCENT_ERROR + 2)


class TestUsecase2EsPvSizing:
    """ESS sized for reliability with fixed PV — unplanned outage."""

    @pytest.fixture(scope="class")
    def case(self):
        d = DERVET(
            UC2 / "Model_Parameters_Template_Usecase3_UnPlanned_ES+PV.csv",
            base_path=REF)
        return d.solve(backend="cpu").instances[0]

    def test_size_within_bound(self, case):
        compare_size_results(case,
                             RES2 / "es+pv/sizeuc3_es+pv_step1.csv",
                             MAX_PERCENT_ERROR)


class TestUsecase2EsPvDgSizing:
    """ESS+PV+DG sized for reliability — unplanned outage (reference:
    Usecase2 es+pv+dg step1)."""

    @pytest.fixture(scope="class")
    def case(self):
        d = DERVET(
            UC2 / "Model_Parameters_Template_Usecase3_UnPlanned_ES+PV+DG_Step1.csv",
            base_path=REF)
        return d.solve(backend="cpu").instances[0]

    def test_size_within_bound(self, case):
        compare_size_results(
            case, RES2 / "es+pv+dg/step1/sizeuc3_es+pv+dg_step1.csv",
            MAX_PERCENT_ERROR)


UC3 = REF / "test/test_validation_report_sept1/Model_params/Usecase3"
RES3 = REF / "test/test_validation_report_sept1/Results/Usecase3"


class TestUsecase3ReliabilitySizing:
    """Usecase3 planned/unplanned reliability sizing across DER mixes."""

    @pytest.mark.parametrize("mp,golden", [
        ("planned/Model_Parameters_Template_Usecase3_Planned_ES.csv",
         "planned/es/sizeuc3.csv"),
        ("planned/Model_Parameters_Template_Usecase3_Planned_ES+PV.csv",
         "planned/es+pv/sizeuc3.csv"),
        ("planned/Model_Parameters_Template_Usecase3_Planned_ES+PV+DG.csv",
         "planned/es+pv+dg/sizeuc3.csv"),
        ("unplanned/Model_Parameters_Template_Usecase3_UnPlanned_ES.csv",
         "unplanned/es/sizeuc3.csv"),
        ("unplanned/Model_Parameters_Template_Usecase3_UnPlanned_ES+PV+DG.csv",
         "unplanned/es+pv+dg/sizeuc3.csv"),
    ])
    def test_size_within_bound(self, mp, golden):
        inst = DERVET(UC3 / mp, base_path=REF).solve(
            backend="cpu").instances[0]
        compare_size_results(inst, RES3 / golden, MAX_PERCENT_ERROR)


LS = REF / "test/test_load_shedding"


class TestLoadShedding:
    """Reliability with/without load shedding, fixed size + sizing
    (reference: test_reliability_module.py classes, 3% bounds)."""

    @pytest.mark.parametrize("mp,golden,check_lcpc", [
        ("mp/Model_Parameters_Template_DER_w_ls1.csv",
         "results/reliability_load_shed1", True),
        ("mp/Model_Parameters_Template_DER_wo_ls1.csv",
         "results/reliability_load_shed_wo_ls1", True),
        ("mp/Sizing/Model_Parameters_Template_DER_w_ls1.csv",
         "results/Sizing/w_ls1", False),
        ("mp/Sizing/Model_Parameters_Template_DER_wo_ls1.csv",
         "results/Sizing/wo_ls1", False),
    ])
    def test_size_proforma_lcpc(self, mp, golden, check_lcpc):
        inst = DERVET(LS / mp, base_path=REF).solve(
            backend="cpu").instances[0]
        compare_size_results(inst, LS / golden / "size_2mw_5hr.csv",
                             MAX_PERCENT_ERROR)
        compare_proforma_results(inst, LS / golden / "pro_forma_2mw_5hr.csv",
                                 MAX_PERCENT_ERROR)
        assert "load_coverage_prob" in inst.drill_down_dict
        if check_lcpc:
            compare_lcpc_results(
                inst, LS / golden / "load_coverage_prob_2mw_5hr.csv",
                MAX_PERCENT_ERROR)


@pytest.fixture(scope="module")
def es_pv_case():
    d = DERVET(UC1 / "Model_Parameters_Template_Usecase1_UnPlanned_ES+PV.csv",
               base_path=REF)
    return d.solve(backend="cpu").instances[0]


class TestUsecase1EsPvSizing:
    """1 ESS sizing + 1 fixed PV (reference: TestUseCase1EssSizingPv4Btm)."""

    def test_size_within_bound(self, es_pv_case):
        compare_size_results(es_pv_case, RES1 / "es+pv/sizeuc3.csv",
                             MAX_PERCENT_ERROR - 1)

    def test_proforma_within_bound(self, es_pv_case):
        compare_proforma_results(es_pv_case, RES1 / "es+pv/pro_formauc3.csv",
                                 MAX_PERCENT_ERROR + 1)

    def test_lcpc_exists(self, es_pv_case):
        assert "load_coverage_prob" in es_pv_case.drill_down_dict


def test_post_facto_reliability_with_user_constraints():
    """Mirrors the reference's
    test_post_facto_calculations_with_user_constraints
    (test_reliability_module.py:128-129): post-facto reliability (no
    active dispatch sizing) with User value-stream constraints runs and
    produces the load-coverage-probability drill-down."""
    inst = DERVET(REF / "test/model_params/"
                  "Model_Parameters_Template_issue162.csv",
                  base_path=REF).solve(backend="cpu").instances[0]
    assert "load_coverage_prob" in inst.drill_down_dict
    assert len(inst.time_series_data) == 8760


# ---------------------------------------------------------------------------
# Jax (TPU-path) backend validation at the NPV level (VERDICT r2 #1).
#
# The sizing usecases (Usecase1/3) route their single year-long sizing
# window to the CPU exact solver BY DESIGN (scenario.py _solve routing:
# one badly-scaled LP solved once vs the batched operational axis), and the
# load-shedding cases are reliability-only (opt_engine=False — no dispatch
# LP at all), so the frozen-golden cases that genuinely exercise the
# batched PDHG dispatch path are the fixed-size economic-dispatch ones:
# Usecase2 step2 (retail + DCM + User min-SOE floor, 12 monthly windows)
# and the storagevet-features cases.  Strategy:
#   * default suite: case 000 (DA + binary battery, 12 monthly windows,
#     ~11 s) runs end-to-end on backend="jax" and must match the CPU
#     backend at the NPV/proforma level within the BASELINE.md 1% gate;
#   * --runslow: Usecase2 step2 on backend="jax" against the FROZEN
#     reference proforma within 1% (the retail floor windows need ~300k
#     PDHG iterations — seconds on TPU, minutes on the CPU test platform).
# ---------------------------------------------------------------------------

SV = REF / "test/test_storagevet_features/model_params"


class TestJaxBackendNPV:
    """Batched PDHG dispatch must reproduce exact-solver economics."""

    @pytest.fixture(scope="class")
    def pair(self):
        jx = DERVET(SV / "000-DA_battery_month.csv",
                    base_path=REF).solve(backend="jax").instances[0]
        cp = DERVET(SV / "000-DA_battery_month.csv",
                    base_path=REF).solve(backend="cpu").instances[0]
        return jx, cp

    def test_jax_dispatch_actually_ran(self, pair):
        jx, _ = pair
        meta = jx.scenario.solve_metadata
        assert meta["backend"] == "jax"
        assert meta["batched_solves"] >= 1 and meta["n_windows"] == 12

    def test_npv_within_1pct(self, pair):
        jx, cp = pair
        assert jx.npv_df is not None and cp.npv_df is not None
        for col in cp.npv_df.columns:
            exp = float(cp.npv_df[col].iloc[0])
            got = float(jx.npv_df[col].iloc[0])
            if abs(exp) < 1.0:
                assert abs(got - exp) < 1.0, (col, exp, got)
            else:
                assert abs(got - exp) / abs(exp) < 0.01, (col, exp, got)

    def test_proforma_within_1pct(self, pair):
        jx, cp = pair
        exp_df, got_df = cp.proforma_df, jx.proforma_df
        assert sorted(exp_df.columns) == sorted(got_df.columns)
        for col in exp_df.columns:
            for idx in exp_df.index:
                exp, got = float(exp_df.loc[idx, col]), float(got_df.loc[idx, col])
                if abs(exp) < 1.0:
                    assert abs(got - exp) < 1.0, (idx, col, exp, got)
                else:
                    assert abs(got - exp) / abs(exp) < 0.01, (idx, col, exp, got)


@pytest.mark.slow
def test_usecase2_step2_jax_proforma_golden():
    """UC2 step2 on the jax backend vs the FROZEN reference proforma:
    dispatch-dependent avoided demand + energy charges within 1%."""
    d = DERVET(UC2 / "Model_Parameters_Template_Usecase3_Planned_ES_Step2.csv",
               base_path=REF)
    inst = d.solve(backend="jax").instances[0]
    assert inst.scenario.solve_metadata["backend"] == "jax"
    compare_proforma_results(
        inst, RES2 / "es/step2/pro_formauc3_es_step2.csv", 1.0)
