"""Deferral-driven ESS sizing floors (reference:
MicrogridServiceAggregator.set_size, :81-107 — the deferral power/energy
requirements become minimum ESS ratings in a sizing run)."""
from pathlib import Path

import pytest

from dervet_tpu.io.params import Params
from dervet_tpu.scenario.scenario import MicrogridScenario

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"


def test_deferral_floors_sizing():
    cases = Params.initialize(MP / "003-DA_Deferral_battery_month.csv",
                              base_path=REF)
    case = cases[0]
    for tag, _, keys in case.ders:
        if tag == "Battery":
            keys["ene_max_rated"] = 0
            keys["ch_max_rated"] = 0
            keys["dis_max_rated"] = 0
    case.scenario["n"] = "year"
    case.scenario["binary"] = False   # sizing forbids the binary formulation
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    d = s.streams["Deferral"]
    # floors use the LAST deferred year's (growth-scaled) requirement
    # (reference set_size semantics), and both power ratings are floored
    last = s.start_year + max(d.min_years - 1, 0)
    req = d.deferral_df.loc[last] if last in d.deferral_df.index \
        else d.deferral_df.iloc[0]
    bat = s.ders[0]
    assert bat.dis_max_rated >= float(req["Power Requirement (kW)"]) - 1e-6
    assert bat.ch_max_rated >= float(req["Power Requirement (kW)"]) - 1e-6
    assert bat.ene_max_rated >= float(req["Energy Requirement (kWh)"]) - 1e-6
    assert bat.dis_max_rated > 0


def test_deferral_sizing_requires_single_ess():
    from dervet_tpu.utils.errors import ParameterError
    cases = Params.initialize(MP / "003-DA_Deferral_battery_month.csv",
                              base_path=REF)
    case = cases[0]
    for tag, _, keys in case.ders:
        if tag == "Battery":
            keys["ene_max_rated"] = 0
    case.ders.append(("ICE", "1", {
        "name": "g", "rated_capacity": 100, "n": 1, "efficiency": 0.05,
        "fuel_cost": 3, "variable_om_cost": 0, "fixed_om_cost": 0,
        "ccost": 0, "ccost_kW": 500}))
    case.scenario["n"] = "year"
    case.scenario["binary"] = False
    s = MicrogridScenario(case)
    with pytest.raises(ParameterError):
        s.optimize_problem_loop(backend="cpu")
