"""Telemetry plane: tracing, metrics registry, ops surface.

The observability contract under test:

* **Spans** — zero-dependency span trees with monotonic durations, a
  deterministic request-derived trace id, ambient + registry parenting,
  single-root validation, cross-process stitching, and a Chrome
  trace-event export with per-device lanes;
* **Registry** — thread-safe counters/gauges/histograms on ONE fixed
  log-bucket layout so percentiles merge exactly across replicas, a
  Prometheus text exposition that round-trips through the parser, and
  bounded ring-buffer time series;
* **Kill switch** — ``DERVET_TPU_TELEMETRY=0`` records nothing, writes
  nothing, and leaves result artifacts byte-identical;
* **End to end** — a served request's trace covers admission → batch
  round → dispatch group (ledger attributes attached) → certification,
  a load-shed request's trace carries the degraded-fidelity marker, and
  a LocalReplica fleet produces one stitched single-root trace per
  request (the SIGKILL subprocess drill rides the existing
  ``test_fleet.py`` crash test).
"""
import json
import math
import threading
import time

import pytest

from dervet_tpu.benchlib import (synthetic_sensitivity_cases,
                                 validate_telemetry_section)
from dervet_tpu.telemetry import ops as tops
from dervet_tpu.telemetry import registry as treg
from dervet_tpu.telemetry import trace as tt


def _cases(n=1, months=1, variant=0):
    cases = synthetic_sensitivity_cases(n, months=months)
    for c in cases:
        for tag, _, keys in c.ders:
            if tag == "Battery":
                keys["ene_max_rated"] = \
                    float(keys["ene_max_rated"]) + 0.5 * variant
    return {i: c for i, c in enumerate(cases)}


@pytest.fixture(autouse=True)
def _clean_collector():
    tt.COLLECTOR.reset()
    yield
    tt.COLLECTOR.reset()


# ---------------------------------------------------------------------------
# trace.py: spans, stitching, validation, chrome export
# ---------------------------------------------------------------------------

class TestTrace:
    def test_trace_id_deterministic_and_rid_derived(self):
        assert tt.trace_id_for("r1") == tt.trace_id_for("r1")
        assert tt.trace_id_for("r1") != tt.trace_id_for("r2")
        root = tt.start_span("request", rid="r1")
        assert root.trace_id == tt.trace_id_for("r1")
        root.end()

    def test_kill_switch_records_nothing(self, monkeypatch, tmp_path):
        monkeypatch.setenv(tt.ENV, "0")
        sp = tt.start_span("request", rid="k1")
        assert sp is tt.NOOP and not sp
        assert sp.child("x") is sp and sp.event("e") is sp
        assert sp.ctx() is None
        with tt.span("block") as s:
            assert s is tt.NOOP
        assert tt.export_request_trace("k1", tmp_path) is None
        assert not list(tmp_path.iterdir())
        assert not treg.enabled()

    def test_parenting_explicit_registry_and_ambient(self):
        root = tt.start_span("request", rid="p1")
        tt.register_request("p1", root)
        # registry parenting (what resolve_group uses on worker threads)
        child = tt.start_span("dispatch_group", rid="p1")
        assert child.parent_id == root.span_id
        assert child.trace_id == root.trace_id
        # ambient parenting
        with tt.span("outer") as outer:
            inner = tt.start_span("inner")
            assert inner.parent_id == outer.span_id
            inner.end()
        # context-dict parenting (the transport payload shape)
        remote = tt.start_span("request", parent=root.ctx())
        assert remote.trace_id == root.trace_id
        assert remote.parent_id == root.span_id
        for s in (child, remote, root):
            s.end()
        tt.release_request("p1")

    def test_registry_parenting_crosses_threads(self):
        root = tt.start_span("request", rid="thr")
        tt.register_request("thr", root)
        got = {}

        def worker():
            sp = tt.start_span("dispatch_group", rid="thr")
            got["parent"] = sp.parent_id
            sp.end()

        th = threading.Thread(target=worker)
        th.start()
        th.join()
        assert got["parent"] == root.span_id
        root.end()
        tt.release_request("thr")

    def test_durations_monotonic_and_error_status(self):
        sp = tt.start_span("s")
        time.sleep(0.01)
        sp.end(error=ValueError("boom"))
        assert sp.duration_s >= 0.01
        assert sp.status == "error"
        assert "ValueError" in sp.attrs["error"]

    def test_validate_trace_contracts(self):
        root = tt.start_span("request", rid="v1")
        kid = tt.start_span("child", parent=root)
        kid.end()
        root.end()
        spans = tt.COLLECTOR.spans(tt.trace_id_for("v1"))
        info = tt.validate_trace(spans)
        assert info["n_spans"] == 2
        assert info["root"]["name"] == "request"
        # two parentless spans -> not a valid single-root trace
        bad = spans + [{"trace_id": spans[0]["trace_id"],
                        "span_id": "zz", "parent_id": None,
                        "name": "orphan", "t_start": 0.0,
                        "duration_s": 0.0, "status": "ok"}]
        with pytest.raises(ValueError, match="exactly one root"):
            tt.validate_trace(bad)
        with pytest.raises(ValueError, match="no spans"):
            tt.validate_trace([])

    def test_merge_dedupes_and_build_tree_stitches(self):
        root = tt.start_span("request", rid="m1").end()
        orphan = {"trace_id": root.trace_id, "span_id": "orph",
                  "parent_id": "gone", "name": "late", "t_start":
                  root.t_start + 1, "duration_s": 0.0, "status": "ok"}
        spans = tt.merge_spans([
            tt.COLLECTOR.spans(root.trace_id),
            tt.COLLECTOR.spans(root.trace_id),     # duplicate export
            [orphan]])
        assert len(spans) == 2
        troot, children = tt.build_tree(spans)
        assert troot["span_id"] == root.span_id
        kids = children[root.span_id]
        assert kids[0]["span_id"] == "orph"
        assert "stitched" in kids[0]["attrs"]

    def test_slowest_path_descends_longest_child(self):
        root = tt.start_span("r", rid="sp")
        fast = tt.start_span("fast", parent=root)
        slow = tt.start_span("slow", parent=root)
        leaf = tt.start_span("leaf", parent=slow)
        for s, d in ((leaf, 0.05), (slow, 0.2), (fast, 0.01)):
            s.duration_s = d
            s._ended = True
            tt.COLLECTOR.add(s)
        root.end()
        spans = tt.COLLECTOR.spans(root.trace_id)
        path = tt.slowest_path(spans)
        assert path == [root.span_id, slow.span_id, leaf.span_id]

    def test_chrome_export_device_lanes(self, tmp_path):
        root = tt.start_span("request", rid="ch").end()
        spans = [root.to_dict(),
                 {**root.to_dict(), "span_id": "d0",
                  "parent_id": root.span_id,
                  "attrs": {"device": 0}},
                 {**root.to_dict(), "span_id": "d1",
                  "parent_id": root.span_id,
                  "attrs": {"device": 1}}]
        doc = tt.to_chrome(spans, "ch")
        lanes = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M"}
        assert {"request", "device:0", "device:1"} <= lanes
        path = tt.export_chrome_trace(spans, tmp_path / "c.json", "ch")
        loaded = json.loads(path.read_text())
        assert loaded["traceEvents"]

    def test_export_pops_and_collector_bounded(self, tmp_path):
        root = tt.start_span("request", rid="ex").end()
        p = tt.export_request_trace("ex", tmp_path)
        doc = json.loads(p.read_text())
        assert doc["trace_id"] == tt.trace_id_for("ex")
        assert doc["spans"][0]["name"] == "request"
        # popped: a second export finds nothing
        assert tt.export_request_trace("ex", tmp_path) is None

    def test_merge_export_unions_late_spans(self, tmp_path):
        """A span ending after its trace was exported (hedge/failover
        loser) re-enters the collector; merge=True re-export records it
        in the file and frees the orphan entry."""
        tid = tt.trace_id_for("lt")
        tt.start_span("request", rid="lt").end()
        late = tt.start_span("transport", trace_id=tid)
        tt.export_request_trace("lt", tmp_path)
        late.end()                  # orphan collector entry under tid
        assert tt.COLLECTOR.spans(tid)
        p = tt.export_request_trace("lt", tmp_path, merge=True)
        doc = json.loads(p.read_text())
        assert {s["name"] for s in doc["spans"]} == {"request",
                                                     "transport"}
        assert not tt.COLLECTOR.spans(tid)      # slot freed


# ---------------------------------------------------------------------------
# registry.py: metrics, merge exactness, exposition round trip
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = treg.MetricsRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(2)
        assert reg.counter("c").value == 3
        with pytest.raises(ValueError):
            reg.counter("c").inc(-1)
        reg.gauge("g", replica="a").set(4.5)
        assert reg.gauge("g", replica="a").value == 4.5
        reg.histogram("h").observe(0.5)
        snap = reg.histogram("h").snapshot()
        assert snap["count"] == 1 and snap["sum"] == 0.5
        # same name different type is a hard error
        with pytest.raises(TypeError):
            reg.gauge("c")

    def test_labels_key_separate_series(self):
        reg = treg.MetricsRegistry()
        reg.counter("w", grade="exact").inc(3)
        reg.counter("w", grade="cold").inc(1)
        assert reg.counter("w", grade="exact").value == 3
        assert reg.counter("w", grade="cold").value == 1

    def test_histogram_merge_is_exact_bucket_add(self):
        a, b = treg.Histogram("h", {}), treg.Histogram("h", {})
        obs_a = [0.001, 0.5, 2.0, 100.0]
        obs_b = [0.002, 0.25, 3.0]
        a.observe_many(obs_a)
        b.observe_many(obs_b)
        merged = treg.merge_histograms([a.snapshot(), b.snapshot()])
        ref = treg.Histogram("h", {})
        ref.observe_many(obs_a + obs_b)
        assert merged["buckets"] == ref.snapshot()["buckets"]
        assert merged["count"] == 7
        assert math.isclose(merged["sum"], sum(obs_a + obs_b))
        # quantiles computed from the merge equal the single-histogram
        # quantiles — the fleet p50/p99 surface is exact, not stacked
        # approximation
        for q in (0.5, 0.99):
            assert treg.quantile_from_buckets(merged, q) == \
                treg.quantile_from_buckets(ref.snapshot(), q)

    def test_merge_rejects_foreign_layout(self):
        with pytest.raises(ValueError, match="layout"):
            treg.merge_histograms([{"count": 1, "sum": 1.0,
                                    "buckets": [1, 0], "overflow": 0}])

    def test_quantile_brackets_observation(self):
        h = treg.Histogram("h", {})
        h.observe_many([0.8] * 100)
        p50 = treg.quantile_from_buckets(h.snapshot(), 0.5)
        # log-bucket resolution: the estimate lands inside the
        # observation's bucket (factor-2 wide)
        assert 0.4 <= p50 <= 1.7

    def test_prometheus_round_trip(self):
        reg = treg.MetricsRegistry()
        reg.counter("dervet_requests_total", outcome="completed").inc(5)
        reg.gauge("dervet_queue_depth").set(3)
        reg.histogram("dervet_request_latency_seconds").observe_many(
            [0.01, 0.2, 0.2, 4.0])
        text = reg.to_prometheus()
        parsed = treg.parse_prometheus(text)
        assert treg.sample_value(parsed, "dervet_requests_total",
                                 {"outcome": "completed"}) == 5
        assert treg.sample_value(parsed, "dervet_queue_depth") == 3
        hist = treg.histogram_from_parsed(
            parsed, "dervet_request_latency_seconds")
        orig = reg.histogram("dervet_request_latency_seconds").snapshot()
        assert hist["buckets"] == orig["buckets"]
        assert hist["count"] == orig["count"]
        with pytest.raises(ValueError, match="unparseable"):
            treg.parse_prometheus("not a metric line !!!")

    def test_label_escaping_round_trips(self):
        # caller-chosen names (replicas, breakers) may carry quotes /
        # backslashes / newlines — the exposition must stay parseable
        # and the values must survive the round trip
        awkward = 'we"ird\\na\nme'
        reg = treg.MetricsRegistry()
        reg.counter("dervet_breaker_trips_total",
                    replica=awkward).inc(2)
        parsed = treg.parse_prometheus(reg.to_prometheus())
        assert treg.sample_value(parsed, "dervet_breaker_trips_total",
                                 {"replica": awkward}) == 2

    def test_foreign_bucket_layout_reads_as_unpublished(self):
        # a mixed-version replica publishing different bounds must come
        # back as "no histogram", never be snapped onto HIST_BOUNDS
        # (a remapped reconstruction would pass merge_histograms'
        # layout check and silently corrupt fleet percentiles)
        text = "\n".join([
            'h_bucket{le="0.15"} 1',
            'h_bucket{le="0.33"} 3',
            'h_bucket{le="+Inf"} 3',
            "h_count 3", "h_sum 0.5", ""])
        assert treg.histogram_from_parsed(
            treg.parse_prometheus(text), "h") is None
        # the fixed layout itself still reconstructs
        good = treg.MetricsRegistry()
        good.histogram("h").observe_many([0.01, 0.2])
        parsed = treg.parse_prometheus(good.to_prometheus())
        assert treg.histogram_from_parsed(parsed, "h")["count"] == 2

    def test_write_prom_atomic_no_tmp_left(self, tmp_path):
        reg = treg.MetricsRegistry()
        reg.counter("c").inc()
        path = reg.write_prom(tmp_path / "telemetry.prom")
        assert path.read_text().startswith("# TYPE c counter")
        assert not list(tmp_path.glob(".*tmp"))

    def test_series_ring_buffer_bounded(self):
        reg = treg.MetricsRegistry()
        g = reg.gauge("depth")
        for i in range(treg.SERIES_CAP + 10):
            g.set(i)
            reg.sample()
        series = reg.series("depth")
        assert len(series) == treg.SERIES_CAP
        assert series[-1][1] == treg.SERIES_CAP + 9

    def test_snapshot_validates_with_benchlib(self):
        reg = treg.MetricsRegistry()
        reg.counter("c").inc()
        reg.gauge("g").set(1)
        reg.histogram("h").observe(0.1)
        snap = validate_telemetry_section(reg.snapshot())
        assert snap["counters"]["c"] == 1
        bad = dict(snap)
        bad["hist_bounds"] = 3
        with pytest.raises(ValueError, match="hist_bounds"):
            validate_telemetry_section(bad)

    def test_http_endpoint_serves_exposition(self):
        import urllib.request
        reg = treg.MetricsRegistry()
        reg.counter("hits").inc(7)
        port = reg.serve_http(0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5).read()
            parsed = treg.parse_prometheus(body.decode())
            assert treg.sample_value(parsed, "hits") == 7
        finally:
            reg.stop_http()


# ---------------------------------------------------------------------------
# ops.py: status / trace CLI surfaces
# ---------------------------------------------------------------------------

def _fake_spool(tmp_path, name, depth=2, drain=1.5, lat=(0.2, 0.4)):
    spool = tmp_path / name
    spool.mkdir()
    (spool / "heartbeat.json").write_text(json.dumps({
        "t": time.time(), "name": name, "draining": False,
        "pending": 1, "queue_depth": depth, "completed": 3, "failed": 0}))
    reg = treg.MetricsRegistry()
    reg.gauge(tops.M_QUEUE_DEPTH).set(depth)
    reg.gauge(tops.M_DRAIN_RATE).set(drain)
    reg.counter(tops.M_WARM, grade="exact").inc(4)
    reg.counter(tops.M_WARM, grade="cold").inc(1)
    reg.histogram(tops.M_REQ_LATENCY).observe_many(lat)
    reg.write_prom(spool / tops.PROM_FILE)
    return spool


class TestOpsStatus:
    def test_replica_status_reads_published_artifacts(self, tmp_path):
        spool = _fake_spool(tmp_path, "r0")
        st = tops.replica_status(spool)
        assert st["state"] == "up"
        assert st["queue_depth"] == 2
        assert st["drain_rate_rps"] == 1.5
        assert st["warm_hit_rate"] == 0.8
        assert st["latency_p50_s"] is not None

    def test_fleet_status_merges_histograms(self, tmp_path):
        _fake_spool(tmp_path, "r0", lat=(0.1, 0.1))
        _fake_spool(tmp_path, "r1", lat=(0.1, 0.1))
        fleet = tops.fleet_status([tmp_path], slo_s=1.0)
        assert fleet["n_replicas"] == 2 and fleet["n_up"] == 2
        assert fleet["queue_depth_total"] == 4
        # 4 observations all ~0.1s: merged p50 in the 0.1 bucket, SLO
        # attainment 100%
        assert 0.05 <= fleet["latency_p50_s"] <= 0.22
        assert fleet["slo_attainment"] == 1.0

    def test_status_cli_exits_zero(self, tmp_path, capsys):
        _fake_spool(tmp_path, "r0")
        assert tops.status_main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "r0" in out and "fleet:" in out
        assert tops.status_main([str(tmp_path), "--json"]) == 0
        json.loads(capsys.readouterr().out)

    def test_missing_spool_is_unknown_not_crash(self, tmp_path):
        fleet = tops.fleet_status([tmp_path / "nope"])
        assert fleet["n_replicas"] == 0


class TestOpsTrace:
    def _export(self, tmp_path, rid="x1"):
        root = tt.start_span("fleet_request", rid=rid)
        tt.start_span("transport", parent=root).end()
        root.event("fence", replica="r0").end()
        return tt.export_request_trace(rid, tmp_path / "traces")

    def test_trace_cli_stitches_and_exits_zero(self, tmp_path, capsys):
        self._export(tmp_path)
        assert tops.trace_main(["x1", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "fleet_request" in out and "transport" in out
        assert "slowest root-to-leaf" in out

    def test_trace_cli_chrome_out(self, tmp_path, capsys):
        self._export(tmp_path)
        chrome = tmp_path / "out.chrome.json"
        assert tops.trace_main(["x1", str(tmp_path),
                                "--chrome", str(chrome)]) == 0
        assert json.loads(chrome.read_text())["traceEvents"]

    def test_trace_cli_missing_rid_exit_3(self, tmp_path):
        assert tops.trace_main(["ghost", str(tmp_path)]) == 3

    def test_journal_fallback_reconstructs_timeline(self, tmp_path):
        from dervet_tpu.service.journal import ServiceJournal
        j = ServiceJournal(tmp_path / "service_journal.jsonl")
        j.admitted("r9", "r9.pkl", trace_id=tt.trace_id_for("r9"))
        j.completed("r9", trace_id=tt.trace_id_for("r9"))
        j.close()
        spans = tops.journal_spans("r9", [tmp_path])
        info = tt.validate_trace(spans)
        assert info["root"]["name"] == "journal_timeline"
        names = {s["name"] for s in spans}
        assert {"journal:admitted", "journal:completed"} <= names
        assert spans[0]["trace_id"] == tt.trace_id_for("r9")


# ---------------------------------------------------------------------------
# journal satellite: wall+mono pair, trace ids, tolerant replay
# ---------------------------------------------------------------------------

class TestJournalTimestamps:
    def test_records_carry_wall_mono_and_trace_id(self, tmp_path):
        from dervet_tpu.service.journal import ServiceJournal
        j = ServiceJournal(tmp_path / "j.jsonl")
        j.admitted("a", "a.csv", trace_id="t" * 32)
        j.completed("a", trace_id="t" * 32)
        j.close()
        recs = [json.loads(ln) for ln in
                (tmp_path / "j.jsonl").read_text().splitlines()]
        for rec in recs:
            assert "t" in rec and "mono" in rec
            assert rec["trace_id"] == "t" * 32
        # mono never steps backwards within one incarnation
        assert recs[1]["mono"] >= recs[0]["mono"]

    def test_replay_tolerates_pre_telemetry_records(self, tmp_path):
        from dervet_tpu.service.journal import ServiceJournal
        path = tmp_path / "j.jsonl"
        # a PR-13-era journal: no mono, no trace_id
        path.write_text(
            '{"event": "admitted", "rid": "old", "t": 1.0, '
            '"file": "old.csv"}\n'
            '{"event": "completed", "rid": "old", "t": 2.0}\n')
        states = ServiceJournal.replay_path(path)
        assert states["old"]["state"] == "completed"
        assert "trace_id" not in states["old"]
        j = ServiceJournal(path)          # append to the old journal
        j.failed("new", {"message": "x"}, trace_id="abc")
        j.close()
        states = ServiceJournal.replay_path(path)
        assert states["new"]["trace_id"] == "abc"
        assert states["old"]["state"] == "completed"


# ---------------------------------------------------------------------------
# End to end: a served request's trace + registry population
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def served_trace():
    """One request through a cpu ScenarioService, trace captured."""
    from dervet_tpu.service import ScenarioService
    tt.COLLECTOR.reset()
    svc = ScenarioService(backend="cpu", max_wait_s=0.0)
    fut = svc.submit(_cases(1), request_id="tr1")
    svc.run_once()
    res = fut.result(timeout=0)
    spans = tt.COLLECTOR.spans(tt.trace_id_for("tr1"))
    svc.close()
    return res, spans


class TestServiceTracing:
    def test_single_root_covers_the_hop_chain(self, served_trace):
        _, spans = served_trace
        info = tt.validate_trace(spans)
        assert info["root"]["name"] == "request"
        names = {s["name"] for s in spans}
        assert {"request", "admission", "batch_round", "dispatch_group",
                "certify"} <= names

    def test_dispatch_group_span_carries_ledger_attrs(self, served_trace):
        res, spans = served_trace
        grp = next(s for s in spans if s["name"] == "dispatch_group")
        attrs = grp["attrs"]
        # the solve-ledger entry is the attribute payload
        for key in ("rung", "backend", "batch", "solve_s", "windows"):
            assert key in attrs, key
        assert attrs["rung"] == "initial"
        assert "tr1" in attrs["requests"]
        led = res.solve_ledger
        assert attrs["batch"] == led["groups"][0]["batch"]

    def test_admission_span_measures_queue_wait(self, served_trace):
        _, spans = served_trace
        adm = next(s for s in spans if s["name"] == "admission")
        assert adm["duration_s"] >= 0
        assert adm["attrs"]["queue_wait_s"] == pytest.approx(
            adm["duration_s"], abs=1e-6)

    def test_registry_populated_from_round(self, served_trace):
        reg = treg.get_registry()
        snap = reg.snapshot()
        assert snap["counters"].get("dervet_rounds_total", 0) >= 1
        assert snap["counters"].get(
            'dervet_requests_total{outcome="completed"}', 0) >= 1
        hist = snap["histograms"].get("dervet_request_latency_seconds")
        assert hist and hist["count"] >= 1
        # certification verdicts feed the registry (the status CLI's
        # cert%% column reads this series)
        assert snap["counters"].get(
            'dervet_certifications_total{verdict="accepted"}', 0) >= 1
        validate_telemetry_section(snap)

    def test_load_shed_trace_carries_degraded_marker(self):
        from dervet_tpu.service import ScenarioService
        svc = ScenarioService(backend="cpu", max_wait_s=0.0,
                              max_queue_depth=8, max_batch_requests=4,
                              shed_threshold_frac=0.5,
                              shed_sustain_rounds=1)
        futs = {}
        for i in range(8):
            futs[i] = svc.submit(_cases(1), request_id=f"sh{i}",
                                 priority=(1 if i % 2 else 0))
        while svc.queue.depth():
            svc.run_once()
        shed_rid = next(f"sh{i}" for i, f in futs.items()
                        if f.result(0).fidelity == "degraded")
        spans = tt.COLLECTOR.spans(tt.trace_id_for(shed_rid))
        svc.close()
        tt.validate_trace(spans)
        root = next(s for s in spans if s["name"] == "request")
        assert root["attrs"].get("fidelity") == "degraded"
        rnd = next(s for s in spans if s["name"] == "batch_round")
        assert rnd["attrs"]["fidelity"] == "degraded"
        assert any(e["name"] == "load_shed"
                   for e in rnd.get("events", ()))

    def test_kill_switch_results_byte_identical(self, tmp_path,
                                                monkeypatch):
        from dervet_tpu.service import ScenarioService

        def serve(out_dir):
            svc = ScenarioService(backend="cpu", max_wait_s=0.0)
            fut = svc.submit(_cases(1), request_id="ks")
            svc.run_once()
            res = fut.result(timeout=0)
            res.save_as_csv(out_dir)
            svc.close()
            return {p.name: p.read_bytes()
                    for p in sorted(out_dir.glob("*.csv"))}

        on = serve(tmp_path / "on")
        assert tt.COLLECTOR.spans(tt.trace_id_for("ks"))
        tt.COLLECTOR.reset()
        monkeypatch.setenv(tt.ENV, "0")
        off = serve(tmp_path / "off")
        assert on and on == off
        assert tt.COLLECTOR.spans(tt.trace_id_for("ks")) == []


# ---------------------------------------------------------------------------
# Fleet: stitched traces + published-load routing (in-process replicas)
# ---------------------------------------------------------------------------

class TestFleetTelemetry:
    def test_local_fleet_single_stitched_trace(self):
        from dervet_tpu.service import ScenarioService
        from dervet_tpu.service.fleet import LocalReplica
        from dervet_tpu.service.router import FleetRouter
        svcs = [ScenarioService(backend="cpu", max_wait_s=0.0)
                for _ in range(2)]
        reps = [LocalReplica(f"lr{i}", s) for i, s in enumerate(svcs)]
        router = FleetRouter(reps, heartbeat_timeout_s=5.0,
                             tick_s=0.02).start()
        try:
            fut = router.submit(_cases(1), request_id="fl1",
                                deadline_s=300.0)
            deadline = time.monotonic() + 120
            while not fut.done() and time.monotonic() < deadline:
                for s in svcs:
                    s.run_once()
                time.sleep(0.01)
            res = fut.result(timeout=1)
            assert res.result is not None
            # ONE trace: the replica's spans parent under the router's
            # root via the transport context — single root, full chain
            spans = tt.COLLECTOR.spans(tt.trace_id_for("fl1"))
            info = tt.validate_trace(spans)
            assert info["root"]["name"] == "fleet_request"
            names = {s["name"] for s in spans}
            assert {"fleet_request", "transport", "request",
                    "admission", "batch_round",
                    "dispatch_group"} <= names
            root = info["root"]
            assert any(e["name"] == "routed"
                       for e in root.get("events", ()))
        finally:
            router.close()
            for s in svcs:
                s.close()

    def test_published_load_outranks_inflight(self):
        from dervet_tpu.service.router import FleetRouter
        from tests.test_fleet import StubReplica
        a, b = StubReplica("a"), StubReplica("b")
        router = FleetRouter([a, b], heartbeat_timeout_s=5.0,
                             tick_s=1000.0)   # no monitor interference
        # a never published -> inflight fallback tier (sorts after b)
        router._pub_load["b"] = {"queue_depth": 0.0,
                                 "drain_rate_rps": 2.0, "pending": 0.0}
        assert router._load_score("a")[0] == 1
        assert router._load_score("b")[0] == 0
        # published backlog ranks by estimated drain seconds
        router._pub_load["a"] = {"queue_depth": 8.0,
                                 "drain_rate_rps": 2.0, "pending": 0.0}
        assert router._load_score("a")[1] == pytest.approx(4.0)
        assert router._load_score("b")[1] == pytest.approx(0.0)
        fut = router.submit(_stub_cases_small(), request_id="lr1")
        assert "lr1" in b.reqs and "lr1" not in a.reqs
        assert not fut.done()
        router.close(terminate_replicas=False)

    def test_stale_publication_falls_back_to_inflight(self):
        from dervet_tpu.service.router import FleetRouter
        from tests.test_fleet import StubReplica
        a, b = StubReplica("a"), StubReplica("b")
        router = FleetRouter([a, b], heartbeat_timeout_s=5.0,
                             tick_s=1000.0)
        # a frozen exposition (dead replica, or one respawned with
        # telemetry off) must not keep ranking as idle: a stale
        # t_published demotes to the inflight fallback tier
        router._pub_load["b"] = {
            "queue_depth": 0.0, "drain_rate_rps": 2.0, "pending": 0.0,
            "t_published": time.time() - 10 * router._pub_stale_s}
        assert router._load_score("b")[0] == 1
        router._pub_load["b"]["t_published"] = time.time()
        assert router._load_score("b")[0] == 0
        # local-transport signals carry no t_published (read live) —
        # they never go stale
        router._pub_load["a"] = {"queue_depth": 1.0,
                                 "drain_rate_rps": 1.0, "pending": 0.0}
        assert router._load_score("a")[0] == 0
        router.close(terminate_replicas=False)

    def test_local_replica_publishes_live_queue(self):
        from dervet_tpu.service import ScenarioService
        from dervet_tpu.service.fleet import LocalReplica
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        rep = LocalReplica("pub", svc)
        pub = rep.published_load()
        assert pub is not None and pub["queue_depth"] == 0
        rep.kill()
        assert rep.published_load() is None
        svc._fail_pending()

    def test_spool_payload_carries_trace_context(self, tmp_path):
        import pickle
        from dervet_tpu.service.fleet import SpoolReplica
        ctx = {"trace_id": "t" * 32, "span_id": "s1"}
        blob = SpoolReplica.encode_payload(
            {"0": None}, priority=1, deadline_epoch=None, trace=ctx)
        assert pickle.loads(blob)["trace"] == ctx
        # probe file carries the context too (heartbeat echo path)
        rep = SpoolReplica("r", tmp_path)
        rep.probe("n1", trace=ctx)
        doc = json.loads((tmp_path / "probe.json").read_text())
        assert doc["nonce"] == "n1" and doc["trace"] == ctx


_STUB_CASES = None


def _stub_cases_small():
    global _STUB_CASES
    if _STUB_CASES is None:
        _STUB_CASES = _cases(1)
    return _STUB_CASES
