"""Fleet-sharded portfolio dual rounds + stabilized Dantzig-Wolfe master.

The PR-15 contract under test:

* STABILIZATION — the in-out / proximal-level master step converges in
  strictly fewer outer rounds than the unstabilized (PR-13 three-regime)
  control at the 16-site smoke shape, WITHOUT moving the answer: the
  monolithic-reference parity stays <= 1e-6, the ``diverging_duals``
  drill still converges + certifies with stabilization on, and the
  ``DERVET_TPU_PORTFOLIO_STABILIZE=0`` kill switch is bit-for-bit
  equivalent to ``master_stabilization=False``;
* SHARD PLANNER — deterministic, structure-aware (fingerprint groups
  stay together until they must split), clamped to the site count,
  LPT-balanced by window count;
* SHARDED-ROUND PARITY — for a FIXED shard plan the per-site columns and
  costs are identical to the single-host path: a local-sharded solve is
  byte-identical to the monolithic one (duals, aggregate, site
  solutions), and a FLEET-sharded solve (real ``FleetRouter`` over
  ``LocalReplica`` services) matches it too, with shard->replica
  assignment STICKY across rounds;
* HINT HANDOFF — ``dual_iterate`` hint-table entries ride the fleet
  memory payload (``export_payload``/``import_payload``), so a failover
  or re-routed portfolio shard reseeds mid-dual-loop instead of
  restarting its sites cold; legacy payloads (bare entries list / dict
  without "hints") still import.
"""
import os
import pickle

import numpy as np
import pytest

from dervet_tpu.ops.warmstart import SolutionMemory
from dervet_tpu.portfolio import (PortfolioSpec, monolithic_reference,
                                  solve_portfolio,
                                  validate_portfolio_section)
from dervet_tpu.portfolio.service import synthetic_portfolio_members
from dervet_tpu.portfolio.shard import merge_summaries, plan_shards
from dervet_tpu.utils import faultinject
from dervet_tpu.utils.errors import ParameterError


def _members(n=8, hours=48, window=24, seed=0):
    return synthetic_portfolio_members(n, hours=hours, window=window,
                                       seed=seed, pv_kw=9000.0)


def _binding_cap(n=8, hours=48, window=24, margin=1500.0):
    probe = solve_portfolio(
        PortfolioSpec(members=_members(n, hours, window),
                      export_cap_kw=1e9, max_outer=1), backend="cpu")
    return float(probe.aggregate["net_export"].max()) - margin


def _assert_same_bytes(a, b):
    """Byte-level equality of two portfolio results (the fixed-plan
    parity contract: duals, aggregate, every site solution array)."""
    assert a.aggregate["net_export"].tobytes() == \
        b.aggregate["net_export"].tobytes()
    for kind in a.duals:
        assert a.duals[kind].tobytes() == b.duals[kind].tobytes(), kind
    for key, arrs in a.site_solutions.items():
        for name, arr in arrs.items():
            assert arr.tobytes() == \
                b.site_solutions[key][name].tobytes(), (key, name)


# ---------------------------------------------------------------------------
# Stabilized Dantzig-Wolfe master
# ---------------------------------------------------------------------------

class TestStabilization:
    def test_cuts_rounds_vs_control_16_sites(self, monkeypatch):
        """The smoke-shape acceptance gate: stabilization reaches the
        gap in STRICTLY fewer outer rounds than the PR-13 control."""
        # margin 4000 kW: the cap binds hard enough that the control's
        # harmonic-decay tail is long (15 rounds vs 5 measured) — a
        # soft cap converges in 2 rounds both ways and gates nothing
        cap = _binding_cap(16, margin=4000.0)
        spec = PortfolioSpec(members=_members(16), export_cap_kw=cap,
                             gap_tol=1e-6, feas_tol=1e-7, max_outer=60)
        stab = solve_portfolio(spec, backend="cpu")
        monkeypatch.setenv("DERVET_TPU_PORTFOLIO_STABILIZE", "0")
        control = solve_portfolio(spec, backend="cpu")
        assert stab.converged and control.converged
        assert stab.stabilized and not control.stabilized
        assert stab.outer_rounds < control.outer_rounds, \
            (stab.outer_rounds, control.outer_rounds)
        # the round records say which regime each step ran
        regimes = {r["regime"] for r in stab.rounds}
        assert regimes & {"in_out_serious", "in_out_null",
                          "in_out_exact"}, regimes
        assert not any(str(r["regime"]).startswith("in_out")
                       for r in control.rounds)

    def test_monolithic_parity_preserved(self):
        """Stabilization must not move the answer: 2-site toy matches
        the monolithic HiGHS coupled LP to 1e-6 (same gate as PR 13)."""
        cap = _binding_cap(2, margin=800.0)
        spec = PortfolioSpec(members=_members(2), export_cap_kw=cap,
                             gap_tol=1e-9, feas_tol=1e-7, max_outer=60)
        res = solve_portfolio(spec, backend="cpu")
        assert res.converged and res.stabilized
        mono = monolithic_reference(
            PortfolioSpec(members=_members(2), export_cap_kw=cap))
        assert mono["status"] == 0
        rel = abs(res.primal_objective - mono["objective_cx"]) \
            / (1.0 + abs(mono["objective_cx"]))
        assert rel < 1e-6, (res.primal_objective, mono["objective_cx"])

    def test_kill_switch_matches_spec_off_bitwise(self, monkeypatch):
        """DERVET_TPU_PORTFOLIO_STABILIZE=0 and
        ``master_stabilization=False`` run the SAME legacy loop — the
        kill switch restores it bit for bit."""
        cap = _binding_cap()
        spec_env = PortfolioSpec(members=_members(), export_cap_kw=cap,
                                 gap_tol=1e-4, feas_tol=1e-6,
                                 max_outer=40)
        monkeypatch.setenv("DERVET_TPU_PORTFOLIO_STABILIZE", "0")
        a = solve_portfolio(spec_env, backend="cpu")
        monkeypatch.delenv("DERVET_TPU_PORTFOLIO_STABILIZE")
        spec_off = PortfolioSpec(members=_members(), export_cap_kw=cap,
                                 gap_tol=1e-4, feas_tol=1e-6,
                                 max_outer=40,
                                 master_stabilization=False)
        b = solve_portfolio(spec_off, backend="cpu")
        assert not a.stabilized and not b.stabilized
        assert a.outer_rounds == b.outer_rounds
        _assert_same_bytes(a, b)

    def test_diverging_duals_converges_certified_stabilized(self):
        """The PR-13 corruption drill under the stabilized master: the
        non-monotone bound is detected, the step contracts toward the
        stability center, and the loop still converges + certifies."""
        probe = solve_portfolio(
            PortfolioSpec(members=_members(4, hours=336, window=168),
                          export_cap_kw=1e9, max_outer=1),
            backend="jax")
        cap = float(probe.aggregate["net_export"].max()) - 2000.0
        with faultinject.inject(diverge_duals_round=1,
                                diverge_duals_scale=25.0) as plan:
            res = solve_portfolio(
                PortfolioSpec(members=_members(4, hours=336,
                                               window=168),
                              export_cap_kw=cap, max_outer=14),
                backend="jax")
        assert ("diverging_duals", "1") in plan.fired
        assert res.stabilized
        assert res.dual_rescales >= 1
        assert res.converged
        assert res.certification["verdict"] in ("certified",
                                                "certified_loose")

    def test_section_schema_carries_new_fields(self):
        cap = _binding_cap(2, margin=800.0)
        res = solve_portfolio(
            PortfolioSpec(members=_members(2), export_cap_kw=cap,
                          gap_tol=1e-4, max_outer=20), backend="cpu")
        section = validate_portfolio_section(res.portfolio_section())
        assert section["stabilized"] is True
        assert section["shards"] == 1
        assert all("regime" in r and "shards" in r
                   for r in section["rounds"])


# ---------------------------------------------------------------------------
# The shard planner
# ---------------------------------------------------------------------------

class _FakeScen:
    def __init__(self, n_windows):
        self.windows = list(range(n_windows))


class TestShardPlanner:
    def test_deterministic_and_partitioning(self):
        scens = {f"s{i:02d}": _FakeScen(2) for i in range(10)}
        fps = {k: f"fp{i % 3}" for i, k in enumerate(sorted(scens))}
        a = plan_shards(scens, 3, fingerprints=fps)
        b = plan_shards(scens, 3, fingerprints=fps)
        assert a == b
        flat = sorted(k for shard in a for k in shard)
        assert flat == sorted(scens)
        assert len(a) == 3

    def test_structure_groups_stay_together(self):
        """Sites sharing a fingerprint co-batch — the planner keeps a
        group on one shard when it fits the per-shard target."""
        scens = {f"s{i}": _FakeScen(2) for i in range(6)}
        fps = {"s0": "A", "s1": "A", "s2": "A",
               "s3": "B", "s4": "B", "s5": "B"}
        plan = plan_shards(scens, 2, fingerprints=fps)
        assert len(plan) == 2
        shard_fps = [{fps[k] for k in shard} for shard in plan]
        assert all(len(s) == 1 for s in shard_fps), plan

    def test_clamps_to_site_count_and_drops_empty(self):
        scens = {f"s{i}": _FakeScen(1) for i in range(3)}
        fps = {k: "same" for k in scens}
        plan = plan_shards(scens, 8, fingerprints=fps)
        assert len(plan) <= 3
        assert sorted(k for s in plan for k in s) == sorted(scens)

    def test_one_shard_is_identity(self):
        scens = {f"s{i}": _FakeScen(1) for i in range(4)}
        assert plan_shards(scens, 1) == [sorted(scens)]

    def test_lpt_balances_window_cost(self):
        scens = {"big0": _FakeScen(8), "big1": _FakeScen(8),
                 "a": _FakeScen(1), "b": _FakeScen(1),
                 "c": _FakeScen(1), "d": _FakeScen(1)}
        fps = {k: k for k in scens}          # all distinct structures
        plan = plan_shards(scens, 2, fingerprints=fps)
        loads = [sum(len(scens[k].windows) for k in shard)
                 for shard in plan]
        assert max(loads) - min(loads) <= 2, (plan, loads)

    def test_spec_knobs(self, monkeypatch):
        spec = PortfolioSpec(members=_members(4), export_cap_kw=1.0,
                             shards=3)
        assert spec.effective_shards(4) == 3
        assert spec.effective_shards(2) == 2     # clamped
        with pytest.raises(ParameterError, match="shards"):
            PortfolioSpec(members=_members(2), export_cap_kw=1.0,
                          shards=0).validate()
        monkeypatch.setenv("DERVET_TPU_PORTFOLIO_SHARDS", "2")
        spec2 = PortfolioSpec(members=_members(4), export_cap_kw=1.0)
        assert spec2.effective_shards(4) == 2    # env fills a None
        assert PortfolioSpec(members=_members(4), export_cap_kw=1.0,
                             shards=1).effective_shards(4) == 1

    def test_merge_summaries_counters_and_weighted_p50(self):
        parts = [{"iters_p50": 100.0, "seeded": 2, "dual_iterate": 2,
                  "substituted": 0, "compile_events": 1, "windows": 6,
                  "iters_p50_seeded": 90.0, "iters_p50_cold": None},
                 {"iters_p50": 300.0, "seeded": 1, "dual_iterate": 1,
                  "substituted": 1, "compile_events": 0, "windows": 2,
                  "iters_p50_seeded": None, "iters_p50_cold": 320.0}]
        m = merge_summaries(parts)
        assert m["windows"] == 8 and m["seeded"] == 3
        assert m["compile_events"] == 1
        assert m["iters_p50"] == 100.0       # windows-weighted median


# ---------------------------------------------------------------------------
# Sharded-round parity (local executor)
# ---------------------------------------------------------------------------

class TestLocalShardParity:
    def test_sharded_byte_identical_to_monolithic(self):
        """For a fixed shard plan the per-site columns and costs are
        identical to the single-host path — cpu backend, so identical
        means BYTES."""
        cap = _binding_cap()
        kw = dict(export_cap_kw=cap, gap_tol=1e-6, feas_tol=1e-7,
                  max_outer=40)
        mono = solve_portfolio(
            PortfolioSpec(members=_members(), **kw), backend="cpu")
        shard = solve_portfolio(
            PortfolioSpec(members=_members(), shards=3, **kw),
            backend="cpu")
        assert mono.converged and shard.converged
        assert shard.outer_rounds == mono.outer_rounds
        assert len(shard.shard_plan) == 3
        _assert_same_bytes(mono, shard)
        # per-round shard records carry the observability surface
        for r in shard.rounds:
            assert r["shards"] == 3
            assert len(r["shard_detail"]) == 3
            assert sum(d["sites"] for d in r["shard_detail"]) == 8

    def test_env_shards_override(self, monkeypatch):
        cap = _binding_cap(4, margin=800.0)
        monkeypatch.setenv("DERVET_TPU_PORTFOLIO_SHARDS", "2")
        res = solve_portfolio(
            PortfolioSpec(members=_members(4), export_cap_kw=cap,
                          gap_tol=1e-4, max_outer=30), backend="cpu")
        assert res.converged
        assert len(res.shard_plan) == 2


# ---------------------------------------------------------------------------
# Fleet-sharded rounds (real router over LocalReplica services)
# ---------------------------------------------------------------------------

class TestFleetShardedRounds:
    def _fleet(self, n=2):
        from dervet_tpu.service.fleet import LocalReplica
        from dervet_tpu.service.router import FleetRouter
        from dervet_tpu.service.server import ScenarioService
        services = [ScenarioService(backend="cpu", max_wait_s=0.0)
                    for _ in range(n)]
        for s in services:
            s.start()
        reps = [LocalReplica(f"n{i}", s)
                for i, s in enumerate(services)]
        router = FleetRouter(reps, heartbeat_timeout_s=5.0,
                             hedging=False).start()
        return router, services

    def test_fleet_round_matches_monolithic_and_sticky(self):
        cap = _binding_cap()
        kw = dict(export_cap_kw=cap, gap_tol=1e-6, feas_tol=1e-7,
                  max_outer=40)
        mono = solve_portfolio(
            PortfolioSpec(members=_members(), **kw), backend="cpu")
        router, services = self._fleet()
        try:
            res = solve_portfolio(
                PortfolioSpec(members=_members(), shards=2, **kw),
                backend="cpu", fleet=router, request_id="pfx")
        finally:
            router.close(terminate_replicas=False)
            for s in services:
                s.close()
        assert res.converged
        assert res.outer_rounds == mono.outer_rounds
        _assert_same_bytes(mono, res)
        # sticky shard->replica assignment: each shard index stays on
        # the replica that served it in round 0 (hint warmth +
        # compiled-program affinity live there)
        detail = [r["shard_detail"] for r in res.rounds]
        homes = {d["shard"]: d["replica"] for d in detail[0]}
        assert set(homes.values()) == {"n0", "n1"}   # shards spread
        for rnd in detail[1:]:
            for d in rnd:
                assert d["replica"] == homes[d["shard"]], detail
        # the replicas counted the shard rounds they served
        shard_reqs = sum(s.metrics()["portfolio"]["shard_requests"]
                        for s in services)
        assert shard_reqs == res.outer_rounds * 2

    def test_two_anonymous_solves_share_one_router(self):
        """Anonymous solves mint unique portfolio ids — shard rids must
        not collide with the router's exactly-once memo on a second
        solve (regression: both used to be 'pf.s00.r000')."""
        cap = _binding_cap(4, margin=800.0)
        spec = PortfolioSpec(members=_members(4), export_cap_kw=cap,
                             gap_tol=1e-4, max_outer=20, shards=2)
        router, services = self._fleet()
        try:
            a = solve_portfolio(spec, backend="cpu", fleet=router)
            b = solve_portfolio(spec, backend="cpu", fleet=router)
        finally:
            router.close(terminate_replicas=False)
            for s in services:
                s.close()
        assert a.converged and b.converged
        assert a.primal_objective == b.primal_objective

    def test_replica_honors_payload_backend(self):
        """The shard payload's backend wins on the replica — the owner
        stamped inner_exact from the backend it requested."""
        from dervet_tpu.portfolio.shard import solve_portfolio_shard
        m = _members(2)
        payload = {"sites": m, "price": np.zeros(48),
                   "seed_tag": "t", "shard": 0, "round": 0,
                   "backend": "cpu", "solver_opts": None}
        res = solve_portfolio_shard(payload)   # no explicit backend
        assert set(res.outcomes) == set(str(k) for k in m)

    def test_local_shards_share_caller_memory(self):
        from dervet_tpu.portfolio.shard import LocalShardExecutor
        m = SolutionMemory(max_entries=4)
        ex = LocalShardExecutor({}, [[], []], backend="cpu", memory=m)
        assert all(c.memory is m for c in ex.caches)

    def test_shard_request_admission_validates(self):
        from dervet_tpu.service.server import ScenarioService
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        svc.start()
        try:
            with pytest.raises(ValueError, match="sites"):
                svc.submit_portfolio_shard({"sites": {}})
        finally:
            svc.close()


class TestShardCaseCache:
    """ROADMAP 1a closed: the full site payload ships once; later dual
    rounds ship a reference (price + plan fingerprint) resolved against
    the replica's bounded case cache, and a cold replica's typed miss
    triggers exactly one full-payload reseed."""

    def _service(self):
        from dervet_tpu.service.server import ScenarioService
        svc = ScenarioService(backend="cpu", max_wait_s=0.0)
        svc.start()
        return svc

    def test_reference_resolves_after_seed(self):
        svc = self._service()
        try:
            m = _members(2)
            full = {"sites": m, "price": np.zeros(48), "seed_tag": "t",
                    "plan_fp": "fp1", "shard": 0, "round": 0,
                    "backend": "cpu", "solver_opts": None}
            res = svc.submit_portfolio_shard(full).result(timeout=300)
            ref = {k: v for k, v in full.items() if k != "sites"}
            ref["round"] = 1
            res2 = svc.submit_portfolio_shard(ref).result(timeout=300)
            assert set(res2.outcomes) == set(res.outcomes)
        finally:
            svc.close()

    def test_cold_reference_raises_typed_miss(self):
        from dervet_tpu.utils.errors import ShardCacheMissError
        svc = self._service()
        try:
            with pytest.raises(ShardCacheMissError):
                svc.submit_portfolio_shard(
                    {"price": np.zeros(48), "seed_tag": "t",
                     "plan_fp": "never-seeded", "shard": 0,
                     "round": 1, "backend": "cpu",
                     "solver_opts": None})
        finally:
            svc.close()

    def test_plan_fp_mismatch_misses(self):
        # same seed_tag, DIFFERENT content fingerprint: the cache must
        # never resolve a stale site set for an edited portfolio
        from dervet_tpu.utils.errors import ShardCacheMissError
        svc = self._service()
        try:
            m = _members(2)
            svc.submit_portfolio_shard(
                {"sites": m, "price": np.zeros(48), "seed_tag": "t",
                 "plan_fp": "fp1", "shard": 0, "round": 0,
                 "backend": "cpu",
                 "solver_opts": None}).result(timeout=300)
            with pytest.raises(ShardCacheMissError):
                svc.submit_portfolio_shard(
                    {"price": np.zeros(48), "seed_tag": "t",
                     "plan_fp": "fp2-edited", "shard": 0, "round": 1,
                     "backend": "cpu", "solver_opts": None})
        finally:
            svc.close()

    def test_cache_is_bounded_lru(self):
        from dervet_tpu.utils.errors import ShardCacheMissError
        svc = self._service()
        svc._shard_cases_cap = 1
        try:
            m = _members(2)
            base = {"price": np.zeros(48), "shard": 0, "round": 0,
                    "backend": "cpu", "solver_opts": None}
            svc.submit_portfolio_shard(
                {**base, "sites": m, "seed_tag": "a",
                 "plan_fp": "fa"}).result(timeout=300)
            svc.submit_portfolio_shard(
                {**base, "sites": m, "seed_tag": "b",
                 "plan_fp": "fb"}).result(timeout=300)
            # "a" evicted by the 1-entry cap: its reference must miss
            with pytest.raises(ShardCacheMissError):
                svc.submit_portfolio_shard(
                    {**base, "seed_tag": "a", "plan_fp": "fa",
                     "round": 1})
        finally:
            svc.close()

    def test_executor_ref_rounds_and_miss_reseed(self):
        """End-to-end executor protocol: round 0 ships full payloads,
        round 1 ships references at a fraction of the bytes, and an
        evicted replica cache (cold after failover/restart) triggers a
        one-shot full reseed that restores the round."""
        from dervet_tpu.portfolio.shard import FleetShardExecutor
        from dervet_tpu.service.fleet import LocalReplica
        from dervet_tpu.service.router import FleetRouter
        from dervet_tpu.service.server import ScenarioService
        services = [ScenarioService(backend="cpu", max_wait_s=0.0)
                    for _ in range(2)]
        for s in services:
            s.start()
        reps = [LocalReplica(f"n{i}", s)
                for i, s in enumerate(services)]
        router = FleetRouter(reps, heartbeat_timeout_s=5.0,
                             hedging=False).start()
        try:
            m = _members(4)
            keys = sorted(m, key=str)
            ex = FleetShardExecutor(
                m, [keys[:2], keys[2:]], router, backend="cpu",
                portfolio_id="pfc", deadline_s=300.0)
            assert all(fp is not None for fp in ex.plan_fps)
            price = np.zeros(48)
            r0 = ex.dispatch_round(price, 0)
            r1 = ex.dispatch_round(price, 1)
            assert all(not rec["ref_mode"] for rec in r0.shard_records)
            assert all(rec["ref_mode"] for rec in r1.shard_records)
            # the remainder's point: a reference round ships a small
            # fraction of the full payload's bytes
            assert ex.wire_bytes_rounds[1] < 0.2 * ex.wire_bytes_rounds[0]
            assert set(r1.outcomes) == set(r0.outcomes) == set(map(str,
                                                                   keys))
            # evict every replica's case cache (what a restart or a
            # failover-moved shard looks like), then round 2 reseeds
            for svc in services:
                with svc._shard_cases_lock:
                    svc._shard_cases.clear()
            r2 = ex.dispatch_round(price, 2)
            assert set(r2.outcomes) == set(r0.outcomes)
            assert ex.wire_bytes_rounds[2] > ex.wire_bytes_rounds[1]
            # and the NEXT round is back to cheap references
            r3 = ex.dispatch_round(price, 3)
            assert all(rec["ref_mode"] for rec in r3.shard_records)
            assert ex.wire_bytes_rounds[3] < 0.2 * ex.wire_bytes_rounds[2]
        finally:
            router.close(terminate_replicas=False)
            for s in services:
                s.close()


# ---------------------------------------------------------------------------
# dual_iterate hints ride the fleet memory handoff
# ---------------------------------------------------------------------------

class TestHintHandoff:
    def test_hints_round_trip_through_payload(self):
        m = SolutionMemory(max_entries=8)
        m.store_hint(("pf", "siteA", 0), np.arange(4.0),
                     np.arange(3.0), -1.5)
        m.store_hint(("pf", "siteB", 1), np.ones(4), np.zeros(3), -2.5)
        payload = pickle.loads(pickle.dumps(m.export_payload()))
        assert payload["hints"]
        m2 = SolutionMemory(max_entries=8)
        assert m2.import_payload(payload) == 0   # no primary entries
        assert m2.stats["imported_hints"] == 2
        e = m2.lookup_hint(("pf", "siteA", 0))
        assert e is not None
        assert np.array_equal(e.x, np.arange(4.0))
        assert np.array_equal(e.y, np.arange(3.0))
        assert m2.snapshot()["hint_entries"] == 2

    def test_local_hint_wins_over_import(self):
        m = SolutionMemory(max_entries=8)
        m.store_hint(("pf", "s", 0), np.zeros(2), np.zeros(1), 0.0)
        payload = m.export_payload()
        m2 = SolutionMemory(max_entries=8)
        m2.store_hint(("pf", "s", 0), np.ones(2), np.ones(1), 9.0)
        m2.import_payload(payload)
        assert np.array_equal(m2.lookup_hint(("pf", "s", 0)).x,
                              np.ones(2))

    def test_legacy_payloads_still_import(self):
        m = SolutionMemory(max_entries=8)
        m2 = SolutionMemory(max_entries=8)
        # bare entries list (pre-PR-11 replicas)
        assert m2.import_payload(m.export_entries()) == 0
        # dict without "hints" (PR-11..14 replicas)
        assert m2.import_payload({"entries": [], "models": None}) == 0
        # malformed hint rows are skipped, good ones land — including
        # an UNHASHABLE key (nested list), which must not abort the
        # rest of the payload
        n = m2.import_hints([("bad", {"x": "nope"}),
                             (("t", ["site", 3]), {"x": np.zeros(1),
                                                   "y": np.zeros(1),
                                                   "obj": 0.0}),
                             (("ok",), {"x": np.zeros(1),
                                        "y": np.zeros(1),
                                        "obj": 1.0})])
        assert n == 1
        assert m2.lookup_hint(("ok",)) is not None

    def test_hint_table_stays_bounded_on_import(self):
        m = SolutionMemory(max_entries=4)
        for i in range(8):
            m.store_hint(("pf", i), np.zeros(1), np.zeros(1), 0.0)
        payload = m.export_payload()
        assert len(payload["hints"]) <= 4
        m2 = SolutionMemory(max_entries=4)
        m2.import_hints(payload["hints"])
        assert m2.snapshot()["hint_entries"] <= 4
