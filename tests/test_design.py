"""Design subsystem (BOOST ordinal-optimization sizing): population
generation, the ordinal screen, and the certified frontier.

The contract under test:

* populations are DETERMINISTIC (Halton low-discrepancy sampling; same
  spec -> same candidates), respect the bounds and the ESS duration
  coupling, and explicit grids are deduplicated + sorted so no candidate
  ever solves twice;
* screening is ORDINAL-ONLY: it rides the batched dispatch with the
  loose screening tiers, certification is forced off thread-locally,
  and no screening answer ever carries a certificate;
* the whole population rides the batch axis — the screening device-
  dispatch count is far below one-dispatch-per-candidate;
* the certified frontier's finalists each carry a full PR-4 float64
  certificate, the screening-vs-final rank correlation is reported, and
  dominated candidates are masked;
* ``sizing_sweep`` remains a faithful legacy surface over the engine:
  same columns, deduped/sorted grid, same guard errors.
"""
import json

import numpy as np
import pytest

from dervet_tpu.benchlib import synthetic_case
from dervet_tpu.design import (DERBounds, DesignSpec, dominated_mask,
                               generate_population, halton, run_design,
                               spearman_rank)
from dervet_tpu.design.frontier import FIDELITY_DEGRADED
from dervet_tpu.design.population import candidate_case
from dervet_tpu.design.screen import screen_candidates
from dervet_tpu.utils.errors import ParameterError


def _case(hours: int = 72, **kw):
    c = synthetic_case(**kw)
    c.scenario["allow_partial_year"] = True
    c.datasets.time_series = c.datasets.time_series.iloc[:hours]
    return c


def _spec(**over):
    base = dict(bounds={("Battery", "1"): DERBounds(kw=(500.0, 2500.0),
                                                    kwh=(1000.0, 9000.0))},
                population=12, top_k=3, refine_rounds=1)
    base.update(over)
    return DesignSpec(**base)


# ---------------------------------------------------------------------------
# Population generation
# ---------------------------------------------------------------------------

class TestPopulation:
    def test_halton_covers_unit_box(self):
        pts = halton(256, 3)
        assert pts.shape == (256, 3)
        assert pts.min() >= 0.0 and pts.max() < 1.0
        # low-discrepancy: every octant of the box is populated
        octant = (pts > 0.5) @ np.array([1, 2, 4])
        assert set(octant.tolist()) == set(range(8))

    def test_population_deterministic_and_in_bounds(self):
        a = generate_population(_spec(population=64))
        b = generate_population(_spec(population=64))
        assert [c.sizes for c in a] == [c.sizes for c in b]
        for c in a:
            (tag, der_id, kw, kwh), = c.sizes
            assert 500.0 <= kw <= 2500.0
            assert 1000.0 <= kwh <= 9000.0

    def test_duration_coupling_bounds_energy(self):
        pop = generate_population(_spec(population=64,
                                        duration_hours=(1.0, 3.0)))
        for c in pop:
            (_, _, kw, kwh), = c.sizes
            # clipped into BOTH the duration box and the kwh bounds
            assert 1000.0 <= kwh <= 9000.0
            assert kwh <= kw * 3.0 + 1e-9 or kwh == 1000.0

    def test_explicit_grid_dedupes_and_sorts(self):
        spec = _spec(population=0, refine_rounds=0,
                     grid=[(1000, 4000), (500, 1000), (500, 1000),
                           (1000, 4000)])
        pop = generate_population(spec)
        pairs = [(c.sizes[0][2], c.sizes[0][3]) for c in pop]
        assert pairs == [(500.0, 1000.0), (1000.0, 4000.0)]
        assert all(c.source == "grid" for c in pop)

    def test_candidate_case_shares_frames_but_not_keys(self):
        case = _case()
        pop = generate_population(_spec(population=2))
        c0 = candidate_case(case, pop[0])
        # the time-series frame is shared (no 512x data copies) ...
        assert c0.datasets.time_series is case.datasets.time_series
        # ... but the Datasets holder and the key dicts are private
        assert c0.datasets is not case.datasets
        (tag, der_id, kw, kwh), = pop[0].sizes
        got = next(k for t, i, k in c0.ders if t == "Battery")
        base = next(k for t, i, k in case.ders if t == "Battery")
        assert got["ene_max_rated"] == kwh
        assert base["ene_max_rated"] != kwh

    def test_spec_validation(self):
        with pytest.raises(ParameterError, match="top_k"):
            _spec(top_k=0).validate()
        with pytest.raises(ParameterError, match="bounds"):
            DesignSpec(bounds={}).validate()
        with pytest.raises(ParameterError, match="lo <= hi"):
            _spec(bounds={("Battery", "1"):
                          DERBounds(kw=(2000.0, 500.0))}).validate()
        with pytest.raises(ParameterError, match="storage"):
            _spec(bounds={("PV", "1"):
                          DERBounds(kw=(1.0, 2.0),
                                    kwh=(1.0, 2.0))}).validate()
        with pytest.raises(ParameterError, match="ONE sized DER"):
            DesignSpec(bounds={
                ("Battery", "1"): DERBounds(kw=(1.0, 2.0)),
                ("PV", "1"): DERBounds(kw=(1.0, 2.0))},
                grid=[(1.0, 1.0)]).validate()

    def test_missing_der_raises(self):
        case = _case()
        spec = _spec(bounds={("CAES", "9"): DERBounds(kw=(1.0, 2.0),
                                                      kwh=(1.0, 2.0))},
                     population=4)
        pop = generate_population(spec)
        with pytest.raises(ParameterError, match="no CAES"):
            candidate_case(case, pop[0])


# ---------------------------------------------------------------------------
# Frontier math helpers
# ---------------------------------------------------------------------------

class TestFrontierMath:
    def test_spearman_perfect_and_inverted(self):
        assert spearman_rank([1, 2, 3, 4], [10, 20, 30, 40]) == 1.0
        assert spearman_rank([1, 2, 3, 4], [40, 30, 20, 10]) == -1.0
        assert spearman_rank([1], [1]) is None

    def test_dominated_mask(self):
        # (capex, op): the cheap-and-good point dominates the
        # expensive-and-bad one; the diagonal trade-off survives
        capex = [100, 200, 300]
        op = [-50, -60, -40]
        out = dominated_mask(capex, op)
        assert list(out) == [False, False, True]
        # duplicates never dominate each other
        assert list(dominated_mask([1, 1], [2, 2])) == [False, False]


# ---------------------------------------------------------------------------
# Screening + certified frontier (end to end, small population)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def frontier():
    return run_design(_case(), _spec(), backend="jax")


class TestDesignEngine:
    def test_frontier_certified_and_ranked(self, frontier):
        f = frontier.frontier
        assert len(f) == 3
        assert f["certified"].all()
        assert frontier.all_finalists_certified
        assert list(f["final_rank"]) == [1, 2, 3]
        # certified totals are ranked ascending (lower = better)
        assert (np.diff(f["total"].to_numpy()) >= 0).all()
        # the winner came from within the screen's own top-k
        assert 1 <= int(frontier.winner["screen_rank"]) <= 3
        assert frontier.rank_correlation is not None

    def test_population_surface_complete(self, frontier):
        pop = frontier.population
        assert len(pop) == 12
        conv = pop[pop.converged]
        # every converged candidate got a rank; ranks are 1..n unique
        ranks = sorted(conv["screen_rank"].dropna())
        assert ranks == list(range(1, len(conv) + 1))
        # refinement actually re-screened a SUBSET at the tighter tier
        assert (conv["screen_round"] == 1).sum() < len(conv)
        assert (conv["screen_round"] == 1).sum() >= 3

    def test_screening_rides_the_batch_axis(self, frontier):
        # 12 candidates over 2 rounds: solo solves would cost >= 12
        # dispatches for round 0 alone; the batched screen stays far
        # below one dispatch per candidate
        assert frontier.screen["dispatches"] * 2 <= 12
        assert frontier.screen["candidates"] == 12

    def test_screening_never_certificate_stamped(self, frontier):
        # the ordinal tier must not have issued certificates; the
        # certified phase's counts live in run_health instead
        assert frontier.screen["certification_stamped"] is False
        cert = frontier.run_health["certification"]
        n_windows = int(sum(
            frontier.run_health["windows"][k]
            for k in ("clean", "inaccurate", "retried", "cpu_fallback")))
        assert cert["windows_certified"] == n_windows
        assert cert["windows"]["rejected_final"] == 0

    def test_save_as_csv_artifacts(self, frontier, tmp_path):
        frontier.save_as_csv(tmp_path)
        assert (tmp_path / "design_frontier.csv").exists()
        assert (tmp_path / "design_population.csv").exists()
        payload = json.loads((tmp_path / "design_frontier.json")
                             .read_text())
        assert payload["fidelity"] == "certified"
        assert len(payload["frontier"]) == 3
        assert payload["spec"]["top_k"] == 3
        assert (tmp_path / "run_health.json").exists()

    def test_degraded_engine_path(self):
        f = run_design(_case(), _spec(population=8, top_k=2,
                                      refine_rounds=0),
                       backend="jax", certify=False)
        assert f.fidelity == FIDELITY_DEGRADED
        assert f.resubmit_hint is not None
        assert not f.frontier["certified"].any()
        # the degraded frontier is the screening order itself
        assert f.rank_correlation == 1.0

    def test_budget_cap_filters_and_reports(self):
        # capex ~ 200*kW + 100*kWh (+ ccost): a tight budget kills the
        # big candidates before any solve
        report = screen_candidates(
            _case(), generate_population(_spec(population=12)),
            backend="jax", refine_rounds=0, top_k=3, budget=800_000.0)
        filtered = [e for e in report.entries if not e.feasible]
        assert filtered and all("budget" in e.reason for e in filtered)
        assert all(not np.isfinite(e.total) for e in filtered)
        # the cap never silently empties the screen below the survivors
        assert report.converged

    def test_budget_filtering_everything_raises(self):
        with pytest.raises(ParameterError, match="filtered out"):
            screen_candidates(
                _case(), generate_population(_spec(population=4)),
                backend="jax", refine_rounds=0, budget=1.0)

    def test_refinement_failure_keeps_prior_scores(self, monkeypatch):
        """A refinement round that fails wholesale must not invert the
        ordering: survivors keep their valid round-0 scores instead of
        handing the frontier to the refinement-cut candidates."""
        import dervet_tpu.design.screen as screen_mod
        from dervet_tpu.utils.errors import AggregatedSolverError
        real = screen_mod.run_dispatch
        calls = {"n": 0}

        def flaky(scens, **kw):
            calls["n"] += 1
            if calls["n"] == 2:     # the refinement round dies
                raise AggregatedSolverError(
                    {s.case.case_id: "injected round failure"
                     for s in scens})
            return real(scens, **kw)

        monkeypatch.setattr(screen_mod, "run_dispatch", flaky)
        report = screen_candidates(
            _case(), generate_population(_spec(population=8)),
            backend="jax", refine_rounds=1, refine_keep=0.5, top_k=2)
        assert calls["n"] == 2
        # every candidate still ranked on its round-0 score
        assert len(report.converged) == 8
        assert all(e.screen_round == 0 for e in report.entries)
        # the survivors of the cut carry the failure note, and the top
        # of the ranking is still drawn from them (not the cut tail)
        noted = [e for e in report.entries if e.reason]
        assert noted and all("refinement round 1 failed" in e.reason
                             for e in noted)
        assert report.top(2)[0].reason is not None

    def test_zero_size_candidate_rejected_anywhere_in_population(self):
        """is_sizing_optimization depends on the CANDIDATE's sizes: a
        zero-rating grid point that doesn't sort first must still be
        refused (it would be silently re-sized by the optimizer)."""
        spec = _spec(population=0, refine_rounds=0,
                     grid=[(500.0, 1000.0), (1000.0, 0.0)])
        with pytest.raises(ParameterError, match="candidate 1.*"
                                                 "FIXED-size"):
            screen_candidates(_case(), generate_population(spec),
                              backend="jax", refine_rounds=0, top_k=1)

    def test_grid_without_bounds_rejected_at_validate(self):
        with pytest.raises(ParameterError, match="grid needs bounds"):
            DesignSpec(bounds={}, grid=[(500.0, 1000.0)]).validate()

    def test_binary_case_rejected(self):
        c = _case()
        c.scenario["binary"] = 1
        with pytest.raises(ParameterError, match="binary"):
            run_design(c, _spec(population=4, top_k=1, refine_rounds=0),
                       backend="jax")

    def test_sizing_case_rejected(self):
        # a zero rating on a NON-target DER would add a size variable
        # the candidate overrides can't reach — the fixed-size guard
        # must refuse before any device work
        c = _case()
        for tag, _id, keys in c.ders:
            if tag == "PV":
                keys["rated_capacity"] = 0
        with pytest.raises(ParameterError, match="FIXED-size"):
            run_design(c, _spec(population=4, top_k=1, refine_rounds=0),
                       backend="jax")


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

class TestDesignCLI:
    def test_parse_bounds(self):
        from dervet_tpu.design.cli import parse_bounds
        assert parse_bounds("kw=200:2000,kwh=500:8000") == {
            "kw": (200.0, 2000.0), "kwh": (500.0, 8000.0)}
        assert parse_bounds("kw=1:2") == {"kw": (1.0, 2.0)}
        with pytest.raises(ParameterError):
            parse_bounds("mw=1:2")
        with pytest.raises(ParameterError):
            parse_bounds("kw=12")

    def test_parser_maps_flags(self):
        from dervet_tpu.design.cli import build_parser
        args = build_parser().parse_args(
            ["case.csv", "--bounds", "kw=1:2,kwh=3:4",
             "--population", "64", "--top-k", "4", "--backend", "cpu"])
        assert args.population == 64 and args.top_k == 4
        assert args.backend == "cpu"
