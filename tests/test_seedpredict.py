"""Learned cold-start seed predictor (ops/seedpredict.py).

The SolutionMemory doubles as a training set: per structure key a cheap
ridge model maps the float16-quantized LP feature vector to initial
iterates, served as the ``predicted`` warm-start grade — below ``near``
(a genuinely nearby stored iterate wins), above the nearest-by-feature
fallback and cold.  Safety: every predicted-seeded window still runs the
full convergence criteria + float64 certification, the ``stale_seed``
fault drill covers the corrupted-prediction shape, and a certificate
rejection drops the structure's model (the training set just proved
untrustworthy there).
"""
import copy

import numpy as np
import pytest

from dervet_tpu.ops import seedpredict, warmstart
from dervet_tpu.ops.pdhg import CompiledLPSolver, PDHGOptions
from dervet_tpu.utils import faultinject
from tests.test_warmstart import _arb_lp


def _trained_memory(solver, lp, n_entries=6, spread=0.1):
    """Memory with ``n_entries`` converged price variants stored."""
    mem = warmstart.SolutionMemory(max_entries=64)
    tag = warmstart.opts_tag(solver.opts)
    for i in range(n_entries):
        lpi = copy.copy(lp)
        lpi.c = lp.c * (1.0 - spread * n_entries / 2 + spread * i)
        r = solver.solve(c=lpi.c)
        assert bool(r.converged)
        mem.store("sk", lpi, tag, np.asarray(r.x), np.asarray(r.y),
                  float(r.obj))
    return mem


def _far_instance(lp, seed=0):
    """Data far (in quantized-digest terms) from every stored entry —
    the feature-fallback / predicted zone."""
    rng = np.random.default_rng(seed)
    lpq = copy.copy(lp)
    lpq.c = lp.c * 1.04 + 0.002 * rng.standard_normal(lp.n) \
        * np.abs(lp.c).mean()
    return lpq


class TestPredictorModel:
    def test_fit_predict_reduces_iterations(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        plans = warmstart.plan_group(mem, "sk", [_far_instance(lp)],
                                     opts, ["w0"])
        assert plans[0].kind == "predicted"
        lpq = _far_instance(lp)
        cold = solver.solve(c=lpq.c)
        seeded = solver.solve(c=lpq.c, x0=plans[0].entry.x,
                              y0=plans[0].entry.y)
        assert bool(seeded.converged)
        assert int(seeded.iters) < int(cold.iters)
        snap = mem.snapshot()["predictor"]
        assert snap["models"] == 1 and snap["fits"] >= 1
        assert mem.snapshot()["hits_predicted"] >= 1

    def test_abstains_below_min_entries(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp, n_entries=2)  # < min_entries
        plans = warmstart.plan_group(mem, "sk", [_far_instance(lp)],
                                     opts, ["w0"])
        # nearest-by-feature fallback still serves (reported as near)
        assert plans[0].kind == "near"
        assert mem.snapshot()["predictor"]["models"] == 0

    def test_near_grade_outranks_prediction(self):
        """A quantized-digest hit (genuinely nearby stored iterate) must
        win over the model interpolation."""
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        stored = mem.entries_for_structure("sk")[-1]
        # repeat one stored instance's data (same quant digest), at a
        # different tolerance tag so the exact grade cannot fire
        lp_same = copy.copy(lp)
        lp_same.c = lp.c * (1.0 + 0.1 * (6 / 2 - 1) - 0.1 * 2)
        loose = PDHGOptions.screening(opts)
        plans = warmstart.plan_group(mem, "sk", [lp_same], loose, ["w0"])
        assert plans[0].kind in ("near", "exact")
        assert plans[0].entry is not None and plans[0].entry.exact != b""
        del stored

    def test_kill_switch_disables_predictions(self, monkeypatch):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        monkeypatch.setenv("DERVET_TPU_SEEDPREDICT", "0")
        plans = warmstart.plan_group(mem, "sk", [_far_instance(lp)],
                                     opts, ["w0"])
        assert plans[0].kind == "near"      # feature fallback, no model
        assert mem.snapshot()["predictor"]["predictions"] == 0

    def test_invalidate_drops_model(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        warmstart.plan_group(mem, "sk", [_far_instance(lp)], opts, ["w0"])
        assert mem.predictor.has_model("sk")
        # the certificate-rejection path: memory.invalidate drops the
        # structure's model alongside the offending entries
        mem.invalidate("sk", lp, np.dtype(opts.dtype))
        assert not mem.predictor.has_model("sk")
        assert mem.snapshot()["predictor"]["invalidated"] == 1

    def test_nonfinite_prediction_rejected(self):
        pred = seedpredict.SeedPredictor()
        bad = [("sk", {"W": np.full((33, 8), np.nan), "n": 4, "m": 4,
                       "trained_on": 5})]
        assert pred.import_models(bad) == 0
        assert pred.predict("sk", np.zeros(32)) is None


class TestPredictorFleetHandoff:
    def test_export_import_models(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        warmstart.plan_group(mem, "sk", [_far_instance(lp)], opts, ["w0"])
        import pickle
        blob = pickle.dumps(mem.export_payload())
        other = warmstart.SolutionMemory(max_entries=64)
        n = other.import_payload(pickle.loads(blob))
        assert n == 6
        assert other.predictor.snapshot()["models"] == 1
        # imported models predict for a structure the replica never
        # solved (entries imported exact-only: no near indices)
        plans = warmstart.plan_group(other, "sk", [_far_instance(lp)],
                                     opts, ["w0"])
        assert plans[0].kind == "predicted"

    def test_legacy_entries_list_still_imports(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp, n_entries=3)
        other = warmstart.SolutionMemory(max_entries=64)
        assert other.import_payload(mem.export_entries()) == 3
        assert other.predictor.snapshot()["models"] == 0

    def test_local_models_win_over_imports(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        warmstart.plan_group(mem, "sk", [_far_instance(lp)], opts, ["w0"])
        local_w = mem.predictor._models["sk"].W.copy()
        foreign = [("sk", {"W": np.zeros_like(local_w), "n": lp.n,
                           "m": lp.m, "trained_on": 99})]
        assert mem.predictor.import_models(foreign) == 0
        assert np.array_equal(mem.predictor._models["sk"].W, local_w)


class TestCorruptedPrediction:
    def test_corrupt_prediction_converges_and_is_attributed(self):
        """The fault-matrix row: a corrupted prediction (stale_seed
        fault on a predicted member) still converges under the normal
        criteria, is attributed in the plan (stale_fault, predicted
        kind), and only costs iterations."""
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        lpq = _far_instance(lp)
        with faultinject.inject(stale_seed={"all"}):
            plans = warmstart.plan_group(mem, "sk", [lpq], opts, ["w0"])
        assert plans[0].kind == "predicted"
        assert plans[0].stale_fault
        assert mem.snapshot()["stale_seed_faults"] >= 1
        res = solver.solve(c=lpq.c, x0=plans[0].entry.x,
                           y0=plans[0].entry.y)
        assert bool(res.converged)      # a bad seed never breaks a solve
