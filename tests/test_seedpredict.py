"""Learned cold-start seed predictor (ops/seedpredict.py).

The SolutionMemory doubles as a training set: per structure key a cheap
ridge model maps the float16-quantized LP feature vector to initial
iterates, served as the ``predicted`` warm-start grade — below ``near``
(a genuinely nearby stored iterate wins), above the nearest-by-feature
fallback and cold.  Safety: every predicted-seeded window still runs the
full convergence criteria + float64 certification, the ``stale_seed``
fault drill covers the corrupted-prediction shape, and a certificate
rejection drops the structure's model (the training set just proved
untrustworthy there).
"""
import copy

import numpy as np
import pytest

from dervet_tpu.ops import seedpredict, warmstart
from dervet_tpu.ops.pdhg import CompiledLPSolver, PDHGOptions
from dervet_tpu.utils import faultinject
from tests.test_warmstart import _arb_lp


def _trained_memory(solver, lp, n_entries=6, spread=0.1):
    """Memory with ``n_entries`` converged price variants stored."""
    mem = warmstart.SolutionMemory(max_entries=64)
    tag = warmstart.opts_tag(solver.opts)
    for i in range(n_entries):
        lpi = copy.copy(lp)
        lpi.c = lp.c * (1.0 - spread * n_entries / 2 + spread * i)
        r = solver.solve(c=lpi.c)
        assert bool(r.converged)
        mem.store("sk", lpi, tag, np.asarray(r.x), np.asarray(r.y),
                  float(r.obj))
    return mem


def _far_instance(lp, seed=0):
    """Data far (in quantized-digest terms) from every stored entry —
    the feature-fallback / predicted zone."""
    rng = np.random.default_rng(seed)
    lpq = copy.copy(lp)
    lpq.c = lp.c * 1.04 + 0.002 * rng.standard_normal(lp.n) \
        * np.abs(lp.c).mean()
    return lpq


class TestPredictorModel:
    def test_fit_predict_reduces_iterations(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        plans = warmstart.plan_group(mem, "sk", [_far_instance(lp)],
                                     opts, ["w0"])
        assert plans[0].kind == "predicted"
        lpq = _far_instance(lp)
        cold = solver.solve(c=lpq.c)
        seeded = solver.solve(c=lpq.c, x0=plans[0].entry.x,
                              y0=plans[0].entry.y)
        assert bool(seeded.converged)
        assert int(seeded.iters) < int(cold.iters)
        snap = mem.snapshot()["predictor"]
        assert snap["models"] == 1 and snap["fits"] >= 1
        assert mem.snapshot()["hits_predicted"] >= 1

    def test_abstains_below_min_entries(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp, n_entries=2)  # < min_entries
        plans = warmstart.plan_group(mem, "sk", [_far_instance(lp)],
                                     opts, ["w0"])
        # nearest-by-feature fallback still serves (reported as near)
        assert plans[0].kind == "near"
        assert mem.snapshot()["predictor"]["models"] == 0

    def test_near_grade_outranks_prediction(self):
        """A quantized-digest hit (genuinely nearby stored iterate) must
        win over the model interpolation."""
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        stored = mem.entries_for_structure("sk")[-1]
        # repeat one stored instance's data (same quant digest), at a
        # different tolerance tag so the exact grade cannot fire
        lp_same = copy.copy(lp)
        lp_same.c = lp.c * (1.0 + 0.1 * (6 / 2 - 1) - 0.1 * 2)
        loose = PDHGOptions.screening(opts)
        plans = warmstart.plan_group(mem, "sk", [lp_same], loose, ["w0"])
        assert plans[0].kind in ("near", "exact")
        assert plans[0].entry is not None and plans[0].entry.exact != b""
        del stored

    def test_kill_switch_disables_predictions(self, monkeypatch):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        monkeypatch.setenv("DERVET_TPU_SEEDPREDICT", "0")
        plans = warmstart.plan_group(mem, "sk", [_far_instance(lp)],
                                     opts, ["w0"])
        assert plans[0].kind == "near"      # feature fallback, no model
        assert mem.snapshot()["predictor"]["predictions"] == 0

    def test_invalidate_drops_model(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        warmstart.plan_group(mem, "sk", [_far_instance(lp)], opts, ["w0"])
        assert mem.predictor.has_model("sk")
        # the certificate-rejection path: memory.invalidate drops the
        # structure's model alongside the offending entries
        mem.invalidate("sk", lp, np.dtype(opts.dtype))
        assert not mem.predictor.has_model("sk")
        assert mem.snapshot()["predictor"]["invalidated"] == 1

    def test_nonfinite_prediction_rejected(self):
        pred = seedpredict.SeedPredictor()
        bad = [("sk", {"W": np.full((33, 8), np.nan), "n": 4, "m": 4,
                       "trained_on": 5})]
        assert pred.import_models(bad) == 0
        assert pred.predict("sk", np.zeros(32)) is None


class TestPredictorFleetHandoff:
    def test_export_import_models(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        warmstart.plan_group(mem, "sk", [_far_instance(lp)], opts, ["w0"])
        import pickle
        blob = pickle.dumps(mem.export_payload())
        other = warmstart.SolutionMemory(max_entries=64)
        n = other.import_payload(pickle.loads(blob))
        assert n == 6
        assert other.predictor.snapshot()["models"] == 1
        # imported models predict for a structure the replica never
        # solved (entries imported exact-only: no near indices)
        plans = warmstart.plan_group(other, "sk", [_far_instance(lp)],
                                     opts, ["w0"])
        assert plans[0].kind == "predicted"

    def test_legacy_entries_list_still_imports(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp, n_entries=3)
        other = warmstart.SolutionMemory(max_entries=64)
        assert other.import_payload(mem.export_entries()) == 3
        assert other.predictor.snapshot()["models"] == 0

    def test_local_models_win_over_imports(self):
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        warmstart.plan_group(mem, "sk", [_far_instance(lp)], opts, ["w0"])
        local_w = mem.predictor._models["sk"].W.copy()
        foreign = [("sk", {"W": np.zeros_like(local_w), "n": lp.n,
                           "m": lp.m, "trained_on": 99})]
        assert mem.predictor.import_models(foreign) == 0
        assert np.array_equal(mem.predictor._models["sk"].W, local_w)


class TestCorruptedPrediction:
    def test_corrupt_prediction_converges_and_is_attributed(self):
        """The fault-matrix row: a corrupted prediction (stale_seed
        fault on a predicted member) still converges under the normal
        criteria, is attributed in the plan (stale_fault, predicted
        kind), and only costs iterations."""
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        lpq = _far_instance(lp)
        with faultinject.inject(stale_seed={"all"}):
            plans = warmstart.plan_group(mem, "sk", [lpq], opts, ["w0"])
        assert plans[0].kind == "predicted"
        assert plans[0].stale_fault
        assert mem.snapshot()["stale_seed_faults"] >= 1
        res = solver.solve(c=lpq.c, x0=plans[0].entry.x,
                           y0=plans[0].entry.y)
        assert bool(res.converged)      # a bad seed never breaks a solve


class TestRicherFeatures:
    """Feature-dim bump (r15): per-window price quantiles + SOE boundary
    state appended to the float16 feature digest — refit-compatible, and
    old-dim models/entries degrade gracefully instead of crashing."""

    def test_feature_vec_dim_and_layout(self):
        lp = _arb_lp()
        f = warmstart.feature_vec(lp)
        assert f.shape == (warmstart.FEATURE_DIM,)
        assert warmstart.FEATURE_DIM == (
            4 * warmstart.FEATURE_BUCKETS
            + len(warmstart.PRICE_QUANTILES) + warmstart.N_SOE_FEATURES)
        # the SOE block reads the soe-named row group's boundary rhs:
        # entry SOE 500 at the first soe row, final rhs 0
        soe = f[-warmstart.N_SOE_FEATURES:]
        assert soe[0] == pytest.approx(500.0)     # mean of first-row rhs
        assert soe[1] == pytest.approx(0.0)       # mean of last-row rhs
        assert soe[2] == pytest.approx(500.0)     # max |boundary|
        assert soe[3] == pytest.approx(1.0)       # one soe range

    def test_price_quantiles_see_shape_not_just_level(self):
        """Two price vectors with the same bucketed means but different
        spread must produce different quantile features — the signal the
        bucketed means saturate on at 1%-per-hour noise."""
        import copy as _copy
        lp = _arb_lp()
        lp2 = _copy.copy(lp)
        # double the spread around the mean: global mean preserved,
        # per-bucket means shift far less than the quantile tails
        lp2.c = lp.c.mean() + 2.0 * (lp.c - lp.c.mean())
        nb = 4 * warmstart.FEATURE_BUCKETS
        nq = len(warmstart.PRICE_QUANTILES)
        f1 = warmstart.feature_vec(lp)
        f2 = warmstart.feature_vec(lp2)
        assert not np.allclose(f1[nb:nb + nq], f2[nb:nb + nq])

    def test_soe_boundary_state_responds(self):
        import copy as _copy
        lp = _arb_lp()
        lp2 = _copy.copy(lp)
        q2 = lp.q.copy()
        q2[lp.row_groups["soe"][0][0]] = 250.0    # halve the entry SOE
        lp2.q = q2
        f1 = warmstart.feature_vec(lp)
        f2 = warmstart.feature_vec(lp2)
        assert not np.allclose(f1[-warmstart.N_SOE_FEATURES:],
                               f2[-warmstart.N_SOE_FEATURES:])

    @pytest.mark.parametrize("pos", ["oldest", "newest"])
    def test_refit_compatible_with_old_dim_entries(self, pos):
        """Entries stored under an OLDER feature layout (a fleet import
        from a pre-bump replica) are skipped at fit time — the model
        still fits from the current-dim entries and serves.  The
        'newest' case pins the reference-dim anchoring: an old-dim
        entry arriving LAST must not flip the skip around and replace
        a healthy model with an old-dim one."""
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp)
        key = (next(iter(mem._entries)) if pos == "oldest"
               else list(mem._entries)[-1])
        legacy = mem._entries[key]
        legacy.feature = legacy.feature[:4 * warmstart.FEATURE_BUCKETS]
        plans = warmstart.plan_group(mem, "sk", [_far_instance(lp)],
                                     opts, ["w0"])
        assert plans[0].kind == "predicted"       # fit survived the mix
        assert mem.predictor._models["sk"].feat_dim \
            == warmstart.FEATURE_DIM

    def test_old_dim_models_dropped_on_import(self):
        """import_models drops models fitted under an older feature
        dimension instead of installing a silent mis-predictor."""
        lp = _arb_lp()
        pred = seedpredict.SeedPredictor()
        d_old = 4 * warmstart.FEATURE_BUCKETS          # pre-bump layout
        old = [("sk-old", {"W": np.zeros((d_old + 1, lp.n + lp.m)),
                           "n": lp.n, "m": lp.m, "trained_on": 8})]
        new = [("sk-new", {"W": np.zeros(
            (warmstart.FEATURE_DIM + 1, lp.n + lp.m)),
            "n": lp.n, "m": lp.m, "trained_on": 8})]
        assert pred.import_models(old) == 0
        assert pred.import_models(new) == 1
        assert not pred.has_model("sk-old")

    def test_old_dim_pool_entry_never_wins_feature_fallback(self):
        """A mixed pool (old-dim import + current entries) must serve
        the nearest CURRENT-dim entry, not crash on the mismatch."""
        lp = _arb_lp()
        opts = PDHGOptions(pallas_chunk=False)
        solver = CompiledLPSolver(lp, opts)
        mem = _trained_memory(solver, lp, n_entries=2)  # no model (< min)
        key = next(iter(mem._entries))
        mem._entries[key].feature = \
            mem._entries[key].feature[:4 * warmstart.FEATURE_BUCKETS]
        plans = warmstart.plan_group(mem, "sk", [_far_instance(lp)],
                                     opts, ["w0"])
        assert plans[0].kind == "near"
        assert plans[0].entry.feature.shape == (warmstart.FEATURE_DIM,)
