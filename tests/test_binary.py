"""Binary on/off formulation (scenario ``binary=1``): exact MILP on the
CPU backend (reference: CVXPY+GLPK_MI boolean variables; SURVEY §2.9 —
the continuous PDHG kernel gets the batched axis, one-off hard problems
route to the exact CPU solver)."""
from pathlib import Path

import numpy as np
import pytest

from dervet_tpu.io.params import Params
from dervet_tpu.scenario.scenario import MicrogridScenario

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"


def _base_case(**scenario_overrides):
    case = Params.initialize(MP / "000-DA_battery_month.csv",
                             base_path=REF)[0]
    case.scenario["allow_partial_year"] = True   # tests trim to January
    case.scenario.update(scenario_overrides)
    for tag, _id, keys in case.ders:
        if tag == "Battery":
            # free the discharge budget so the energy-burning relaxation
            # artifact is actually profitable (the cycle cap otherwise
            # spends all discharge kWh on ordinary arbitrage)
            keys["daily_cycle_limit"] = 0
    return case


def test_binary_battery_no_simultaneous_charge_discharge():
    """Negative prices + a full battery make simultaneous charge/discharge
    profitable in the LP relaxation (burning energy through the round-trip
    loss while being paid to consume); the binary formulation forbids it."""
    case = _base_case(binary=1)
    ts = case.datasets.time_series
    price_col = next(c for c in ts.columns if "DA Price" in c)
    prices = ts[price_col].to_numpy().copy()
    prices[:12] = -0.05                 # half a negative day
    ts[price_col] = prices
    # 1-day horizon keeps branch-and-bound small (48 binaries)
    case.datasets.time_series = ts.iloc[: 24]
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="jax")   # must route itself to MILP
    res = s.timeseries_results()
    bat = next(d for d in s.ders if d.tag == "Battery")
    ch = res[bat.col("Charge (kW)")].to_numpy()
    dis = res[bat.col("Discharge (kW)")].to_numpy()
    assert (np.minimum(ch, dis) <= 1e-6).all()
    # the negative-price window actually pays the battery to charge
    assert ch[:12].max() > 0


def test_relaxed_battery_does_simultaneously_dump():
    """Sanity for the test above: WITHOUT binary, the same case exploits
    the relaxation (otherwise the binary assertion proves nothing)."""
    case = _base_case(binary=0)      # the input file sets binary=1
    ts = case.datasets.time_series
    price_col = next(c for c in ts.columns if "DA Price" in c)
    prices = ts[price_col].to_numpy().copy()
    prices[:12] = -0.05
    ts[price_col] = prices
    case.datasets.time_series = ts.iloc[: 24]
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    res = s.timeseries_results()
    bat = next(d for d in s.ders if d.tag == "Battery")
    ch = res[bat.col("Charge (kW)")].to_numpy()
    dis = res[bat.col("Discharge (kW)")].to_numpy()
    assert np.minimum(ch, dis).max() > 1.0


def test_binary_genset_min_power():
    """ICE with min_power under binary=1: output is 0 or >= min_power."""
    case = _base_case(binary=1)
    case.ders.append(("ICE", "1", {
        "name": "genset", "rated_capacity": 500, "n": 1, "min_power": 200,
        "efficiency": 12.0, "fuel_cost": 1.0, "variable_om_cost": 0.001,
        "fixed_om_cost": 0.0}))
    ts = case.datasets.time_series
    case.datasets.time_series = ts.iloc[: 24 * 2]
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="jax")
    res = s.timeseries_results()
    ice = next(d for d in s.ders if d.tag == "ICE")
    gen = res[ice.col("Electric Generation (kW)")].to_numpy()
    on = gen > 1e-6
    assert (gen[on] >= 200 - 1e-4).all()
    assert (gen <= 500 + 1e-6).all()


def test_binary_genset_multi_unit_commitment():
    """n=2 units with min_power: aggregate output lands in
    {0} u [min, rated] u [2*min, 2*rated] (integer commitment count)."""
    case = _base_case(binary=1)
    case.ders.append(("ICE", "1", {
        "name": "fleet", "rated_capacity": 500, "n": 2, "min_power": 400,
        "efficiency": 12.0, "fuel_cost": 1.0, "variable_om_cost": 0.001,
        "fixed_om_cost": 0.0}))
    ts = case.datasets.time_series
    case.datasets.time_series = ts.iloc[: 24]
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    res = s.timeseries_results()
    ice = next(d for d in s.ders if d.tag == "ICE")
    gen = res[ice.col("Electric Generation (kW)")].to_numpy()
    tol = 1e-4
    in_zero = gen <= tol
    in_one = (gen >= 400 - tol) & (gen <= 500 + tol)
    in_two = (gen >= 800 - tol) & (gen <= 1000 + tol)
    assert (in_zero | in_one | in_two).all()
