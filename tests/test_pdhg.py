"""Unit tests for the LP IR and the batched PDHG solver vs scipy HiGHS.

This is the new-framework analog of the reference's missing solver unit
tests (SURVEY.md §4: "add real unit tests around the new LP kernel — PDHG
vs. reference solver on small problems").
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dervet_tpu.ops import (CompiledLPSolver, LPBuilder, PDHGOptions,
                            solve_lp_cpu)


def random_feasible_lp(rng, n=40, m_eq=10, m_ge=15):
    """Random bounded-feasible LP: x* interior draw, rhs built around it."""
    b = LPBuilder()
    x_star = rng.uniform(-1.0, 1.0, n)
    v = b.var("x", n, lb=-2.0, ub=2.0)
    b.add_cost(v, rng.uniform(-1.0, 1.0, n))
    A_eq = rng.standard_normal((m_eq, n))
    b.add_rows("eq", [(v, A_eq)], "eq", A_eq @ x_star)
    A_ge = rng.standard_normal((m_ge, n))
    b.add_rows("ge", [(v, A_ge)], "ge", A_ge @ x_star - rng.uniform(0, 1, m_ge))
    return b.build()


def battery_like_lp(T=48, price=None):
    """A small battery-arbitrage LP with the same block structure the
    dispatch engine emits (SOE recursion + box bounds + linear prices)."""
    rng = np.random.default_rng(1)
    price = rng.uniform(10, 80, T) / 1000 if price is None else price
    dt, rte = 1.0, 0.85
    ch_max, dis_max, ene_max = 250.0, 250.0, 1000.0
    b = LPBuilder()
    ch = b.var("ch", T, 0.0, ch_max)
    dis = b.var("dis", T, 0.0, dis_max)
    ene = b.var("ene", T, 0.0, ene_max)
    # ene[t] - ene[t-1] - rte*dt*ch[t] + dt*dis[t] == 0 ; ene[-1] = ene0
    D = np.eye(T) - np.eye(T, k=-1)
    rhs = np.zeros(T)
    rhs[0] = ene_max / 2  # initial SOE enters the rhs
    b.add_rows("soe", [(ene, D), (ch, -rte * dt), (dis, dt)], "eq", rhs)
    b.add_cost(ch, price * dt)
    b.add_cost(dis, -price * dt)
    return b.build()


class TestLPBuilder:
    def test_shapes_and_groups(self):
        lp = battery_like_lp(T=24)
        assert lp.n == 72 and lp.m == 24 and lp.n_eq == 24
        assert lp.row_groups["soe"] == [(0, 24)]
        assert lp.var_refs["dis"].start == 24

    def test_le_sense_negated(self):
        b = LPBuilder()
        v = b.var("x", 3, 0, 10)
        b.add_rows("cap", [(v, 1.0)], "le", 5.0)
        lp = b.build()
        assert lp.n_eq == 0
        np.testing.assert_allclose(lp.dense_K(), -np.eye(3))
        np.testing.assert_allclose(lp.q, -5.0)

    def test_presolve_clamps_never_binding_rhs(self):
        """Sentinel "no limit" values (the reference datasets use 999999,
        our requirement fills 1e30) must not survive into q: they inflate
        ||q||_2 and poison the PDHG relative termination criterion.  A
        never-binding 'le' rhs is clamped to the row's activity bound; a
        binding rhs is untouched; rows touching unbounded variables are
        left alone."""
        b = LPBuilder()
        x = b.var("x", 2, 0.0, 10.0)
        f = b.var("free", 1)                      # unbounded
        b.add_rows("never", [(x, 1.0)], "le", 999999.0)   # max activity 10
        b.add_rows("binds", [(x, 1.0)], "le", 5.0)
        b.add_rows("unbounded", [(f, np.ones((1, 1)))], "le", 999999.0)
        lp = b.build()
        rows = {name: r[0] for name, r in lp.row_groups.items()}
        a, _ = rows["never"]
        # 'le' rows are negated to 'ge': q = -rhs, clamped up to -10
        np.testing.assert_allclose(lp.q[a:a + 2], -10.0)
        a, _ = rows["binds"]
        np.testing.assert_allclose(lp.q[a:a + 2], -5.0)
        a, _ = rows["unbounded"]
        np.testing.assert_allclose(lp.q[a], -999999.0)

    def test_presolve_keeps_problem_equivalent(self):
        """Solving with a sentinel-polluted extra row gives the same
        optimum as without it (HiGHS)."""
        from dervet_tpu.ops.cpu_ref import solve_lp_cpu
        lp_plain = battery_like_lp(T=24)
        b = LPBuilder()
        ch = b.var("ch", 24, 0.0, 250.0)
        dis = b.var("dis", 24, 0.0, 250.0)
        ene = b.var("ene", 24, 0.0, 1000.0)
        D = np.eye(24) - np.eye(24, k=-1)
        rhs = np.zeros(24)
        rhs[0] = 500.0
        b.add_rows("soe", [(ene, D), (ch, -0.85), (dis, 1.0)], "eq", rhs)
        rng = np.random.default_rng(1)
        price = rng.uniform(10, 80, 24) / 1000
        b.add_cost(ch, price)
        b.add_cost(dis, -price)
        b.add_rows("sentinel_cap", [(ene, 1.0)], "le", 999999.0)
        lp_sent = b.build()
        assert np.abs(lp_sent.q).max() <= 1000.0    # clamped to activity
        assert abs(solve_lp_cpu(lp_sent).obj - solve_lp_cpu(lp_plain).obj) < 1e-9


class TestPDHGvsHiGHS:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_lp(self, seed):
        lp = random_feasible_lp(np.random.default_rng(seed))
        ref = solve_lp_cpu(lp)
        assert ref.status == 0
        res = CompiledLPSolver(lp, PDHGOptions(max_iters=60_000)).solve()
        assert bool(res.converged)
        scale = max(1.0, abs(ref.obj))
        assert abs(float(res.obj) - ref.obj) / scale < 2e-3

    def test_battery_arbitrage(self):
        lp = battery_like_lp(T=96)
        ref = solve_lp_cpu(lp)
        assert ref.status == 0
        res = CompiledLPSolver(lp).solve()
        assert bool(res.converged)
        assert abs(float(res.obj) - ref.obj) / max(1.0, abs(ref.obj)) < 1e-3
        # solution should respect SOE dynamics
        x = np.asarray(res.x)
        ene = lp.value(x, "ene")
        ch = lp.value(x, "ch")
        dis = lp.value(x, "dis")
        soe = 500.0
        for t in range(96):
            soe = soe + 0.85 * ch[t] - dis[t]
            assert abs(ene[t] - soe) < 1.0

    def test_batched_price_scenarios(self):
        lp = battery_like_lp(T=48)
        rng = np.random.default_rng(7)
        B = 8
        prices = rng.uniform(5, 100, (B, 48)) / 1000
        c_b = np.zeros((B, lp.n))
        for i in range(B):
            c_b[i, lp.var_refs["ch"].sl] = prices[i]
            c_b[i, lp.var_refs["dis"].sl] = -prices[i]
        solver = CompiledLPSolver(lp)
        res = solver.solve(c=c_b)
        assert res.x.shape == (B, lp.n)
        for i in range(B):
            ref = solve_lp_cpu(lp, c=c_b[i])
            assert bool(res.converged[i])
            assert abs(float(res.obj[i]) - ref.obj) / max(1.0, abs(ref.obj)) < 2e-3

    def test_batched_bounds_only(self):
        """Sizing sweeps batch u (capacity bounds) with a shared c."""
        lp = battery_like_lp(T=24)
        B = 4
        u_b = np.tile(lp.u, (B, 1))
        for i in range(B):
            u_b[i, lp.var_refs["ene"].sl] = 250.0 * (i + 1)
        res = CompiledLPSolver(lp).solve(u=u_b)
        assert res.x.shape == (B, lp.n)
        for i in range(B):
            ref = solve_lp_cpu(lp, u=u_b[i])
            assert bool(res.converged[i])
            assert abs(float(res.obj[i]) - ref.obj) / max(1.0, abs(ref.obj)) < 2e-3

    def test_infeasible_flags_not_converged(self):
        b = LPBuilder()
        v = b.var("x", 2, 0, 1)
        b.add_rows("sum_hi", [(v, np.ones((1, 2)))], "ge", 5.0)  # impossible
        b.add_cost(v, np.ones(2))
        lp = b.build()
        res = CompiledLPSolver(lp, PDHGOptions(max_iters=2000)).solve()
        assert not bool(res.converged)


class TestSparseEllPath:
    """The ELL gather-matvec backend must match the dense backend exactly
    (same algorithm, different matvec) and unlock large structures."""

    def test_ell_matches_dense(self):
        lp = battery_like_lp(T=96)
        dense = CompiledLPSolver(lp, PDHGOptions()).solve()
        ell = CompiledLPSolver(
            lp, PDHGOptions(dense_bytes_limit=0)).solve()
        from dervet_tpu.ops.pdhg import DenseOp, EllOp
        assert isinstance(CompiledLPSolver(lp).op, DenseOp)
        assert isinstance(CompiledLPSolver(lp, PDHGOptions(dense_bytes_limit=0)).op, EllOp)
        assert bool(ell.converged)
        ref = solve_lp_cpu(lp)
        assert abs(float(ell.obj) - ref.obj) / max(1.0, abs(ref.obj)) < 1e-3
        assert abs(float(ell.obj) - float(dense.obj)) / max(1.0, abs(ref.obj)) < 1e-3

    def test_ell_batched(self):
        lp = battery_like_lp(T=48)
        rng = np.random.default_rng(3)
        B = 4
        prices = rng.uniform(5, 100, (B, 48)) / 1000
        c_b = np.zeros((B, lp.n))
        for i in range(B):
            c_b[i, lp.var_refs["ch"].sl] = prices[i]
            c_b[i, lp.var_refs["dis"].sl] = -prices[i]
        res = CompiledLPSolver(lp, PDHGOptions(dense_bytes_limit=0)).solve(c=c_b)
        for i in range(B):
            ref = solve_lp_cpu(lp, c=c_b[i])
            assert bool(res.converged[i])
            assert abs(float(res.obj[i]) - ref.obj) / max(1.0, abs(ref.obj)) < 2e-3


class TestInfeasibilityCertificate:
    def test_early_exit_with_status(self):
        from dervet_tpu.ops.pdhg import (STATUS_PRIMAL_INFEASIBLE,
                                         diagnose_infeasibility)
        b = LPBuilder()
        v = b.var("x", 4, 0, 1)
        b.add_rows("impossible_demand", [(v, np.ones((1, 4)))], "ge", 100.0)
        b.add_cost(v, np.ones(4))
        lp = b.build()
        res = CompiledLPSolver(lp, PDHGOptions(max_iters=100_000)).solve()
        assert not bool(res.converged)
        assert int(res.status) == STATUS_PRIMAL_INFEASIBLE
        # certificate fires long before the iteration limit burns out
        assert int(res.iters) < 20_000
        msg = diagnose_infeasibility(lp, res.y)
        assert "impossible_demand" in msg

    def test_feasible_not_flagged(self):
        from dervet_tpu.ops.pdhg import STATUS_CONVERGED
        lp = battery_like_lp(T=48)
        res = CompiledLPSolver(lp).solve()
        assert int(res.status) == STATUS_CONVERGED


def test_window_fusion_padding_exact():
    """build_window_lps(pad_to_max=True) collapses the monthly length
    groups into one byte-identical structure WITHOUT changing any
    window's optimum: padded steps pin dispatch to zero and the tail SOE
    to the window target, so the exit pin constrains the real month
    exactly like the unpadded window."""
    from dervet_tpu.benchlib import build_window_lps, synthetic_case
    from dervet_tpu.ops.cpu_ref import solve_lp_cpu
    from dervet_tpu.scenario.scenario import MicrogridScenario

    _, fused = build_window_lps(synthetic_case(), pad_to_max=True)
    assert list(fused) == [744] and len(fused[744]) == 12
    keys = {MicrogridScenario._structure_key(lp) for lp in fused[744]}
    assert len(keys) == 1
    s = MicrogridScenario(synthetic_case())
    # February (shortest) and April (30-day): the two padded lengths
    for label in (1, 3):
        plain = solve_lp_cpu(s.build_window_lp(s.windows[label])).obj
        padded = solve_lp_cpu(fused[744][label]).obj
        assert abs(plain - padded) / max(1.0, abs(plain)) < 1e-9


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="fused Pallas chunk kernel runs on TPU only")
def test_pallas_chunk_matches_scan_on_tpu():
    """On-chip parity: the fused Pallas chunk path and the XLA scan path
    converge to the same objectives (both vs HiGHS).  Skipped on the
    CPU test platform — the bench and real-case NPV gate cover it in
    driver runs; this guards any future on-chip CI."""
    from dervet_tpu.ops.cpu_ref import solve_lp_cpu
    from dervet_tpu.ops.pdhg import CompiledLPSolver, PDHGOptions

    from dervet_tpu.ops import pallas_chunk

    lp = battery_like_lp(T=96)
    rng = np.random.default_rng(5)
    C = np.stack([lp.c * rng.uniform(0.8, 1.2, lp.n) for _ in range(130)])
    solver_p = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=True))
    # the kernel must actually be in play, else this compares scan to scan
    assert pallas_chunk.supports(solver_p.op, solver_p.opts.dtype,
                                 solver_p.opts.precision)
    res_p = solver_p.solve(c=C)
    assert not pallas_chunk.RUNTIME_DISABLED, \
        "kernel fell back at runtime — compile failed on this backend?"
    res_s = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=False)).solve(c=C)
    assert bool(np.asarray(res_p.converged).all())
    for i in (0, 64, 129):
        ref = solve_lp_cpu(lp, c=C[i]).obj
        for r in (res_p, res_s):
            rel = abs(float(np.asarray(r.obj)[i]) - ref) / max(1.0, abs(ref))
            assert rel < 1e-3, (i, rel)


class TestCpuStragglerRescue:
    """Batched driver hands a small unconverged minority to the exact CPU
    solver once past cpu_rescue_after iterations (division of labor at
    runtime: the batch rides the accelerator, pathological outliers ride
    HiGHS)."""

    def test_minority_rescued_with_exact_objective(self):
        lp = battery_like_lp(T=96)
        B = 16
        # 15 ordinary instances (converge in ~1.5k iterations) + 1
        # degenerate zero-cost instance, which the first-order method
        # never terminates on (measured: >100k iterations) — the
        # archetypal straggler
        C = np.tile(lp.c, (B, 1))
        C[0] = 0.0
        opts = PDHGOptions(max_iters=8192, compact_chunk_iters=512,
                           cpu_rescue_after=2048, pallas_chunk=False)
        res = CompiledLPSolver(lp, opts).solve(c=C)
        conv = np.asarray(res.converged)
        assert bool(conv.all()), conv
        # the rescued instance carries the exact CPU answer (obj 0 for a
        # zero-cost LP), not a truncated first-order iterate
        got = float(np.asarray(res.obj)[0])
        assert abs(got) < 1e-9, got
        assert int(np.asarray(res.status)[0]) == 0
        # the rescue must fire shortly past the threshold — if the early
        # break is broken the device burns the whole max_iters budget
        # before the post-loop fallback saves the result
        it0 = int(np.asarray(res.iters)[0])
        assert it0 <= 2048 + 512, it0
        # and a feasible primal: SOE dynamics hold
        x = np.asarray(res.x)[0]
        ene, ch, dis = (lp.value(x, k) for k in ("ene", "ch", "dis"))
        soe = 500.0
        for t in range(96):
            soe = soe + 0.85 * ch[t] - dis[t]
            assert abs(ene[t] - soe) < 1e-6

    def test_majority_not_rescued(self):
        """A broadly-unconverged batch is a systemic budget problem, not
        outliers — it must NOT be silently CPU-solved."""
        lp = battery_like_lp(T=96)
        B = 8
        rng = np.random.default_rng(2)
        C = np.stack([lp.c * rng.uniform(0.9, 1.1, lp.n) for _ in range(B)])
        opts = PDHGOptions(max_iters=256, compact_chunk_iters=128,
                           cpu_rescue_after=128, pallas_chunk=False)
        res = CompiledLPSolver(lp, opts).solve(c=C)
        # none converge in 256 iterations and none may be rescued
        assert not bool(np.asarray(res.converged).any())


class TestBandedOp:
    """Diagonal-band decomposition for large time-structured LPs
    (VERDICT r3 #5 enabler): the ELL gather matvec measured ~5 ms per
    105k-step year on TPU; static shifted slices measured ~0.1 ms.  Both
    directions must match scipy exactly, bands + residual + dense-column
    block composing correctly."""

    def _check(self, K, expect):
        import scipy.sparse as sp  # noqa: F401

        from dervet_tpu.ops.pdhg import make_op, op_matvec, op_rmatvec

        rng = np.random.default_rng(0)
        op = make_op(K.tocsr(), dense_bytes_limit=0)
        assert type(op).__name__ == expect, type(op).__name__
        m, n = K.shape
        x = rng.standard_normal(n)
        y = rng.standard_normal(m)
        hi = jax.lax.Precision.HIGHEST
        a = np.asarray(op_matvec(op, jnp.asarray(x, jnp.float32), hi))
        np.testing.assert_allclose(a, K @ x, rtol=2e-5, atol=1e-4)
        at = np.asarray(op_rmatvec(op, jnp.asarray(y, jnp.float32), hi))
        np.testing.assert_allclose(at, K.T @ y, rtol=2e-5, atol=1e-4)

    def test_soe_structure_goes_banded(self):
        import scipy.sparse as sp
        T = 2000
        D = sp.diags([np.ones(T), -0.9 * np.ones(T - 1)], [0, -1])
        Z = sp.hstack([D, -0.8 * sp.eye(T), 0.5 * sp.eye(T)])
        self._check(Z.tocsr(), "BandedOp")

    def test_aggregation_rows_ride_wide_pair(self):
        import scipy.sparse as sp
        rng = np.random.default_rng(3)
        T = 2000
        D = sp.diags([np.ones(T), -0.9 * np.ones(T - 1)], [0, -1])
        Z = sp.hstack([D, -0.8 * sp.eye(T), 0.5 * sp.eye(T)])
        agg = sp.coo_matrix(
            (np.ones(300), (np.zeros(300, int),
                            rng.choice(3 * T, 300, replace=False))),
            shape=(1, 3 * T))
        op_k = sp.vstack([Z, agg]).tocsr()
        from dervet_tpu.ops.pdhg import make_op
        op = make_op(op_k, dense_bytes_limit=0)
        # r5: few-row aggregation residuals ride the low-rank wide pair
        # (kernel-eligible), not an ELL residual
        assert op.ell is None and op.wide_w is not None
        assert op.wide_w.shape == (1, 3 * T)
        self._check(op_k, "BandedOp")

    def test_many_residual_rows_ride_residual_ell(self):
        import scipy.sparse as sp
        rng = np.random.default_rng(4)
        T = 2000
        D = sp.diags([np.ones(T), -0.9 * np.ones(T - 1)], [0, -1])
        Z = sp.hstack([D, -0.8 * sp.eye(T), 0.5 * sp.eye(T)])
        from dervet_tpu.ops.pdhg import WIDE_MAX_ROWS, make_op
        n_many = WIDE_MAX_ROWS + 16
        many = sp.coo_matrix(
            (np.ones(2 * n_many),
             (np.repeat(np.arange(n_many), 2),
              rng.integers(0, 3 * T, 2 * n_many))),
            shape=(n_many, 3 * T))
        op_k = sp.vstack([Z, many]).tocsr()
        op = make_op(op_k, dense_bytes_limit=0)
        assert op.ell is not None and op.wide_w is None
        self._check(op_k, "BandedOp")

    def test_wide_row_cap_counts_selector_bytes(self):
        """The wide-row pair is TWO dense blocks — the (r, n) values AND
        the (m, r) selector: a TALL matrix with a few wide rows must fall
        back to the ELL residual once the selector alone would blow the
        byte cap, or every scan-path matvec pays an m x r dense matmul
        (ADVICE r5).  The shape here passes the OLD values-only cap and
        fails the corrected one."""
        import scipy.sparse as sp
        from dervet_tpu.ops.pdhg import WIDE_MAX_BYTES, make_op
        rng = np.random.default_rng(5)
        n = 20_000
        r = 30
        m = n + r
        assert r * n * 8 <= WIDE_MAX_BYTES < r * (n + m) * 8
        diag = sp.eye(n, n, format="coo")
        wide = sp.coo_matrix(
            (np.ones(100 * r),
             (np.repeat(np.arange(r), 100),
              rng.integers(0, n, 100 * r))), shape=(r, n))
        op_k = sp.vstack([diag, wide]).tocsr()
        op = make_op(op_k)
        assert op.wide_w is None and op.ell is not None

    def test_unstructured_falls_back_to_ell(self):
        import scipy.sparse as sp
        R = sp.random(1500, 4000, density=0.002, random_state=3)
        self._check(R.tocsr(), "EllOp")

    @pytest.mark.slow
    def test_banded_solve_matches_dense_and_highs(self):
        """End-to-end: force the banded path on the canonical battery LP
        and match the dense path and HiGHS.  Slow: a T=1024 window needs
        tens of thousands of scan-path iterations on the CPU platform."""
        from dervet_tpu.ops.cpu_ref import solve_lp_cpu
        from dervet_tpu.ops.pdhg import BandedOp, CompiledLPSolver, \
            PDHGOptions

        lp = battery_like_lp(T=1024)    # bands need >= 256 entries each
        s_banded = CompiledLPSolver(lp, PDHGOptions(dense_bytes_limit=0))
        assert isinstance(s_banded.op, BandedOp)
        res_b = s_banded.solve()
        res_d = CompiledLPSolver(lp, PDHGOptions()).solve()
        ref = solve_lp_cpu(lp).obj
        for r in (res_b, res_d):
            assert bool(np.asarray(r.converged))
            assert abs(float(r.obj) - ref) / max(1.0, abs(ref)) < 1e-3


def test_banded_kernel_support_gate():
    """The fused-chunk gate admits BandedOp only without a residual ELL
    part (residual entries would need the gather the banded path exists
    to avoid), and the compile-failure handlers may consult it with
    ignore_runtime_disabled=True (the failing program was traced before a
    concurrent thread flipped the kill switch)."""
    import scipy.sparse as sp

    from dervet_tpu.ops import pallas_chunk
    from dervet_tpu.ops.pdhg import BandedOp, make_op, ruiz_scaling

    # T sized so the step footprint fits the kernel's VMEM envelope
    # (BLK * (9n + 5m) * 4 <= MAX_STEP_BYTES)
    T = 700
    D = sp.diags([np.ones(T), -0.9 * np.ones(T - 1)], [0, -1])
    Z = sp.hstack([D, -0.8 * sp.eye(T), 0.5 * sp.eye(T)]).tocsr()
    d_r, d_c = ruiz_scaling(Z, 5)
    Zs = Z.multiply(d_r[:, None]).multiply(d_c[None, :]).tocsr()
    op = make_op(Zs, dense_bytes_limit=0)
    assert isinstance(op, BandedOp) and op.ell is None
    # gate passes on a TPU backend spec (platform-independent args)
    assert pallas_chunk.supports(op, jnp.float32, backend="tpu")
    # a few aggregation rows ride the low-rank wide-row pair and KEEP
    # kernel support (r5: daily-cycle rows disqualified the kernel on
    # every real monthly window when they rode a residual ELL)
    rng = np.random.default_rng(0)
    agg = sp.coo_matrix(
        (np.ones(400), (np.zeros(400, int),
                        rng.choice(3 * T, 400, replace=False))),
        shape=(1, 3 * T))
    op2 = make_op(sp.vstack([Zs, agg]).tocsr(), dense_bytes_limit=0)
    assert isinstance(op2, BandedOp) and op2.ell is None
    assert op2.wide_w is not None and op2.wide_w.shape[0] == 1
    assert pallas_chunk.supports(op2, jnp.float32, backend="tpu")
    # beyond WIDE_MAX_ROWS distinct residual rows the fallback is still a
    # residual ELL, which disqualifies the kernel
    from dervet_tpu.ops.pdhg import WIDE_MAX_ROWS
    n_many = WIDE_MAX_ROWS + 16
    many = sp.coo_matrix(
        (np.ones(2 * n_many),
         (np.repeat(np.arange(n_many), 2),
          rng.integers(0, 3 * T, 2 * n_many))),
        shape=(n_many, 3 * T))
    op3 = make_op(sp.vstack([Zs, many]).tocsr(), dense_bytes_limit=0)
    assert isinstance(op3, BandedOp) and op3.ell is not None
    assert op3.wide_w is None
    assert not pallas_chunk.supports(op3, jnp.float32, backend="tpu")
    # the kill switch is overridable for compile-failure handlers
    pallas_chunk.RUNTIME_DISABLED = True
    try:
        assert not pallas_chunk.supports(op, jnp.float32, backend="tpu")
        assert pallas_chunk.supports(op, jnp.float32, backend="tpu",
                                     ignore_runtime_disabled=True)
    finally:
        pallas_chunk.RUNTIME_DISABLED = False
    # wide multi-DER-like shapes that blow the 128-row envelope drop to a
    # 64-row block (the banded kernel is VPU-bound, so a half block only
    # shrinks VMEM); beyond even that, the gate declines
    assert pallas_chunk._banded_blk(op) == 128
    Tw = 2100          # n = 3*Tw = 6300: fails blk=128, fits blk=64
    Dw = sp.diags([np.ones(Tw), -0.9 * np.ones(Tw - 1)], [0, -1])
    Zw = sp.hstack([Dw, -0.8 * sp.eye(Tw), 0.5 * sp.eye(Tw)]).tocsr()
    op_w = make_op(Zw, dense_bytes_limit=0)
    assert isinstance(op_w, BandedOp) and op_w.ell is None
    assert pallas_chunk._banded_blk(op_w) == 64
    assert pallas_chunk.supports(op_w, jnp.float32, backend="tpu")
    Th = 9000          # n = 27000: fails both block sizes
    Dh = sp.diags([np.ones(Th), -0.9 * np.ones(Th - 1)], [0, -1])
    Zh = sp.hstack([Dh, -0.8 * sp.eye(Th), 0.5 * sp.eye(Th)]).tocsr()
    op_h = make_op(Zh, dense_bytes_limit=0)
    if isinstance(op_h, BandedOp):
        assert pallas_chunk._banded_blk(op_h) is None
        assert not pallas_chunk.supports(op_h, jnp.float32, backend="tpu")


def test_make_op_prefers_banded_over_dense_when_covered():
    """A dense-fitting but fully-banded matrix routes to BandedOp (23%
    faster than dense+Pallas at bench shapes, PERF.md r4); low band
    coverage keeps dense."""
    import scipy.sparse as sp

    from dervet_tpu.ops.pdhg import BandedOp, DenseOp, make_op

    T = 1024
    D = sp.diags([np.ones(T), -0.9 * np.ones(T - 1)], [0, -1])
    Z = sp.hstack([D, -0.8 * sp.eye(T), 0.5 * sp.eye(T)]).tocsr()
    assert isinstance(make_op(Z, dense_bytes_limit=1 << 30), BandedOp)
    R = sp.random(1024, 3072, density=0.002, random_state=1).tocsr()
    assert isinstance(make_op(R, dense_bytes_limit=1 << 30), DenseOp)


def test_widened_bounds_with_default_q_rejected():
    """The presolve rhs clamp's contract (ADVICE r3): per-instance l/u
    passed to solve() with a defaulted q must stay INSIDE the build-time
    box — widening it could make a clamped 'ge' row bind incorrectly with
    no diagnostic.  Tighter bounds and explicit-q calls stay allowed."""
    from dervet_tpu.ops.pdhg import CompiledLPSolver, PDHGOptions

    lp = battery_like_lp(T=24)
    solver = CompiledLPSolver(lp, PDHGOptions(max_iters=512))
    wide_u = lp.u * 2.0
    with pytest.raises(ValueError, match="build-time box"):
        solver.solve(u=wide_u)
    with pytest.raises(ValueError, match="build-time box"):
        solver.solve(l=lp.l - 1.0, u=None)
    # inside the box: fine (shrinking is exactly what the clamp allows)
    solver.solve(u=lp.u * 0.5)
    # explicit q: the clamp contract is the caller's problem, no gate
    solver.solve(q=lp.q, u=wide_u)


def test_pallas_compile_failure_classifier():
    """The runtime fallback must catch exactly the kernel's COMPILE
    failure signatures — Mosaic scoped-VMEM rejections and the
    remote-compile helper crash — and must NOT swallow generic device
    errors that merely mention VMEM (a runtime resource exhaustion from
    an oversized batch has to propagate, not retry slowly on the scan
    path)."""
    from dervet_tpu.ops.pdhg import is_pallas_compile_failure

    caught = [
        "INTERNAL: http://127.0.0.1:8103/remote_compile: HTTP 500: "
        "tpu_compile_helper subprocess exit code 1",
        "Mosaic failed to compile TPU kernel: …",
        "RESOURCE_EXHAUSTED: scoped vmem limit exceeded",
        "requested vmem limit 104857600 exceeds device maximum",
    ]
    caught.append(
        # generic remote-compile HTTP failure without the helper line
        "INTERNAL: http://127.0.0.1:8103/remote_compile: HTTP 503: "
        "compile backend unavailable")
    passed_through = [
        "RESOURCE_EXHAUSTED: Out of memory allocating 2.1G in vmem/hbm",
        "RESOURCE_EXHAUSTED: out of HBM allocating batch buffers",
        "FAILED_PRECONDITION: device halted",
        "some unrelated ValueError",
        # a RUNTIME error that merely embeds the remote-compile endpoint
        # must propagate — the bare URL is on every error from such
        # backends (ADVICE r4)
        "RESOURCE_EXHAUSTED: out of memory while executing program "
        "fetched via http://127.0.0.1:8103/remote_compile",
    ]
    for msg in caught:
        assert is_pallas_compile_failure(Exception(msg)), msg
    for msg in passed_through:
        assert not is_pallas_compile_failure(Exception(msg)), msg


class TestStepVariants:
    """Reflected / Halpern-anchored PDHG (ops/pdhg.py variants): same
    answers as vanilla within tolerance, fewer iterations, the same
    certificates — and the DERVET_TPU_PDHG_VARIANT kill switch restores
    the vanilla iteration bit for bit."""

    @pytest.mark.parametrize("variant", ["vanilla", "reflected", "halpern"])
    def test_variant_matches_higgs(self, variant):
        lp = battery_like_lp(T=96)
        ref = solve_lp_cpu(lp)
        res = CompiledLPSolver(lp, PDHGOptions(variant=variant)).solve()
        assert bool(res.converged)
        assert abs(float(res.obj) - ref.obj) / max(1.0, abs(ref.obj)) < 1e-3

    def test_reflected_cuts_iterations(self):
        """The acceptance direction on a dispatch-shaped LP: the default
        reflected step needs strictly fewer iterations than vanilla
        (both deterministic, so this is a fixed comparison, not a
        flaky benchmark)."""
        lp = battery_like_lp(T=96)
        it = {}
        for variant in ("vanilla", "reflected"):
            res = CompiledLPSolver(lp, PDHGOptions(variant=variant)).solve()
            assert bool(res.converged)
            it[variant] = int(res.iters)
        assert it["reflected"] < it["vanilla"]

    def test_restarts_counted(self):
        lp = battery_like_lp(T=96)
        res = CompiledLPSolver(lp, PDHGOptions()).solve()
        assert int(res.restarts) > 0
        # batched: per-member counts ride the same fused result
        resb = CompiledLPSolver(lp, PDHGOptions()).solve(
            c=np.stack([lp.c, lp.c * 1.01]))
        assert np.asarray(resb.restarts).shape == (2,)
        assert int(np.asarray(resb.restarts).min()) > 0

    @pytest.mark.parametrize("variant", ["reflected", "halpern"])
    def test_infeasibility_certificate_survives_variant(self, variant):
        from dervet_tpu.ops.pdhg import STATUS_PRIMAL_INFEASIBLE
        b = LPBuilder()
        v = b.var("x", 4, 0, 1)
        b.add_rows("impossible_demand", [(v, np.ones((1, 4)))], "ge", 100.0)
        b.add_cost(v, np.ones(4))
        lp = b.build()
        res = CompiledLPSolver(
            lp, PDHGOptions(variant=variant, max_iters=100_000)).solve()
        assert int(res.status) == STATUS_PRIMAL_INFEASIBLE
        assert int(res.iters) < 20_000

    def test_kill_switch_restores_vanilla_bitwise(self, monkeypatch):
        """DERVET_TPU_PDHG_VARIANT=vanilla on a halpern-configured solver
        reproduces the vanilla solver's results bit for bit — the
        operator kill path."""
        lp = battery_like_lp(T=48)
        vanilla = CompiledLPSolver(
            lp, PDHGOptions(variant="vanilla")).solve()
        monkeypatch.setenv("DERVET_TPU_PDHG_VARIANT", "vanilla")
        killed = CompiledLPSolver(
            lp, PDHGOptions(variant="halpern")).solve()
        assert np.array_equal(np.asarray(killed.x), np.asarray(vanilla.x))
        assert np.array_equal(np.asarray(killed.y), np.asarray(vanilla.y))
        assert int(killed.iters) == int(vanilla.iters)

    def test_env_forces_variant(self, monkeypatch):
        from dervet_tpu.ops.pdhg import resolved_variant
        monkeypatch.setenv("DERVET_TPU_PDHG_VARIANT", "halpern")
        assert resolved_variant(PDHGOptions(variant="vanilla")) == "halpern"
        monkeypatch.setenv("DERVET_TPU_PDHG_VARIANT", "not-a-variant")
        # typo'd env is ignored (warn once), options win
        assert resolved_variant(PDHGOptions(variant="reflected")) \
            == "reflected"
        monkeypatch.delenv("DERVET_TPU_PDHG_VARIANT")
        with pytest.raises(ValueError, match="variant"):
            resolved_variant(PDHGOptions(variant="bogus"))

    def test_variant_kernel_selection_enum(self, monkeypatch):
        """Post-variant-native-kernel regression: a variant solve must
        never emit a variant-specific fallback reason — the kernel
        implements all three steps.  Off-TPU without interpret mode the
        reason is the machine-stable FALLBACK_BACKEND enum; under
        interpret mode the kernel is selected outright."""
        from dervet_tpu.ops import pallas_chunk
        from dervet_tpu.ops.pdhg import (FALLBACK_BACKEND, KERNEL_PALLAS,
                                         KERNEL_FALLBACK_REASONS,
                                         kernel_selection)
        lp = battery_like_lp(T=48)
        monkeypatch.delenv(pallas_chunk.INTERPRET_ENV, raising=False)
        solver = CompiledLPSolver(lp, PDHGOptions(variant="reflected"))
        kern, why, detail = kernel_selection(solver, batched=True)
        if jax.default_backend() == "tpu":
            assert kern == KERNEL_PALLAS and why is None
        else:
            assert kern == "xla_scan"
            assert why == FALLBACK_BACKEND
            assert why in KERNEL_FALLBACK_REASONS
            assert "variant" not in why
        monkeypatch.setenv(pallas_chunk.INTERPRET_ENV, "1")
        solver2 = CompiledLPSolver(lp, PDHGOptions(variant="halpern"))
        kern2, why2, _ = kernel_selection(solver2, batched=True)
        assert kern2 == KERNEL_PALLAS and why2 is None

    def test_fallback_reasons_are_machine_stable(self):
        """Every reason kernel_selection can emit is a member of
        KERNEL_FALLBACK_REASONS (the enum the ledger aggregation and
        bench.check_kernel_gate match on)."""
        from dervet_tpu.ops.pdhg import (KERNEL_FALLBACK_REASONS,
                                         kernel_selection)
        lp = battery_like_lp(T=48)
        solver = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=False))
        kern, why, _ = kernel_selection(solver, batched=True)
        assert why in KERNEL_FALLBACK_REASONS
        kern, why, _ = kernel_selection(solver, batched=False)
        assert why in KERNEL_FALLBACK_REASONS


class TestRestartSchemes:
    """The Halpern-native fixed-point-residual restart criterion
    (restart_scheme='fixed_point', MPAX): restart when ‖T(z) - z‖ stops
    decaying geometrically, re-anchoring at the CURRENT iterate — the
    scheme that stops the halpern anchor from fighting the PDLP
    weighted-average schedule."""

    def test_auto_mapping(self):
        from dervet_tpu.ops.pdhg import resolved_restart_scheme
        assert resolved_restart_scheme(
            PDHGOptions(variant="halpern")) == "fixed_point"
        assert resolved_restart_scheme(
            PDHGOptions(variant="reflected")) == "kkt"
        assert resolved_restart_scheme(
            PDHGOptions(variant="vanilla")) == "kkt"
        # selectable per-variant: any explicit combination is legal
        assert resolved_restart_scheme(PDHGOptions(
            variant="reflected",
            restart_scheme="fixed_point")) == "fixed_point"
        assert resolved_restart_scheme(PDHGOptions(
            variant="halpern", restart_scheme="kkt")) == "kkt"
        with pytest.raises(ValueError, match="restart_scheme"):
            resolved_restart_scheme(PDHGOptions(restart_scheme="bogus"))

    def test_kill_switch_resolves_scheme_too(self, monkeypatch):
        """DERVET_TPU_PDHG_VARIANT=vanilla on a halpern-configured
        solver must restore the KKT scheme (auto follows the RESOLVED
        variant) — part of the bit-exact kill path."""
        from dervet_tpu.ops.pdhg import resolved_restart_scheme
        monkeypatch.setenv("DERVET_TPU_PDHG_VARIANT", "vanilla")
        assert resolved_restart_scheme(
            PDHGOptions(variant="halpern")) == "kkt"

    def test_halpern_fp_restarts_engage(self):
        lp = battery_like_lp(T=96)
        solver = CompiledLPSolver(lp, PDHGOptions(variant="halpern"))
        assert solver.restart_scheme == "fixed_point"
        res = solver.solve(c=np.stack([lp.c, lp.c * 1.02]))
        assert bool(np.asarray(res.converged).all())
        assert int(np.asarray(res.restarts).min()) > 0
        assert solver.last_stats.restart_scheme == "fixed_point"

    def test_halpern_fp_closes_on_reflected(self):
        """The acceptance shape, small: halpern under its native scheme
        lands within 15% of reflected median cold iterations (it
        trailed badly under the KKT schedule)."""
        lp = battery_like_lp(T=96)
        C = np.stack([lp.c * (1 + 0.01 * i) for i in range(4)])
        it = {}
        for v in ("reflected", "halpern"):
            res = CompiledLPSolver(lp, PDHGOptions(variant=v)).solve(c=C)
            assert bool(np.asarray(res.converged).all())
            it[v] = float(np.percentile(np.asarray(res.iters), 50))
        assert it["halpern"] <= 1.15 * it["reflected"], it

    def test_explicit_kkt_is_default_trace_for_vanilla(self):
        """restart_scheme='kkt' spelled out reproduces the default
        (auto) vanilla solve bit for bit — the legacy path is the same
        trace, not a near-copy."""
        lp = battery_like_lp(T=48)
        a = CompiledLPSolver(lp, PDHGOptions(variant="vanilla")).solve()
        b = CompiledLPSolver(lp, PDHGOptions(
            variant="vanilla", restart_scheme="kkt")).solve()
        assert np.array_equal(np.asarray(a.x), np.asarray(b.x))
        assert int(a.iters) == int(b.iters)

    def test_fp_scheme_on_reflected_converges(self):
        lp = battery_like_lp(T=48)
        res = CompiledLPSolver(lp, PDHGOptions(
            variant="reflected", restart_scheme="fixed_point")).solve()
        assert bool(res.converged)


class TestAdaptiveCadence:
    """The restart/termination check cadence starts short and backs off
    geometrically (PDHGOptions.check_every_min), so short seeded solves
    exit near their true iteration count instead of overshooting by most
    of a fixed 128-iteration window."""

    def test_seeded_solve_exits_before_first_legacy_check(self):
        lp = battery_like_lp(T=96)
        solver = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=False))
        cold = solver.solve()
        warm = solver.solve(x0=np.asarray(cold.x), y0=np.asarray(cold.y))
        assert bool(warm.converged)
        # a fixed cadence of 128 cannot report fewer than 128 iterations;
        # the adaptive schedule catches the re-solve at its first checks
        assert int(warm.iters) < solver.opts.check_every

    def test_realized_cadence_recorded_and_saturates(self):
        lp = battery_like_lp(T=96)
        solver = CompiledLPSolver(lp, PDHGOptions(pallas_chunk=False))
        res = solver.solve()
        assert bool(res.converged)
        # a cold solve runs long enough to saturate the schedule
        assert solver.last_stats.cadence_final == solver.opts.check_every

    def test_disabled_cadence_matches_legacy_fixed_schedule(self):
        """check_every_min=0 restores the fixed-cadence path: iteration
        counts quantize to whole check_every windows again."""
        lp = battery_like_lp(T=96)
        solver = CompiledLPSolver(
            lp, PDHGOptions(pallas_chunk=False, check_every_min=0))
        res = solver.solve()
        assert bool(res.converged)
        assert int(res.iters) % solver.opts.check_every == 0
        assert solver.last_stats.cadence_final == solver.opts.check_every
