"""Electric-vehicle DERs (reference MicrogridDER/ElectricVehicles.py:
EV1 plug-window session charging to ene_target :194-297; EV2 baseline
load control between (1-ctrl)*baseline and baseline :495-544)."""
from pathlib import Path

import numpy as np
import pytest

from dervet_tpu.io.params import Params
from dervet_tpu.scenario.scenario import MicrogridScenario

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"


def _case_with(der_tag, keys):
    cases = Params.initialize(MP / "000-DA_battery_month.csv", base_path=REF)
    case = cases[0]
    case.ders.append((der_tag, "1", keys))
    return case


def test_ev1_sessions_reach_target():
    case = _case_with("ElectricVehicle1", {
        "name": "ev1", "ch_max_rated": 50, "ch_min_rated": 0,
        "ene_target": 80, "plugin_time": 19, "plugout_time": 7})
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    ts = s.timeseries_results()
    ch = ts["ELECTRICVEHICLE1: ev1 Charge (kW)"]
    hours = ch.index.hour
    plugged = (hours >= 19) | (hours < 7)
    # never charges unplugged
    assert (ch[~plugged] <= 1e-6).all()
    # overnight sessions fully inside a window deliver the target energy
    session_sums = ch.groupby((plugged != np.roll(plugged, 1)).cumsum()).sum()
    full_sessions = session_sums[(session_sums > 1.0)]
    assert len(full_sessions) > 300
    med = float(np.median(full_sessions))
    assert med == pytest.approx(80.0, rel=1e-4)


def test_ev2_baseline_bounds():
    case = _case_with("ElectricVehicle2", {
        "name": "fleet", "max_load_ctrl": 40, "lost_load_cost": 10000})
    rng = np.random.default_rng(3)
    case.datasets.time_series["EV fleet/1"] = rng.uniform(
        10, 60, len(case.datasets.time_series))
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    ts = s.timeseries_results()
    ch = ts["ELECTRICVEHICLE2: fleet Charge (kW)"].to_numpy()
    from dervet_tpu.scenario.window import grab_column
    base = grab_column(case.datasets.time_series.loc[ts.index],
                       "EV fleet", "1")
    assert (ch <= base + 1e-6).all()
    assert (ch >= 0.6 * base - 1e-6).all()


def test_ev1_report_soe_and_capex():
    case = _case_with("ElectricVehicle1", {
        "name": "ev1", "ch_max_rated": 50, "ch_min_rated": 0,
        "ene_target": 80, "plugin_time": 19, "plugout_time": 7,
        "ccost": 12000, "fixed_om": 500})
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    ev = next(d for d in s.ders if d.tag == "ElectricVehicle1")
    assert ev.get_capex() == 12000
    pf = ev.proforma_report([2017])
    assert float(pf["ELECTRICVEHICLE1: ev1 Fixed O&M Cost"].iloc[0]) == -500
    ts = s.timeseries_results()
    soe = ts["ELECTRICVEHICLE1: ev1 State of Energy (kWh)"]
    assert float(soe.max()) == pytest.approx(80.0, rel=1e-3)
    hours = soe.index.hour
    # begin-of-step convention (reference ene): 0 AT plug-in, ene_target
    # AT plug-out, held while unplugged
    assert (soe[hours == 19] == 0).all()
    plugout = soe[hours == 7].to_numpy()
    # sessions fully inside a window end at the target; the ~11 sessions
    # truncated by a monthly-window boundary are unconstrained
    frac_at_target = np.mean(np.isclose(plugout, 80.0, rtol=1e-3))
    assert frac_at_target > 0.9
    assert float(np.median(plugout)) == pytest.approx(80.0, rel=1e-3)
    assert (ts["ELECTRICVEHICLE1: ev1 Power (kW)"]
            == -ts["ELECTRICVEHICLE1: ev1 Charge (kW)"]).all()


def test_ev2_market_headroom_with_fr():
    """EV2 participating in FR: up-award bounded by sheddable baseline
    (reference get_charge_up/down_schedule, ElectricVehicles.py:467-493)."""
    cases = Params.initialize(MP / "001-DA_FR_battery_month.csv",
                              base_path=REF)
    case = cases[0]
    case.ders.append(("ElectricVehicle2", "1", {
        "name": "fleet", "max_load_ctrl": 40, "lost_load_cost": 10000}))
    rng = np.random.default_rng(5)
    case.datasets.time_series["EV fleet/1"] = rng.uniform(
        20, 80, len(case.datasets.time_series))
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    ts = s.timeseries_results()
    assert "FR Awarded Up (kW)" in ts.columns
    ch = ts["ELECTRICVEHICLE2: fleet Charge (kW)"].to_numpy()
    from dervet_tpu.scenario.window import grab_column
    base = grab_column(case.datasets.time_series.loc[ts.index],
                       "EV fleet", "1")
    bat = next(d for d in s.ders if d.tag == "Battery")
    bch = ts[bat.col("Charge (kW)")].to_numpy()
    bdis = ts[bat.col("Discharge (kW)")].to_numpy()
    up = ts["FR Awarded Up (kW)"].to_numpy()
    headroom = ((bat.discharge_capacity() - bdis) + bch
                + (ch - 0.6 * base))
    assert (up <= headroom + 1e-4).all()
