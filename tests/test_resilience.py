"""Solver resilience layer: escalation ladder, case-level failure
isolation, pre-dispatch input guards, and the run-health report — every
recovery rung exercised deterministically through the fault-injection
harness (``dervet_tpu.utils.faultinject``) rather than trusted.

The reference tool's per-window solve either returns optimal or kills the
run; the batched dispatch loop instead treats first-order non-convergence
as an expected operating condition (PDLP-family solvers have heavy-tailed
iteration counts, PAPERS.md: MPAX) and degrades gracefully."""
import logging

import numpy as np
import pytest

from dervet_tpu.benchlib import synthetic_case
from dervet_tpu.scenario.scenario import (MicrogridScenario, resolve_group,
                                          run_dispatch, solve_group,
                                          validate_lp_inputs)
from dervet_tpu.utils import faultinject
from dervet_tpu.utils.errors import AggregatedSolverError, SolverError


def _small_case(case_id: int = 0, days: int = 2, infeasible: bool = False):
    """Two days of the synthetic Battery+PV+DA case in 12-hour windows
    (4 small window-LPs) — fast enough for per-rung fault drills."""
    case = synthetic_case()
    case.case_id = case_id
    case.scenario["allow_partial_year"] = True
    case.scenario["n"] = 12
    ts = case.datasets.time_series.iloc[: 24 * days].copy()
    if infeasible:
        # an aggregate energy floor far above the battery's capacity for
        # two hours of window 1: genuinely primal infeasible
        case.streams["User"] = {"price": 0.0}
        floor = np.zeros(len(ts))
        floor[14:16] = 1e6
        ts["Aggregate Energy Min (kWh)"] = floor
    case.datasets.time_series = ts
    return case


class TestEscalationLadder:
    def test_retry_rung_recovers(self):
        """A window forced non-converged at the initial solve recovers on
        the boosted-budget retry; the run completes with the same
        objectives as an uninjected run."""
        ref = MicrogridScenario(_small_case())
        ref.optimize_problem_loop(backend="cpu")
        with faultinject.inject(nonconverge={1}) as plan:
            s = MicrogridScenario(_small_case())
            s.optimize_problem_loop(backend="cpu")
        assert plan.fired == [("solve", "1")]
        assert s.quarantine is None
        assert s.health["retried"] == 1
        assert s.health["clean"] == len(s.windows) - 1
        assert s.health["cpu_fallback"] == 0
        assert s.health["retry_seconds"] > 0
        assert set(s.objective_values) == set(ref.objective_values)
        for k in ref.objective_values:
            assert s.objective_values[k]["Total Objective"] == \
                pytest.approx(ref.objective_values[k]["Total Objective"],
                              rel=1e-9)

    def test_cpu_fallback_rung(self):
        """Forced non-convergence at BOTH the initial solve and the retry
        drops the window to the exact CPU fallback; rungs fire in ladder
        order and the case still completes."""
        with faultinject.inject(nonconverge={1},
                                rungs={"solve", "retry"}) as plan:
            s = MicrogridScenario(_small_case())
            s.optimize_problem_loop(backend="cpu")
        assert plan.fired == [("solve", "1"), ("retry", "1")]
        assert s.quarantine is None
        # health buckets are disjoint final outcomes: the window landed on
        # the CPU fallback, so it is NOT also counted as retried (the retry
        # rung's firing is asserted through plan.fired above)
        assert s.health["retried"] == 0
        assert s.health["cpu_fallback"] == 1
        assert len(s.objective_values) == len(s.windows)

    def test_ladder_exhaustion_quarantines(self):
        """When the CPU fallback itself fails the ladder is exhausted: the
        case is quarantined with the window named, and the (single-case)
        run raises ONE aggregated SolverError at the end."""
        with faultinject.inject(nonconverge={1}, rungs={"solve", "retry"},
                                cpu_fail={1}) as plan:
            s = MicrogridScenario(_small_case())
            with pytest.raises(AggregatedSolverError) as ei:
                s.optimize_problem_loop(backend="cpu")
        # every rung fired, in escalation order
        assert plan.fired == [("solve", "1"), ("retry", "1"), ("cpu", "1")]
        assert isinstance(ei.value, SolverError)
        assert s.quarantine is not None and s.quarantine["window"] == 1
        assert s.health["quarantined"] == 1
        assert "window 1" in str(ei.value)

    def test_ladder_on_jax_backend(self):
        """The same ladder drives the batched PDHG path: a member forced
        non-converged re-solves alone (not the whole group) and the run
        completes."""
        with faultinject.inject(nonconverge={2}) as plan:
            s = MicrogridScenario(_small_case())
            s.optimize_problem_loop(backend="jax")
        assert plan.fired == [("solve", "2")]
        assert s.quarantine is None
        assert s.health["retried"] == 1
        assert len(s.objective_values) == len(s.windows)


class TestCaseIsolation:
    def test_one_infeasible_case_does_not_kill_the_sweep(self):
        """Acceptance drill: a 4-case sweep with one deliberately
        infeasible case completes the other 3 and emits a health report
        counting the quarantined case — no full-run abort."""
        from dervet_tpu.io.summary import run_health_report
        scens = [MicrogridScenario(_small_case(i, infeasible=(i == 2)))
                 for i in range(4)]
        run_dispatch(scens, backend="cpu")     # must not raise
        for i, s in enumerate(scens):
            if i == 2:
                assert s.quarantine is not None
                assert "nfeasible" in s.quarantine["reason"]
                assert s.quarantine["window"] == 1
            else:
                assert s.quarantine is None
                assert len(s.objective_values) == len(s.windows)
        report = run_health_report(
            {i: s.health for i, s in enumerate(scens)},
            {i: s.quarantine for i, s in enumerate(scens)
             if s.quarantine is not None})
        assert report["cases_quarantined"] == ["2"]
        assert report["windows"]["quarantined"] == 1
        # the infeasible case's other windows still solved (and were
        # checkpoint-eligible); the three healthy cases are fully clean
        assert report["windows"]["clean"] == 3 * 4 + 3

    def test_all_cases_failed_raises_aggregated(self):
        scens = [MicrogridScenario(_small_case(i, infeasible=True))
                 for i in range(2)]
        with pytest.raises(AggregatedSolverError) as ei:
            run_dispatch(scens, backend="cpu")
        assert set(ei.value.failures) == {0, 1}
        assert all("nfeasible" in r for r in ei.value.failures.values())

    def test_all_failed_duplicate_case_ids_still_abort(self):
        """Caller-supplied case ids may collide — the all-failed abort
        counts scenarios, not unique ids, and keeps every diagnosis."""
        scens = [MicrogridScenario(_small_case(0, infeasible=True))
                 for _ in range(2)]
        with pytest.raises(AggregatedSolverError) as ei:
            run_dispatch(scens, backend="cpu")
        assert len(ei.value.failures) == 2

    def test_checkpoint_flushed_before_quarantine(self, tmp_path):
        """A case leaving the dispatch mid-run persists its already-solved
        windows first: the resumed run (fault cleared) re-solves ONLY the
        failed window."""
        with faultinject.inject(nonconverge={2}, rungs={"solve", "retry"},
                                cpu_fail={2}):
            s = MicrogridScenario(_small_case())
            with pytest.raises(SolverError):
                s.optimize_problem_loop(backend="cpu",
                                        checkpoint_dir=tmp_path)
        assert s._checkpoint_path(tmp_path).exists()
        s2 = MicrogridScenario(_small_case())
        s2.optimize_problem_loop(backend="cpu", checkpoint_dir=tmp_path)
        assert s2.quarantine is None
        assert len(s2.objective_values) == len(s2.windows)
        # windows 0/1/3 resumed from the flushed checkpoint; only the
        # previously-failed window 2 solved fresh
        assert s2.health["clean"] == 1


class TestInputGuards:
    def test_poisoned_case_quarantined_others_complete(self):
        with faultinject.inject(poison_cases={1}) as plan:
            a = MicrogridScenario(_small_case(0))
            b = MicrogridScenario(_small_case(1))
            run_dispatch([a, b], backend="cpu")
        assert ("poison", "1") in plan.fired
        assert a.quarantine is None
        assert len(a.objective_values) == len(a.windows)
        assert b.quarantine is not None
        assert "non-finite" in b.quarantine["reason"]
        assert "window" in b.quarantine["reason"]    # window-labeled
        assert b.health["quarantined"] == 1
        # the poisoned case's never-dispatched remainder is accounted, so
        # its buckets still sum to its window count
        assert b.health["quarantined"] + b.health["skipped"] + \
            b.health["clean"] == len(b.windows)

    def test_validate_rejects_nan_inf_and_crossed_bounds(self):
        s = MicrogridScenario(_small_case())
        lp = s.build_window_lp(s.windows[0])
        assert validate_lp_inputs(lp, 0) is None
        lp.c[3] = np.nan
        msg = validate_lp_inputs(lp, 7)
        assert msg is not None and "window 7" in msg and "c (costs)" in msg
        lp.c[3] = 0.0
        lp.q[0] = np.inf
        msg = validate_lp_inputs(lp, 7)
        assert msg is not None and "q (constraint rhs)" in msg
        lp.q[0] = 0.0
        lp.l[5] = 10.0
        lp.u[5] = 1.0
        msg = validate_lp_inputs(lp, 7)
        assert msg is not None and "crossed bound" in msg
        lp.l[5] = np.nan
        msg = validate_lp_inputs(lp, 7)
        assert msg is not None and "NaN in bound" in msg

    def test_rejection_happens_before_dispatch(self, monkeypatch):
        """The guard fires pre-dispatch: the solver is never entered for a
        poisoned case."""
        import dervet_tpu.scenario.scenario as scn
        calls = []
        real = scn.solve_group

        def counting(lp0, lps, backend, opts, **kw):
            calls.append(len(lps))
            return real(lp0, lps, backend, opts, **kw)

        monkeypatch.setattr(scn, "solve_group", counting)
        with faultinject.inject(poison_cases={0}):
            s = MicrogridScenario(_small_case(0))
            with pytest.raises(SolverError):
                s.optimize_problem_loop(backend="cpu")
        assert calls == []      # nothing reached the solver


class TestDiagnostics:
    def _arb_lp(self, T=48):
        """Small battery-arbitrage LP (same block structure the dispatch
        engine emits)."""
        from dervet_tpu.ops import LPBuilder
        rng = np.random.default_rng(1)
        price = rng.uniform(10, 80, T) / 1000
        b = LPBuilder()
        ch = b.var("ch", T, 0.0, 250.0)
        dis = b.var("dis", T, 0.0, 250.0)
        ene = b.var("ene", T, 0.0, 1000.0)
        D = np.eye(T) - np.eye(T, k=-1)
        rhs = np.zeros(T)
        rhs[0] = 500.0
        b.add_rows("soe", [(ene, D), (ch, -0.85), (dis, 1.0)], "eq", rhs)
        b.add_cost(ch, price)
        b.add_cost(dis, -price)
        return b.build()

    def test_inaccurate_warning_names_window_and_residual(self, caplog):
        """STATUS_INACCURATE acceptance names the window label and the
        actual KKT residuals — an anonymous warning is unactionable at
        hundreds of batched windows."""
        from dervet_tpu.ops.pdhg import PDHGOptions
        lp = self._arb_lp()
        # a tiny budget against near-zero tolerances cannot converge, but
        # an enormous inaccurate_factor accepts the exit as INACCURATE
        opts = PDHGOptions(max_iters=512, eps_abs=1e-15, eps_rel=1e-12,
                           inaccurate_factor=1e12, pallas_chunk=False,
                           cpu_rescue_after=None)
        with caplog.at_level(logging.WARNING, logger="dervet_tpu"):
            xs, objs, ok, diags, statuses = solve_group(
                lp, [lp], "jax", opts, labels=[42])
        assert ok == [True]
        msgs = [r.message for r in caplog.records
                if "reduced accuracy" in r.message]
        assert msgs, caplog.records
        assert "window 42" in msgs[0]
        assert "residual" in msgs[0] and "e-" in msgs[0] or "e+" in msgs[0]

    def test_status_specific_diags(self):
        """Each failure status carries its own message: an iteration-limit
        exit must not be labeled as anything else, and unknown codes are
        surfaced as such (the old fallback labeled EVERY non-infeasible
        failure 'iteration limit')."""
        from dervet_tpu.ops.pdhg import (STATUS_CONVERGED, STATUS_INACCURATE,
                                         STATUS_ITER_LIMIT,
                                         STATUS_PRIMAL_INFEASIBLE,
                                         PDHGOptions, status_message)
        seen = {status_message(s) for s in
                (STATUS_CONVERGED, STATUS_ITER_LIMIT,
                 STATUS_PRIMAL_INFEASIBLE, STATUS_INACCURATE)}
        assert len(seen) == 4          # all distinct
        assert "iteration limit" in status_message(STATUS_ITER_LIMIT)
        assert "reduced accuracy" in status_message(STATUS_INACCURATE)
        assert "status 99" in status_message(99)
        # a genuine iteration-limit exit reports exactly that
        lp = self._arb_lp()
        opts = PDHGOptions(max_iters=256, eps_abs=1e-15, eps_rel=1e-12,
                           inaccurate_factor=1.0, pallas_chunk=False,
                           cpu_rescue_after=None)
        xs, objs, ok, diags, statuses = solve_group(lp, [lp], "jax", opts,
                                                    labels=[0])
        assert ok == [False]
        assert statuses == [STATUS_ITER_LIMIT]
        assert diags[0] == status_message(STATUS_ITER_LIMIT)

    def test_resolve_group_rescues_genuine_iteration_limit(self):
        """No fault injection: a REAL iteration-limit exit (budget too
        small for the tolerance) climbs the real ladder and lands on the
        exact CPU fallback with a correct objective."""
        from dervet_tpu.ops.cpu_ref import solve_lp_cpu
        from dervet_tpu.ops.pdhg import PDHGOptions

        class _Ctx:
            label = 5

        class _Scn:
            def __init__(self):
                self.health = {"clean": 0, "inaccurate": 0, "retried": 0,
                               "cpu_fallback": 0, "quarantined": 0,
                               "retry_seconds": 0.0}

            class case:
                case_id = 0

        lp = self._arb_lp()
        opts = PDHGOptions(max_iters=64, eps_abs=1e-15, eps_rel=1e-12,
                           inaccurate_factor=1.0, pallas_chunk=False,
                           cpu_rescue_after=None)
        s = _Scn()
        xs, objs, ok, diags = resolve_group([(s, _Ctx(), lp)], "jax", opts)
        assert ok == [True]
        assert s.health["retried"] == 0       # disjoint: rung 1 failed
        assert s.health["retry_seconds"] > 0  # ...but the ladder ran
        assert s.health["cpu_fallback"] == 1  # rung 2 rescued it
        ref = solve_lp_cpu(lp)
        assert objs[0] == pytest.approx(ref.obj, rel=1e-9)


class TestHealthReport:
    def test_report_shape_and_totals(self):
        from dervet_tpu.io.summary import run_health_report
        h0 = {"clean": 10, "inaccurate": 1, "retried": 2, "cpu_fallback": 1,
              "quarantined": 0, "retry_seconds": 1.5}
        h1 = {"clean": 11, "inaccurate": 0, "retried": 0, "cpu_fallback": 0,
              "quarantined": 1, "retry_seconds": 0.25}
        rep = run_health_report(
            {0: h0, 1: h1}, {1: {"reason": "boom", "window": 3}})
        assert rep["windows"] == {"clean": 21, "inaccurate": 1,
                                  "retried": 2, "cpu_fallback": 1,
                                  "quarantined": 1, "skipped": 0}
        assert rep["retry_seconds"] == 1.75
        assert rep["cases_quarantined"] == ["1"]
        assert rep["quarantine_reasons"] == {"1": "boom"}
        assert rep["per_case"]["0"]["clean"] == 10

    def test_health_in_solve_metadata(self):
        s = MicrogridScenario(_small_case())
        s.optimize_problem_loop(backend="cpu")
        h = s.solve_metadata["health"]
        assert h["clean"] == len(s.windows)
        assert s.solve_metadata["quarantined"] is None

    def test_run_health_json_written(self, tmp_path):
        from dervet_tpu.io.summary import run_health_report
        from dervet_tpu.results.result import Result
        r = Result({})
        r.run_health = run_health_report({0: {"clean": 4}}, {})
        r.save_as_csv(tmp_path)
        import json
        data = json.loads((tmp_path / "run_health.json").read_text())
        assert data["windows"]["clean"] == 4


class TestFaultInjectEnv:
    def test_env_knobs_parse(self, monkeypatch):
        monkeypatch.setenv("DERVET_TPU_FAULT_NONCONVERGE", "3,7")
        monkeypatch.setenv("DERVET_TPU_FAULT_RUNGS", "solve,retry")
        monkeypatch.setenv("DERVET_TPU_FAULT_CPU_FAIL", "all")
        plan = faultinject.get_plan()
        assert plan is not None
        assert plan.force_nonconverge(3, "solve")
        assert plan.force_nonconverge(7, "retry")
        assert not plan.force_nonconverge(4, "solve")
        assert plan.cpu_should_fail(123)      # 'all' wildcard
        assert not plan.should_poison(0)

    def test_no_env_no_plan(self, monkeypatch):
        for var in ("DERVET_TPU_FAULT_NONCONVERGE",
                    "DERVET_TPU_FAULT_POISON_CASE",
                    "DERVET_TPU_FAULT_CPU_FAIL"):
            monkeypatch.delenv(var, raising=False)
        assert faultinject.get_plan() is None

    def test_context_manager_restores(self):
        assert faultinject.get_plan() is None
        with faultinject.inject(nonconverge={1}):
            assert faultinject.get_plan() is not None
        assert faultinject.get_plan() is None
