"""Program value streams: User, Backup, Deferral, DR, RA (VERDICT r1 #3).

Spec: storagevet program-stream surface (SURVEY.md §2.8) driven through the
reference's own test inputs (test_storagevet_features/model_params/003, 011,
012-016); the reference's tests assert completion + results presence.
"""
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from dervet_tpu.api import DERVET
from dervet_tpu.utils.errors import ParameterError

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"


def run(name, **kw):
    d = DERVET(MP / name, base_path=REF)
    return d.solve(backend="cpu", **kw)


@pytest.fixture(scope="module")
def solved_user():
    return run("011-DA_User_battery_month.csv")


def test_user_constraints_respected(solved_user):
    inst = solved_user.instances[0]
    ts = inst.time_series_data
    s = inst.scenario
    raw = s.case.datasets.time_series.loc[ts.index]
    from dervet_tpu.scenario.window import grab_column
    emax = grab_column(raw, "Aggregate Energy Max (kWh)")
    emin = grab_column(raw, "Aggregate Energy Min (kWh)")
    soe = ts["Aggregated State of Energy (kWh)"].to_numpy()
    if emax is not None:
        ok = np.isfinite(emax)
        assert (soe[ok] <= emax[ok] + 1e-3).all()
    if emin is not None:
        ok = np.isfinite(emin)
        assert (soe[ok] >= emin[ok] - 1e-3).all()
    assert "User Constraints Value" in inst.proforma_df.columns


def test_deferral_runs_and_reports():
    res = run("003-DA_Deferral_battery_month.csv")
    inst = res.instances[0]
    assert "Deferral: Avoided Upgrade" in inst.proforma_df.columns
    dd = inst.drill_down_dict.get("deferral_results")
    assert dd is not None
    assert {"Power Requirement (kW)", "Energy Requirement (kWh)",
            "Deferral Possible"} <= set(dd.columns)
    s = inst.scenario
    vs = s.streams["Deferral"]
    # substation import limit respected in the dispatch
    ts = inst.time_series_data
    from dervet_tpu.scenario.window import grab_column
    dload = grab_column(s.case.datasets.time_series.loc[ts.index],
                        "Deferral Load (kW)")
    net_export = -ts["Net Load (kW)"].to_numpy()
    substation_import = dload - net_export
    assert (substation_import <= vs.planned_load_limit + 1e-3).all()


@pytest.mark.parametrize("name", [
    "012-DA_RApeakmonth_battery_month.csv",
    "013-DA_RApeakmonthActive_battery_month.csv",
    "014-DA_RApeakyear_battery_month.csv",
])
def test_ra_cases_run(name):
    inst = run(name).instances[0]
    assert "RA Capacity Payment" in inst.proforma_df.columns
    assert float(inst.proforma_df.loc[2017, "RA Capacity Payment"]) > 0
    assert "RA Event (y/n)" in inst.time_series_data.columns


@pytest.mark.parametrize("name", [
    "015-DA_DRdayahead_battery_month.csv",
    "016-DA_DRdayof_battery_month.csv",
])
def test_dr_cases_run(name):
    inst = run(name).instances[0]
    assert "DR Capacity Payment" in inst.proforma_df.columns


def test_dr_length_end_hour_validation():
    """Exactly one of length/program_end_hour may be left nan; both missing
    or conflicting raises (reference inputs 021/022 exercise the nan
    derivation; 023/024 the error paths)."""
    from dervet_tpu.models.streams.programs import DemandResponse

    class DS:
        monthly = pd.DataFrame(
            {"DR Capacity (kW)": [10.0]},
            index=pd.MultiIndex.from_tuples([(2017, 1)],
                                            names=["Year", "Month"]))
        time_series = pd.DataFrame(
            index=pd.date_range("2017-01-01", periods=24, freq="h"))

    base = {"days": 2, "weekend": 0, "day_ahead": 1,
            "program_start_hour": 13}
    dr = DemandResponse({**base, "length": 4, "program_end_hour": "nan"},
                        {"dt": 1}, DS())
    assert dr.end_he == 16
    dr = DemandResponse({**base, "length": "nan", "program_end_hour": 16},
                        {"dt": 1}, DS())
    assert dr.length == 4
    with pytest.raises(ParameterError):
        DemandResponse({**base, "length": "nan", "program_end_hour": "nan"},
                       {"dt": 1}, DS())
    with pytest.raises(ParameterError):
        DemandResponse({**base, "length": 4, "program_end_hour": 20},
                       {"dt": 1}, DS())


def test_dr_day_ahead_event_discharge():
    """Day-ahead DR: the battery discharges the committed capacity during
    selected event steps."""
    res = run("015-DA_DRdayahead_battery_month.csv")
    inst = res.instances[0]
    s = inst.scenario
    vs = s.streams["DR"]
    ts = inst.time_series_data
    mask = vs.event_mask(ts.index)
    if mask.any():
        from dervet_tpu.models.streams.programs import _monthly_series
        cap = _monthly_series(s.case.datasets.monthly, "DR Capacity (kW)",
                              ts.index).fillna(0.0).to_numpy()
        bat = next(d for d in s.ders if d.tag == "Battery")
        dis = ts[bat.col("Discharge (kW)")].to_numpy()
        assert (dis[mask] >= cap[mask] - 1e-3).all()


def test_backup_reservation():
    """Backup holds the monthly energy floor (synthetic: flip Backup on in
    a DA case with monthly backup energy present)."""
    from dervet_tpu.io.params import Params
    from dervet_tpu.scenario.scenario import MicrogridScenario
    cases = Params.initialize(MP / "000-DA_battery_month.csv", base_path=REF)
    case = cases[0]
    if case.datasets.monthly is None or \
            "Backup Energy (kWh)" not in case.datasets.monthly.columns:
        pytest.skip("monthly backup data not present in dataset")
    case.streams["Backup"] = {}
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    ts = s.timeseries_results()
    from dervet_tpu.models.streams.programs import _monthly_series
    floor = _monthly_series(case.datasets.monthly, "Backup Energy (kWh)",
                            ts.index).fillna(0.0).to_numpy()
    soe = ts["Aggregated State of Energy (kWh)"].to_numpy()
    assert (soe >= floor - 1e-3).all()
