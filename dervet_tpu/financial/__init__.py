"""Financial post-processing (CBA/proforma/NPV)."""
from .cba import CostBenefitAnalysis
