"""Retail tariff engine: billing-period masks, energy prices, demand charges.

Re-implements the behavior of the reference's customer-tariff machinery (the
storagevet ``Financial`` billing helpers, SURVEY.md §2.8; tariff format per
``/root/reference/data/tariff.csv`` and the frozen billing outputs under
``/root/reference/test/test_validation_report_sept1/Results/``):

* a tariff is a table of billing periods — ``Start Month``/``End Month``
  (inclusive), ``Start Time``/``End Time`` in hour-ENDING units (inclusive),
  optional ``Excluding Start Time``/``Excluding End Time``, ``Weekday?``
  (0 weekend / 1 weekday / 2 both), ``Value`` and ``Charge``
  ('energy' $/kWh or 'demand' $/kW, case-insensitive)
* the retail energy price of a timestep is the SUM of every applicable
  energy period's value (stacking adders)
* demand charges apply per calendar month: value x the month's maximum net
  load (kW) over the period's masked timesteps, floored at zero
* billing reports: ``adv_monthly_bill`` (per month x billing period) and
  ``simple_monthly_bill`` (per month totals) with Original columns computed
  on the pre-DER load, matching the reference's output columns.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from ..utils.errors import TariffError


def _num(row, key, default=None):
    val = row.get(key, default)
    if val is None or (isinstance(val, float) and np.isnan(val)):
        return default
    try:
        return float(val)
    except (TypeError, ValueError):
        return default


class TariffEngine:
    """Vectorised billing-period masks over a datetime index."""

    def __init__(self, tariff: pd.DataFrame):
        if tariff is None or not len(tariff):
            raise TariffError("a customer tariff with at least one billing "
                              "period is required")
        self.tariff = tariff
        low = {str(c).strip().lower(): c for c in tariff.columns}
        need = ["start month", "end month", "start time", "end time",
                "weekday?", "value", "charge"]
        missing = [c for c in need if c not in low]
        if missing:
            raise TariffError(f"tariff is missing columns {missing}")
        self._col = low

    def _c(self, name: str) -> str:
        return self._col[name]

    # ------------------------------------------------------------------
    def period_mask(self, period_id, index: pd.DatetimeIndex) -> np.ndarray:
        """Boolean mask of timesteps (hour-beginning index) the billing
        period applies to."""
        row = self.tariff.loc[period_id]
        he = np.asarray(index.hour) + 1          # hour-ending label, 1..24
        month = np.asarray(index.month)
        weekday = np.asarray(index.weekday) < 5
        m0, m1 = _num(row, self._c("start month")), _num(row, self._c("end month"))
        t0, t1 = _num(row, self._c("start time")), _num(row, self._c("end time"))
        if None in (m0, m1, t0, t1):
            raise TariffError(
                f"billing period {period_id!r} has a blank/non-numeric "
                "Start/End Month or Start/End Time")
        mask = (month >= m0) & (month <= m1) & (he >= t0) & (he <= t1)
        x0 = _num(row, self._c("excluding start time")) \
            if "excluding start time" in self._col else None
        x1 = _num(row, self._c("excluding end time")) \
            if "excluding end time" in self._col else None
        if x0 is not None and x1 is not None:
            mask &= ~((he >= x0) & (he <= x1))
        wd = _num(row, self._c("weekday?"), 2)
        if wd == 1:
            mask &= weekday
        elif wd == 0:
            mask &= ~weekday
        return mask

    def _rows_of(self, kind: str) -> List:
        col = self._c("charge")
        return [pid for pid in self.tariff.index
                if str(self.tariff.loc[pid, col]).strip().lower() == kind]

    @property
    def energy_periods(self) -> List:
        return self._rows_of("energy")

    @property
    def demand_periods(self) -> List:
        return self._rows_of("demand")

    def value_of(self, period_id) -> float:
        return float(self.tariff.loc[period_id, self._c("value")])

    # ------------------------------------------------------------------
    def energy_price(self, index: pd.DatetimeIndex) -> np.ndarray:
        """Per-timestep retail energy price ($/kWh): sum of applicable
        energy-period values."""
        price = np.zeros(len(index))
        for pid in self.energy_periods:
            price[self.period_mask(pid, index)] += self.value_of(pid)
        return price

    def demand_masks(self, index: pd.DatetimeIndex
                     ) -> List[Tuple[object, float, np.ndarray]]:
        """``(period_id, $/kW value, mask)`` per demand billing period."""
        return [(pid, self.value_of(pid), self.period_mask(pid, index))
                for pid in self.demand_periods]

    def billing_periods_by_step(self, index: pd.DatetimeIndex) -> pd.Series:
        """Per-timestep list of applicable DEMAND billing periods (reference
        output column 'Demand Charge Billing Periods')."""
        masks = self.demand_masks(index)
        out = []
        for i in range(len(index)):
            out.append([pid for pid, _, m in masks if m[i]])
        return pd.Series(out, index=index)

    # ------------------------------------------------------------------
    def monthly_bill(self, net_load: pd.Series,
                     original_load: Optional[pd.Series] = None, dt: float = 1.0
                     ) -> Tuple[pd.DataFrame, pd.DataFrame]:
        """Compute the customer bill per month x billing period.

        ``net_load``/``original_load``: kW drawn from the grid (positive =
        import), indexed by hour-beginning timesteps.  Returns
        ``(adv_monthly_bill, simple_monthly_bill)`` frames matching the
        reference CSV columns.
        """
        index = net_load.index
        if original_load is None:
            original_load = net_load
        month_year = index.to_period("M")
        adv_rows = []
        simple_rows: Dict = {}
        for my in month_year.unique():
            in_month = np.asarray(month_year == my)
            sub_index = index[in_month]
            nl = net_load.to_numpy()[in_month]
            ol = original_load.to_numpy()[in_month]
            e_tot = oe_tot = d_tot = od_tot = 0.0
            applicable = []
            for pid in self.energy_periods:
                mask = self.period_mask(pid, sub_index)
                if not mask.any():
                    continue
                applicable.append(pid)
                val = self.value_of(pid)
                e = float(np.sum(nl[mask]) * val * dt)
                oe = float(np.sum(ol[mask]) * val * dt)
                e_tot += e
                oe_tot += oe
                adv_rows.append({"Month-Year": my, "Billing Period": pid,
                                 "Energy Charge ($)": e,
                                 "Original Energy Charge ($)": oe,
                                 "Demand Charge ($)": np.nan,
                                 "Original Demand Charge ($)": np.nan})
            for pid, val, mask in self.demand_masks(sub_index):
                if not mask.any():
                    continue
                applicable.append(pid)
                d = val * max(0.0, float(np.max(nl[mask])))
                od = val * max(0.0, float(np.max(ol[mask])))
                d_tot += d
                od_tot += od
                adv_rows.append({"Month-Year": my, "Billing Period": pid,
                                 "Energy Charge ($)": np.nan,
                                 "Original Energy Charge ($)": np.nan,
                                 "Demand Charge ($)": d,
                                 "Original Demand Charge ($)": od})
            simple_rows[str(my)] = {
                "Energy Charge ($)": e_tot,
                "Original Energy Charge ($)": oe_tot,
                "Billing Period": str(np.array(sorted(applicable))),
                "Demand Charge ($)": d_tot,
                "Original Demand Charge ($)": od_tot,
            }
        adv = pd.DataFrame(adv_rows)
        if len(adv):
            adv = adv.set_index("Month-Year")
        simple = pd.DataFrame(simple_rows).T
        simple.index.name = "Month-Year"
        return adv, simple

    def demand_charges_table(self) -> pd.DataFrame:
        """The demand rows of the tariff (reference 'demand_charges' CSV)."""
        return self.tariff.loc[self.demand_periods]
