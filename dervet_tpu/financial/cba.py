"""Financial post-processing: proforma, NPV, payback, cost-benefit, taxes.

Re-implements dervet/CBA.py (CostBenefitAnalysis) + the storagevet
Financial surface (SURVEY.md §2.6/§2.8) as pure pandas/numpy
post-processing of the dispatch tensors:

* proforma assembly: one column per cost/benefit stream, rows CAPEX Year +
  every project year (start_year..end_year), non-optimized years filled
  forward from the nearest optimized year
* capital costs land in the CAPEX Year row (construction-year handling,
  reference CBA.py:392-407)
* salvage value / decommissioning at end of analysis (CBA.py:409-438)
* MACRS depreciation + state/federal taxes (CBA.py:440-477) or economic
  carrying cost substitution (ecc_mode)
* NPV by column, payback + discounted payback, IRR, benefit-cost ratio
  (CBA.py:479-523)
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import pandas as pd

from ..utils.errors import ModelParameterError, ParameterError, TellUser

# MACRS half-year convention depreciation schedules (% of basis per year),
# as carried by the reference (CBA.py:81-92).  NOTE the 15-year table's
# year-5 entry is 6.83 — the IRS Pub 946 table says 6.93 there, but parity
# with the reference's tax rows wins over the IRS erratum (VERDICT r3 #7);
# tests/test_taxes.py pins this entry deliberately.
MACRS_TABLES: Dict[int, List[float]] = {
    3: [33.33, 44.45, 14.81, 7.41],
    5: [20.0, 32.0, 19.2, 11.52, 11.52, 5.76],
    7: [14.29, 24.49, 17.49, 12.49, 8.93, 8.92, 8.93, 4.46],
    10: [10.0, 18.0, 14.4, 11.52, 9.22, 7.37, 6.55, 6.55, 6.56, 6.55, 3.28],
    15: [5.0, 9.5, 8.55, 7.7, 6.83, 6.23, 5.9, 5.9, 5.91, 5.9, 5.91, 5.9,
         5.91, 5.9, 5.91, 2.95],
    20: [3.75, 7.219, 6.677, 6.177, 5.713, 5.285, 4.888, 4.522, 4.462, 4.461,
         4.462, 4.461, 4.462, 4.461, 4.462, 4.461, 4.462, 4.461, 4.462,
         4.461, 2.231],
}

CAPEX_ROW = "CAPEX Year"


def npv_series(rate: float, values: np.ndarray) -> float:
    """Present value of values[0..n] where values[k] occurs at year k
    (k=0 not discounted) — numpy-financial npv semantics (CBA.py:212)."""
    return float(sum(v / (1.0 + rate) ** k for k, v in enumerate(values)))


def irr(values: np.ndarray, lo=-0.99, hi=10.0, tol=1e-10) -> float:
    """Internal rate of return by bisection (replaces removed np.irr)."""
    def f(r):
        return sum(v / (1.0 + r) ** k for k, v in enumerate(values))
    flo, fhi = f(lo), f(hi)
    if flo * fhi > 0:
        return float("nan")
    for _ in range(200):
        mid = 0.5 * (lo + hi)
        fm = f(mid)
        if abs(fm) < tol:
            return mid
        if flo * fm < 0:
            hi, fhi = mid, fm
        else:
            lo, flo = mid, fm
    return 0.5 * (lo + hi)


class CostBenefitAnalysis:
    """Project-lifetime economics for one scenario case."""

    def __init__(self, finance: Dict, start_year: int, end_year: int,
                 opt_years: List[int], dt: float = 1.0, yearly=None):
        self.finance = finance
        g = lambda k, d=0.0: float(finance.get(k, d) or 0.0)
        self.inflation_rate = g("inflation_rate") / 100.0
        self.npv_discount_rate = g("npv_discount_rate") / 100.0
        self.federal_tax_rate = g("federal_tax_rate") / 100.0
        self.state_tax_rate = g("state_tax_rate") / 100.0
        self.property_tax_rate = g("property_tax_rate") / 100.0
        self.analysis_horizon_mode = int(g("analysis_horizon_mode", 1) or 1)
        self.ecc_mode = bool(finance.get("ecc_mode", False))
        self.external_incentives = bool(finance.get("external_incentives", False))
        self.start_year = int(start_year)
        self.end_year = int(end_year)
        self.opt_years = sorted(int(y) for y in opt_years)
        self.dt = dt
        self.yearly = yearly    # Year-indexed incentives data (optional)
        self.proforma: Optional[pd.DataFrame] = None
        self.npv: Optional[pd.DataFrame] = None
        self.payback: Optional[pd.DataFrame] = None
        self.cost_benefit: Optional[pd.DataFrame] = None
        self.tax_breakdown: Optional[pd.DataFrame] = None

    # ------------------------------------------------------------------
    def find_end_year(self, der_list) -> int:
        """Analysis-horizon modes (reference CBA.py:94-130): 1 = user,
        2 = start year + shortest DER lifetime - 1, 3 = longest.  Sizing +
        mode 2/3 is an input error (the lifetime is not yet known)."""
        if self.analysis_horizon_mode not in (2, 3):
            # unrecognized modes keep the user-supplied end year (reference
            # falls through unchanged)
            return self.end_year
        if any(d.being_sized() for d in der_list):
            raise ParameterError(
                "analysis_horizon_mode 2/3 cannot be combined with sizing "
                "(reference: CBA.find_end_year + MicrogridScenario.py:142-146)")
        if self.analysis_horizon_mode == 2:
            # shortest lifetime over ALL DERs (loads included)
            lifetimes = [d.expected_lifetime for d in der_list
                         if d.expected_lifetime]
            agg = min
        else:
            # longest lifetime excluding loads (reference CBA.py:108-118)
            lifetimes = [d.expected_lifetime for d in der_list
                         if d.expected_lifetime and d.technology_type != "Load"]
            agg = max
        if not lifetimes:
            return self.end_year
        return self.start_year + agg(lifetimes) - 1

    def annuity_scalar(self, opt_years: List[int]) -> float:
        """Scalar converting one optimized year's cost to lifetime present
        value (exact reference formula, CBA.py:190-213): n = end - start
        project years, $1 at the base optimized year escalated by inflation
        in both directions, then npv with the base-year cashflow at k=1."""
        n_years = self.end_year - self.start_year
        if n_years <= 0:
            return 1.0
        dollars = np.ones(n_years)
        base = min(int(y) for y in opt_years) - self.start_year
        base = min(max(base, 0), n_years - 1)
        for k in range(base, n_years - 1):
            dollars[k + 1] = dollars[k] * (1 + self.inflation_rate)
        for k in range(base, 0, -1):
            dollars[k - 1] = dollars[k] / (1 + self.inflation_rate)
        pv = sum(d / (1 + self.npv_discount_rate) ** (k + 1)
                 for k, d in enumerate(dollars))
        return float(pv)

    # ------------------------------------------------------------------
    def calculate(self, ders, value_streams: Dict, results: pd.DataFrame,
                  opt_years: List[int], poi=None) -> None:
        self.proforma = self.proforma_report(ders, value_streams, results,
                                             opt_years, poi)
        self.npv = self.npv_report(self.proforma)
        self.cost_benefit = self.cost_benefit_report(self.proforma)
        self.payback = self.payback_report(self.proforma, self.npv,
                                           self.cost_benefit)

    # ------------------------------------------------------------------
    def proforma_report(self, ders, value_streams: Dict,
                        results: pd.DataFrame, opt_years: List[int],
                        poi=None) -> pd.DataFrame:
        years = list(range(self.start_year, self.end_year + 1))
        index = [CAPEX_ROW] + years
        # columns accumulate in a dict and become ONE DataFrame below:
        # per-column ``proforma[name] = ...`` insertion plus the per-year
        # scalar setitem loop cost ~20 ms per case — a material slice of
        # a 128-case sweep's post-processing (VERDICT r5 #1)
        col_map: Dict[str, pd.Series] = {}

        growth_map: Dict[str, Optional[float]] = {}
        for der in ders:
            cols = self._der_columns(der, opt_years, results)
            col_map.update(cols)
            # DER columns with their own escalation (PV PPA inflation)
            growth_map.update(der.proforma_growth_rates())

        yr_set = set(years)
        for vs in value_streams.values():
            df = vs.proforma_report(opt_years, poi, results)
            if df is None:
                continue
            yrs = np.array([per.year if hasattr(per, "year") else int(per)
                            for per in df.index])
            keep = np.isin(yrs, list(yr_set))
            for name in df.columns:
                col = pd.Series(0.0, index=index, dtype=float)
                col.loc[yrs[keep]] = df[name].to_numpy()[keep]
                col_map[name] = col
                # each stream's columns escalate at that stream's own
                # proforma growth rate in fill-forward years (reference:
                # case 041 growth=0 stays flat, Usecase1 2.2% escalates);
                # streams with fill_forward=False pay only in opt years
                if not getattr(vs, "fill_forward", True):
                    growth_map[name] = None
                else:
                    override = getattr(vs, "proforma_growth", None)
                    growth_map[name] = float(
                        override if override is not None
                        else getattr(vs, "growth", 0.0) or 0.0)

        proforma = (pd.DataFrame(col_map, index=pd.Index(index))
                    if col_map else pd.DataFrame(index=pd.Index(index)))
        proforma = self._fill_forward(proforma, opt_years, growth_map)
        # incentives come from explicit per-year data — after fill-forward
        # so missing years stay zero instead of escalating
        self._external_incentive_columns(proforma)
        proforma = self._zero_out_dead_ders(proforma, ders)
        proforma = self._move_capex_to_construction_year(proforma, ders)
        # an all-zero CAPEX Year row is dropped (reference CBA.py:316-318);
        # npv discounting is row-positional on both sides, so the drop
        # shifts year-1 cashflows to k=0 exactly as the reference does
        if CAPEX_ROW in proforma.index \
                and not proforma.loc[CAPEX_ROW].abs().any():
            proforma = proforma.drop(index=CAPEX_ROW)
        # ECC substitution and income taxes are mutually exclusive branches
        # in the reference (CBA.py:323-346: `if ecc_mode ... else
        # calculate_taxes`)
        if self.ecc_mode:
            proforma = self._ecc_substitution(proforma, ders)
        else:
            proforma = self.calculate_taxes(proforma, ders)
        proforma = proforma.sort_index(axis=1)
        proforma = proforma.fillna(0.0)
        proforma["Yearly Net Value"] = proforma.sum(axis=1)
        return proforma

    def ecc_checks(self, ders, streams: Dict) -> None:
        """ECC-mode validity: an economic-carrying-cost analysis requires a
        Reliability or Deferral service, and every DER's technology
        escalation rate must stay below the project discount rate
        (reference CBA.py:132-158)."""
        if not set(streams) & {"Reliability", "Deferral"}:
            TellUser.error(
                "An ecc analysis does not make sense for the case you "
                "selected. A reliability or asset deferral case would be "
                "better suited for economic carrying cost analysis")
            raise ModelParameterError(
                "The combination of services does not work with the rest "
                "of your case settings. Please see log file for more "
                "information.")
        for der in ders:
            if der.escalation_rate >= self.npv_discount_rate:
                TellUser.error(
                    f"The technology escalation rate "
                    f"({der.escalation_rate}) cannot be greater than the "
                    f"project discount rate ({self.npv_discount_rate}). "
                    f"Please edit the 'ter' value for {der.name}.")
                raise ModelParameterError(
                    "TER and discount rates conflict. Please see log file "
                    "for more information.")

    def _move_capex_to_construction_year(self, proforma: pd.DataFrame,
                                         ders) -> pd.DataFrame:
        """Capital cost lands on the construction year when construction
        starts at or after the project start year; otherwise it stays in
        the CAPEX Year row (reference CBA.py:392-407 +
        DERExtension.put_capital_cost_on_construction_year, :190-206)."""
        for der in ders:
            cy = der.construction_year
            if not cy or cy < self.start_year or cy not in proforma.index:
                # outside the proforma's year range: leave the capital
                # cost in the CAPEX Year row rather than deleting it
                continue
            col = f"{der.unique_tech_id} Capital Cost"
            if col not in proforma.columns:
                continue
            proforma[col] = 0.0
            proforma.loc[cy, col] = -der.get_capex()
        return proforma

    def _der_columns(self, der, opt_years, results) -> Dict[str, pd.Series]:
        years = [CAPEX_ROW] + list(range(self.start_year, self.end_year + 1))
        cols: Dict[str, pd.Series] = {}
        uid = der.unique_tech_id
        zero = lambda: pd.Series(0.0, index=years, dtype=float)

        capex = der.get_capex()
        cap = zero()
        cap[CAPEX_ROW] = -capex
        cols[f"{uid} Capital Cost"] = cap

        df = der.proforma_report(opt_years)
        if df is not None:
            for name in df.columns:
                col = zero()
                for per, val in df[name].items():
                    yr = per.year if hasattr(per, "year") else int(per)
                    if yr in col.index:
                        col[yr] = val
                cols[name] = col

        # lifecycle: replacements at failure years (escalated at ter from
        # the operation year), decommissioning at min(end, last op + 1),
        # salvage at end of analysis (reference CBA.py:348-438 +
        # DERExtension.py:162-265).  Non-owned assets (PV PPA) have none
        # of these (IntermittentResourceSizing.py:295-316)
        if not der.owns_asset():
            return cols
        failure_years = der.set_failure_years(self.end_year, self.start_year)
        if der.replaceable and failure_years:
            rep = zero()
            rcost = der.replacement_cost()
            for fy in failure_years:
                pay_year = fy + 1 - der.replacement_construction_time
                if pay_year in rep.index and fy < self.end_year:
                    esc = (1 + der.escalation_rate) ** \
                        (pay_year - (der.operation_year or self.start_year))
                    rep[pay_year] += -rcost * esc
            # the reference joins the replacement report only when some
            # failure year precedes the end year (CBA.py:355-362 +
            # DERExtension.replacement_report:170-189 — a failure AT the
            # end year emits no column; an earlier failure whose payment
            # falls outside the proforma still emits an all-zero one)
            if any(fy < self.end_year for fy in failure_years):
                cols[f"{uid} Replacement Costs"] = rep
        base_yr = min(opt_years) if opt_years else self.start_year
        decomm = float(der.keys.get("decommissioning_cost", 0) or 0)
        dec = zero()
        if decomm:
            dec_year = min(self.end_year,
                           getattr(der, "last_operation_year", self.end_year) + 1)
            # escalate the nominal cost at inflation from the optimized year
            # (reference CBA.py:419-435)
            dec[dec_year] = -decomm * (1 + self.inflation_rate) ** \
                (dec_year - base_yr)
        cols[f"{uid} Decommissioning Cost"] = dec
        salvage = self._salvage_value(der, capex)
        sal = zero()
        if salvage:
            sal[self.end_year] = salvage * (1 + der.escalation_rate) ** \
                (self.end_year - base_yr)
        cols[f"{uid} Salvage Value"] = sal
        return cols

    def _external_incentive_columns(self, proforma: pd.DataFrame) -> None:
        """'Tax Credit' / 'Other Incentives' rows from the yearly data file
        when external_incentives is on (reference: storagevet Financial
        yearly-data surface; golden proforma columns)."""
        if not self.external_incentives or self.yearly is None:
            return
        cols = {str(c).strip().lower(): c for c in self.yearly.columns}
        for label, stem in (("Tax Credit", "tax credit"),
                            ("Other Incentives", "other incentive")):
            src = next((c for k, c in cols.items() if k.startswith(stem)),
                       None)
            if src is None:
                continue
            series = pd.Series(0.0, index=proforma.index, dtype=float)
            for yr, val in self.yearly[src].items():
                if yr in series.index and not pd.isna(val):
                    series[yr] = float(val)
            proforma[label] = series

    def _zero_out_dead_ders(self, proforma: pd.DataFrame, ders
                            ) -> pd.DataFrame:
        """Zero every cost/benefit column of a non-replaceable DER past its
        last operational year; once ALL DERs are dead, zero the whole
        proforma (reference CBA.py:366-390)."""
        last_years = []
        for der in ders:
            if der.replaceable or not der.expected_lifetime:
                last_years.append(self.end_year)
                continue
            last = getattr(der, "last_operation_year", self.end_year)
            last_years.append(last)
            uid = der.unique_tech_id
            dead = [y for y in proforma.index
                    if y != CAPEX_ROW and y > last]
            for col in proforma.columns:
                if col.startswith(uid) and "Salvage" not in col \
                        and "Decommissioning" not in col:
                    proforma.loc[dead, col] = 0.0
        if last_years:
            no_more_der_yr = max(last_years)
            dead_all = [y for y in proforma.index
                        if y != CAPEX_ROW and y > no_more_der_yr]
            if dead_all:
                keep = [c for c in proforma.columns
                        if "Salvage" in c or "Decommissioning" in c]
                zero_cols = [c for c in proforma.columns if c not in keep]
                proforma.loc[dead_all, zero_cols] = 0.0
        return proforma

    def _ecc_substitution(self, proforma: pd.DataFrame, ders
                          ) -> pd.DataFrame:
        """ECC mode: replace capex + replacement columns with annualized
        economic carrying costs (reference CBA.py:323-338 +
        DERExtension.economic_carrying_cost_report, :267-306)."""
        self.ecc_breakdown = {}
        for der in ders:
            if not der.ecc_perc or not der.expected_lifetime:
                continue
            uid = der.unique_tech_id
            capex_col = f"{uid} Capital Cost"
            rep_col = f"{uid} Replacement Costs"
            proforma[capex_col] = 0.0
            if rep_col in proforma.columns:
                proforma[rep_col] = 0.0
            op = der.operation_year or self.start_year
            last = min(op + der.expected_lifetime - 1, self.end_year)
            cc = pd.Series(0.0, index=proforma.index, dtype=float)
            capex = der.get_capex()
            for y in range(op, last + 1):
                infl = (1 + self.inflation_rate) ** \
                    (y - (der.construction_year or op))
                if y in cc.index:
                    cc[y] = -capex * der.ecc_perc * infl
            proforma[f"{uid} Carrying Cost"] = cc
            self.ecc_breakdown[uid] = cc
        return proforma

    def equipment_lifetime_report(self, ders) -> pd.DataFrame:
        """Beginning of Life / Operation Begins / End of Life per DER
        (reference CBA.py:525-536; golden equipment_lifetimes CSV)."""
        cols = {d.unique_tech_id:
                d.equipment_lifetime_row(self.end_year, self.start_year)
                for d in ders}
        return pd.DataFrame(cols)

    def _salvage_value(self, der, capex: float) -> float:
        """'sunk cost' -> 0; otherwise salvage applies only when the (last
        replacement's) life extends beyond the analysis end: 'linear
        salvage value' -> capex * years-beyond-project / lifetime; numeric
        -> $ (reference DERExtension.calculate_salvage_value, :218-250)."""
        raw = der.keys.get("salvage_value", 0)
        label = raw.strip().lower() if isinstance(raw, str) else None
        if label == "sunk cost":
            return 0.0
        last_op = getattr(der, "last_operation_year", self.end_year)
        if last_op + 1 <= self.end_year:
            return 0.0
        years_beyond = last_op - self.end_year
        if years_beyond < 0:
            return 0.0
        if label == "linear salvage value":
            lifetime = der.expected_lifetime
            return capex * years_beyond / lifetime if lifetime else 0.0
        try:
            return float(raw or 0)
        except ValueError:
            return 0.0

    def _fill_forward(self, proforma: pd.DataFrame, opt_years: List[int],
                      growth_map: Dict[str, Optional[float]]) -> pd.DataFrame:
        """Fill each non-optimized year from the nearest previous optimized
        year.  Each value-stream column escalates at that stream's own
        growth rate (reference: case 041 retailETS growth=0 stays flat;
        Usecase1 growth=2.2%/yr escalates); DER operating-cost columns stay
        flat."""
        years = [y for y in proforma.index if y != CAPEX_ROW]
        opt_set = sorted(set(opt_years))
        for y in years:
            if y in opt_set:
                continue
            prev = [o for o in opt_set if o < y]
            src = prev[-1] if prev else opt_set[0]
            for colname in proforma.columns:
                col = proforma[colname]
                # only fill operating rows (CAPEX/salvage/decommissioning
                # rows live on specific years)
                if "Capital Cost" in colname:
                    continue
                if "Salvage" in colname or "Decommissioning" in colname:
                    continue
                rate = growth_map.get(colname, 0.0)
                if rate is None:      # paid only in optimized years
                    continue
                if col[y] == 0.0 and col[src] != 0.0:
                    proforma.loc[y, colname] = col[src] * (1 + rate) ** (y - src)
        return proforma

    # ------------------------------------------------------------------
    def calculate_taxes(self, proforma: pd.DataFrame, ders
                        ) -> pd.DataFrame:
        """MACRS depreciation + state/federal income tax on yearly net
        income (reference CBA.py:440-477): per-DER MACRS columns plus a
        capex 'disregard' column cancel capital costs out of taxable
        income; state tax applies to the net of every year (negative years
        earn a credit), federal tax applies net-of-state-tax; all three
        burden columns are added to the proforma exactly as the reference
        does."""
        tax_calcs = proforma.copy(deep=True)
        for der in ders:
            contrib = self._tax_contribution(der, tax_calcs.index)
            if contrib is not None:
                tax_calcs = pd.concat([tax_calcs, contrib], axis=1)
        yearly_net = tax_calcs.sum(axis=1)
        tax_calcs["Taxable Yearly Net"] = yearly_net
        state = yearly_net * -self.state_tax_rate
        tax_calcs["State Tax Burden"] = state
        federal = (yearly_net + state) * -self.federal_tax_rate
        tax_calcs["Federal Tax Burden"] = federal
        tax_calcs["Overall Tax Burden"] = state + federal
        self.tax_breakdown = tax_calcs
        proforma["State Tax Burden"] = state
        proforma["Federal Tax Burden"] = federal
        proforma["Overall Tax Burden"] = state + federal
        return proforma

    def _tax_contribution(self, der, index) -> Optional[pd.DataFrame]:
        """MACRS Depreciation + Disregard From Taxable Income columns for
        one DER (reference DERExtension.tax_contribution, :308-349):
        depreciation starts at max(construction_year + 1, start_year);
        the disregard adds capex back so taxable income excludes it."""
        term = der.keys.get("macrs_term")
        if not term or not der.owns_asset():
            return None
        table = MACRS_TABLES.get(int(float(term)))
        if table is None:
            TellUser.warning(f"no MACRS table for term {term}; skipped")
            return None
        capex = der.get_capex()
        uid = der.unique_tech_id
        out = pd.DataFrame(
            0.0, index=index,
            columns=[f"{uid} MACRS Depreciation",
                     f"{uid} Disregard From Taxable Income"])
        cy = der.construction_year
        start_taxing = max((cy + 1) if cy else self.start_year,
                           self.start_year)
        years = [y for y in index if y != CAPEX_ROW and y >= start_taxing]
        for k, yr in enumerate(years):
            pct = table[k] if k < len(table) else 0.0
            out.loc[yr, f"{uid} MACRS Depreciation"] = -capex * pct / 100.0
        disregard_row = (CAPEX_ROW if start_taxing == self.start_year
                         else cy)
        if disregard_row not in out.index:
            # construction year outside the proforma's year range: the
            # capital cost stayed in the CAPEX Year row (see
            # _move_capex_to_construction_year), so disregard it there —
            # otherwise the CAPEX row would be taxed as a loss and
            # generate a phantom tax credit
            disregard_row = CAPEX_ROW
        if disregard_row in out.index:
            out.loc[disregard_row,
                    f"{uid} Disregard From Taxable Income"] = capex
        return out

    # ------------------------------------------------------------------
    def npv_report(self, proforma: pd.DataFrame) -> pd.DataFrame:
        rate = self.npv_discount_rate
        out = {}
        for colname in proforma.columns:
            if colname == "Yearly Net Value":
                continue
            vals = proforma[colname].to_numpy(dtype=float)
            out[colname] = npv_series(rate, vals)
        total = sum(out.values())
        out["Lifetime Present Value"] = total
        return pd.DataFrame(out, index=["NPV"])

    def payback_report(self, proforma: pd.DataFrame,
                       npv: Optional[pd.DataFrame] = None,
                       cost_benefit: Optional[pd.DataFrame] = None
                       ) -> pd.DataFrame:
        """Simple payback = capital cost / first-year operating net benefit;
        discounted payback from cumulative discounted operating net
        (reference CBA.py:479-523 + storagevet Financial.payback_report).
        Capital cost is summed from the Capital Cost columns wherever the
        proforma placed them (CAPEX Year row or construction-year row);
        Lifetime Net Present Value and Benefit-Cost Ratio restate the
        npv/cost-benefit report totals exactly as the reference merges
        ``self.npv['Lifetime Present Value']`` and
        ``benefit_cost_ratio(self.cost_benefit)``."""
        cap_cols = [c for c in proforma.columns
                    if c.endswith(" Capital Cost")]
        capex = (-float(proforma[cap_cols].to_numpy(dtype=float).sum())
                 if cap_cols else 0.0)
        years = [y for y in proforma.index if y != CAPEX_ROW]
        op = proforma.loc[years].drop(
            columns=cap_cols + ["Yearly Net Value"], errors="ignore")
        net = op.sum(axis=1).to_numpy(dtype=float)
        first = net[0] if len(net) else 0.0
        payback = capex / first if first > 0 else float("nan")
        rate = self.npv_discount_rate
        disc = np.array([v / (1 + rate) ** (k + 1) for k, v in enumerate(net)])
        cum = np.cumsum(disc)
        dpb = float("nan")
        for k, c in enumerate(cum):
            if c >= capex:
                over = c - capex
                dpb = (k + 1) - over / disc[k] if disc[k] else (k + 1)
                break
        rate_irr = irr(proforma["Yearly Net Value"].to_numpy(dtype=float))
        if npv is None:
            npv = self.npv_report(proforma)
        npv_total = float(npv["Lifetime Present Value"].iloc[0])
        cb = (cost_benefit if cost_benefit is not None
              else self.cost_benefit_report(proforma))
        pv_cost = float(cb.loc["Lifetime Present Value", "Cost ($)"])
        pv_ben = float(cb.loc["Lifetime Present Value", "Benefit ($)"])
        bcr = pv_ben / pv_cost if pv_cost else float("nan")
        return pd.DataFrame({
            "Unit": ["Years", "$", "-"],
            "Payback Period": [payback, None, None],
            "Discounted Payback Period": [dpb, None, None],
            "Lifetime Net Present Value": [None, npv_total, None],
            "Internal Rate of Return": [None, None, rate_irr],
            "Benefit-Cost Ratio": [None, None, bcr],
        })

    def cost_benefit_report(self, proforma: pd.DataFrame) -> pd.DataFrame:
        rate = self.npv_discount_rate
        rows = {}
        tot_cost = tot_ben = 0.0
        for colname in proforma.columns:
            if colname == "Yearly Net Value":
                continue
            pv = npv_series(rate, proforma[colname].to_numpy(dtype=float))
            cost, ben = (-pv, 0.0) if pv < 0 else (0.0, pv)
            rows[colname] = {"Cost ($)": cost, "Benefit ($)": ben}
            tot_cost += cost
            tot_ben += ben
        out = pd.DataFrame(rows).T
        top = pd.DataFrame(
            {"Cost ($)": [tot_cost], "Benefit ($)": [tot_ben]},
            index=["Lifetime Present Value"])
        return pd.concat([top, out])
