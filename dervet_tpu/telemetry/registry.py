"""Time-series metrics registry with a Prometheus text exposition.

The stack already computes every number an autoscaler or an operator
needs — queue depth, drain rate, per-replica inflight, breaker states,
iters per window, certification verdicts, warm-grade mix, steal counts —
but each lives in a point-in-time ``metrics()`` dict or a per-run
artifact.  This registry makes them survive as signals:

* **Counters / gauges / histograms**, thread-safe, created on demand by
  name + label set.  Histograms use ONE fixed log-bucket layout
  (:data:`HIST_BOUNDS`, factor-2 buckets spanning 1e-4..~1.3e4) so
  percentile estimates are **mergeable across replicas** by adding
  bucket counts — the fleet ``status`` CLI merges N replicas' request-
  latency histograms into one fleet p50/p99 without ever seeing a raw
  sample.
* **Bounded ring-buffer time series** — :meth:`MetricsRegistry.sample`
  snapshots every gauge/counter into a per-metric ``deque`` (the
  heartbeat cadence), so "queue depth over the last minute" is a real
  series, not a single number.  Bounded: a service that never dies must
  not grow history forever.
* **Prometheus text exposition** — :meth:`to_prometheus` renders the
  standard text format; the serve loop writes it atomically next to the
  heartbeat (``telemetry.prom``) and the router SCRAPES replica files to
  route on *published* load (the ROADMAP-3 capacity-signal down
  payment).  :func:`parse_prometheus` is the matching reader.
* **Optional localhost HTTP endpoint** — :meth:`serve_http` exposes
  ``/metrics`` on 127.0.0.1 for an ad-hoc scrape; file exposition stays
  the primary transport (the fleet is same-host/same-filesystem today).

Stdlib-only, like ``telemetry.trace``.  The process-default registry
(:func:`get_registry`) is what the serving stack populates; bench legs
snapshot it per leg.
"""
from __future__ import annotations

import bisect
import math
import re
import threading
import time
from collections import deque
from pathlib import Path
from typing import Dict, List, Optional, Tuple

# one fixed log-bucket layout for every histogram: factor-2 buckets from
# 100 µs to ~13.1 ks (28 finite bounds + +Inf).  Fixed so merges across
# replicas/processes are exact bucket-count adds; wide enough for both
# latencies (seconds) and iteration counts (hundreds..thousands).
HIST_BOUNDS: Tuple[float, ...] = tuple(1e-4 * 2 ** i for i in range(28))

SERIES_CAP = 512            # ring-buffer samples kept per metric


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _esc_label(v) -> str:
    # Prometheus text-format label escaping: backslash, quote, newline.
    # Label values come from caller-chosen names (replica/spool/breaker
    # names) — an unescaped quote would render an exposition our own
    # parser rejects.
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    """Monotone counter."""

    __slots__ = ("name", "labels", "_lock", "value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount


class Gauge:
    """Set-to-current-value gauge."""

    __slots__ = ("name", "labels", "_lock", "value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Histogram:
    """Fixed log-bucket histogram (cumulative-bucket exposition).

    ``buckets[i]`` counts observations <= ``HIST_BOUNDS[i]`` (NON-
    cumulative internally; the exposition cumulates).  Identical bounds
    everywhere make :func:`merge_histograms` an exact elementwise add."""

    __slots__ = ("name", "labels", "_lock", "buckets", "overflow",
                 "count", "sum")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self.buckets = [0] * len(HIST_BOUNDS)
        self.overflow = 0           # > last finite bound (the +Inf bucket)
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        self.observe_many((value,))

    def observe_many(self, values) -> None:
        idxs = []
        total = 0.0
        n = 0
        for v in values:
            v = float(v)
            if v != v:          # NaN: not observable
                continue
            idxs.append(bisect.bisect_left(HIST_BOUNDS, v))
            total += v
            n += 1
        if not n:
            return
        with self._lock:
            for i in idxs:
                if i >= len(HIST_BOUNDS):
                    self.overflow += 1
                else:
                    self.buckets[i] += 1
            self.count += n
            self.sum += total

    def snapshot(self) -> Dict:
        with self._lock:
            return {"count": self.count, "sum": round(self.sum, 6),
                    "buckets": list(self.buckets),
                    "overflow": self.overflow}

    def quantile(self, q: float) -> Optional[float]:
        return quantile_from_buckets(self.snapshot(), q)


def quantile_from_buckets(snap: Dict, q: float) -> Optional[float]:
    """Quantile estimate from a histogram snapshot (log-interpolated
    within the landing bucket); None when empty.  Works on merged
    snapshots too — that is the point of the fixed layout."""
    count = int(snap.get("count") or 0)
    if count <= 0:
        return None
    rank = q * count
    seen = 0.0
    buckets = snap["buckets"]
    for i, c in enumerate(buckets):
        if c <= 0:
            continue
        if seen + c >= rank:
            hi = HIST_BOUNDS[i]
            lo = HIST_BOUNDS[i - 1] if i else hi / 2.0
            frac = (rank - seen) / c
            # log interpolation matches the bucket geometry
            return float(lo * (hi / lo) ** max(0.0, min(1.0, frac)))
        seen += c
    return float(HIST_BOUNDS[-1])


def merge_histograms(snaps: List[Dict]) -> Dict:
    """Exact merge of same-layout histogram snapshots (bucket-count
    adds) — the fleet-wide percentile surface."""
    out = {"count": 0, "sum": 0.0,
           "buckets": [0] * len(HIST_BOUNDS), "overflow": 0}
    for s in snaps:
        if not s:
            continue
        b = s.get("buckets") or []
        if len(b) != len(HIST_BOUNDS):
            raise ValueError(
                f"histogram layout mismatch: {len(b)} buckets != "
                f"{len(HIST_BOUNDS)} — merge requires the fixed layout")
        for i, c in enumerate(b):
            out["buckets"][i] += int(c)
        out["count"] += int(s.get("count") or 0)
        out["sum"] += float(s.get("sum") or 0.0)
        out["overflow"] += int(s.get("overflow") or 0)
    return out


class MetricsRegistry:
    """Name+labels -> metric, with snapshot / series / exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple], object] = {}
        self._series: Dict[str, deque] = {}
        self._http = None

    # -- construction ---------------------------------------------------
    def _get(self, cls, name: str, labels: Optional[Dict]) -> object:
        labels = {str(k): str(v) for k, v in (labels or {}).items()}
        key = (str(name), tuple(sorted(labels.items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = cls(str(name), labels)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r}{_label_str(labels)} already "
                    f"registered as {type(m).__name__}")
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    # -- time series ----------------------------------------------------
    def sample(self) -> None:
        """Append every counter/gauge's current value to its bounded
        ring-buffer series (call at the heartbeat cadence)."""
        now = round(time.time(), 3)
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, (Counter, Gauge)):
                key = f"{m.name}{_label_str(m.labels)}"
                with self._lock:
                    series = self._series.get(key)
                    if series is None:
                        series = self._series[key] = deque(
                            maxlen=SERIES_CAP)
                series.append((now, m.value))

    def series(self, name: str, **labels) -> List[Tuple[float, float]]:
        key = f"{name}{_label_str({str(k): str(v) for k, v in labels.items()})}"
        with self._lock:
            return list(self._series.get(key, ()))

    # -- snapshot / exposition ------------------------------------------
    def snapshot(self) -> Dict:
        """JSON-ready view: the shape ``benchlib.
        validate_telemetry_section`` checks and bench legs publish."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        histograms: Dict[str, Dict] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            key = f"{m.name}{_label_str(m.labels)}"
            if isinstance(m, Counter):
                counters[key] = m.value
            elif isinstance(m, Gauge):
                gauges[key] = m.value
            elif isinstance(m, Histogram):
                histograms[key] = m.snapshot()
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms,
                "series_cap": SERIES_CAP,
                "hist_bounds": len(HIST_BOUNDS),
                "t": round(time.time(), 3)}

    def to_prometheus(self) -> str:
        """Standard Prometheus text format (histograms as cumulative
        ``_bucket{le=}`` + ``_sum`` + ``_count``)."""
        with self._lock:
            metrics = list(self._metrics.values())
        by_name: Dict[str, List] = {}
        for m in metrics:
            by_name.setdefault(m.name, []).append(m)
        lines: List[str] = []
        for name in sorted(by_name):
            group = by_name[name]
            kind = ("counter" if isinstance(group[0], Counter)
                    else "gauge" if isinstance(group[0], Gauge)
                    else "histogram")
            lines.append(f"# TYPE {name} {kind}")
            for m in group:
                ls = _label_str(m.labels)
                if isinstance(m, (Counter, Gauge)):
                    lines.append(f"{name}{ls} {_fmt(m.value)}")
                    continue
                snap = m.snapshot()
                cum = 0
                for bound, c in zip(HIST_BOUNDS, snap["buckets"]):
                    cum += c
                    lab = dict(m.labels)
                    lab["le"] = _fmt(bound)
                    lines.append(f"{name}_bucket{_label_str(lab)} {cum}")
                lab = dict(m.labels)
                lab["le"] = "+Inf"
                lines.append(f"{name}_bucket{_label_str(lab)} "
                             f"{snap['count']}")
                lines.append(f"{name}_sum{ls} {_fmt(snap['sum'])}")
                lines.append(f"{name}_count{ls} {snap['count']}")
        lines.append(f"# EOF t={round(time.time(), 3)}")
        return "\n".join(lines) + "\n"

    def write_prom(self, path) -> Path:
        """Atomic exposition write (dot-tmp + fsync + replace) — the
        router's scrape never sees a torn file."""
        from .trace import _atomic_write_text
        path = Path(path)
        _atomic_write_text(path, self.to_prometheus())
        return path

    # -- optional localhost endpoint ------------------------------------
    def serve_http(self, port: int = 0, host: str = "127.0.0.1"):
        """Start a daemon-thread HTTP server answering ``/metrics`` with
        the text exposition; returns the bound port.  Localhost-only by
        default — this is an operator convenience, not a public API."""
        import http.server

        registry = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (stdlib naming)
                if self.path not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = registry.to_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silent: CI output hygiene
                pass

        server = http.server.ThreadingHTTPServer((host, int(port)),
                                                 Handler)
        thread = threading.Thread(target=server.serve_forever,
                                  name="dervet-telemetry-http",
                                  daemon=True)
        thread.start()
        self._http = server
        return server.server_address[1]

    def stop_http(self) -> None:
        if self._http is not None:
            self._http.shutdown()
            self._http = None

    def reset(self) -> None:
        """Test hook: drop every metric and series."""
        with self._lock:
            self._metrics.clear()
            self._series.clear()


# ---------------------------------------------------------------------------
# Exposition parsing (the router-side scrape + smoke validation)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unesc_label(v: str) -> str:
    return re.sub(r"\\(.)",
                  lambda m: "\n" if m.group(1) == "n" else m.group(1), v)


def parse_prometheus(text: str) -> Dict[str, List[Dict]]:
    """Parse a text exposition into ``name -> [{labels, value}, ...]``.
    Tolerant of comments/blank lines; raises ``ValueError`` on a
    malformed sample line (the smoke's parse gate)."""
    out: Dict[str, List[Dict]] = {}
    for ln, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line {ln}: "
                             f"{line!r}")
        raw = m.group("value")
        try:
            value = float("inf") if raw == "+Inf" else float(raw)
        except ValueError:
            raise ValueError(f"non-numeric sample value on line {ln}: "
                             f"{raw!r}")
        labels = {k: _unesc_label(v)
                  for k, v in _LABEL_RE.findall(m.group("labels") or "")}
        out.setdefault(m.group("name"), []).append(
            {"labels": labels, "value": value})
    return out


def sample_value(parsed: Dict, name: str,
                 labels: Optional[Dict] = None) -> Optional[float]:
    """First sample of ``name`` whose labels are a superset of
    ``labels`` (None when absent)."""
    want = {str(k): str(v) for k, v in (labels or {}).items()}
    for s in parsed.get(name, ()):
        if all(s["labels"].get(k) == v for k, v in want.items()):
            return s["value"]
    return None


def histogram_from_parsed(parsed: Dict, name: str,
                          labels: Optional[Dict] = None
                          ) -> Optional[Dict]:
    """Reconstruct a mergeable histogram snapshot from a parsed
    exposition (de-cumulating the ``_bucket`` series)."""
    want = {str(k): str(v) for k, v in (labels or {}).items()}
    rows = []
    for s in parsed.get(f"{name}_bucket", ()):
        ls = dict(s["labels"])
        le = ls.pop("le", None)
        if le is None or not all(ls.get(k) == v
                                 for k, v in want.items()):
            continue
        bound = math.inf if le == "+Inf" else float(le)
        rows.append((bound, s["value"]))
    if not rows:
        return None
    rows.sort()
    buckets = [0] * len(HIST_BOUNDS)
    prev = 0.0
    overflow = 0
    for bound, cum in rows:
        delta = int(cum - prev)
        prev = cum
        if bound == math.inf:
            overflow = delta
            continue
        # a foreign bucket layout (mixed-version fleet) must surface as
        # "no histogram", never be snapped onto HIST_BOUNDS — a remapped
        # reconstruction would pass merge_histograms' layout check and
        # silently corrupt fleet p50/p99
        i = bisect.bisect_left(HIST_BOUNDS, bound * (1 - 1e-9))
        if i >= len(HIST_BOUNDS) or \
                abs(HIST_BOUNDS[i] - bound) > 1e-9 * max(1.0, bound):
            return None
        buckets[i] = delta
    count = sample_value(parsed, f"{name}_count", want)
    total = sample_value(parsed, f"{name}_sum", want)
    return {"count": int(count or prev), "sum": float(total or 0.0),
            "buckets": buckets, "overflow": overflow}


# ---------------------------------------------------------------------------
# Process-default registry
# ---------------------------------------------------------------------------

_default = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default


def enabled() -> bool:
    """Registry population honors the same kill switch as tracing —
    ONE implementation, so the two planes can never drift apart."""
    from .trace import enabled as _trace_enabled
    return _trace_enabled()
