"""Telemetry plane: request tracing, metrics registry, ops surface.

Three stdlib-only modules (importable from the deepest solver code
without dragging jax/pandas in):

* :mod:`.trace` — span trees following one request across router →
  transport → admission → batch round → dispatch groups → certification
  (and the design/portfolio phases), exported per request as
  ``trace.<rid>.json`` plus a Chrome trace-event timeline.
* :mod:`.registry` — thread-safe counters/gauges/histograms (fixed
  log buckets, so percentiles merge exactly across replicas) with
  bounded ring-buffer time series and a Prometheus text exposition the
  serve loop publishes next to its heartbeat (``telemetry.prom``) and
  the fleet router scrapes for capacity-aware routing.
* :mod:`.ops` — the ``dervet-tpu status`` / ``dervet-tpu trace`` CLIs.

``DERVET_TPU_TELEMETRY=0`` is a true kill switch: spans become the
shared no-op instance, registry population is skipped, and no telemetry
file is ever written — result artifacts are byte-identical either way.
"""
from . import registry, trace  # noqa: F401
from .registry import get_registry  # noqa: F401
from .trace import NOOP, Span, enabled, span, start_span, trace_id_for  # noqa: F401

__all__ = ["trace", "registry", "get_registry", "enabled", "span",
           "start_span", "trace_id_for", "Span", "NOOP"]
