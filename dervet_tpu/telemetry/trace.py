"""Zero-dependency request tracing: a span tree that follows one request
across the whole serving stack.

The serving path is now router -> replica transport -> admission queue ->
continuous batcher -> (elastic) dispatch groups -> escalation rungs ->
certification, plus the design-screen and portfolio-dual-loop phases —
and until now no single record followed one request across those hops
(the solve ledger is per-round, ``run_health`` per-request-after-the-
fact, ``metrics()`` point-in-time).  A *trace* is that record: a tree of
**spans** (one per hop, monotonic-clock durations anchored to one wall
timestamp) sharing a ``trace_id``, with typed attributes (the solve
ledger entry IS the attribute payload of a dispatch-group span) and
point events (warm-start grades, breaker decisions, failover/hedge/
harvest, certification rejections).

Design constraints, in order:

* **Kill switch is a real kill switch** — ``DERVET_TPU_TELEMETRY=0``
  makes every span constructor return the singleton no-op span: no
  allocation beyond the enabled() check, no locks, no files, and the
  solve path is untouched either way (tracing only ever *observes*;
  bench gate: warm-serving p50 regression < 2% with telemetry ON).
* **Zero dependencies** — stdlib only, importable from the deepest ops
  code without dragging jax/pandas in.
* **Cross-process stitching** — the trace id is DERIVED from the request
  id (:func:`trace_id_for`), and trace context additionally rides the
  fleet transport payload, so the router process and every replica
  process agree on the id even across a SIGKILL failover; the ``trace``
  CLI stitches their exported ``trace.<rid>.json`` files into one tree.

Thread model: span creation/finish may happen on any thread (the
collector is lock-protected).  Ambient parenting (``with span(...)``)
is per-thread; code that crosses threads — the batcher handing a request
to pool workers, the elastic device workers — parents explicitly via the
request registry (:func:`register_request` / :func:`context_for_request`)
keyed by the request id that already rides :class:`MicrogridScenario`.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Dict, List, Optional

ENV = "DERVET_TPU_TELEMETRY"

# bounded collector: a service that never dies must not grow one span
# list per request forever (traces evict LRU once delivered/exported)
MAX_TRACES = 512
MAX_SPANS_PER_TRACE = 8192
MAX_REQUEST_CONTEXTS = 4096


def enabled() -> bool:
    """Telemetry kill switch (``DERVET_TPU_TELEMETRY=0`` off).  Read per
    call so tests (and a live operator) can flip it without restarting;
    a dict lookup + compare is the entire disabled-path cost."""
    return os.environ.get(ENV, "1").strip().lower() \
        not in ("0", "false", "off")


def trace_id_for(rid) -> str:
    """Deterministic trace id for a request id: every process that sees
    ``rid`` (router, replica, post-crash recovery, the ``trace`` CLI)
    derives the same id, so stitching never depends on in-band context
    having survived."""
    return hashlib.sha256(f"dervet-trace:{rid}".encode()).hexdigest()[:32]


_span_seq = itertools.count(1)


def _new_span_id() -> str:
    # unique across processes: pid + in-process counter (no randomness —
    # dispatch determinism contracts forbid entropy on this path)
    return f"{os.getpid():08x}-{next(_span_seq):06x}"


class _NoopSpan:
    """The disabled-path span: every method is a no-op, every child is
    itself.  One shared instance, so the hot path allocates nothing."""

    __slots__ = ()
    recording = False
    trace_id = None
    span_id = None

    def set_attr(self, key, value):
        return self

    def set_attrs(self, attrs):
        return self

    def event(self, name, **attrs):
        return self

    def child(self, name, **attrs):
        return self

    def end(self, error=None):
        return self

    def ctx(self):
        return None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def __bool__(self):
        # `if span:` reads as "is telemetry recording this?"
        return False


NOOP = _NoopSpan()


class Span:
    """One timed hop.  Create via :func:`start_span` / :func:`span` (the
    constructor itself never checks the kill switch)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t_start",
                 "_t0_mono", "duration_s", "attrs", "events", "status",
                 "_ambient", "_ended")
    recording = True

    def __init__(self, name: str, trace_id: str,
                 parent_id: Optional[str] = None,
                 t_start: Optional[float] = None,
                 duration_s: Optional[float] = None,
                 attrs: Optional[Dict] = None):
        self.name = str(name)
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        # wall anchor + monotonic duration: the exported record is wall-
        # timestamped (stitchable across processes) but durations never
        # go negative under clock steps
        self.t_start = time.time() if t_start is None else float(t_start)
        self._t0_mono = time.monotonic()
        self.duration_s = duration_s
        self.attrs: Dict = dict(attrs) if attrs else {}
        self.events: List[Dict] = []
        self.status = "ok"
        self._ambient = False
        self._ended = duration_s is not None
        if self._ended:
            COLLECTOR.add(self)

    # -- recording ------------------------------------------------------
    def set_attr(self, key, value) -> "Span":
        self.attrs[str(key)] = value
        return self

    def set_attrs(self, attrs: Dict) -> "Span":
        for k, v in attrs.items():
            self.attrs[str(k)] = v
        return self

    def event(self, name: str, **attrs) -> "Span":
        ev = {"name": str(name), "t": round(time.time(), 6)}
        if attrs:
            ev["attrs"] = attrs
        self.events.append(ev)
        return self

    def child(self, name: str, **attrs) -> "Span":
        if not enabled():
            return NOOP
        return Span(name, self.trace_id, parent_id=self.span_id,
                    attrs=attrs or None)

    def ctx(self) -> Dict:
        """The propagation context: what rides a transport payload."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def end(self, error=None) -> "Span":
        if self._ended:
            return self
        self._ended = True
        self.duration_s = time.monotonic() - self._t0_mono
        if error is not None:
            self.status = "error"
            self.attrs.setdefault("error", f"{type(error).__name__}: "
                                           f"{error}"
                                  if isinstance(error, BaseException)
                                  else str(error))
        COLLECTOR.add(self)
        return self

    # -- ambient context manager ---------------------------------------
    def __enter__(self) -> "Span":
        _tls_stack().append(self)
        self._ambient = True
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._ambient:
            stack = _tls_stack()
            if stack and stack[-1] is self:
                stack.pop()
            self._ambient = False
        self.end(error=exc)
        return False

    def __bool__(self):
        return True

    def to_dict(self) -> Dict:
        out = {"trace_id": self.trace_id, "span_id": self.span_id,
               "parent_id": self.parent_id, "name": self.name,
               "t_start": round(self.t_start, 6),
               "duration_s": (round(self.duration_s, 6)
                              if self.duration_s is not None else None),
               "status": self.status}
        if self.attrs:
            out["attrs"] = self.attrs
        if self.events:
            out["events"] = self.events
        return out


# ---------------------------------------------------------------------------
# Ambient (per-thread) parenting
# ---------------------------------------------------------------------------

_tls = threading.local()


def _tls_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current() -> Optional[Span]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


# ---------------------------------------------------------------------------
# Collector: finished spans per trace + the request-context registry
# ---------------------------------------------------------------------------

class _Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, List[Dict]]" = OrderedDict()
        # rid -> Span: where deep code (resolve_group on a worker
        # thread, the portfolio loop) finds the parent for request-
        # scoped spans without any plumbing through the solve stack
        self._requests: "OrderedDict[str, Span]" = OrderedDict()
        self.dropped = 0

    def add(self, span: Span) -> None:
        rec = span.to_dict()
        with self._lock:
            spans = self._traces.get(span.trace_id)
            if spans is None:
                spans = self._traces[span.trace_id] = []
                while len(self._traces) > MAX_TRACES:
                    self._traces.popitem(last=False)
            self._traces.move_to_end(span.trace_id)
            if len(spans) >= MAX_SPANS_PER_TRACE:
                self.dropped += 1
                return
            spans.append(rec)

    def spans(self, trace_id: str) -> List[Dict]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def pop(self, trace_id: str) -> List[Dict]:
        with self._lock:
            return self._traces.pop(trace_id, [])

    # -- request registry ----------------------------------------------
    def register_request(self, rid, span: Span) -> None:
        with self._lock:
            self._requests[str(rid)] = span
            self._requests.move_to_end(str(rid))
            while len(self._requests) > MAX_REQUEST_CONTEXTS:
                self._requests.popitem(last=False)

    def context_for_request(self, rid) -> Optional[Span]:
        with self._lock:
            return self._requests.get(str(rid))

    def release_request(self, rid) -> None:
        with self._lock:
            self._requests.pop(str(rid), None)

    def reset(self) -> None:
        """Test hook: drop every collected trace and registration."""
        with self._lock:
            self._traces.clear()
            self._requests.clear()
            self.dropped = 0


COLLECTOR = _Collector()

register_request = COLLECTOR.register_request
context_for_request = COLLECTOR.context_for_request
release_request = COLLECTOR.release_request


def trace_id_of(rid) -> Optional[str]:
    """The trace id a live request is recording under (None when
    telemetry is off or the request was never registered)."""
    span = COLLECTOR.context_for_request(rid)
    return span.trace_id if span is not None else None


# ---------------------------------------------------------------------------
# Span construction
# ---------------------------------------------------------------------------

def start_span(name: str, *, parent=None, trace_id: Optional[str] = None,
               rid=None, t_start: Optional[float] = None,
               duration_s: Optional[float] = None,
               attrs: Optional[Dict] = None):
    """Start one span (the caller ends it).  Returns :data:`NOOP` when
    telemetry is off.

    Parent resolution, most explicit first: ``parent`` (a :class:`Span`
    or a ``{"trace_id", "span_id"}`` context dict, e.g. off a transport
    payload), then the span registered for ``rid``, then the calling
    thread's ambient span, else a root (``trace_id`` defaults to
    :func:`trace_id_for` of ``rid`` when given, else a fresh id)."""
    if not enabled():
        return NOOP
    parent_id = None
    if parent is None and rid is not None:
        parent = COLLECTOR.context_for_request(rid)
    if parent is None:
        parent = current()
    if isinstance(parent, Span):
        trace_id = trace_id or parent.trace_id
        parent_id = parent.span_id
    elif isinstance(parent, dict) and parent.get("trace_id"):
        trace_id = trace_id or str(parent["trace_id"])
        parent_id = (str(parent["span_id"])
                     if parent.get("span_id") else None)
    if trace_id is None:
        trace_id = trace_id_for(rid) if rid is not None \
            else _new_span_id()
    return Span(name, trace_id, parent_id=parent_id, t_start=t_start,
                duration_s=duration_s, attrs=attrs)


def span(name: str, **attrs):
    """Ambient-parented span for ``with`` blocks."""
    return start_span(name, attrs=attrs or None)


# ---------------------------------------------------------------------------
# Export / tree assembly
# ---------------------------------------------------------------------------

def _atomic_write_text(path, text: str) -> None:
    # the codebase's ONE atomic-write path (dot-tmp + fsync + replace);
    # utils.supervisor is stdlib-only, so this module stays light
    from ..utils.supervisor import atomic_write
    atomic_write(path, text)


def export_request_trace(rid, out_dir, trace_id: Optional[str] = None,
                         pop: bool = True, chrome: bool = False,
                         merge: bool = False) -> Optional[Path]:
    """Write ``trace.<rid>.json`` for one request into ``out_dir``
    (created if needed).  Returns the path, or None when telemetry is
    off / no spans were recorded.  ``pop`` drops the trace from the
    collector after export (the serving loop's delivery path — a
    long-lived process must not keep delivered traces pinned);
    ``chrome`` also writes the ``trace.<rid>.chrome.json`` timeline
    from the same in-memory spans.  ``merge`` unions with an existing
    export instead of clobbering it — the late-answer path: a span that
    ended after the request's trace was already exported (a hedge or
    failover loser) re-enters the collector as an orphan entry, and the
    merged re-export both records its timing and frees the slot."""
    if not enabled():
        return None
    tid = trace_id or trace_id_of(rid) or trace_id_for(rid)
    spans = COLLECTOR.pop(tid) if pop else COLLECTOR.spans(tid)
    if not spans:
        return None
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    path = out_dir / f"trace.{rid}.json"
    if merge and path.exists():
        try:
            prev = json.loads(path.read_text()).get("spans", [])
        except (OSError, ValueError):
            prev = []
        if prev:
            spans = merge_spans([prev, spans])
    _atomic_write_text(path, json.dumps(
        {"request_id": str(rid), "trace_id": tid, "spans": spans},
        indent=1, default=str))
    if chrome:
        export_chrome_trace(spans, out_dir / f"trace.{rid}.chrome.json",
                            rid)
    return path


def merge_spans(span_lists) -> List[Dict]:
    """Union span records from several exports (router + replicas + a
    failover inheritor), deduped by span id (a harvested request's trace
    may be exported twice)."""
    seen: Dict[str, Dict] = {}
    for spans in span_lists:
        for s in spans or ():
            sid = s.get("span_id")
            if sid and sid not in seen:
                seen[sid] = s
    return sorted(seen.values(), key=lambda s: s.get("t_start") or 0.0)


def build_tree(spans: List[Dict]):
    """Assemble ``(root, children)`` from span records.  Exactly-one-
    root is the stitched-trace contract: when several parentless spans
    exist (processes that never saw each other's context), the earliest
    becomes the root and the rest are REPARENTED under it with a
    ``stitched`` mark — the tree stays single-rooted, and the surgery is
    visible rather than silent.  Returns ``(None, {})`` on empty."""
    if not spans:
        return None, {}
    by_id = {s["span_id"]: s for s in spans}
    roots = [s for s in spans
             if not s.get("parent_id") or s["parent_id"] not in by_id]
    roots.sort(key=lambda s: (s.get("t_start") or 0.0, s["span_id"]))
    root = roots[0]
    for orphan in roots[1:]:
        if orphan.get("parent_id") not in by_id:
            orphan = dict(orphan)
            by_id[orphan["span_id"]] = orphan
            orphan.setdefault("attrs", {})
            if orphan["attrs"].get("stitched") is None:
                orphan["attrs"]["stitched"] = (
                    "reparented: original parent "
                    f"{orphan.get('parent_id')!r} not in trace"
                    if orphan.get("parent_id") else "reparented root")
            orphan["parent_id"] = root["span_id"]
    children: Dict[str, List[Dict]] = {}
    for s in by_id.values():
        if s["span_id"] != root["span_id"]:
            children.setdefault(s["parent_id"], []).append(s)
    for kids in children.values():
        kids.sort(key=lambda s: (s.get("t_start") or 0.0, s["span_id"]))
    return root, children


def validate_trace(spans: List[Dict]) -> Dict:
    """Structural validation for the smoke/CI gates: non-empty, unique
    span ids, a SINGLE root (before any stitching surgery), every other
    span's parent present, no negative durations.  Raises ``ValueError``
    naming the violation; returns ``{"root": ..., "n_spans": ...}``."""
    if not spans:
        raise ValueError("trace has no spans")
    ids = [s.get("span_id") for s in spans]
    if len(set(ids)) != len(ids):
        raise ValueError("trace has duplicate span ids")
    by_id = set(ids)
    roots = [s for s in spans
             if not s.get("parent_id") or s["parent_id"] not in by_id]
    if len(roots) != 1:
        raise ValueError(
            f"trace must have exactly one root, found {len(roots)}: "
            f"{[s.get('name') for s in roots]}")
    tids = {s.get("trace_id") for s in spans}
    if len(tids) != 1:
        raise ValueError(f"trace mixes trace ids: {sorted(tids)}")
    for s in spans:
        d = s.get("duration_s")
        if d is not None and d < 0:
            raise ValueError(f"span {s.get('name')!r} has negative "
                             f"duration {d}")
    return {"root": roots[0], "n_spans": len(spans)}


def slowest_path(spans: List[Dict]) -> List[str]:
    """Span ids of the critical path: from the root, repeatedly descend
    into the longest-duration child — the chain the ``trace`` CLI
    highlights."""
    root, children = build_tree(spans)
    if root is None:
        return []
    path = [root["span_id"]]
    node = root
    while True:
        kids = children.get(node["span_id"])
        if not kids:
            return path
        node = max(kids, key=lambda s: s.get("duration_s") or 0.0)
        path.append(node["span_id"])


# ---------------------------------------------------------------------------
# Chrome trace-event export (chrome://tracing / Perfetto)
# ---------------------------------------------------------------------------

def to_chrome(spans: List[Dict], request_id: Optional[str] = None) -> Dict:
    """Chrome trace-event JSON for one trace: complete ("X") events on
    named lanes.  Dispatch-group spans carry the elastic scheduler's
    ``device`` attribute, so each device gets its own lane — the
    per-device occupancy timeline the serving benches gate on, loadable
    without any custom tooling."""
    lanes: Dict[str, int] = {}
    events: List[Dict] = []

    def lane(s: Dict) -> int:
        attrs = s.get("attrs") or {}
        if attrs.get("device") is not None:
            name = f"device:{attrs['device']}"
        elif attrs.get("replica"):
            name = f"replica:{attrs['replica']}"
        else:
            name = "request"
        if name not in lanes:
            lanes[name] = len(lanes) + 1
            events.append({"ph": "M", "pid": 1, "tid": lanes[name],
                           "name": "thread_name",
                           "args": {"name": name}})
        return lanes[name]

    for s in spans:
        tid = lane(s)
        ts = (s.get("t_start") or 0.0) * 1e6
        events.append({
            "ph": "X", "pid": 1, "tid": tid, "name": s.get("name"),
            "cat": "dervet", "ts": ts,
            "dur": max(1.0, (s.get("duration_s") or 0.0) * 1e6),
            "args": {**(s.get("attrs") or {}),
                     "span_id": s.get("span_id"),
                     "status": s.get("status")},
        })
        for ev in s.get("events") or ():
            events.append({"ph": "i", "pid": 1, "tid": tid, "s": "t",
                           "name": ev.get("name"), "cat": "dervet",
                           "ts": (ev.get("t") or 0.0) * 1e6,
                           "args": ev.get("attrs") or {}})
    meta = {"request_id": request_id} if request_id else {}
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": meta}


def export_chrome_trace(spans: List[Dict], path,
                        request_id: Optional[str] = None) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    # default=str mirrors the span-export serialization, so in-memory
    # spans and re-loaded trace.json spans render identically
    _atomic_write_text(path, json.dumps(to_chrome(spans, request_id),
                                        default=str))
    return path
