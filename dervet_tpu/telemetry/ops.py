"""Operator surface: ``dervet-tpu status`` and ``dervet-tpu trace``.

``status SPOOL_DIR [SPOOL_DIR...]`` renders live fleet health from each
replica spool's published artifacts — ``heartbeat.json`` (liveness,
queue depth, request counters) and ``telemetry.prom`` (the metrics
registry exposition the serve loop rewrites at the heartbeat cadence) —
plus the router's ``fleet_telemetry.prom``/``fleet_metrics.json`` when a
fleet directory is given.  Per-replica request-latency histograms share
one fixed bucket layout, so the fleet-wide p50/p99 and SLO attainment
are EXACT bucket merges, not approximations over approximations.

``trace RID DIR [DIR...]`` stitches one request's exported span trees
(``trace.<rid>.json`` from the router and every replica that touched the
request — a failover leaves two) into a single tree and pretty-prints it
with the slowest root-to-leaf path highlighted; ``--chrome OUT.json``
additionally writes a Chrome trace-event timeline (chrome://tracing /
Perfetto) with per-device occupancy lanes.  When no trace file exists
(pre-crash, or telemetry was off) the spool journals are consulted:
their records carry wall+mono timestamps and the active trace id
(PR 14), so a timeline of journaled events is still reconstructable.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional

from . import registry as _registry
from . import trace as _trace

PROM_FILE = "telemetry.prom"
FLEET_PROM_FILE = "fleet_telemetry.prom"

# metric names shared between the publishers (server/router) and this
# reader — one place, so the surface cannot silently fork
M_QUEUE_DEPTH = "dervet_queue_depth"
M_DRAIN_RATE = "dervet_drain_rate_rps"
M_PENDING = "dervet_pending_requests"
M_REQ_LATENCY = "dervet_request_latency_seconds"
M_REQUESTS = "dervet_requests_total"
M_WINDOWS = "dervet_windows_total"
M_WARM = "dervet_warm_windows_total"
M_CERT = "dervet_certifications_total"
M_BREAKER_OPEN = "dervet_breaker_open"
M_STEALS = "dervet_elastic_steals_total"


def _read_json(path: Path) -> Optional[Dict]:
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


def _read_prom(path: Path) -> Optional[Dict]:
    # ValueError too: a corrupt/foreign exposition reads as
    # "unpublished", it must never crash the status CLI for the fleet
    try:
        return _registry.parse_prometheus(path.read_text())
    except (OSError, ValueError):
        return None


def discover_spools(dirs: List[Path]) -> List[Path]:
    """Replica spools among/under the given dirs: a dir with a
    ``heartbeat.json`` or ``telemetry.prom`` is a spool; otherwise its
    immediate children are scanned (a fleet root holding ``replica*/``
    spools)."""
    out: List[Path] = []
    for d in dirs:
        d = Path(d)
        if (d / "heartbeat.json").exists() or (d / PROM_FILE).exists():
            out.append(d)
            continue
        for child in sorted(p for p in d.iterdir() if p.is_dir()) \
                if d.is_dir() else ():
            if (child / "heartbeat.json").exists() or \
                    (child / PROM_FILE).exists():
                out.append(child)
    return out


def replica_status(spool: Path) -> Dict:
    """One replica's health/load view from its published artifacts."""
    hb = _read_json(spool / "heartbeat.json")
    parsed = _read_prom(spool / PROM_FILE)
    now = time.time()
    out: Dict = {
        "spool": str(spool),
        "name": (hb or {}).get("name") or spool.name,
        "heartbeat_age_s": (round(now - float(hb["t"]), 2)
                            if hb and "t" in hb else None),
        "draining": (hb or {}).get("draining"),
        "pending": (hb or {}).get("pending"),
        "queue_depth": (hb or {}).get("queue_depth"),
        "completed": (hb or {}).get("completed"),
        "failed": (hb or {}).get("failed"),
        "published": parsed is not None,
    }
    age = out["heartbeat_age_s"]
    out["state"] = ("unknown" if age is None
                    else "stale" if age > 10.0 else "up")
    if parsed:
        sv = _registry.sample_value
        qd = sv(parsed, M_QUEUE_DEPTH)
        if qd is not None:
            out["queue_depth"] = qd
        out["drain_rate_rps"] = sv(parsed, M_DRAIN_RATE)
        out["breakers_open"] = int(sum(
            s["value"] for s in parsed.get(M_BREAKER_OPEN, ())))
        out["windows"] = sv(parsed, M_WINDOWS)
        warm = sum(s["value"] for s in parsed.get(M_WARM, ())
                   if s["labels"].get("grade") not in (None, "cold"))
        cold = sv(parsed, M_WARM, {"grade": "cold"}) or 0.0
        out["warm_hit_rate"] = (round(warm / (warm + cold), 4)
                                if warm + cold else None)
        cert_ok = sv(parsed, M_CERT, {"verdict": "accepted"}) or 0.0
        cert_rej = sv(parsed, M_CERT, {"verdict": "rejected"}) or 0.0
        out["cert_accept_rate"] = (round(cert_ok / (cert_ok + cert_rej), 4)
                                   if cert_ok + cert_rej else None)
        out["latency_hist"] = _registry.histogram_from_parsed(
            parsed, M_REQ_LATENCY)
        if out["latency_hist"]:
            out["latency_p50_s"] = _registry.quantile_from_buckets(
                out["latency_hist"], 0.5)
            out["latency_p99_s"] = _registry.quantile_from_buckets(
                out["latency_hist"], 0.99)
    return out


def slo_attainment(hist: Optional[Dict], slo_s: float) -> Optional[float]:
    """Fraction of observed request latencies at or under ``slo_s``,
    from the merged histogram.  Only buckets whose UPPER bound is
    <= ``slo_s`` count as attained — bucket i holds observations in
    ``(HIST_BOUNDS[i-1], HIST_BOUNDS[i]]``, so including the bucket
    that straddles ``slo_s`` would credit latencies up to a factor 2
    past the target (conservative under-count, never over)."""
    if not hist or not hist.get("count"):
        return None
    import bisect
    cut = bisect.bisect_right(_registry.HIST_BOUNDS, float(slo_s))
    under = sum(hist["buckets"][:cut])
    return round(min(1.0, under / hist["count"]), 4)


def fleet_status(dirs: List[Path], slo_s: float = 60.0) -> Dict:
    spools = discover_spools(dirs)
    replicas = [replica_status(s) for s in spools]
    merged = _registry.merge_histograms(
        [r.get("latency_hist") or {} for r in replicas])
    fleet: Dict = {
        "replicas": replicas,
        "n_replicas": len(replicas),
        "n_up": sum(1 for r in replicas if r["state"] == "up"),
        "queue_depth_total": sum(int(r.get("queue_depth") or 0)
                                 for r in replicas),
        "completed_total": sum(int(r.get("completed") or 0)
                               for r in replicas),
        "failed_total": sum(int(r.get("failed") or 0) for r in replicas),
        "latency_p50_s": _registry.quantile_from_buckets(merged, 0.5),
        "latency_p99_s": _registry.quantile_from_buckets(merged, 0.99),
        "slo_s": slo_s,
        "slo_attainment": slo_attainment(merged, slo_s),
    }
    # router-side view when one of the dirs is a fleet directory
    for d in dirs:
        d = Path(d)
        fm = _read_json(d / "fleet_metrics.json")
        parsed = _read_prom(d / FLEET_PROM_FILE)
        if fm or parsed:
            fleet["router"] = {
                "dir": str(d),
                "routing": (fm or {}).get("routing"),
                "scraped": {k: [dict(s) for s in v]
                            for k, v in (parsed or {}).items()
                            if k.startswith("dervet_fleet_")} or None,
            }
            break
    # lifecycle supervisor view (supervisor_state.json, published by
    # service/lifecycle.py): per-replica restart counts, crash-loop /
    # quarantine state, and last restart reason — merged into the
    # replica rows by name so the table shows WHY a replica vanished,
    # not just that its heartbeat aged out
    sup = None
    for d in dirs:
        sup = _read_json(Path(d) / "supervisor_state.json")
        if sup is not None:
            break
    if sup is not None:
        fleet["supervisor"] = {k: v for k, v in sup.items()
                               if k != "replicas"}
        by_name = sup.get("replicas") or {}
        for r in replicas:
            s = by_name.get(r["name"])
            if s is not None:
                r["restarts"] = s.get("restarts")
                r["lifecycle"] = s.get("state")
                r["last_restart_reason"] = s.get("last_restart_reason")
    return fleet


def _fmt_cell(v, unit: str = "") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3g}{unit}"
    return f"{v}{unit}"


def render_status(fleet: Dict) -> str:
    # supervisor columns only when a supervisor_state.json was found —
    # an unsupervised fleet's table stays byte-identical to before
    supervised = fleet.get("supervisor") is not None
    cols = ("name", "state", "age", "queue", "drain/s", "pending",
            "done", "failed", "warm%", "cert%", "p50", "p99", "brk")
    if supervised:
        cols = cols + ("restarts", "life", "last restart")
    rows = []
    for r in fleet["replicas"]:
        row = (
            r["name"], r["state"], _fmt_cell(r.get("heartbeat_age_s"), "s"),
            _fmt_cell(r.get("queue_depth")),
            _fmt_cell(r.get("drain_rate_rps")),
            _fmt_cell(r.get("pending")), _fmt_cell(r.get("completed")),
            _fmt_cell(r.get("failed")),
            _fmt_cell(None if r.get("warm_hit_rate") is None
                      else round(100 * r["warm_hit_rate"], 1)),
            _fmt_cell(None if r.get("cert_accept_rate") is None
                      else round(100 * r["cert_accept_rate"], 1)),
            _fmt_cell(r.get("latency_p50_s"), "s"),
            _fmt_cell(r.get("latency_p99_s"), "s"),
            _fmt_cell(r.get("breakers_open")),
        )
        if supervised:
            reason = r.get("last_restart_reason")
            row = row + (
                _fmt_cell(r.get("restarts")),
                _fmt_cell(r.get("lifecycle")),
                ("-" if not reason else
                 reason if len(reason) <= 40 else reason[:37] + "..."),
            )
        rows.append(row)
    widths = [max(len(str(c)), *(len(str(row[i])) for row in rows))
              if rows else len(str(c)) for i, c in enumerate(cols)]
    lines = [" ".join(str(c).ljust(widths[i])
                      for i, c in enumerate(cols))]
    lines.append(" ".join("-" * w for w in widths))
    for row in rows:
        lines.append(" ".join(str(v).ljust(widths[i])
                              for i, v in enumerate(row)))
    lines.append("")
    att = fleet.get("slo_attainment")
    lines.append(
        f"fleet: {fleet['n_up']}/{fleet['n_replicas']} up, "
        f"queue {fleet['queue_depth_total']}, "
        f"completed {fleet['completed_total']}, "
        f"failed {fleet['failed_total']}, merged latency p50/p99 "
        f"{_fmt_cell(fleet.get('latency_p50_s'), 's')}/"
        f"{_fmt_cell(fleet.get('latency_p99_s'), 's')}, "
        f"SLO({fleet['slo_s']:g}s) "
        f"{'-' if att is None else f'{100 * att:.1f}%'}")
    router = fleet.get("router")
    if router and router.get("routing"):
        rt = router["routing"]
        lines.append(
            f"router: submitted {rt.get('submitted')}, completed "
            f"{rt.get('completed')}, failovers {rt.get('failovers')}, "
            f"harvested {rt.get('harvested')}, hedged "
            f"{rt.get('hedged')}, affinity hit rate "
            f"{rt.get('affinity_hit_rate')}")
    sup = fleet.get("supervisor")
    if sup is not None:
        c = sup.get("counters") or {}
        lines.append(
            f"supervisor: restarts {c.get('restarts')}, quarantined "
            f"{c.get('quarantined')}, scale up/down "
            f"{c.get('scale_up')}/{c.get('scale_down')}, warm imports "
            f"{c.get('warm_imports')}, bounds "
            f"[{sup.get('min_replicas')}, {sup.get('max_replicas')}]")
    return "\n".join(lines)


def status_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dervet-tpu status",
        description="live fleet status from replica-published telemetry")
    parser.add_argument("dirs", nargs="+",
                        help="replica spool dir(s), a fleet root "
                             "containing them, and/or the router's "
                             "fleet dir")
    parser.add_argument("--slo-s", type=float, default=60.0,
                        help="latency bound for the SLO-attainment "
                             "column (default 60s)")
    parser.add_argument("--json", action="store_true",
                        help="emit the raw status dict instead of the "
                             "table")
    args = parser.parse_args(argv)
    fleet = fleet_status([Path(d) for d in args.dirs], slo_s=args.slo_s)
    if args.json:
        print(json.dumps(fleet, indent=2, default=str))
    else:
        print(render_status(fleet))
    return 0


# ---------------------------------------------------------------------------
# trace: stitch + pretty-print one request's span tree
# ---------------------------------------------------------------------------

def find_trace_files(rid: str, dirs: List[Path]) -> List[Path]:
    """Every ``trace.<rid>.json`` under the given dirs: direct, in a
    ``traces/`` subdir (router export), in ``results/<rid>/`` (replica
    export), or one directory level down (a fleet root)."""
    fname = f"trace.{rid}.json"
    hits: List[Path] = []
    for d in dirs:
        d = Path(d)
        candidates = [d / fname, d / "traces" / fname,
                      d / "results" / rid / fname]
        if d.is_dir():
            for child in sorted(p for p in d.iterdir() if p.is_dir()):
                candidates += [child / fname, child / "traces" / fname,
                               child / "results" / rid / fname]
        for c in candidates:
            if c.exists() and c not in hits:
                hits.append(c)
    return hits


def journal_spans(rid: str, dirs: List[Path]) -> List[Dict]:
    """Timeline reconstruction from spool/fleet journals when no trace
    export exists (pre-crash, or telemetry was off at the replica):
    every journal record for ``rid`` becomes a zero-duration span under
    a synthesized root, using the wall timestamps (and trace id) the
    journal records carry."""
    records: List[Dict] = []
    for d in dirs:
        d = Path(d)
        paths = list(d.glob("*journal.jsonl"))
        if d.is_dir():
            paths += list(d.glob("*/*journal.jsonl"))
        for p in paths:
            try:
                lines = p.read_text(encoding="utf-8").splitlines()
            except OSError:
                continue
            for line in lines:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if str(rec.get("rid")) == str(rid):
                    rec["_journal"] = str(p)
                    records.append(rec)
    if not records:
        return []
    records.sort(key=lambda r: r.get("t") or 0.0)
    tid = next((r["trace_id"] for r in records if r.get("trace_id")),
               _trace.trace_id_for(rid))
    t0 = records[0].get("t") or time.time()
    t1 = records[-1].get("t") or t0
    root = {"trace_id": tid, "span_id": f"journal-root-{rid}",
            "parent_id": None, "name": "journal_timeline",
            "t_start": t0, "duration_s": round(max(0.0, t1 - t0), 6),
            "status": "ok",
            "attrs": {"request_id": rid, "source": "journal replay"}}
    spans = [root]
    for i, rec in enumerate(records):
        spans.append({
            "trace_id": tid, "span_id": f"journal-{rid}-{i}",
            "parent_id": root["span_id"],
            "name": f"journal:{rec.get('event')}",
            "t_start": rec.get("t"), "duration_s": 0.0, "status": "ok",
            "attrs": {k: v for k, v in rec.items()
                      if k not in ("event", "t")},
        })
    return spans


def load_stitched_trace(rid: str, dirs: List[Path]) -> List[Dict]:
    """All span records for ``rid`` across the given dirs, merged and
    deduped; falls back to journal reconstruction when no export
    exists."""
    lists = []
    for path in find_trace_files(rid, dirs):
        doc = _read_json(path)
        if doc:
            lists.append(doc.get("spans") or [])
    spans = _trace.merge_spans(lists)
    if not spans:
        spans = journal_spans(rid, dirs)
    return spans


def render_trace(spans: List[Dict], highlight: bool = True) -> str:
    root, children = _trace.build_tree(spans)
    if root is None:
        return "(no spans)"
    hot = set(_trace.slowest_path(spans)) if highlight else set()
    lines: List[str] = []

    def fmt(s: Dict, depth: int) -> None:
        dur = s.get("duration_s")
        mark = "*" if s["span_id"] in hot else " "
        bits = [f"{mark} {'  ' * depth}{s.get('name')}"]
        bits.append(f"[{dur * 1e3:.1f}ms]" if dur is not None
                    else "[?]")
        if s.get("status") == "error":
            bits.append("ERROR")
        attrs = s.get("attrs") or {}
        for key in ("replica", "device", "rung", "fidelity", "variant",
                    "kernel", "verdict", "batch", "stitched"):
            if attrs.get(key) is not None:
                bits.append(f"{key}={attrs[key]}")
        evs = s.get("events") or ()
        if evs:
            bits.append("events=" + ",".join(e.get("name", "?")
                                             for e in evs[:8]))
        lines.append(" ".join(bits))
        for kid in children.get(s["span_id"], ()):
            fmt(kid, depth + 1)

    fmt(root, 0)
    lines.append("")
    lines.append("* = slowest root-to-leaf path")
    return "\n".join(lines)


def trace_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dervet-tpu trace",
        description="stitch and pretty-print one request's span tree")
    parser.add_argument("rid", help="request id")
    parser.add_argument("dirs", nargs="+",
                        help="spool / fleet / results dir(s) holding "
                             "trace.<rid>.json exports (journals are "
                             "consulted when no export exists)")
    parser.add_argument("--chrome", default=None, metavar="OUT.json",
                        help="also write a Chrome trace-event timeline")
    parser.add_argument("--json", action="store_true",
                        help="emit the stitched span list instead of "
                             "the tree rendering")
    args = parser.parse_args(argv)
    spans = load_stitched_trace(args.rid, [Path(d) for d in args.dirs])
    if not spans:
        print(f"trace: no spans or journal records found for "
              f"{args.rid!r} under {args.dirs}", file=sys.stderr)
        return 3
    if args.chrome:
        path = _trace.export_chrome_trace(spans, args.chrome,
                                          request_id=args.rid)
        print(f"chrome trace written to {path}", file=sys.stderr)
    if args.json:
        print(json.dumps(spans, indent=1, default=str))
    else:
        print(render_trace(spans))
    return 0
