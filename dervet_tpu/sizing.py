"""Grid sizing sweep — now a thin compatibility shim over the design
engine (``dervet_tpu/design``).

The original module enumerated a (power x energy) candidate grid and
batched every candidate's year of dispatch windows into one PDHG call
per window-length group.  That machinery has been promoted into the
BOOST design subsystem — explicit-grid population generation
(``design/population.py``), batched evaluation through the real
``run_dispatch`` pipeline (``design/screen.py``), and a certified
frontier (``design/frontier.py``) — so this function now just drives
the engine in legacy mode: the grid IS the population (deduplicated and
sorted, so duplicate ``(kW, kWh)`` pairs can no longer solve twice or
make the winner tie-dependent on input order), screening runs at FULL
fidelity (the caller's solver options, not the loose ordinal tier —
the legacy contract is that every surface value is a real solve), and
``top_k=1`` certifies the winner, asserting parity with the surface's
own argmin.

Callers that want the modern surface — huge sampled populations, loose
ordinal screening with refinement, a certified top-k frontier — should
use :func:`dervet_tpu.design.run_design` directly.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import pandas as pd

from .io.params import CaseParams
from .ops.pdhg import PDHGOptions
from .scenario.scenario import MicrogridScenario
from .utils.errors import ParameterError, TellUser


def _candidate_scenario(case: CaseParams, der_tag: str, der_id: str,
                        kw: float, kwh: float) -> MicrogridScenario:
    """A scenario whose target ESS is fixed at the candidate ratings
    (kept for callers/tests that probe single candidates)."""
    from .design.population import Candidate, candidate_case
    cand = Candidate(index=0,
                     sizes=((der_tag, der_id, float(kw), float(kwh)),))
    return MicrogridScenario(candidate_case(case, cand))


def sizing_sweep(case: CaseParams, kw_grid: Sequence[float],
                 kwh_grid: Sequence[float], der_tag: str = "Battery",
                 der_id: str = "1", solver_opts: Optional[PDHGOptions] = None,
                 ) -> pd.DataFrame:
    """Sweep an ESS power/energy grid; dispatch every candidate's year on
    the batch axis.

    Returns a DataFrame with one row per DISTINCT (kW, kWh) candidate
    (duplicates deduplicated, rows sorted by (kW, kWh)):

    * ``operating_value`` — total dispatch objective over the year
      (negative = net benefit), summed across windows
    * ``capex`` — the candidate's capital cost
    * ``total`` — operating_value + capex (rank by this; it is the
      sweep's analogue of the sizing LP's objective)
    * ``converged`` — all of the candidate's windows converged
    * ``lifetime_npv`` — the optimized year's net operating value
      recurring with inflation over the project horizon, discounted,
      less capex

    The grid is dense by construction — callers read the response
    surface, pick a region, and refine with a tighter grid or the exact
    continuous-sizing path.
    """
    from .design.frontier import run_design
    from .design.population import DERBounds, DesignSpec

    kw_grid = [float(kw) for kw in kw_grid]
    kwh_grid = [float(kwh) for kwh in kwh_grid]
    pairs = sorted({(kw, kwh) for kw in kw_grid for kwh in kwh_grid})
    if not pairs:
        raise ParameterError("sizing_sweep: empty candidate grid")
    if len(pairs) < len(kw_grid) * len(kwh_grid):
        TellUser.warning(
            f"sizing_sweep: candidate grid had duplicate (kW, kWh) "
            f"pairs — deduplicated to {len(pairs)} distinct candidate(s)")
    kws = [p[0] for p in pairs]
    kwhs = [p[1] for p in pairs]
    spec = DesignSpec(
        bounds={(der_tag, der_id): DERBounds(
            kw=(min(kws), max(kws)), kwh=(min(kwhs), max(kwhs)))},
        population=0, grid=pairs, top_k=1, refine_rounds=0)
    # legacy full-fidelity contract: every candidate solves at the
    # caller's tolerances (the ordinal screening tier is opt-in via
    # design.run_design); the engine still batches the whole grid onto
    # the device axis per window-length group
    frontier = run_design(case, spec, backend="jax",
                          solver_opts=solver_opts,
                          screen_opts_override=solver_opts
                          or PDHGOptions())

    out = frontier.population[
        ["kW", "kWh", "operating_value", "capex", "total", "converged",
         "lifetime_npv"]].copy()
    out = out.sort_values(["kW", "kWh"]).reset_index(drop=True)
    best = out.loc[out[out.converged]["total"].idxmin()] if \
        out.converged.any() else None
    if best is not None:
        TellUser.info(f"sizing_sweep: best candidate {best['kW']:.0f} kW / "
                      f"{best['kWh']:.0f} kWh (total {best['total']:.0f})")
        # parity assertion against the engine's certified winner: the
        # surface's argmin and the certified top-1 must agree (up to a
        # genuine near-tie — the certified re-solve is an independent
        # dispatch of the same LP)
        w = frontier.winner
        if w is not None and np.isfinite(w.get("total", np.nan)):
            same = (float(w["kW"]) == float(best["kW"])
                    and float(w["kWh"]) == float(best["kWh"]))
            scale = max(1.0, abs(float(best["total"])))
            if not same and abs(float(w["total"])
                                - float(best["total"])) / scale > 1e-4:
                TellUser.warning(
                    "sizing_sweep: certified winner "
                    f"({w['kW']:.0f} kW / {w['kWh']:.0f} kWh, total "
                    f"{w['total']:.0f}) disagrees with the surface argmin "
                    "beyond tie tolerance — trust the certified answer")
    return out
