"""Grid sizing sweep on the batch axis (the north-star pattern).

The reference sizes by making ratings CVXPY variables inside one MILP
(``ESSSizing.py:82-138``); this framework's continuous-sizing path mirrors
that (``models/der/ess.py::_build_sizing``).  The TPU-NATIVE alternative
this module adds is the BASELINE.json north-star shape: enumerate a
(power x energy) candidate grid and let the grid BE the batch axis — every
candidate's year of dispatch windows solves in one batched PDHG call per
window-length group, so a 20x20 sweep costs barely more wall time than a
single case and returns the full response surface instead of one point
(VERDICT r1 next-round item 8).

All candidates share one LP *structure* per window (fixed-size builds
differ only in bounds/rhs/costs), which is exactly what
:class:`CompiledLPSolver`'s batched data path wants.
"""
from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from .io.params import CaseParams
from .ops.pdhg import CompiledLPSolver, PDHGOptions
from .scenario.scenario import MicrogridScenario
from .utils.errors import ParameterError, TellUser


def _candidate_scenario(case: CaseParams, der_tag: str, der_id: str,
                        kw: float, kwh: float) -> MicrogridScenario:
    """A scenario whose target ESS is fixed at the candidate ratings."""
    c = copy.deepcopy(case)
    found = False
    for tag, i, keys in c.ders:
        if tag == der_tag and (i or "1") == (der_id or "1"):
            keys["ch_max_rated"] = kw
            keys["dis_max_rated"] = kw
            keys["ene_max_rated"] = kwh
            found = True
    if not found:
        raise ParameterError(f"sizing_sweep: no {der_tag} id={der_id!r}")
    return MicrogridScenario(c)


def sizing_sweep(case: CaseParams, kw_grid: Sequence[float],
                 kwh_grid: Sequence[float], der_tag: str = "Battery",
                 der_id: str = "1", solver_opts: Optional[PDHGOptions] = None,
                 ) -> pd.DataFrame:
    """Sweep an ESS power/energy grid; dispatch every candidate's year on
    the batch axis.

    Returns a DataFrame with one row per (kW, kWh) candidate:

    * ``operating_value`` — total dispatch objective over the year
      (negative = net benefit), summed across windows
    * ``capex`` — the candidate's capital cost
    * ``total`` — operating_value + capex (rank by this; it is the
      sweep's analogue of the sizing LP's objective)
    * ``converged`` — all of the candidate's windows converged

    The grid is dense by construction — callers read the response
    surface, pick a region, and refine with a tighter grid or the exact
    continuous-sizing path.
    """
    candidates: List[Tuple[float, float]] = [
        (float(kw), float(kwh)) for kw in kw_grid for kwh in kwh_grid]
    if not candidates:
        raise ParameterError("sizing_sweep: empty candidate grid")

    # one scenario per candidate (host-side assembly); window STRUCTURE is
    # identical across candidates, so LPs group by window length and the
    # candidate axis concatenates into the solver's batch dimension.
    # Candidates differ only in bounds/rhs/costs, so after the first
    # candidate builds a window label, its siblings assemble DATA-ONLY
    # against the shared K (digest-verified; VERDICT r5 #7)
    scens = [_candidate_scenario(case, der_tag, der_id, kw, kwh)
             for kw, kwh in candidates]
    groups: Dict[int, List[Tuple[int, object]]] = {}
    templates: Dict[int, object] = {}
    for ci, s in enumerate(scens):
        if s.poi.is_sizing_optimization:
            raise ParameterError(
                "sizing_sweep drives FIXED-size candidates; zero ratings "
                "elsewhere in the case would add size variables")
        for ctx in s.windows:
            lp = s.build_window_lp(ctx, template=templates.get(ctx.label))
            templates.setdefault(ctx.label, lp)
            groups.setdefault(ctx.T, []).append((ci, lp))

    n_cand = len(candidates)
    op_value = np.zeros(n_cand)
    all_ok = np.ones(n_cand, bool)
    any_lp = next(iter(groups.values()))[0][1]
    if any_lp.integrality is not None:
        # the product dispatch path routes binary windows to the exact
        # CPU MILP; the sweep's batched device path cannot — it would
        # silently solve the LP RELAXATION and rank candidates on
        # objectives the binary formulation never attains.  The reference
        # hard-errors on binary+sizing (MicrogridPOI.py:132-147); a
        # warning that scrolls past a 400-candidate sweep is a
        # correctness trap, not a notice (VERDICT r5 weak #3).  Also:
        # with binary=1 the capacity coefficient enters the on/off rows,
        # so candidates stop sharing K and lose template reuse.
        raise ParameterError(
            "sizing_sweep cannot size with the binary formulation "
            "(scenario binary=1): the batched sweep would silently solve "
            "the LP relaxation of the on/off windows.  Set binary=0 for "
            "the sweep, or use the exact continuous-sizing path "
            "(reference forbids binary+sizing, MicrogridPOI.py:132-147)")

    def solve_group_batch(T, entries):
        """Returns per-group (objs+c0, ok) aligned with ``entries`` —
        accumulation into the shared candidate arrays happens on the
        MAIN thread after join (every candidate has windows in every
        group, so threaded `op_value[ci] +=` would be a data race)."""
        lps = [lp for _, lp in entries]
        lp0 = lps[0]
        solver = CompiledLPSolver(lp0, solver_opts or PDHGOptions())
        C = np.stack([lp.c for lp in lps])
        Q = np.stack([lp.q for lp in lps])
        L = np.stack([lp.l for lp in lps])
        U = np.stack([lp.u for lp in lps])
        res = solver.solve(c=C, q=Q, l=L, u=U)
        objs = np.asarray(res.obj)
        ok = np.asarray(res.converged)
        TellUser.debug(f"sizing_sweep: group T={T} solved "
                       f"{len(entries)} window-LPs")
        return ([float(objs[k]) + lp.c0 for k, (_, lp) in enumerate(entries)],
                [bool(v) for v in ok])

    # one thread per window-length group: the groups compile DIFFERENT
    # XLA programs, and compiling them concurrently (remote compiles
    # release the GIL) collapses the sweep's cold start — same pattern
    # as bench.py's warm-up.  Unlike run_dispatch, the pool is NOT
    # capped by cpu_count: measured on the 1-CPU bench host, threaded
    # steady state is a wash vs serial (39.2 s vs ~41 s — one big solve
    # per group, little host-side contention) while cold start improves
    # 3.3x (340 s -> 103 s), so compile overlap pays for the pool.
    import concurrent.futures as cf
    items = sorted(groups.items())
    with cf.ThreadPoolExecutor(max_workers=max(1, len(items))) as pool:
        futs = [pool.submit(solve_group_batch, T, entries)
                for T, entries in items]
        for (T, entries), f in zip(items, futs):
            vals, oks = f.result()
            for (ci, _), v, k_ok in zip(entries, vals, oks):
                op_value[ci] += v
                all_ok[ci] &= k_ok

    rows = []
    for ci, (kw, kwh) in enumerate(candidates):
        der = next(d for d in scens[ci].ders
                   if d.tag == der_tag and (d.id or "1") == (der_id or "1"))
        capex = der.get_capex()
        rows.append({"kW": kw, "kWh": kwh,
                     "operating_value": op_value[ci], "capex": capex,
                     "total": op_value[ci] + capex,
                     "converged": bool(all_ok[ci])})
    out = pd.DataFrame(rows)
    # vectorized per-candidate lifetime NPV (the north-star's "batched
    # proforma without a Python loop"): the optimized year's net operating
    # value recurs with inflation over the project horizon, discounted at
    # the case's rate, less capex in year zero
    fin = case.finance
    rate = float(fin.get("npv_discount_rate", 0) or 0) / 100.0
    infl = float(fin.get("inflation_rate", 0) or 0) / 100.0
    s0 = scens[0]
    n_years = s0.end_year - s0.start_year + 1
    k = np.arange(1, n_years + 1)
    annuity = float(np.sum((1 + infl) ** (k - 1) / (1 + rate) ** k))
    out["lifetime_npv"] = -out["capex"] - out["operating_value"] * annuity
    best = out.loc[out[out.converged]["total"].idxmin()] if \
        out.converged.any() else None
    if best is not None:
        TellUser.info(f"sizing_sweep: best candidate {best['kW']:.0f} kW / "
                      f"{best['kWh']:.0f} kWh (total {best['total']:.0f})")
    return out
