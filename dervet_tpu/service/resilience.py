"""Service resilience: load shedding, backend-loss recovery, poison
quarantine — the self-healing layer around the batch round.

A long-lived service meets failure modes a one-shot run never sees:

* **Sustained overload** — rejecting everything is one answer; BOOST
  (PAPERS.md: arxiv 2501.10842) shows a cheap low-fidelity solve is a
  legitimate product tier, so :class:`LoadShedder` instead routes
  low-priority requests to a loose-tolerance, short-budget PDHG
  screening solve (``PDHGOptions.screening``) answered with an explicit
  ``fidelity: "degraded"`` mark and NO certificate — clients resubmit
  for a certified answer when the storm passes.
* **Backend death** — a device loss / XLA runtime crash mid-round kills
  the dispatch, not the service: :class:`BackendRecovery` tears the
  backend down, re-initializes it (``warmup_devices``), replays the
  round from the PR-2 checkpoint material, and fails over to the exact
  CPU backend after N consecutive re-init failures (DuaLip-GPU-scale LP
  fleets treat worker loss as routine, arxiv 2603.04621).
* **Poison requests** — a request whose cases keep crashing the
  dispatch would re-kill every round it is co-batched into:
  :class:`PoisonRegistry` fingerprints request content, strikes it on
  every attributed crash, and after two strikes quarantines it with a
  typed ``PoisonRequestError`` and blocklists the fingerprint so
  resubmission is rejected fast at admission.
"""
from __future__ import annotations

import contextlib
import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.errors import DeviceLossError, TellUser

# result fidelity marks (Result.fidelity): the explicit degraded-answer
# contract — a degraded result is NEVER certificate-stamped, and carries
# a resubmit hint instead
FIDELITY_FULL = "certified"
FIDELITY_DEGRADED = "degraded"


# ---------------------------------------------------------------------------
# Request fingerprinting (poison registry key)
# ---------------------------------------------------------------------------

def case_fingerprint(case) -> str:
    """Content hash of one :class:`CaseParams` — the inputs that
    determine its dispatch (the scenario-level analogue of
    ``MicrogridScenario._checkpoint_fingerprint``, computable WITHOUT
    constructing a scenario, so the admission boundary can consult the
    poison blocklist before any expensive work)."""
    h = hashlib.sha256()
    h.update(repr(sorted(case.scenario.items(), key=str)).encode())
    for tag, der_id, keys in case.ders:
        h.update(repr((tag, der_id, sorted(keys.items()))).encode())
    for tag, keys in sorted(case.streams.items()):
        h.update(repr((tag, sorted(keys.items()))).encode())
    ts = case.datasets.time_series
    if ts is not None:
        h.update(np.ascontiguousarray(
            ts.to_numpy(dtype=np.float64, na_value=np.nan)).tobytes())
    return h.hexdigest()


def request_fingerprint(cases: Dict) -> str:
    """Fingerprint of a whole request's case set (order-independent)."""
    h = hashlib.sha256()
    for key in sorted(cases, key=str):
        h.update(str(key).encode())
        h.update(case_fingerprint(cases[key]).encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Backend-loss classification
# ---------------------------------------------------------------------------

# substrings (lowercased) that mark a runtime-layer device death in the
# wild: jaxlib raises XlaRuntimeError with messages like these when a
# TPU worker is reclaimed or the transfer runtime dies mid-dispatch
_BACKEND_LOSS_MARKERS = (
    "device lost", "device is lost", "devicelost", "device or resource",
    "poisoned", "data transfer", "tpu is dead", "backend is gone",
    "failed to connect", "socket closed", "deadline exceeded",
)


def is_backend_loss(exc: BaseException) -> bool:
    """Is this exception a device/runtime death (recoverable by backend
    re-init + replay) rather than a data- or code-shaped crash?  Typed
    check first (the injected :class:`DeviceLossError`), then the
    runtime's own exception type, then message markers."""
    if isinstance(exc, DeviceLossError):
        return True
    name = type(exc).__name__
    if name == "XlaRuntimeError":
        msg = str(exc).lower()
        return any(m in msg for m in _BACKEND_LOSS_MARKERS) or \
            "internal" in msg
    return False


# ---------------------------------------------------------------------------
# Degraded-tier certification bypass
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def certification_disabled():
    """Disable the float64 certification layer for a degraded-tier
    dispatch (its loose screening solutions are honest best-effort — a
    certificate would reject every one and climb the full ladder,
    defeating the shed).  THREAD-LOCAL (``certify.policy_override``):
    only the dispatching thread's rounds are affected — a concurrent
    independent solve in the same process keeps its own env-derived
    policy, so the degraded tier can never silently strip certification
    from a bystander."""
    import dataclasses

    from ..ops import certify
    policy = dataclasses.replace(certify.policy_from_env(), enabled=False)
    with certify.policy_override(policy):
        yield


# ---------------------------------------------------------------------------
# Load shedding
# ---------------------------------------------------------------------------

class LoadShedder:
    """Overload detector + request partitioner for the degraded tier.

    Overload is judged per round from queue pressure (depth at or past
    ``threshold_frac`` of capacity) or deadline-miss pressure (any
    expiries since the last round); shedding engages only once the
    pressure is SUSTAINED for ``sustain_rounds`` consecutive rounds —
    a one-round blip should not degrade anyone's answer — and releases
    the moment a round starts unpressured."""

    def __init__(self, threshold_frac: float = 0.75,
                 sustain_rounds: int = 2, shed_priority_max: int = 0):
        self.threshold_frac = float(threshold_frac)
        self.sustain_rounds = int(sustain_rounds)
        # only requests at or below this priority are shed (degraded);
        # higher-priority work always gets the certified tier
        self.shed_priority_max = int(shed_priority_max)
        self._consecutive = 0
        self._last_expired = 0
        self.shed_rounds = 0
        self.degraded_requests = 0
        # per-request-TYPE shed accounting ("scenario" vs "design"), so
        # design-screening load is distinguishable from scenario load in
        # service.metrics() — a shed design request is answered with a
        # screening-only frontier, a shed scenario request with a
        # degraded screening dispatch
        self.degraded_by_kind: Dict[str, int] = {}

    def observe(self, depth: int, max_depth: int, expired_total: int
                ) -> bool:
        """Feed one round-start observation; returns True when shedding
        is engaged for this round."""
        misses = expired_total - self._last_expired
        self._last_expired = expired_total
        pressured = (max_depth > 0
                     and depth >= self.threshold_frac * max_depth) \
            or misses > 0
        self._consecutive = self._consecutive + 1 if pressured else 0
        return self._consecutive >= self.sustain_rounds

    def partition(self, requests: List) -> Tuple[List, List]:
        """Split a round's requests into (certified, degraded) by the
        shed-priority cutoff.  Call only when shedding is engaged."""
        certified = [r for r in requests
                     if r.priority > self.shed_priority_max]
        degraded = [r for r in requests
                    if r.priority <= self.shed_priority_max]
        if degraded:
            self.shed_rounds += 1
            self.degraded_requests += len(degraded)
            for r in degraded:
                kind = getattr(r, "kind", "scenario") or "scenario"
                self.degraded_by_kind[kind] = \
                    self.degraded_by_kind.get(kind, 0) + 1
        return certified, degraded

    def snapshot(self) -> Dict:
        return {"engaged_streak": self._consecutive,
                "shed_rounds": self.shed_rounds,
                "degraded_requests": self.degraded_requests,
                "degraded_by_kind": dict(self.degraded_by_kind),
                "threshold_frac": self.threshold_frac,
                "shed_priority_max": self.shed_priority_max}


# ---------------------------------------------------------------------------
# Poison-request quarantine
# ---------------------------------------------------------------------------

class PoisonRegistry:
    """Two-strike crash registry keyed by request-content fingerprint.

    ``strike`` records one ATTRIBUTED crash (the request was dispatched
    alone and the dispatch died); at ``threshold`` strikes the
    fingerprint is blocklisted with its diagnosis.  ``blocked`` is the
    admission-time fast path — a blocklisted resubmission is rejected
    in microseconds instead of re-crashing a co-batched round."""

    def __init__(self, threshold: int = 2, max_entries: int = 1024):
        self.threshold = int(threshold)
        # bounded: a service fed unbounded distinct poison must not grow
        # host memory forever; oldest entries are evicted FIFO
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._strikes: Dict[str, Dict] = {}
        self.quarantined = 0

    def strike(self, fingerprint: str, request_id: str,
               diagnosis: str) -> int:
        """Record one attributed crash; returns the new strike count."""
        with self._lock:
            entry = self._strikes.get(fingerprint)
            if entry is None:
                if len(self._strikes) >= self.max_entries:
                    self._strikes.pop(next(iter(self._strikes)))
                entry = {"count": 0, "diagnosis": "", "request_ids": []}
                self._strikes[fingerprint] = entry
            entry["count"] += 1
            entry["diagnosis"] = str(diagnosis)
            entry["request_ids"].append(str(request_id))
            if entry["count"] == self.threshold:
                self.quarantined += 1
                TellUser.error(
                    f"poison quarantine: request {request_id!r} "
                    f"(fingerprint {fingerprint[:12]}…) crashed the "
                    f"dispatch {entry['count']} times — blocklisted; "
                    f"diagnosis: {diagnosis}")
            return entry["count"]

    def strikes(self, fingerprint: str) -> int:
        with self._lock:
            entry = self._strikes.get(fingerprint)
            return entry["count"] if entry else 0

    def blocked(self, fingerprint: str) -> Optional[str]:
        """The stored diagnosis when the fingerprint is blocklisted,
        else None — the admission-time check."""
        with self._lock:
            entry = self._strikes.get(fingerprint)
            if entry and entry["count"] >= self.threshold:
                return entry["diagnosis"]
            return None

    def snapshot(self) -> Dict:
        with self._lock:
            return {"tracked": len(self._strikes),
                    "quarantined": self.quarantined,
                    "threshold": self.threshold}


# ---------------------------------------------------------------------------
# Backend-loss recovery
# ---------------------------------------------------------------------------

class BackendRecovery:
    """Teardown / re-init / failover policy for backend death.

    One instance per service; the batch round calls :meth:`reinit` after
    classifying a dispatch crash as backend loss.  After
    ``max_reinits`` consecutive failed re-initializations the round
    fails over to the exact CPU backend (``failover_backend``); a
    successful re-init resets the consecutive count."""

    def __init__(self, max_reinits: int = 2,
                 failover_backend: str = "cpu"):
        self.max_reinits = int(max_reinits)
        self.failover_backend = str(failover_backend)
        self.losses = 0
        self.reinits = 0
        self.reinit_failures = 0
        self.failovers = 0
        self._consecutive_failures = 0

    def note_loss(self) -> None:
        self.losses += 1

    def begin_round(self) -> None:
        """Fresh re-init budget for a new round.  Without this, the
        consecutive-failure counter left at max by one bad episode would
        make EVERY later round skip re-init and fail straight over to
        the slow CPU backend — even after the accelerator healed."""
        self._consecutive_failures = 0

    def should_failover(self) -> bool:
        return self._consecutive_failures >= self.max_reinits

    def reinit(self, solver_cache=None) -> bool:
        """Tear down and re-initialize the accelerator backend.  Clears
        the compiled-solver cache (its buffers live on the dead device)
        and jax's compilation caches, then re-warms the device.  Returns
        True on success; False counts a consecutive failure toward the
        CPU failover."""
        if solver_cache is not None:
            # compiled programs + preconditioning hold dead-device
            # buffers: drop them — including the elastic per-device
            # shards — and the warm cache rebuilds on re-init
            if hasattr(solver_cache, "clear"):
                solver_cache.clear()
            else:
                solver_cache.solvers.clear()
        try:
            import jax
            try:
                jax.clear_caches()
            except Exception:   # cache clearing is best-effort
                pass
            from ..parallel.mesh import warmup_devices
            # inventory-only probe: re-init must be FAST (it repeats up
            # to max_reinits times against a possibly-dead backend, and
            # time-to-CPU-failover scales with it); the per-device warm
            # solves are a service-START cost, and the first elastic
            # round after recovery rebuilds its shards anyway
            info = warmup_devices(per_device_solve=False)
            # the injected device_loss fault also fails the warm-up
            # probe while armed, so N-consecutive-failure drills work
            from ..utils import faultinject
            faultinject.maybe_device_loss()
            self.reinits += 1
            self._consecutive_failures = 0
            TellUser.warning(
                f"backend recovery: device re-initialized "
                f"({info['n_devices']}x {info['platform']}) — replaying "
                "the in-flight round from checkpoints")
            return True
        except Exception as e:
            self.reinit_failures += 1
            self._consecutive_failures += 1
            TellUser.error(
                f"backend recovery: re-init attempt failed "
                f"({self._consecutive_failures}/{self.max_reinits}): {e}")
            return False

    def snapshot(self) -> Dict:
        return {"losses": self.losses, "reinits": self.reinits,
                "reinit_failures": self.reinit_failures,
                "failovers": self.failovers,
                "max_reinits": self.max_reinits,
                "failover_backend": self.failover_backend}
