"""Fleet replicas: the per-replica half of multi-replica serving.

ROADMAP item 2's multi-host tier: one host's mesh saturates under the
elastic scheduler (PR 9), so "heavy traffic from millions of users"
means N service replicas behind a router (DuaLip-GPU runs LP fleets at
exactly this shape, PAPERS.md: arxiv 2603.04621).  This module defines
what a *replica* is to the router; :mod:`dervet_tpu.service.router`
builds the routing/health/failover brain on top.

Two transports, one interface (:class:`ReplicaHandle`):

* :class:`SpoolReplica` — a real ``dervet-tpu serve`` process over its
  own spool directory.  Requests travel as atomically-renamed pickle
  payloads into ``incoming/``; answers are the spool's normal
  ``results/<rid>/`` artifacts plus the ``done/``/``failed/`` terminal
  markers; liveness is the ``heartbeat.json`` the serve loop rewrites
  every ``--heartbeat-s``; the replica's crash-safe
  ``service_journal.jsonl`` (PR 6) is what makes failover exactly-once
  rather than best-effort.  :func:`spawn_replica` launches one.
* :class:`LocalReplica` — an in-process :class:`ScenarioService`
  behind the same interface (tests, single-process benches).

Affinity key: :func:`structure_fingerprint` hashes the facts that
determine a request's COMPILED LP structure (DER set, window scheme,
horizon length, stream set) and nothing content-like (prices, loads) —
two requests with the same fingerprint hit the same compiled programs
and warm-start structure pools, so the router keeps them on the replica
that is already warm for that shape.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import signal
import subprocess
import sys
import time
from concurrent.futures import Future
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ..utils.errors import TellUser
from .journal import ServiceJournal

# spool layout bits the router and the serve loop agree on
HEARTBEAT_FILE = "heartbeat.json"
PROBE_FILE = "probe.json"
CANCEL_DIR = "cancel"
MEMORY_EXPORT_FILE = "memory_export.pkl"
MEMORY_IN_DIR = "memory_in"
JOURNAL_FILE = "service_journal.jsonl"
PAYLOAD_SUFFIX = ".pkl"


# ---------------------------------------------------------------------------
# Structure-fingerprint affinity key
# ---------------------------------------------------------------------------

# scenario keys that shape the compiled LP program set (window scheme,
# step, horizon, included couplings) — NOT content like prices/loads
_STRUCTURAL_SCENARIO_KEYS = (
    "n", "dt", "opt_years", "start_year", "end_year", "incl_site_load",
    "incl_thermal_load", "allow_partial_year", "binary",
)


def structure_fingerprint(cases: Dict) -> str:
    """Hash of a request's LP *structure*: per case, the DER set
    (tags + ids + which keys each carries), the stream tags, the
    window-shaping scenario keys, and the time-series LENGTH — everything
    that decides which compiled programs and warm-start structure pools
    the request will hit, and nothing about the numbers in them.  Two
    requests that differ only in prices/ratings/loads share the
    fingerprint (and should share a warm replica); a different horizon
    or DER mix does not."""
    h = hashlib.sha256()
    for key in sorted(cases, key=str):
        case = cases[key]
        scen = case.scenario
        h.update(repr([(k, scen.get(k))
                       for k in _STRUCTURAL_SCENARIO_KEYS]).encode())
        h.update(repr(sorted((tag, der_id, tuple(sorted(keys)))
                             for tag, der_id, keys in case.ders)).encode())
        h.update(repr(sorted(case.streams)).encode())
        ts = case.datasets.time_series
        h.update(str(0 if ts is None else len(ts)).encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Replica handles
# ---------------------------------------------------------------------------

class ReplicaHandle:
    """One replica as the router sees it: submit / poll / health / fence.

    Subclasses implement the transport; the router only ever talks to
    this surface.  ``state`` is router-owned ("up" | "dead")."""

    def __init__(self, name: str):
        self.name = str(name)
        self.state = "up"
        # heartbeat-epoch fence (lifecycle supervisor): ``epoch`` is the
        # incarnation this handle's process was spawned with (None =
        # unsupervised, no fencing); ``fence_epoch`` is set by the
        # router at declare-dead time to the LAST epoch it saw beat —
        # only a beat with a STRICTLY HIGHER epoch can resurrect the
        # name, so a fenced zombie's late heartbeat writes over the
        # shared spool can never re-open routing to the corpse
        self.epoch: Optional[int] = None
        self.fence_epoch: Optional[int] = None
        # restart metadata (stamped by the supervisor on each respawn;
        # surfaced through snapshot() -> metrics -> `dervet-tpu status`)
        self.restarts = 0
        self.last_restart_reason: Optional[str] = None
        self.last_restart_t: Optional[float] = None

    # -- request path ---------------------------------------------------
    def submit(self, cases, rid: str, *, priority: int = 0,
               deadline_epoch: Optional[float] = None,
               payload: Optional[bytes] = None,
               trace_ctx: Optional[Dict] = None,
               extra: Optional[Dict] = None) -> None:
        """Hand one request to the replica.  May raise the replica's
        typed admission errors synchronously (local transport); spool
        transport never raises here — outcomes arrive via :meth:`poll`.

        ``trace_ctx`` is the router's telemetry trace context
        (``{"trace_id", "span_id"}``): it rides the transport (spool
        pickle payload / local submit kwarg) so the replica-side span
        tree parents under the router's — one stitched trace per
        request across processes.

        ``extra`` carries request-kind extensions through the same
        transport — today the ``portfolio_shard`` payload (one shard of
        a fleet-sharded portfolio dual round: site cases + dual-price
        vector; see ``dervet_tpu.portfolio.shard``)."""
        raise NotImplementedError

    def poll(self, rid: str) -> Optional[Tuple[str, object]]:
        """The replica's answer for ``rid`` if it has one:
        ``("done", answer)`` / ``("failed", error_payload_dict)`` /
        ``None`` while still in flight."""
        raise NotImplementedError

    def request_state(self, rid: str) -> str:
        """Failover-time classification: ``"completed"`` (an answer
        exists and can be harvested), ``"failed"``, or ``"pending"``
        (must be re-routed)."""
        outcome = self.poll(rid)
        if outcome is None:
            return "pending"
        return "completed" if outcome[0] == "done" else "failed"

    def retract(self, rid: str) -> None:
        """Best-effort removal of a not-yet-served request (failover
        fencing / hedge-loser cancellation before admission)."""

    def cancel(self, rid: str) -> None:
        """Ask the replica to drop ``rid`` at the next round boundary
        (hedge loser).  Best-effort: an answer that still arrives is
        simply discarded by the router's exactly-once delivery."""

    # -- health ---------------------------------------------------------
    def heartbeat(self) -> Optional[Dict]:
        """The replica's latest heartbeat record (None = none yet)."""
        raise NotImplementedError

    def probe(self, nonce: str, trace: Optional[Dict] = None) -> None:
        """Leave a probe nonce for the replica to echo in its next
        heartbeat — the router's cheap liveness probe (no solve).
        ``trace`` is an optional telemetry context the replica echoes
        back alongside the nonce, so probe round-trips are traceable."""

    def published_load(self) -> Optional[Dict]:
        """The replica's SELF-published load signal (queue depth + drain
        rate from its telemetry exposition), or None when it has never
        published — the router's least-loaded ranking prefers this over
        its own inflight counts, which go stale across failover."""
        return None

    def alive(self) -> Optional[bool]:
        """Process-level liveness when known (None = not owned here)."""
        return None

    def kill(self) -> None:
        """Fence: make sure the replica can do no further work (router
        calls this before re-routing its in-flight requests)."""

    # -- warm-start handoff ---------------------------------------------
    def read_memory_export(self) -> Optional[bytes]:
        """The replica's last published warm-start memory export (pickle
        bytes), if any."""
        return None

    def import_memory(self, blob: bytes) -> None:
        """Hand another replica's memory export to this one."""

    def snapshot(self) -> Dict:
        return {"name": self.name, "state": self.state}


class SpoolReplica(ReplicaHandle):
    """A ``dervet-tpu serve`` process over its own spool directory.

    The handle only touches the spool filesystem (plus the process
    handle when this router spawned the replica): requests are pickle
    payloads atomically renamed into ``incoming/`` (a half-written file
    is never visible to the replica's scan), answers are the terminal
    ``done/``/``failed/`` markers plus ``results/<rid>/`` artifacts, and
    health is ``heartbeat.json`` freshness.  Payloads carry pickled
    ``CaseParams`` — a same-trust-domain transport (the replicas are our
    own processes on our own host/cluster), not a wire format."""

    def __init__(self, name: str, spool, process: Optional[
            subprocess.Popen] = None):
        super().__init__(name)
        self.spool = Path(spool)
        self.process = process
        self.incoming = self.spool / "incoming"
        self.results_root = self.spool / "results"
        self.done_dir = self.spool / "done"
        self.failed_dir = self.spool / "failed"
        self.cancel_dir = self.spool / CANCEL_DIR
        self.memory_in = self.spool / MEMORY_IN_DIR
        for d in (self.incoming, self.results_root, self.done_dir,
                  self.failed_dir, self.cancel_dir, self.memory_in):
            d.mkdir(parents=True, exist_ok=True)

    # -- request path ---------------------------------------------------
    @staticmethod
    def encode_payload(cases, *, priority: int = 0,
                       deadline_epoch: Optional[float] = None,
                       trace: Optional[Dict] = None,
                       extra: Optional[Dict] = None,
                       cases_blob: Optional[bytes] = None) -> bytes:
        # "trace" is the router's telemetry context: the replica's
        # submit_pickle hands it to ScenarioService.submit as trace_ctx;
        # "extra" merges kind extensions (the portfolio_shard payload)
        # into the same transport record.  A portfolio_shard extra IS
        # the request (submit_pickle dispatches on it and never reads
        # "cases"), so cases are omitted — shipping them too used to
        # double every shard payload on the wire.  "cases_blob" is the
        # client's one-time pickle of the cases dict: embedding the
        # bytes is a memcpy, not a re-serialization of every DataFrame.
        record = {"priority": int(priority),
                  "deadline_epoch": deadline_epoch,
                  **({"trace": trace} if trace else {}),
                  **(extra or {})}
        if "portfolio_shard" not in record:
            if cases_blob is not None:
                record["cases_pickle"] = cases_blob
            else:
                record["cases"] = cases
        return pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL)

    def _fname(self, rid: str) -> str:
        return f"{rid}{PAYLOAD_SUFFIX}"

    def submit(self, cases, rid: str, *, priority: int = 0,
               deadline_epoch: Optional[float] = None,
               payload: Optional[bytes] = None,
               trace_ctx: Optional[Dict] = None,
               extra: Optional[Dict] = None) -> None:
        if payload is None:
            payload = self.encode_payload(cases, priority=priority,
                                          deadline_epoch=deadline_epoch,
                                          trace=trace_ctx, extra=extra)
        # dot-prefixed tmp + rename: the serve scan globs non-dot names,
        # so a half-written payload can never be admitted
        final = self.incoming / self._fname(rid)
        tmp = self.incoming / f".{final.name}.tmp"
        tmp.write_bytes(payload)
        os.replace(tmp, final)

    def poll(self, rid: str) -> Optional[Tuple[str, object]]:
        fname = self._fname(rid)
        if (self.done_dir / fname).exists():
            return "done", self.results_root / rid
        err_json = self.failed_dir / f"{fname}.error.json"
        if (self.failed_dir / fname).exists() or err_json.exists():
            try:
                payload = json.loads(err_json.read_text())
            except (OSError, ValueError):
                payload = {"error": "unknown", "kind": "error",
                           "message": "replica recorded a failure but "
                                      "its error payload is unreadable",
                           "retry_hint": None}
            return "failed", payload
        return None

    def request_state(self, rid: str) -> str:
        outcome = self.poll(rid)
        if outcome is not None:
            return "completed" if outcome[0] == "done" else "failed"
        # the terminal marker may be missing only because the kill
        # landed between persisting results and moving the input file:
        # trust the replica's own journal (results are persisted BEFORE
        # "completed" is journaled, so a journaled completion always has
        # its results on disk — harvestable, no re-solve)
        state = ServiceJournal.replay_path(
            self.spool / JOURNAL_FILE).get(rid, {}).get("state")
        if state == "completed" and (self.results_root / rid).is_dir():
            return "completed"
        if state == "failed":
            return "failed"
        return "pending"

    def retract(self, rid: str) -> None:
        try:
            (self.incoming / self._fname(rid)).unlink()
        except FileNotFoundError:
            pass

    def cancel(self, rid: str) -> None:
        # marker file; the serve scan retracts the input if it has not
        # been admitted yet (round-boundary cancellation)
        try:
            (self.cancel_dir / str(rid)).touch()
        except OSError:
            pass

    # -- health ---------------------------------------------------------
    def heartbeat(self) -> Optional[Dict]:
        try:
            return json.loads((self.spool / HEARTBEAT_FILE).read_text())
        except (OSError, ValueError):
            return None         # missing or torn mid-replace: no beat

    def probe(self, nonce: str, trace: Optional[Dict] = None) -> None:
        from ..utils.supervisor import atomic_write
        atomic_write(self.spool / PROBE_FILE,
                     json.dumps({"nonce": str(nonce),
                                 "t": round(time.time(), 3),
                                 **({"trace": trace} if trace else {})}))

    def published_load(self) -> Optional[Dict]:
        """Parse the replica's ``telemetry.prom`` exposition (written
        atomically by its serve loop at the heartbeat cadence) into the
        routing load signal.  None when the file does not exist (replica
        never published / telemetry off) or is unreadable."""
        from ..telemetry import ops as telemetry_ops
        from ..telemetry import registry as telemetry_registry
        prom = self.spool / telemetry_ops.PROM_FILE
        try:
            text = prom.read_text()
            parsed = telemetry_registry.parse_prometheus(text)
            t_published = prom.stat().st_mtime
        except (OSError, ValueError):
            return None
        depth = telemetry_registry.sample_value(
            parsed, telemetry_ops.M_QUEUE_DEPTH)
        if depth is None:
            return None
        return {
            "queue_depth": float(depth),
            "drain_rate_rps": telemetry_registry.sample_value(
                parsed, telemetry_ops.M_DRAIN_RATE) or 0.0,
            "pending": telemetry_registry.sample_value(
                parsed, telemetry_ops.M_PENDING) or 0.0,
            # wall-clock publish time (exposition mtime): the router
            # treats a signal older than its staleness bound as
            # never-published — a frozen file from a dead/restarted
            # replica must not keep ranking it as idle
            "t_published": t_published,
        }

    def alive(self) -> Optional[bool]:
        if self.process is None:
            return None
        return self.process.poll() is None

    def kill(self) -> None:
        """SIGKILL the owned process (fencing before failover: a hung
        replica must not wake up and keep writing once its requests have
        been re-routed — its spool stays readable for harvest/journal
        replay, its compute is done)."""
        if self.process is not None and self.process.poll() is None:
            try:
                self.process.send_signal(signal.SIGKILL)
                self.process.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired) as e:
                TellUser.warning(
                    f"fleet: could not fence replica {self.name!r}: {e}")

    def terminate(self, timeout: float = 30.0) -> None:
        """Polite shutdown of an owned process (router drain path)."""
        if self.process is None or self.process.poll() is not None:
            return
        try:
            self.process.terminate()
            self.process.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.process.kill()
            self.process.wait(timeout=10)
        except OSError:
            pass

    # -- failover -------------------------------------------------------
    def journal_states(self) -> Dict[str, Dict]:
        return ServiceJournal.replay_path(self.spool / JOURNAL_FILE)

    def read_memory_export(self) -> Optional[bytes]:
        try:
            return (self.spool / MEMORY_EXPORT_FILE).read_bytes()
        except OSError:
            return None

    def import_memory(self, blob: bytes) -> None:
        # dropped into memory_in/ for the serve loop to install on its
        # next scan; unique name so two handoffs never clobber
        target = self.memory_in / f"import-{time.time_ns()}.pkl"
        tmp = target.with_name(f".{target.name}.tmp")
        tmp.write_bytes(blob)
        os.replace(tmp, target)

    def snapshot(self) -> Dict:
        alive = self.alive()
        return {"name": self.name, "state": self.state,
                "spool": str(self.spool),
                "pid": self.process.pid if self.process else None,
                "process_alive": alive,
                "epoch": self.epoch,
                "restarts": self.restarts,
                "last_restart_reason": self.last_restart_reason}


class LocalReplica(ReplicaHandle):
    """An in-process :class:`ScenarioService` behind the replica
    interface — the unit-test / single-process transport.  ``submit``
    raises the service's typed admission errors synchronously (the
    router's redirect path catches queue-full and tries the next
    replica); health is synthesized from service state.  ``kill`` only
    simulates death to the ROUTER (heartbeats stop); the underlying
    service keeps running unless ``hard=True`` drains it — that is
    exactly what a flapping/hung replica looks like from outside, which
    is what the router tests need."""

    def __init__(self, name: str, service):
        super().__init__(name)
        self.service = service
        self._futures: Dict[str, Future] = {}
        self._killed = False
        self._t0 = time.time()

    def submit(self, cases, rid: str, *, priority: int = 0,
               deadline_epoch: Optional[float] = None,
               payload: Optional[bytes] = None,
               trace_ctx: Optional[Dict] = None,
               extra: Optional[Dict] = None) -> None:
        deadline_s = None
        if deadline_epoch is not None:
            deadline_s = max(0.0, deadline_epoch - time.time())
        # the rid rides through unchanged: each LocalReplica wraps its
        # OWN service, so ids cannot cross-wire between replicas, and
        # artifact names stay identical to a single-replica run
        if extra and extra.get("portfolio_shard") is not None:
            self._futures[rid] = self.service.submit_portfolio_shard(
                extra["portfolio_shard"], request_id=rid,
                priority=priority, deadline_s=deadline_s,
                trace_ctx=trace_ctx)
            return
        self._futures[rid] = self.service.submit(
            cases, request_id=rid, priority=priority,
            deadline_s=deadline_s, trace_ctx=trace_ctx)

    def poll(self, rid: str) -> Optional[Tuple[str, object]]:
        fut = self._futures.get(rid)
        if fut is None or not fut.done():
            return None
        err = fut.exception()
        if err is None:
            return "done", fut.result()
        return "failed", err

    def retract(self, rid: str) -> None:
        self._futures.pop(rid, None)

    def heartbeat(self) -> Optional[Dict]:
        if self._killed:
            return None
        return {"t": time.time(), "name": self.name,
                "pending": self.service.queue.depth(),
                "draining": self.service._draining.is_set()}

    def alive(self) -> Optional[bool]:
        return not self._killed

    def published_load(self) -> Optional[Dict]:
        """In-process transport: the service's live queue IS the
        published signal (no exposition file round-trip), gated on the
        same kill switch so routing behavior matches the spool
        transport's never-published fallback."""
        from ..telemetry import registry as telemetry_registry
        if self._killed or not telemetry_registry.enabled():
            return None
        return {"queue_depth": float(self.service.queue.depth()),
                "drain_rate_rps": self.service.queue.drain_rate() or 0.0,
                "pending": 0.0}

    def kill(self, hard: bool = False) -> None:
        self._killed = True
        if hard:
            self.service.request_stop()

    def read_memory_export(self) -> Optional[bytes]:
        mem = self.service.solver_cache.memory
        if mem is None:
            return None
        # entries + learned seed models (ops/seedpredict.py) in one
        # payload; import_payload on the receiving side accepts both
        # this dict and the older bare-entries list
        return pickle.dumps(mem.export_payload(),
                            protocol=pickle.HIGHEST_PROTOCOL)

    def import_memory(self, blob: bytes) -> None:
        mem = self.service.solver_cache.memory
        if mem is not None:
            mem.import_payload(pickle.loads(blob))

    def snapshot(self) -> Dict:
        return {"name": self.name, "state": self.state,
                "local": True, "killed": self._killed}


# ---------------------------------------------------------------------------
# Replica process spawning
# ---------------------------------------------------------------------------

def spawn_replica(spool, *, name: Optional[str] = None,
                  backend: str = "cpu", heartbeat_s: float = 0.25,
                  poll_s: float = 0.05, max_queue_depth: int = 64,
                  force_cpu_platform: bool = True,
                  epoch: Optional[int] = None,
                  extra_args: Optional[List[str]] = None,
                  env: Optional[Dict[str, str]] = None,
                  stdout=subprocess.DEVNULL,
                  stderr=subprocess.DEVNULL) -> SpoolReplica:
    """Launch one ``dervet-tpu serve`` replica process over ``spool``
    and return its :class:`SpoolReplica` handle (process attached, so
    the router can fence it).

    ``force_cpu_platform`` pins the CHILD to the CPU XLA backend through
    ``jax.config`` before any dervet import (the env-var route is too
    late on hosts whose sitecustomize pre-imports jax) — fleet drills
    and CI replicas are CPU-deterministic by design; a real accelerator
    fleet passes ``force_cpu_platform=False`` and its own env.

    ``epoch`` is the incarnation number the lifecycle supervisor bumps
    on every respawn over a reused spool: the child stamps it into each
    heartbeat, and the router only credits beats at or above the
    handle's epoch — a fenced zombie still writing the old spool can
    never impersonate its replacement."""
    spool = Path(spool)
    spool.mkdir(parents=True, exist_ok=True)
    # a reused spool's previous-incarnation heartbeat must not be read
    # as this replica's (the router also grants startup grace until the
    # first FRESH beat, but a stale file is simply wrong state)
    try:
        (spool / HEARTBEAT_FILE).unlink()
    except FileNotFoundError:
        pass
    name = name or spool.name
    argv = [str(spool), "--backend", backend,
            "--poll-s", str(poll_s), "--heartbeat-s", str(heartbeat_s),
            "--max-queue-depth", str(max_queue_depth),
            "--replica-name", name] + \
        (["--heartbeat-epoch", str(int(epoch))]
         if epoch is not None else []) + list(extra_args or [])
    preamble = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
                if force_cpu_platform else "")
    code = (f"import sys, json; {preamble}"
            "from dervet_tpu.service.server import serve_main; "
            f"sys.exit(serve_main(json.loads({json.dumps(json.dumps(argv))})))")
    child_env = dict(os.environ)
    # the child must import THIS checkout's dervet_tpu even when the
    # package is not pip-installed (test runs from the repo root)
    repo_root = str(Path(__file__).resolve().parents[2])
    child_env["PYTHONPATH"] = repo_root + os.pathsep + \
        child_env.get("PYTHONPATH", "")
    if force_cpu_platform:
        child_env["JAX_PLATFORMS"] = "cpu"
    child_env.update(env or {})
    proc = subprocess.Popen([sys.executable, "-c", code], env=child_env,
                            stdout=stdout, stderr=stderr)
    handle = SpoolReplica(name, spool, process=proc)
    if epoch is not None:
        handle.epoch = int(epoch)
    return handle
