"""Fleet lifecycle supervisor: the control loop that makes replica
death a routine, automatically repaired event (ROADMAP item 2's last
open hole — the router detects dead replicas and re-routes their work
exactly-once, but nothing relaunched them and nothing resized the
fleet).

:class:`FleetSupervisor` owns :class:`~dervet_tpu.service.fleet
.SpoolReplica` processes end to end:

* **Respawn with crash-loop backoff** — when the router declares a
  replica dead (``_declare_dead`` hands the corpse here AFTER fencing
  and exactly-once failover), the supervisor schedules a replacement
  over the SAME spool with an exponentially-backed-off delay
  (``backoff_base_s · 2^k``, capped at ``backoff_max_s``).  A replica
  that keeps dying within ``rapid_crash_window_s`` of each respawn is
  parked in the typed ``quarantined`` terminal state after
  ``quarantine_after`` rapid crashes (:class:`~dervet_tpu.utils.errors
  .ReplicaQuarantinedError` carries the diagnosis) instead of
  hot-looping spawn/crash forever; an operator clears it with
  :meth:`FleetSupervisor.release`.
* **Heartbeat-epoch fencing** — every respawn bumps the incarnation
  epoch (``spawn_replica(epoch=...)`` → ``--heartbeat-epoch`` → stamped
  into each beat).  The router discredits beats below the handle's
  epoch and, once a name is declared dead, only resurrects it for a
  STRICTLY higher epoch — so a fenced zombie still writing the shared
  spool can neither fake liveness nor close the breaker via a probe
  echo, and can never double-deliver (late answers fall to the
  router's first-answer-wins dedup).
* **Warm respawn** — the replacement imports the dead incarnation's
  last ``memory_export.pkl`` blob through the PR-10/15 export-import
  path (dropped into ``memory_in/`` for the new serve loop's scan), so
  already-converged windows re-solve as exact-match substitutions.
  The dead replica's journaled in-flight requests were already
  re-routed by the router's exactly-once failover before the
  supervisor ever saw the corpse.
* **Telemetry-driven autoscaling** — the autoscaler reads the same
  replica-published load signals the router scrapes from each
  ``telemetry.prom`` (queue depth + drain rate + pending, via
  :meth:`FleetRouter.load_snapshot`): sustained per-replica backlog
  above ``scale_up_backlog`` for ``scale_pressure_s`` adds a replica
  (up to ``max_replicas``); a sustained-idle fleet sheds
  supervisor-added replicas (never the configured baseline, never
  below ``min_replicas``) only after a CLEAN drain — the victim is
  first unrouted (``handle.draining``), then waits for zero inflight
  and an empty spool, then gets a polite SIGTERM.

Kill switch: ``DERVET_TPU_FLEET_SUPERVISE=0`` makes :meth:`start` a
no-op (no thread, no router attachment, no state file) — the fleet
behaves bit for bit as it does today.

Env knobs (the ``DERVET_TPU_FLEET_*`` family; constructor args win):

======================================  =================================
``DERVET_TPU_FLEET_SUPERVISE``          kill switch (default on)
``DERVET_TPU_FLEET_MIN_REPLICAS``       autoscale floor
``DERVET_TPU_FLEET_MAX_REPLICAS``       autoscale ceiling
``DERVET_TPU_FLEET_BACKOFF_BASE_S``     first-respawn delay (0.5)
``DERVET_TPU_FLEET_BACKOFF_MAX_S``      backoff cap (30)
``DERVET_TPU_FLEET_RAPID_CRASH_S``      rapid-crash window (5)
``DERVET_TPU_FLEET_QUARANTINE_AFTER``   rapid crashes before quarantine (3)
``DERVET_TPU_FLEET_SCALE_UP_BACKLOG``   per-replica backlog trigger (8)
``DERVET_TPU_FLEET_SCALE_PRESSURE_S``   sustained-pressure window (5)
``DERVET_TPU_FLEET_SCALE_DOWN_IDLE_S``  sustained-idle window (30)
======================================  =================================
"""
from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional

from ..telemetry import registry as telemetry_registry
from ..telemetry import trace as telemetry_trace
from ..utils.errors import ReplicaQuarantinedError, TellUser
from .fleet import (MEMORY_EXPORT_FILE, ReplicaHandle, SpoolReplica,
                    spawn_replica)

SUPERVISE_ENV = "DERVET_TPU_FLEET_SUPERVISE"
STATE_FILE = "supervisor_state.json"

# lifecycle states (record.state); terminal ones are QUARANTINED (until
# released) and STOPPED (scale-down complete)
SPAWNING = "spawning"
UP = "up"
BACKOFF = "backoff"
DRAINING = "draining"
QUARANTINED = "quarantined"
STOPPED = "stopped"


def supervision_enabled() -> bool:
    """The ``DERVET_TPU_FLEET_SUPERVISE`` kill switch (default ON)."""
    return os.environ.get(SUPERVISE_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return float(default)
    try:
        return float(raw)
    except ValueError:
        TellUser.warning(f"lifecycle: ignoring non-numeric {name}={raw!r}")
        return float(default)


def _env_int(name: str, default: Optional[int]) -> Optional[int]:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return int(raw)
    except ValueError:
        TellUser.warning(f"lifecycle: ignoring non-integer {name}={raw!r}")
        return default


class ReplicaSpec:
    """How to (re)spawn ONE replica: the spool it lives over plus the
    ``spawn_replica`` kwargs.  The supervisor keeps the spec so a
    respawn reproduces the original launch exactly (same backend, same
    queue bound, same extra args) with only the epoch bumped."""

    def __init__(self, spool, *, name: Optional[str] = None,
                 backend: str = "cpu", heartbeat_s: float = 0.25,
                 poll_s: float = 0.05, max_queue_depth: int = 64,
                 force_cpu_platform: bool = True,
                 extra_args: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None):
        self.spool = Path(spool)
        self.name = str(name or self.spool.name)
        self.backend = backend
        self.heartbeat_s = float(heartbeat_s)
        self.poll_s = float(poll_s)
        self.max_queue_depth = int(max_queue_depth)
        self.force_cpu_platform = bool(force_cpu_platform)
        self.extra_args = list(extra_args or [])
        self.env = dict(env or {})

    def spawn(self, epoch: int, spawn_fn: Callable = spawn_replica
              ) -> SpoolReplica:
        return spawn_fn(self.spool, name=self.name, backend=self.backend,
                        heartbeat_s=self.heartbeat_s, poll_s=self.poll_s,
                        max_queue_depth=self.max_queue_depth,
                        force_cpu_platform=self.force_cpu_platform,
                        epoch=int(epoch), extra_args=self.extra_args,
                        env=self.env)

    def with_spool(self, spool, name: str) -> "ReplicaSpec":
        """A copy of this spec over a different spool — the autoscaler's
        template for scale-up replicas."""
        return ReplicaSpec(spool, name=name, backend=self.backend,
                           heartbeat_s=self.heartbeat_s, poll_s=self.poll_s,
                           max_queue_depth=self.max_queue_depth,
                           force_cpu_platform=self.force_cpu_platform,
                           extra_args=self.extra_args, env=self.env)


class _Record:
    """Supervisor-side lifecycle state for one replica name."""

    __slots__ = ("spec", "state", "epoch", "restarts", "rapid",
                 "last_restart_reason", "last_restart_t",
                 "last_spawn_mono", "backoff_until", "pending_reason",
                 "quarantine", "scaled", "warm_imports", "drain_since")

    def __init__(self, spec: ReplicaSpec, *, epoch: int = 0,
                 state: str = SPAWNING, scaled: bool = False):
        self.spec = spec
        self.state = state
        self.epoch = int(epoch)
        self.restarts = 0
        self.rapid = 0                  # consecutive rapid-crash streak
        self.last_restart_reason: Optional[str] = None
        self.last_restart_t: Optional[float] = None
        self.last_spawn_mono: Optional[float] = None
        self.backoff_until: Optional[float] = None
        self.pending_reason: Optional[str] = None
        self.quarantine: Optional[Dict] = None
        self.scaled = bool(scaled)      # autoscaler-added (down-scalable)
        self.warm_imports = 0
        self.drain_since: Optional[float] = None

    def as_dict(self) -> Dict:
        now = time.monotonic()
        return {
            "state": self.state,
            "epoch": self.epoch,
            "restarts": self.restarts,
            "rapid_crashes": self.rapid,
            "last_restart_reason": self.last_restart_reason,
            "last_restart_t": self.last_restart_t,
            "backoff_remaining_s": (
                round(max(0.0, self.backoff_until - now), 3)
                if self.backoff_until is not None
                and self.state == BACKOFF else None),
            "quarantine": self.quarantine,
            "scaled": self.scaled,
            "warm_imports": self.warm_imports,
        }


class FleetSupervisor:
    """Replica lifecycle control loop over a :class:`FleetRouter`.

    Construction wires nothing; :meth:`start` attaches to the router
    (``router.attach_supervisor``), adopts/spawns the configured
    replicas, and starts the supervisor thread — unless the
    ``DERVET_TPU_FLEET_SUPERVISE=0`` kill switch is set, in which case
    ``start()`` is a complete no-op and the fleet behaves exactly as an
    unsupervised one.

    ``spawn_fn`` is injectable (tests supervise fake replicas without
    subprocesses); it must accept ``spawn_replica``'s signature.
    """

    def __init__(self, router, specs=(), *,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 backoff_base_s: Optional[float] = None,
                 backoff_max_s: Optional[float] = None,
                 rapid_crash_window_s: Optional[float] = None,
                 quarantine_after: Optional[int] = None,
                 scale_up_backlog: Optional[float] = None,
                 scale_pressure_s: Optional[float] = None,
                 scale_down_idle_s: Optional[float] = None,
                 warm_respawn: bool = True,
                 tick_s: float = 0.25,
                 spool_root=None,
                 spawn_fn: Callable = spawn_replica):
        self.router = router
        spec_list = (list(specs.values()) if isinstance(specs, dict)
                     else list(specs))
        self._records: Dict[str, _Record] = {}
        for spec in spec_list:
            if spec.name in self._records:
                raise ValueError(f"duplicate replica spec {spec.name!r}")
            self._records[spec.name] = _Record(spec)
        n0 = len(spec_list)
        self.min_replicas = (int(min_replicas) if min_replicas is not None
                             else _env_int("DERVET_TPU_FLEET_MIN_REPLICAS",
                                           None))
        if self.min_replicas is None:
            self.min_replicas = n0
        self.max_replicas = (int(max_replicas) if max_replicas is not None
                             else _env_int("DERVET_TPU_FLEET_MAX_REPLICAS",
                                           None))
        if self.max_replicas is None:
            # default: no autoscale-up — the ceiling is the configured
            # fleet size (deployments opt into growth by raising it)
            self.max_replicas = max(n0, self.min_replicas)
        self.backoff_base_s = (float(backoff_base_s)
                               if backoff_base_s is not None else
                               _env_float("DERVET_TPU_FLEET_BACKOFF_BASE_S",
                                          0.5))
        self.backoff_max_s = (float(backoff_max_s)
                              if backoff_max_s is not None else
                              _env_float("DERVET_TPU_FLEET_BACKOFF_MAX_S",
                                         30.0))
        self.rapid_crash_window_s = (
            float(rapid_crash_window_s)
            if rapid_crash_window_s is not None else
            _env_float("DERVET_TPU_FLEET_RAPID_CRASH_S", 5.0))
        self.quarantine_after = (
            int(quarantine_after) if quarantine_after is not None else
            _env_int("DERVET_TPU_FLEET_QUARANTINE_AFTER", 3))
        self.scale_up_backlog = (
            float(scale_up_backlog) if scale_up_backlog is not None else
            _env_float("DERVET_TPU_FLEET_SCALE_UP_BACKLOG", 8.0))
        self.scale_pressure_s = (
            float(scale_pressure_s) if scale_pressure_s is not None else
            _env_float("DERVET_TPU_FLEET_SCALE_PRESSURE_S", 5.0))
        self.scale_down_idle_s = (
            float(scale_down_idle_s) if scale_down_idle_s is not None else
            _env_float("DERVET_TPU_FLEET_SCALE_DOWN_IDLE_S", 30.0))
        self.warm_respawn = bool(warm_respawn)
        self.tick_s = float(tick_s)
        self.spool_root = Path(spool_root) if spool_root else None
        self.spawn_fn = spawn_fn
        self.enabled = supervision_enabled()
        self._lock = threading.RLock()
        self._counters = {"restarts": 0, "quarantined": 0,
                          "released": 0, "scale_up": 0, "scale_down": 0,
                          "warm_imports": 0, "spawn_failures": 0}
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._scale_seq = 0
        self._publish_last = 0.0
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "FleetSupervisor":
        """Attach to the router, adopt/spawn the fleet, start the loop.
        A no-op under the kill switch: no attachment, no thread, no
        state file — today's unsupervised behavior, bit for bit."""
        if not self.enabled or self._thread is not None:
            return self
        self.router.attach_supervisor(self)
        self._adopt_existing()
        with self._lock:
            records = list(self._records.items())
        for name, rec in records:
            if rec.state == SPAWNING and name not in self.router.replicas:
                self._spawn(rec, epoch=rec.epoch + 1, reason=None)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dervet-fleet-supervisor")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the control loop.  Replica processes are NOT touched —
        they stay registered with the router, whose ``close()`` owns
        their termination."""
        with self._lock:
            self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self.enabled:
            self._publish(force=True)

    def __enter__(self) -> "FleetSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _adopt_existing(self) -> None:
        """Bring router replicas the caller spawned themselves under
        management: every ``SpoolReplica`` without a spec gets one
        synthesized from its handle (default spawn kwargs, its spool);
        in-process ``LocalReplica``s cannot be respawned and stay
        unmanaged."""
        for name, h in list(self.router.replicas.items()):
            with self._lock:
                rec = self._records.get(name)
                if rec is None:
                    if not isinstance(h, SpoolReplica):
                        continue
                    rec = _Record(ReplicaSpec(h.spool, name=name))
                    self._records[name] = rec
                # the handle is already live: record its incarnation
                rec.state = UP if h.state == "up" else rec.state
                rec.epoch = int(h.epoch or 0)
                rec.last_spawn_mono = time.monotonic()

    # -- router death hook ----------------------------------------------
    def on_replica_dead(self, name: str, reason: str) -> None:
        """Router ``_declare_dead`` hands the corpse here AFTER fencing
        + exactly-once failover.  Schedules the respawn (with crash-loop
        backoff) or quarantines; never spawns inline — the router's
        monitor thread must not block on process launch."""
        if not self.enabled:
            return
        with self._lock:
            if self._closed:
                return
            rec = self._records.get(name)
            if rec is None or rec.state in (BACKOFF, QUARANTINED,
                                            STOPPED):
                return
            if rec.state == DRAINING:
                # scale-down victim exiting after its SIGTERM: death is
                # the drain completing, not a crash
                return
            now = time.monotonic()
            uptime = (None if rec.last_spawn_mono is None
                      else now - rec.last_spawn_mono)
            rapid = (uptime is not None
                     and uptime <= self.rapid_crash_window_s)
            rec.rapid = rec.rapid + 1 if rapid else 1
            rec.pending_reason = reason
            if rec.rapid >= max(1, self.quarantine_after):
                self._quarantine_locked(name, rec, reason)
                return
            delay = min(self.backoff_max_s,
                        self.backoff_base_s * (2.0 ** (rec.rapid - 1)))
            rec.state = BACKOFF
            rec.backoff_until = now + delay
        TellUser.warning(
            f"lifecycle: replica {name!r} died ({reason}) — respawn in "
            f"{delay:.2f}s (crash streak {rec.rapid})")
        self._span(name, "crash", reason=reason, streak=rec.rapid,
                   backoff_s=round(delay, 3))

    def _quarantine_locked(self, name: str, rec: _Record,
                           reason: str) -> None:
        rec.state = QUARANTINED
        err = ReplicaQuarantinedError(
            f"replica {name!r} quarantined after {rec.rapid} rapid "
            f"crashes (each within {self.rapid_crash_window_s:g}s of "
            f"its respawn); last reason: {reason}",
            replica=name, crashes=rec.rapid, last_reason=reason)
        rec.quarantine = err.as_dict()
        self._counters["quarantined"] += 1
        TellUser.error(f"lifecycle: {err}")
        if self.router.journal is not None:
            self.router.journal.note("replica_quarantined", name,
                                     crashes=rec.rapid, reason=reason)
        self._span(name, "quarantine", crashes=rec.rapid, reason=reason)

    def release(self, name: str) -> bool:
        """Operator override: clear a quarantined replica and respawn
        it immediately (fresh crash streak)."""
        with self._lock:
            rec = self._records.get(name)
            if rec is None or rec.state != QUARANTINED:
                return False
            rec.state = BACKOFF
            rec.backoff_until = time.monotonic()
            rec.rapid = 0
            rec.quarantine = None
            self._counters["released"] += 1
        self._span(name, "release")
        return True

    # -- spawning -------------------------------------------------------
    def _spawn(self, rec: _Record, *, epoch: int,
               reason: Optional[str]) -> Optional[ReplicaHandle]:
        name = rec.spec.name
        blob = None
        if self.warm_respawn and reason is not None:
            try:
                blob = (rec.spec.spool / MEMORY_EXPORT_FILE).read_bytes()
            except OSError:
                blob = None
        try:
            handle = rec.spec.spawn(epoch, self.spawn_fn)
        except Exception as e:
            with self._lock:
                self._counters["spawn_failures"] += 1
                rec.rapid += 1
                if rec.rapid >= max(1, self.quarantine_after):
                    self._quarantine_locked(name, rec,
                                            f"spawn failed: {e}")
                    return None
                delay = min(self.backoff_max_s, self.backoff_base_s
                            * (2.0 ** (rec.rapid - 1)))
                rec.state = BACKOFF
                rec.backoff_until = time.monotonic() + delay
            TellUser.warning(f"lifecycle: spawning {name!r} failed "
                             f"({e}) — retry in {delay:.2f}s")
            return None
        with self._lock:
            rec.epoch = int(epoch)
            rec.state = SPAWNING
            rec.last_spawn_mono = time.monotonic()
            rec.backoff_until = None
            if reason is not None:
                rec.restarts += 1
                rec.last_restart_reason = reason
                rec.last_restart_t = time.time()
                self._counters["restarts"] += 1
            handle.restarts = rec.restarts
            handle.last_restart_reason = rec.last_restart_reason
            handle.last_restart_t = rec.last_restart_t
        self.router.adopt_replica(handle)
        if blob is not None:
            try:
                handle.import_memory(blob)
                with self._lock:
                    rec.warm_imports += 1
                    self._counters["warm_imports"] += 1
            except Exception as e:
                TellUser.warning(f"lifecycle: warm-start import for "
                                 f"{name!r} failed: {e}")
        self._span(name, "respawn" if reason is not None else "spawn",
                   epoch=epoch, reason=reason, warm=blob is not None)
        if reason is not None:
            TellUser.warning(f"lifecycle: replica {name!r} respawned "
                             f"(epoch {epoch}, warm={blob is not None})")
        return handle

    # -- control loop ---------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
            try:
                self._tick()
            except Exception as e:    # the loop must survive anything
                TellUser.warning(f"lifecycle: supervisor tick failed: "
                                 f"{e}")
            time.sleep(self.tick_s)

    def _tick(self) -> None:
        self._reap_transitions()
        self._process_backoffs()
        self._process_drains()
        self._autoscale()
        self._publish()

    def _reap_transitions(self) -> None:
        """SPAWNING → UP once the router has seen the incarnation's
        first FRESH beat (its startup grace is the router's)."""
        with self._lock:
            spawning = [(n, r) for n, r in self._records.items()
                        if r.state == SPAWNING]
        for name, rec in spawning:
            h = self.router.replicas.get(name)
            if h is None:
                continue
            if h.state == "up" and \
                    self.router._first_seen.get(name) is not None:
                with self._lock:
                    if rec.state == SPAWNING:
                        rec.state = UP

    def _process_backoffs(self) -> None:
        now = time.monotonic()
        with self._lock:
            due = [(n, r) for n, r in self._records.items()
                   if r.state == BACKOFF and r.backoff_until is not None
                   and now >= r.backoff_until]
        for name, rec in due:
            self._spawn(rec, epoch=rec.epoch + 1,
                        reason=rec.pending_reason or "crash")

    # -- autoscaling ----------------------------------------------------
    def _live_names(self) -> List[str]:
        with self._lock:
            return [n for n, r in self._records.items()
                    if r.state in (SPAWNING, UP, BACKOFF)]

    def _backlogs(self) -> Dict[str, float]:
        """Per-replica backlog estimate from the router's load view:
        the replica-published queue depth + pending (the same
        ``telemetry.prom`` signal routing ranks on), falling back to
        the router's inflight count for a replica that never
        published."""
        out: Dict[str, float] = {}
        for name, view in self.router.load_snapshot().items():
            if view["state"] != "up":
                continue
            pub = view.get("published")
            if pub is not None:
                out[name] = (float(pub.get("queue_depth") or 0.0)
                             + float(pub.get("pending") or 0.0))
            else:
                out[name] = float(view.get("inflight") or 0)
        return out

    def _autoscale(self) -> None:
        backlogs = self._backlogs()
        live = self._live_names()
        now = time.monotonic()
        n_live = len(live)
        if backlogs:
            avg = sum(backlogs.values()) / max(1, len(backlogs))
        else:
            avg = 0.0
        # -- scale up on sustained pressure
        if avg >= self.scale_up_backlog and n_live < self.max_replicas:
            if self._pressure_since is None:
                self._pressure_since = now
            elif now - self._pressure_since >= self.scale_pressure_s:
                self._pressure_since = None
                self._scale_up()
        else:
            self._pressure_since = None
        # -- scale down after sustained idle (clean drain first)
        idle = bool(backlogs) and all(v <= 0.0 for v in backlogs.values())
        with self._lock:
            has_victim = any(r.scaled and r.state == UP
                             for r in self._records.values())
        if idle and n_live > self.min_replicas and has_victim:
            if self._idle_since is None:
                self._idle_since = now
            elif now - self._idle_since >= self.scale_down_idle_s:
                self._idle_since = None
                self._begin_scale_down()
        else:
            self._idle_since = None

    def _scale_up(self) -> None:
        with self._lock:
            template = next((r.spec for r in self._records.values()
                             if not r.scaled), None)
            if template is None and self._records:
                template = next(iter(self._records.values())).spec
            if template is None:
                return
            self._scale_seq += 1
            name = f"scale{self._scale_seq:02d}"
            while name in self._records:
                self._scale_seq += 1
                name = f"scale{self._scale_seq:02d}"
            root = self.spool_root or template.spool.parent
            spec = template.with_spool(root / name, name)
            rec = _Record(spec, scaled=True)
            self._records[name] = rec
            self._counters["scale_up"] += 1
        TellUser.warning(f"lifecycle: sustained backlog — scaling up "
                         f"({name!r})")
        handle = self._spawn(rec, epoch=1, reason=None)
        # warm the newcomer from any up replica's published memory
        if handle is not None and self.warm_respawn:
            for other, h in list(self.router.replicas.items()):
                if other == name or not isinstance(h, SpoolReplica):
                    continue
                blob = h.read_memory_export()
                if blob:
                    try:
                        handle.import_memory(blob)
                        with self._lock:
                            rec.warm_imports += 1
                            self._counters["warm_imports"] += 1
                    except Exception:
                        pass
                    break
        self._span(name, "scale_up")

    def _begin_scale_down(self) -> None:
        with self._lock:
            victims = [(n, r) for n, r in self._records.items()
                       if r.scaled and r.state == UP]
            if not victims:
                return
            name, rec = victims[-1]      # newest scaled replica first
            rec.state = DRAINING
            rec.drain_since = time.monotonic()
        h = self.router.replicas.get(name)
        if h is not None:
            # unroute FIRST: _eligible skips a draining handle, so no
            # new request can land in the SIGTERM window
            h.draining = True
        TellUser.warning(f"lifecycle: fleet idle — draining {name!r} "
                         "for scale-down")
        self._span(name, "scale_down_begin")

    def _process_drains(self) -> None:
        with self._lock:
            draining = [(n, r) for n, r in self._records.items()
                        if r.state == DRAINING]
        for name, rec in draining:
            h = self.router.replicas.get(name)
            if h is None:
                with self._lock:
                    rec.state = STOPPED
                continue
            inflight = self.router._inflight.get(name, 0)
            spool_busy = False
            if isinstance(h, SpoolReplica):
                try:
                    spool_busy = any(p.suffix != ".tmp" for p in
                                     h.incoming.iterdir())
                except OSError:
                    spool_busy = False
            if inflight > 0 or spool_busy:
                continue                 # clean drain: wait it out
            alive = h.alive()
            if alive:
                term = getattr(h, "terminate", None)
                if term is not None:
                    term(timeout=30.0)   # polite SIGTERM: serve drains
                continue                 # re-check liveness next tick
            if self.router.remove_replica(name):
                with self._lock:
                    rec.state = STOPPED
                    self._counters["scale_down"] += 1
                TellUser.warning(f"lifecycle: replica {name!r} drained "
                                 "clean and removed (scale-down)")
                self._span(name, "scale_down_done")

    # -- observability --------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "backoff_base_s": self.backoff_base_s,
                "quarantine_after": self.quarantine_after,
                "counters": dict(self._counters),
                "replicas": {n: r.as_dict()
                             for n, r in self._records.items()},
            }

    def _publish(self, force: bool = False) -> None:
        """State file (``supervisor_state.json``, read by `dervet-tpu
        status`) + supervisor gauges into the router's fleet telemetry
        registry, at ~1s cadence."""
        now = time.monotonic()
        if not force and now - self._publish_last < 1.0:
            return
        self._publish_last = now
        snap = self.snapshot()
        snap["t"] = round(time.time(), 3)
        if telemetry_registry.enabled():
            reg = self.router._telemetry
            c = snap["counters"]
            reg.gauge("dervet_fleet_restarts_total").set(
                float(c["restarts"]))
            reg.gauge("dervet_fleet_scale_events").set(
                float(c["scale_up"] + c["scale_down"]))
            reg.gauge("dervet_fleet_quarantined_replicas").set(
                float(sum(1 for r in snap["replicas"].values()
                          if r["state"] == QUARANTINED)))
            reg.gauge("dervet_fleet_supervised_replicas").set(
                float(sum(1 for r in snap["replicas"].values()
                          if r["state"] in (SPAWNING, UP))))
        state_dir = self.router.fleet_dir or self.spool_root
        if state_dir is not None:
            from ..utils.supervisor import atomic_write
            try:
                state_dir.mkdir(parents=True, exist_ok=True)
                atomic_write(state_dir / STATE_FILE,
                             json.dumps(snap, indent=2, default=str))
            except OSError as e:
                TellUser.warning(f"lifecycle: state publish failed: {e}")

    def _span(self, name: str, event: str, **attrs) -> None:
        """One lifecycle span per event on the per-replica
        ``lifecycle.<name>`` trace, exported (or discarded) immediately
        — same discipline as the router's probe traces, so a long-lived
        supervisor never pins spans in the collector."""
        if not telemetry_trace.enabled():
            return
        try:
            rid = f"lifecycle.{name}"
            span = telemetry_trace.start_span(
                event, trace_id=telemetry_trace.trace_id_for(rid),
                attrs={"replica": name,
                       **{k: v for k, v in attrs.items()
                          if v is not None}})
            if span:
                span.end()
            exported = None
            if self.router.fleet_dir is not None:
                exported = telemetry_trace.export_request_trace(
                    rid, self.router.fleet_dir / "traces")
            if exported is None:
                telemetry_trace.COLLECTOR.pop(
                    telemetry_trace.trace_id_for(rid))
        except Exception:               # observability must never block
            pass


# ---------------------------------------------------------------------------
# CLI: `dervet-tpu fleet` — run a supervised fleet as an ops surface
# ---------------------------------------------------------------------------

def fleet_main(argv=None) -> int:
    """``dervet-tpu fleet FLEET_DIR``: spawn and supervise an
    N-replica spool fleet until SIGTERM/SIGINT (or ``--duration-s``),
    then print the final supervisor snapshot as JSON.  Replica spools
    live under ``FLEET_DIR/replicaNN``; `dervet-tpu status FLEET_DIR`
    in another terminal shows live lifecycle columns."""
    import argparse
    import signal as _signal

    from .router import FleetRouter

    parser = argparse.ArgumentParser(
        prog="dervet-tpu fleet",
        description="run a supervised multi-replica serve fleet")
    parser.add_argument("fleet_dir", type=Path)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--min-replicas", type=int, default=None)
    parser.add_argument("--max-replicas", type=int, default=None)
    parser.add_argument("--backend", default="cpu")
    parser.add_argument("--heartbeat-s", type=float, default=0.25)
    parser.add_argument("--heartbeat-timeout-s", type=float, default=3.0)
    parser.add_argument("--max-queue-depth", type=int, default=64)
    parser.add_argument("--duration-s", type=float, default=None,
                        help="exit after this long (default: run until "
                             "SIGTERM/SIGINT)")
    args = parser.parse_args(argv)

    fleet_dir = args.fleet_dir
    fleet_dir.mkdir(parents=True, exist_ok=True)
    specs = [ReplicaSpec(fleet_dir / f"replica{i:02d}",
                         backend=args.backend,
                         heartbeat_s=args.heartbeat_s,
                         max_queue_depth=args.max_queue_depth)
             for i in range(max(1, args.replicas))]
    router = FleetRouter([], fleet_dir=fleet_dir,
                         heartbeat_timeout_s=args.heartbeat_timeout_s)
    supervisor = FleetSupervisor(
        router, specs, min_replicas=args.min_replicas,
        max_replicas=args.max_replicas, spool_root=fleet_dir)
    if not supervisor.enabled:
        TellUser.warning(f"fleet: {SUPERVISE_ENV}=0 — replicas will be "
                         "spawned once but never respawned")
        for spec in specs:
            router.adopt_replica(spec.spawn(epoch=1))
    stop = threading.Event()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        try:
            _signal.signal(sig, lambda *_: stop.set())
        except (ValueError, OSError):
            pass
    router.start()
    supervisor.start()
    t0 = time.monotonic()
    try:
        while not stop.is_set():
            if args.duration_s is not None and \
                    time.monotonic() - t0 >= args.duration_s:
                break
            stop.wait(0.25)
    finally:
        supervisor.stop()
        router.close()
    print(json.dumps(supervisor.snapshot(), indent=2, default=str))
    return 0
