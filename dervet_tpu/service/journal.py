"""Crash-safe service journal: the serve loop's append-only ledger.

The PR-2 drain path covers the POLITE kill (SIGTERM: close admissions,
finish or checkpoint the round, flush manifests, exit 0).  A SIGKILL /
OOM / power loss gets none of that — so the serve loop journals every
spool admission and completion to an append-only JSONL file (one
``write + flush + fsync`` per event; a torn final line from a crash
mid-append is tolerated and ignored on replay).  On startup the loop
replays the journal and reconciles the spool:

* ``admitted`` with no terminal event + input file still in
  ``incoming/`` — the round died with the request in flight; the normal
  scan re-serves it, and solved-window checkpoints bound the re-work.
  Results are re-written atomically, so recovery is idempotent.
* ``completed``/``failed`` but the input file still in ``incoming/`` —
  the kill landed between recording the outcome and moving the file;
  the file is moved to its terminal directory WITHOUT re-serving.

Event order in the happy path is deliberate: results are persisted
first, THEN ``completed`` is journaled, THEN the input file moves — so
at every kill point the journal either under-claims (re-serve, idempotent)
or exactly matches the spool, never over-claims a result that does not
exist on disk.
"""
from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ..utils.errors import TellUser

TERMINAL_EVENTS = ("completed", "failed")
# a hedge loser retracted before admission (fleet router cancel): not a
# terminal answer, but recovery must finish its input-file removal, not
# re-serve it
CANCELLED_EVENT = "cancelled"


class ServiceJournal:
    """Append-only admissions/completions journal for one spool."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        # line-buffered append handle held for the process life; every
        # event fsyncs so the journal survives a SIGKILL mid-round
        self._fh = open(self.path, "a", encoding="utf-8")

    # ------------------------------------------------------------------
    def _append(self, event: str, rid: str, **extra) -> None:
        # wall + MONOTONIC timestamp pair: replay/`dervet-tpu trace` can
        # order pre-crash events robustly (mono never steps backwards
        # within one process incarnation) while the wall time anchors
        # them against other processes' traces.  Readers tolerate
        # records without these fields (pre-PR-14 journals).
        rec = {"event": event, "rid": str(rid), "t": round(time.time(), 3),
               "mono": round(time.monotonic(), 6),
               **{k: v for k, v in extra.items() if v is not None}}
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())

    def admitted(self, rid: str, file: Optional[str] = None,
                 trace_id: Optional[str] = None) -> None:
        self._append("admitted", rid, trace_id=trace_id,
                     **({"file": str(file)} if file else {}))

    def completed(self, rid: str,
                  trace_id: Optional[str] = None) -> None:
        self._append("completed", rid, trace_id=trace_id)

    def failed(self, rid: str, error: Optional[Dict] = None,
               trace_id: Optional[str] = None) -> None:
        self._append("failed", rid, trace_id=trace_id,
                     **({"error": error} if error else {}))

    def note(self, event: str, rid: str, **extra) -> None:
        """Journal an arbitrary event (fsync'd like the rest).  The
        fleet layer uses this for its routing ledger (``routed`` /
        ``rerouted`` / ``hedged`` / ``cancelled``) on top of the three
        spool events above."""
        self._append(str(event), rid, **extra)

    def close(self) -> None:
        with self._lock:
            self._fh.close()

    # ------------------------------------------------------------------
    @staticmethod
    def replay_path(path) -> Dict[str, Dict]:
        """Read-only replay of a journal file that may belong to ANOTHER
        process (the fleet router inspecting a dead replica's spool) —
        no append handle is opened, so this never touches the file."""
        out: Dict[str, Dict] = {}
        path = Path(path)
        if not path.exists():
            return out
        for line in path.read_text(encoding="utf-8").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue        # torn tail from a hard kill: ignore
            rid = str(rec.get("rid"))
            entry = out.setdefault(rid, {"state": None, "file": None})
            event = rec.get("event")
            # terminal states are FINAL: a non-terminal note appended
            # after completed/failed (a late hedge/reroute record, a
            # request-cache annotation) must not resurrect the rid into
            # a replayable state — recovery would re-serve an already
            # answered request.  Cancellation may still supersede (the
            # retract-vs-answer race resolves toward the cancel record,
            # which only finishes a file removal).
            if entry["state"] in TERMINAL_EVENTS and \
                    event not in TERMINAL_EVENTS + (CANCELLED_EVENT,):
                pass
            else:
                entry["state"] = event
            if rec.get("file"):
                entry["file"] = rec["file"]
            if rec.get("trace_id"):
                # pre-crash timeline reconstruction (telemetry/ops.py)
                entry["trace_id"] = rec["trace_id"]
        return out

    def replay(self) -> Dict[str, Dict]:
        """Reconstruct each request id's LAST journaled state:
        ``rid -> {"state": admitted|completed|failed, "file": ...}``.
        A torn final line (crash mid-append) is skipped, not fatal."""
        with self._lock:
            self._fh.flush()
        return self.replay_path(self.path)

    def unfinished(self) -> List[Tuple[str, Optional[str]]]:
        """Request ids admitted but never terminal — the set a restarted
        serve loop must recover."""
        return [(rid, e.get("file")) for rid, e in self.replay().items()
                if e["state"] == "admitted"]

    # ------------------------------------------------------------------
    def recover_spool(self, incoming: Path, done_dir: Path,
                      failed_dir: Optional[Path] = None) -> Dict:
        """Post-SIGKILL reconciliation (called at serve startup).

        Returns ``{"reserve": [rids...], "moved": [rids...]}``:
        ``reserve`` are admitted-but-unanswered requests whose input
        files still sit in ``incoming/`` — the scan loop re-serves them
        (idempotently: results re-write atomically, checkpoints bound
        re-work); ``moved`` are terminal requests whose file move was
        lost to the kill — finished now (``completed`` -> ``done/``,
        ``failed`` -> ``failed/``), NOT re-served."""
        reserve: List[str] = []
        moved: List[str] = []
        for rid, entry in self.replay().items():
            fname = entry.get("file")
            src = (incoming / fname) if fname else None
            if src is None or not src.exists():
                continue
            if entry["state"] == "admitted":
                reserve.append(rid)
            elif entry["state"] == CANCELLED_EVENT:
                # kill landed between journaling the cancel and removing
                # the input: finish the removal, never re-serve a
                # retracted hedge loser
                try:
                    src.unlink()
                except FileNotFoundError:
                    pass
            elif entry["state"] in TERMINAL_EVENTS:
                # a journaled FAILURE must not be misfiled as a success
                target = (failed_dir if entry["state"] == "failed"
                          and failed_dir is not None else done_dir)
                try:
                    src.replace(target / src.name)
                except FileNotFoundError:
                    # a CONCURRENT recovery (router failover firing while
                    # the replica restarts) won the move between our
                    # exists() check and the replace — the outcome is the
                    # same file in the same terminal directory, so the
                    # race is benign; claiming the move twice is not
                    continue
                moved.append(rid)
        if reserve or moved:
            TellUser.warning(
                f"serve: journal recovery after hard kill — "
                f"{len(reserve)} unanswered request(s) will be "
                f"re-served, {len(moved)} completed file move(s) "
                "replayed")
        return {"reserve": reserve, "moved": moved}
