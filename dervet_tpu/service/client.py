"""Client-side conveniences for the scenario service.

The service is in-process (a network front end would wrap
:class:`~dervet_tpu.service.server.ScenarioService` behind whatever
transport a deployment uses); this module provides the client-side
discipline such a front end needs anyway: retry-after handling for
backpressure rejections and a blocking solve wrapper.
"""
from __future__ import annotations

import time
from concurrent.futures import Future
from typing import Optional

from ..utils.errors import TellUser
from .queue import QueueFullError


class ScenarioClient:
    """Thin client over a :class:`ScenarioService`.

    ``submit`` honors the service's backpressure contract: a
    :class:`~dervet_tpu.service.queue.QueueFullError` carries a
    ``retry_after_s`` hint, and the client sleeps it out and retries up
    to ``max_retries`` times before surfacing the rejection — the
    behavior every caller of a loaded service needs and nobody should
    hand-roll."""

    def __init__(self, service, max_retries: int = 3,
                 backoff_cap_s: float = 30.0):
        self.service = service
        self.max_retries = int(max_retries)
        self.backoff_cap_s = float(backoff_cap_s)

    def submit(self, cases, *, request_id=None, priority: int = 0,
               deadline_s: Optional[float] = None) -> Future:
        """Admit with bounded retry-after backoff on queue-full."""
        attempt = 0
        while True:
            try:
                return self.service.submit(cases, request_id=request_id,
                                           priority=priority,
                                           deadline_s=deadline_s)
            except QueueFullError as e:
                attempt += 1
                if attempt > self.max_retries:
                    raise
                wait = min(e.retry_after_s, self.backoff_cap_s)
                TellUser.info(
                    f"client: queue full, retry {attempt}/"
                    f"{self.max_retries} in {wait:.2f}s")
                time.sleep(wait)

    def solve(self, cases, *, timeout: Optional[float] = None,
              **kwargs):
        """Submit and block for the request's
        :class:`~dervet_tpu.results.result.Result`."""
        return self.submit(cases, **kwargs).result(timeout=timeout)
