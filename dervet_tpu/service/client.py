"""Client-side conveniences for the scenario service.

The service is in-process (a network front end would wrap
:class:`~dervet_tpu.service.server.ScenarioService` behind whatever
transport a deployment uses); this module provides the client-side
discipline such a front end needs anyway: retry-after handling for
backpressure rejections and a blocking solve wrapper.
"""
from __future__ import annotations

import inspect
import pickle
import random
import time
from concurrent.futures import Future
from typing import Optional

from ..utils.errors import TellUser
from .queue import QueueFullError


class ScenarioClient:
    """Thin client over a :class:`ScenarioService` — or a
    :class:`~dervet_tpu.service.router.FleetRouter`, which exposes the
    same ``submit`` surface.

    ``submit`` honors the backpressure contract end-to-end: a
    :class:`~dervet_tpu.service.queue.QueueFullError` carries a
    ``retry_after_s`` hint (derived from the service's observed drain
    rate), and the client sleeps it out — CAPPED and JITTERED — and
    retries up to ``max_retries`` times before surfacing the rejection.
    Router redirects preserve the discipline: when every replica behind
    a fleet router rejects, the router raises
    :class:`~dervet_tpu.utils.errors.FleetUnavailableError` — a
    ``QueueFullError`` whose ``retry_after_s`` is the SMALLEST hint any
    replica offered — so the per-replica drain-rate hint survives the
    routing hop and the same capped ±25% backoff applies unchanged.
    The jitter matters at fleet scale: a burst of rejected clients all
    honoring the same hint verbatim would re-arrive in one synchronized
    spike and re-overload the fleet they just backed off from."""

    def __init__(self, service, max_retries: int = 3,
                 backoff_cap_s: float = 30.0, jitter_frac: float = 0.25,
                 jitter_seed: Optional[int] = None):
        self.service = service
        self.max_retries = int(max_retries)
        self.backoff_cap_s = float(backoff_cap_s)
        self.jitter_frac = float(jitter_frac)
        # seedable so drills/tests are deterministic
        self._rng = random.Random(jitter_seed)

    def _backoff_s(self, hint: float) -> float:
        """Cap the server's hint, then jitter ±jitter_frac around it."""
        wait = min(float(hint), self.backoff_cap_s)
        if self.jitter_frac > 0:
            wait *= 1.0 + self._rng.uniform(-self.jitter_frac,
                                            self.jitter_frac)
        return max(0.0, wait)

    def _submit_with_retry(self, label: str, attempt_fn) -> Future:
        """The one retry discipline every request type shares: bounded
        attempts, capped ±jittered backoff on the server's retry-after
        hint (see class docstring)."""
        attempt = 0
        while True:
            try:
                return attempt_fn()
            except QueueFullError as e:
                attempt += 1
                if attempt > self.max_retries:
                    raise
                wait = self._backoff_s(e.retry_after_s)
                TellUser.info(
                    f"client: queue full, {label}retry {attempt}/"
                    f"{self.max_retries} in {wait:.2f}s")
                time.sleep(wait)

    def submit(self, cases, *, request_id=None, priority: int = 0,
               deadline_s: Optional[float] = None) -> Future:
        """Admit with bounded, jittered retry-after backoff on
        queue-full.

        Serialize ONCE: against a fleet router, the case payload is
        pickled and content-digested here, before the retry loop, and
        the same bytes/digest ride every attempt — a queue-full storm
        used to re-pickle the full payload per attempt (and the router
        needs the digest for its request-cache key anyway)."""
        kwargs = {}
        try:
            params = inspect.signature(self.service.submit).parameters
        except (TypeError, ValueError):
            params = {}
        if "cases_blob" in params and "content_digest" in params:
            if not isinstance(cases, dict):
                cases = dict(enumerate(cases))
            try:
                from . import reqcache
                kwargs["cases_blob"] = pickle.dumps(
                    cases, protocol=pickle.HIGHEST_PROTOCOL)
                kwargs["content_digest"] = \
                    reqcache.request_content_digest(cases)
            except Exception:       # fall back to the plain path
                kwargs = {}
        return self._submit_with_retry(
            "", lambda: self.service.submit(cases,
                                            request_id=request_id,
                                            priority=priority,
                                            deadline_s=deadline_s,
                                            **kwargs))

    def solve(self, cases, *, timeout: Optional[float] = None,
              **kwargs):
        """Submit and block for the request's
        :class:`~dervet_tpu.results.result.Result`.  Check
        ``result.fidelity`` — a ``"degraded"`` answer was load-shed to
        the screening tier and should be resubmitted (see
        ``result.resubmit_hint``) when a certified answer is needed."""
        return self.submit(cases, **kwargs).result(timeout=timeout)

    def submit_design(self, case, spec=None, *, request_id=None,
                      priority: int = 0,
                      deadline_s: Optional[float] = None,
                      **spec_kwargs) -> Future:
        """Admit a DESIGN request (BOOST sizing frontier) with the same
        bounded, jittered retry-after backoff as :meth:`submit`."""
        return self._submit_with_retry(
            "design ", lambda: self.service.submit_design(
                case, spec, request_id=request_id, priority=priority,
                deadline_s=deadline_s, **spec_kwargs))

    def design(self, case, spec=None, *,
               timeout: Optional[float] = None, **kwargs):
        """Submit a design request and block for its
        :class:`~dervet_tpu.design.frontier.DesignFrontier`.  Check
        ``frontier.fidelity`` — a ``"degraded"`` frontier was load-shed
        and is ranked by the ordinal screen only (no certificates)."""
        return self.submit_design(case, spec, **kwargs).result(
            timeout=timeout)

    def submit_montecarlo(self, case, spec=None, *, request_id=None,
                          priority: int = 0,
                          deadline_s: Optional[float] = None,
                          **spec_kwargs) -> Future:
        """Admit a MONTE-CARLO request (batched uncertainty valuation)
        with the same bounded, jittered retry-after backoff as
        :meth:`submit`."""
        return self._submit_with_retry(
            "montecarlo ", lambda: self.service.submit_montecarlo(
                case, spec, request_id=request_id, priority=priority,
                deadline_s=deadline_s, **spec_kwargs))

    def montecarlo(self, case, spec=None, *,
                   timeout: Optional[float] = None, **kwargs):
        """Submit a monte-carlo request and block for its
        :class:`~dervet_tpu.stochastic.distribution.MCDistribution`.
        Check ``result.fidelity`` — a ``"degraded"`` distribution was
        load-shed to a reduced screening-tier sample set and carries no
        certificates."""
        return self.submit_montecarlo(case, spec, **kwargs).result(
            timeout=timeout)

    def submit_portfolio(self, spec, *, request_id=None,
                         priority: int = 0,
                         deadline_s: Optional[float] = None) -> Future:
        """Admit a PORTFOLIO request (coupled-fleet co-optimization)
        with the same bounded, jittered retry-after backoff as
        :meth:`submit`."""
        return self._submit_with_retry(
            "portfolio ", lambda: self.service.submit_portfolio(
                spec, request_id=request_id, priority=priority,
                deadline_s=deadline_s))

    def portfolio(self, spec, *, timeout: Optional[float] = None,
                  **kwargs):
        """Submit a portfolio request and block for its
        :class:`~dervet_tpu.portfolio.solve.PortfolioResult`.  Check
        ``result.fidelity`` — a ``"degraded"`` answer was load-shed to
        the screening tier and carries no certificates."""
        return self.submit_portfolio(spec, **kwargs).result(
            timeout=timeout)
