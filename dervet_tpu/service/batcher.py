"""Continuous batcher: coalesce queued requests into one dispatch round.

One round takes every request the admission queue handed over, builds all
their cases' scenarios, and runs a SINGLE ``run_dispatch`` over the union
— the existing structure-key grouping then batches windows ACROSS
requests exactly as it batches sensitivity cases, so a 1-case request
arriving next to a 32-case request rides the big request's device batches
for free.  Everything downstream is the existing stack, reused rather
than forked: solves go through the PR-3 overlapped pipeline, failures
climb the PR-1 escalation ladder, every window is PR-4 certified, and a
SIGTERM lands in the PR-2 supervisor's graceful-drain path with per-case
checkpoints plus per-request manifests flushed.

Per-request isolation: case ids are namespaced ``<request_id>.<key>`` so
checkpoints/manifest entries cannot collide across requests, each request
gets its own run-health report and solve-ledger slice, and one request's
total failure (all cases quarantined) answers THAT request with a typed
error while the round's other requests complete normally.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from ..io.summary import run_health_report
from ..ops.certify import aggregate_audits
from ..results.result import Result
from ..scenario.scenario import MicrogridScenario, run_dispatch
from ..telemetry import trace as telemetry_trace
from ..utils.errors import (AggregatedSolverError, PoisonRequestError,
                            PreemptedError, TellUser)
from . import resilience
from .queue import (DeadlineExpiredError, QueuedRequest,
                    RequestFailedError, RequestPreemptedError,
                    ServiceError)

# per-request ledger slices aggregate these numeric fields over the
# request's groups (a subset of the full ledger's totals: only what is
# attributable to a single request — shared round-level walls stay under
# ``round`` below)
_SLICE_SUM_KEYS = ("solve_s", "stack_s", "h2d_s", "sync_wait_s",
                   "result_fetch_s", "h2d_bytes", "result_bytes",
                   "dispatches", "chunks", "compile_events")


def slice_request_ledger(ledger: Optional[Dict], request_id: str,
                         n_windows: Optional[int] = None
                         ) -> Optional[Dict]:
    """A request's view of the round's solve ledger: the per-group
    entries whose batch carried this request's windows (tagged by
    ``resolve_group`` via ``meta['requests']``), their summed line items,
    and the shared round totals for context.  Escalation-rung entries
    (retry / cpu_fallback) carry no request tag and stay round-level.
    The summed line items cover the SHARED groups the request rode —
    ``totals.batched_windows`` is that co-batched total, ``windows`` the
    request's own count."""
    if ledger is None:
        return None
    rid = str(request_id)
    groups = [g for g in ledger.get("groups", ())
              if rid in (g.get("requests") or ())]
    totals = {k: round(sum(float(g.get(k, 0)) for g in groups), 4)
              for k in _SLICE_SUM_KEYS}
    totals["batched_windows"] = sum(int(g.get("batch", 0)) for g in groups
                                    if g.get("rung") in (None, "initial"))
    if n_windows is not None:
        totals["windows"] = int(n_windows)
    return {
        "request_id": rid,
        "groups": groups,
        "totals": totals,
        # groups whose batch mixed several requests: the cross-request
        # coalescing observable (windows this request amortized against
        # other requests' batches)
        "coalesced_groups": sum(1 for g in groups
                                if len(g.get("requests") or ()) > 1),
        "round": {k: ledger.get(k) for k in
                  ("dispatch_solve_s", "pipeline", "max_inflight")},
        "round_totals": ledger.get("totals"),
    }


def build_request_result(req: QueuedRequest,
                         scenarios: Dict[object, MicrogridScenario],
                         ledger: Optional[Dict],
                         fidelity: str = resilience.FIDELITY_FULL,
                         breakers: Optional[Dict] = None) -> Result:
    """Assemble one request's :class:`Result` from its solved scenarios —
    the same collection path as ``api.DERVET.solve``'s tail (results
    registry, run-health report, invariant audit, sensitivity summary),
    scoped to the request.  Raises :class:`RequestFailedError` when every
    case quarantined.

    ``fidelity`` marks the answer tier: a load-shed ``"degraded"``
    screening answer carries the mark in the Result AND its run-health
    report, plus a resubmit hint — and is never certificate-stamped
    (certification is disabled for the degraded dispatch).  ``breakers``
    (the service board's snapshot) rides the run-health report so a
    request served during a tripped-breaker episode says so."""
    results = Result.initialize(req.cases)
    results.request_id = req.request_id
    report = run_health_report(
        {key: getattr(s, "health", {}) for key, s in scenarios.items()},
        {key: s.quarantine for key, s in scenarios.items()
         if s.quarantine is not None},
        certification_by_case={key: getattr(s, "certification", None)
                               for key, s in scenarios.items()})
    report["fidelity"] = fidelity
    if breakers:
        report["breakers"] = breakers
    results.fidelity = fidelity
    if fidelity == resilience.FIDELITY_DEGRADED:
        results.resubmit_hint = (
            "degraded-fidelity screening answer (service was shedding "
            "load): no certificate was issued — resubmit with a higher "
            "priority for a certified answer")
    results.run_health = report
    if all(s.quarantine is not None for s in scenarios.values()):
        raise RequestFailedError(
            {key: s.quarantine["reason"] for key, s in scenarios.items()})
    for key, s in scenarios.items():
        if s.quarantine is not None:
            TellUser.error(
                f"request {req.request_id}: case {key} excluded from "
                f"results (quarantined): {s.quarantine['reason']}")
            continue
        results.add_instance(key, s)
    audit = aggregate_audits(
        {key: getattr(inst, "invariant_audit", None)
         for key, inst in results.instances.items()})
    report["invariant_audit"] = audit
    if not audit["ok"]:
        TellUser.warning(
            f"request {req.request_id}: invariant audit FAILED for "
            f"case(s) {sorted(audit['failing'])}")
    results.sensitivity_summary()
    results.solve_ledger = slice_request_ledger(
        ledger, req.request_id,
        n_windows=sum(len(s.windows) for s in scenarios.values()))
    return results


class BatchRound:
    """One coalesced dispatch round over a list of admitted requests.

    ``on_stats(round)`` fires once per round, after the ledger/stats are
    final but BEFORE any request future resolves — so a client that
    wakes on ``fut.result()`` can immediately read service metrics and
    ``last_round_ledger`` without racing the bookkeeping."""

    def __init__(self, requests: List[QueuedRequest], *, backend: str,
                 solver_opts=None, solver_cache=None, supervisor=None,
                 checkpoint_dir=None, on_stats=None,
                 gc_checkpoints: bool = True, board=None, recovery=None,
                 poison_registry=None, degraded: bool = False):
        self.requests = requests
        self.backend = backend
        self.solver_opts = solver_opts
        self.solver_cache = solver_cache
        self.supervisor = supervisor
        # degraded rounds get NO checkpoint namespace: a checkpoint only
        # records case content (not solver fidelity), so a loose
        # screening solution persisted here would be reloaded verbatim
        # by a later CERTIFIED resume of the same request id and shipped
        # with a certified stamp — the exact integrity hole the
        # degraded tier must never open.  Screening solves are cheap;
        # they replay from scratch instead of resuming.
        self.checkpoint_dir = None if degraded else checkpoint_dir
        self.on_stats = on_stats
        # a persistent service must not grow one checkpoint set per
        # request served forever: a successfully DELIVERED request's
        # npz checkpoints + manifest slice are garbage-collected (their
        # resume value is spent); failed/preempted requests keep theirs
        self.gc_checkpoints = bool(gc_checkpoints)
        # resilience layer (all optional — a bare BatchRound behaves
        # exactly like the pre-resilience one):
        # breaker board gating the escalation-ladder rungs + the round's
        # certify-storm backend override
        self.board = board
        # backend-loss recovery policy (teardown/re-init/failover)
        self.recovery = recovery
        # two-strike poison-request registry for crash attribution
        self.poison_registry = poison_registry
        # degraded tier: loose-tolerance short-budget screening solve,
        # certification off, results explicitly marked
        self.degraded = bool(degraded)
        # per-request scenario maps, built in run(); round observables
        self.scenarios: Dict[str, Dict[object, MicrogridScenario]] = {}
        self.ledger: Optional[Dict] = None
        self.stats: Dict[str, object] = {}
        self.preempted = False
        # what the round ACTUALLY dispatched on (breaker override /
        # backend-loss failover may differ from the service backend)
        self.backend_used = backend
        # requests answered during batch assembly (expired / duplicate
        # id / assembly failure) — kept so the service's request
        # accounting still covers them
        self.answered_early: List[QueuedRequest] = []
        # telemetry: per-request batch_round spans (ended in
        # _finish_stats, which every exit path reaches exactly once)
        self._round_spans: Dict[str, object] = {}

    # ------------------------------------------------------------------
    def _build_scenarios(self) -> List[MicrogridScenario]:
        """Construct every live request's scenarios (namespaced case
        ids); a request whose assembly raises is answered with that
        error and dropped from the round — it cannot poison the batch."""
        all_scens: List[MicrogridScenario] = []
        live: List[QueuedRequest] = []
        for req in self.requests:
            if req.expired():
                req.future.set_exception(DeadlineExpiredError(
                    f"request {req.request_id!r} expired before its "
                    "batch was assembled"))
                self.answered_early.append(req)
                continue
            if req.request_id in self.scenarios:
                # same-id requests in one round would cross-wire results
                # (scenario maps, checkpoints, manifests are all keyed by
                # request id) — the service rejects duplicates at
                # admission; this guards direct queue users too
                req.future.set_exception(ServiceError(
                    f"duplicate request id {req.request_id!r} in one "
                    "batch round"))
                self.answered_early.append(req)
                continue
            try:
                scens: Dict[object, MicrogridScenario] = {}
                for key, case in req.cases.items():
                    namespaced = dataclasses.replace(
                        case, case_id=f"{req.request_id}.{key}")
                    s = MicrogridScenario(namespaced)
                    s.request_id = req.request_id
                    scens[key] = s
            except Exception as e:      # bad inputs fail only this request
                TellUser.error(f"request {req.request_id}: scenario "
                               f"assembly failed: {e}")
                req.future.set_exception(e)
                self.answered_early.append(req)
                continue
            self.scenarios[req.request_id] = scens
            all_scens.extend(scens.values())
            live.append(req)
        self.requests = live
        return all_scens

    def _write_one_manifest(self, req: QueuedRequest) -> None:
        if not self.checkpoint_dir:
            return
        from ..utils import supervisor as _sup
        scens = self.scenarios.get(req.request_id)
        if scens:
            _sup.write_manifest(self.checkpoint_dir,
                                list(scens.values()), self.backend,
                                request_id=req.request_id)

    def _write_request_manifests(self) -> None:
        """Flush one namespaced resume manifest per live request (the
        drain path: preserved so resubmission resumes)."""
        for req in self.requests:
            self._write_one_manifest(req)

    def _gc_request_artifacts(self, req: QueuedRequest) -> None:
        """Drop a successfully delivered request's on-disk resume
        material — its value is spent, and a hot service would otherwise
        accumulate one checkpoint set per request forever."""
        if not (self.checkpoint_dir and self.gc_checkpoints):
            return
        import contextlib
        from ..utils.supervisor import manifest_path
        for s in self.scenarios.get(req.request_id, {}).values():
            with contextlib.suppress(OSError):
                s._checkpoint_path(self.checkpoint_dir).unlink(
                    missing_ok=True)
        with contextlib.suppress(OSError):
            manifest_path(self.checkpoint_dir,
                          req.request_id).unlink(missing_ok=True)

    def _emit_stats(self) -> None:
        if self.on_stats is not None:
            try:
                self.on_stats(self)
            except Exception:
                pass    # bookkeeping must never break delivery

    # ------------------------------------------------------------------
    def _opts(self):
        """The round's solver options — the BOOST-style loose-tolerance
        short-budget screening options when this is a degraded round."""
        if not self.degraded:
            return self.solver_opts
        from ..ops.pdhg import PDHGOptions
        return PDHGOptions.screening(self.solver_opts)

    def _dispatch(self, all_scens, backend: str) -> None:
        """One dispatch attempt.  Degraded rounds run with the float64
        certification layer disabled — their screening solutions are
        honest best-effort estimates, and a certificate would reject
        every one and climb the full ladder, defeating the shed."""
        import contextlib
        ctx = (resilience.certification_disabled() if self.degraded
               else contextlib.nullcontext())
        with ctx:
            run_dispatch(all_scens, backend=backend,
                         solver_opts=self._opts(),
                         checkpoint_dir=self.checkpoint_dir,
                         supervisor=self.supervisor,
                         solver_cache=self.solver_cache,
                         breaker_board=self.board)

    def _rebuild_scenarios(self) -> List[MicrogridScenario]:
        """Fresh scenario objects for the live requests (a replay after
        backend loss must not reuse state a dying dispatch half-mutated;
        already-solved windows reload from their checkpoints)."""
        self.scenarios = {}
        return self._build_scenarios()

    def run(self) -> None:
        """Dispatch the round and deliver every request's future.

        Raises :class:`~dervet_tpu.utils.errors.PreemptedError` after
        answering the in-flight requests with
        :class:`RequestPreemptedError` (manifests flushed) — the server
        loop treats that as the drain signal.

        Failure handling beyond the PR-5 baseline: a dispatch crash
        classified as BACKEND LOSS tears down and re-initializes the
        backend, replays the round from checkpoints, and fails over to
        the exact CPU backend after N consecutive re-init failures; any
        other unexpected crash with a poison registry attached runs the
        ISOLATION protocol — each request re-dispatched alone, crashes
        attributed and struck, two strikes = typed PoisonRequestError +
        fingerprint blocklist — so one poisonous request never takes its
        co-batched innocents down with it."""
        t0 = time.monotonic()
        backend = self.backend
        if self.board is not None and backend != "cpu" and \
                self.board.is_open("certify"):
            # certification-rejection storm: the accelerated path's data
            # handling is suspect — serve this round from the exact CPU
            # solver (the healthy rung) until a probe heals the breaker
            TellUser.warning(
                "service: certify breaker OPEN — routing this round to "
                "the exact CPU backend")
            backend = "cpu"
        self.backend_used = backend
        all_scens = self._build_scenarios()
        self._start_round_spans(breaker_reroute=(backend != self.backend))
        if not all_scens:
            self._finish_stats(all_scens, t0)
            self._emit_stats()
            return
        try:
            # replay loop: backend losses re-init + replay (bounded by
            # the recovery policy's failover); other errors fall through
            # to the except arms below on the LAST attempt
            replays = 0
            max_replays = 0
            if self.recovery is not None:
                self.recovery.begin_round()
                max_replays = self.recovery.max_reinits + 2
            while True:
                try:
                    self._dispatch(all_scens, backend)
                    break
                except Exception as e:
                    if self.recovery is None or replays >= max_replays \
                            or not resilience.is_backend_loss(e):
                        raise
                    replays += 1
                    self.recovery.note_loss()
                    TellUser.error(
                        f"service: backend loss mid-round ({e}) — "
                        "tearing down and re-initializing")
                    reinited = False
                    while not reinited and \
                            not self.recovery.should_failover():
                        reinited = self.recovery.reinit(self.solver_cache)
                    if not reinited:
                        if backend == self.recovery.failover_backend:
                            raise   # already on the failover backend
                        self.recovery.failovers += 1
                        backend = self.recovery.failover_backend
                        self.backend_used = backend
                        TellUser.error(
                            f"service: {self.recovery.max_reinits} "
                            "consecutive re-init failures — failing this "
                            f"round over to the {backend!r} backend")
                    # fresh scenario objects; solved windows reload from
                    # the PR-2 checkpoints, so replay work is bounded
                    all_scens = self._rebuild_scenarios()
                    if not all_scens:
                        self._finish_stats(all_scens, t0)
                        self._emit_stats()
                        return
        except PreemptedError as e:
            # run_dispatch already flushed per-case checkpoints + the
            # shared sweep manifest; add the per-request slices, then
            # answer every in-flight future with the typed, resumable
            # preemption error
            self.preempted = True
            self._write_request_manifests()
            self._finish_stats(all_scens, t0)
            self._emit_stats()
            from ..utils.supervisor import manifest_path
            for req in self.requests:
                if not req.future.done():
                    req.future.set_exception(RequestPreemptedError(
                        f"request {req.request_id!r} preempted mid-"
                        f"dispatch ({e}); resubmit with the same request "
                        "id and checkpoint directory to resume",
                        manifest_path=(manifest_path(self.checkpoint_dir,
                                                     req.request_id)
                                       if self.checkpoint_dir else None)))
            raise
        except AggregatedSolverError:
            # every case of every request quarantined: answer each
            # request with ITS slice of the diagnoses; the service stays
            # up (the error is data-shaped, not service-shaped)
            self.ledger = all_scens[0].solve_metadata.get("solve_ledger")
            self._finish_stats(all_scens, t0)
            self._emit_stats()
            for req in self.requests:
                self._write_one_manifest(req)   # keep resume material
                scens = self.scenarios[req.request_id]
                req.future.set_exception(RequestFailedError(
                    {key: (s.quarantine or {}).get("reason")
                     for key, s in scens.items()}))
            return
        except Exception as e:
            if self.poison_registry is not None:
                # unexpected crash with attribution machinery attached:
                # run the isolation protocol — each request re-dispatched
                # ALONE so innocents complete and the poisonous request
                # is struck, quarantined, and blocklisted
                self._finish_stats(all_scens, t0)
                self._emit_stats()
                self._isolate_poison(e, backend)
                return
            # an unexpected dispatch error (device OOM, driver bug) must
            # still ANSWER every in-flight future — a leaked unresolved
            # future hangs its client forever — before propagating to
            # the service loop for logging
            self._finish_stats(all_scens, t0)
            self._emit_stats()
            for req in self.requests:
                if not req.future.done():
                    req.future.set_exception(e)
            raise
        self.ledger = all_scens[0].solve_metadata.get("solve_ledger")
        self._finish_stats(all_scens, t0)
        self._emit_stats()
        for req in self.requests:
            self._deliver(req, self.scenarios[req.request_id], self.ledger)

    def _deliver(self, req: QueuedRequest, scens, ledger) -> None:
        """Build and deliver one request's result (or its typed
        failure), with the round's fidelity mark and breaker states.

        Design requests deliver a
        :class:`~dervet_tpu.design.frontier.DesignFrontier` instead of a
        scenario :class:`Result`: their ``scens`` are the screened
        finalists' certified solves, and the screening state carried on
        the request supplies the population surface and ordinal ranks."""
        try:
            if req.kind == "design" and req.design_state is not None:
                from ..design.service import finalize_service_request
                frontier = finalize_service_request(
                    req, scens, ledger,
                    breakers=(self.board.snapshot()
                              if self.board is not None else None))
                frontier.request_latency_s = \
                    time.monotonic() - req.t_submit
                req.future.set_result(frontier)
                self._gc_request_artifacts(req)
                return
            results = build_request_result(
                req, scens, ledger,
                fidelity=(resilience.FIDELITY_DEGRADED if self.degraded
                          else resilience.FIDELITY_FULL),
                breakers=(self.board.snapshot()
                          if self.board is not None else None))
            results.request_latency_s = time.monotonic() - req.t_submit
            req.future.set_result(results)
            self._gc_request_artifacts(req)
        except Exception as e:      # post failure stays per-request
            if not isinstance(e, RequestFailedError):
                TellUser.error(f"request {req.request_id}: result "
                               f"collection failed: {e}")
            self._write_one_manifest(req)   # keep resume material
            req.future.set_exception(e)

    # ------------------------------------------------------------------
    # Poison-request isolation
    # ------------------------------------------------------------------
    def _isolate_poison(self, batch_exc: Exception, backend: str) -> None:
        """Attribution protocol after an unexpected round crash: each
        live request re-dispatches ALONE (fresh scenarios; solved windows
        reload from checkpoints).  Innocent requests complete normally;
        a request whose solo dispatch crashes is STRUCK in the registry
        — at two strikes it is quarantined with a typed
        :class:`PoisonRequestError` (diagnosis attached) and its
        fingerprint blocklisted, so resubmission is rejected fast at
        admission instead of re-crashing another co-batched round."""
        registry = self.poison_registry
        TellUser.error(
            f"service: round with {len(self.requests)} request(s) "
            f"crashed unexpectedly ({batch_exc}) — isolating: each "
            "request re-dispatches alone for crash attribution")
        for req in self.requests:
            if req.future.done():
                continue
            fp = req.fingerprint or resilience.request_fingerprint(
                req.cases)
            delivered = False
            while not delivered:
                try:
                    self._solo_dispatch(req, backend)
                    delivered = True
                except PreemptedError as pe:
                    # drain signal mid-isolation: every still-unanswered
                    # request (this one AND the not-yet-isolated rest)
                    # gets the typed resumable answer before the signal
                    # propagates — a leaked unresolved future hangs its
                    # client forever
                    self.preempted = True
                    self._write_request_manifests()
                    from ..utils.supervisor import manifest_path
                    for r in self.requests:
                        if not r.future.done():
                            r.future.set_exception(RequestPreemptedError(
                                f"request {r.request_id!r} preempted "
                                f"during crash isolation ({pe}); "
                                "resubmit with the same request id and "
                                "checkpoint directory to resume",
                                manifest_path=(
                                    manifest_path(self.checkpoint_dir,
                                                  r.request_id)
                                    if self.checkpoint_dir else None)))
                    raise
                except AggregatedSolverError as e:
                    # data-shaped total failure: the existing typed
                    # answer, not a poison strike
                    self._write_one_manifest(req)
                    req.future.set_exception(RequestFailedError(
                        {key: (s.quarantine or {}).get("reason")
                         for key, s in
                         self.scenarios[req.request_id].items()}))
                    delivered = True
                except Exception as e:
                    diag = f"{type(e).__name__}: {e}"
                    count = registry.strike(fp, req.request_id, diag)
                    if count >= registry.threshold:
                        req.future.set_exception(PoisonRequestError(
                            f"request {req.request_id!r} crashed the "
                            f"dispatch {count} times and is quarantined; "
                            "its content fingerprint is blocklisted — "
                            "fix the inputs before resubmitting",
                            diagnosis=diag))
                        delivered = True
                    else:
                        TellUser.warning(
                            f"service: request {req.request_id!r} crashed "
                            f"alone (strike {count}/{registry.threshold})"
                            " — retrying once")

    def _solo_dispatch(self, req: QueuedRequest, backend: str) -> None:
        """Dispatch ONE request by itself and deliver its result.
        Raises on crash (the caller attributes it)."""
        scens: Dict[object, MicrogridScenario] = {}
        for key, case in req.cases.items():
            namespaced = dataclasses.replace(
                case, case_id=f"{req.request_id}.{key}")
            s = MicrogridScenario(namespaced)
            s.request_id = req.request_id
            scens[key] = s
        self.scenarios[req.request_id] = scens
        self._dispatch(list(scens.values()), backend)
        ledger = next(iter(scens.values())).solve_metadata.get(
            "solve_ledger")
        self._deliver(req, scens, ledger)

    def _start_round_spans(self, breaker_reroute: bool = False) -> None:
        """Per-request telemetry for this round: a retro ``admission``
        span covering the queue wait (submit -> round start) plus a live
        ``batch_round`` span that dispatch-group spans parent under (the
        rid registration is re-pointed here so ``resolve_group`` on any
        worker thread finds the right parent without plumbing)."""
        if not telemetry_trace.enabled():
            return
        now_mono = time.monotonic()
        for req in self.requests:
            parent = req.span
            if parent is None:
                continue
            wait_s = max(0.0, now_mono - req.t_submit)
            telemetry_trace.start_span(
                "admission", parent=parent, t_start=parent.t_start,
                duration_s=wait_s,
                attrs={"queue_wait_s": round(wait_s, 6),
                       "priority": req.priority})
            rs = telemetry_trace.start_span(
                "batch_round", parent=parent,
                attrs={"fidelity": (resilience.FIDELITY_DEGRADED
                                    if self.degraded
                                    else resilience.FIDELITY_FULL),
                       "backend": self.backend_used,
                       "requests_in_round": len(self.requests)})
            if self.degraded:
                # the degraded-fidelity marker must ride the TRACE, not
                # only the Result — an operator reading a shed request's
                # timeline sees why it was fast
                parent.set_attr("fidelity", resilience.FIDELITY_DEGRADED)
                rs.event("load_shed",
                         reason="sustained overload — answered by the "
                                "degraded screening tier")
            if breaker_reroute:
                rs.event("breaker_certify_open",
                         rerouted_backend=self.backend_used)
            self._round_spans[req.request_id] = rs
            telemetry_trace.register_request(req.request_id, rs)

    def _end_round_spans(self, led: Dict) -> None:
        """Close every live ``batch_round`` span with the round's ledger
        summary attributes and re-point the rid registration back to the
        request root (delivery-time spans parent under the request, not
        a finished round)."""
        if not self._round_spans:
            return
        warm = led.get("warm_start") or {}
        for req in self.requests:
            rs = self._round_spans.pop(req.request_id, None)
            if rs is None:
                continue
            rs.set_attrs({
                "backend": self.backend_used,
                "windows": sum(len(s.windows)
                               for s in self.scenarios.get(
                                   req.request_id, {}).values()),
                "compile_events": int(
                    (led.get("totals") or {}).get("compile_events", 0)),
                "warm_seeded": int(warm.get("seeded", 0)),
                "warm_substituted": int(warm.get("substituted", 0)),
                "preempted": self.preempted,
            })
            rs.end()
            if req.span is not None:
                telemetry_trace.register_request(req.request_id, req.span)
        # requests that left the round early (expiry/duplicate) still
        # hold a round span — end those too
        for rid, rs in list(self._round_spans.items()):
            rs.end()
        self._round_spans.clear()

    def _finish_stats(self, all_scens, t0) -> None:
        led = self.ledger or {}
        self._end_round_spans(led)
        initial = [g for g in led.get("groups", ())
                   if g.get("rung") in (None, "initial")]
        self.stats = {
            "round_s": time.monotonic() - t0,
            "fidelity": (resilience.FIDELITY_DEGRADED if self.degraded
                         else resilience.FIDELITY_FULL),
            "backend_used": self.backend_used,
            "requests": len(self.requests),
            "cases": len(all_scens),
            "windows": sum(len(s.windows) for s in all_scens),
            "device_groups": len(initial),
            # continuous-batching occupancy: windows per device batch
            # (the whole point — small requests riding big batches)
            "mean_batch": (sum(g.get("batch", 0) for g in initial)
                           / len(initial)) if initial else 0.0,
            "cross_request_groups": sum(
                1 for g in initial if len(g.get("requests") or ()) > 1),
            "compile_events": int(
                (led.get("totals") or {}).get("compile_events", 0)),
            # warm-start observables (ops/warmstart.py): how many of the
            # round's windows rode a seed, and how many repeat windows
            # shipped a re-verified stored solution with zero device work
            "seeded_windows": int(
                (led.get("warm_start") or {}).get("seeded", 0)),
            "substituted_windows": int(
                (led.get("warm_start") or {}).get("substituted", 0)),
        }
        # elastic-scheduler observables (parallel/elastic.py): which
        # devices this round's groups landed on, how many steals the
        # stragglers cost, and the worst per-device occupancy — the
        # serving-bench gate's raw material
        el = led.get("elastic")
        if el:
            occ = [d["occupancy"] for d in el["devices"].values()
                   if d["groups"]]
            self.stats["elastic"] = {
                "n_devices": el["n_devices"],
                "devices_with_groups": el["devices_with_groups"],
                "steals": el["n_steals"],
                "min_occupancy": min(occ) if occ else None,
                "round_wall_s": el["round_wall_s"],
            }
