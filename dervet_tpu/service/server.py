"""ScenarioService: the long-lived serving layer.

``DERVET.solve`` is a cold one-shot batch run — every caller pays device
warm-up, XLA compiles, and a full sweep even for a single case.  The
service amortizes all of it across requests:

* **Persistent compile cache** — one :class:`~dervet_tpu.scenario.
  scenario.SolverCache` lives across rounds, so a structure seen once
  never re-preconditions or recompiles; the steady state of a hot
  service is zero compile events per round.
* **Cross-request continuous batching** — each round coalesces every
  pending request's window LPs through ONE ``run_dispatch``, whose
  structure-key grouping batches them across request boundaries into the
  existing compaction buckets; ``max_wait_s`` / ``max_batch_requests``
  are the usual continuous-batching knobs.
* **Bounded admission with backpressure** — a full queue rejects with a
  typed retry-after error (never unbounded buffering); priorities and
  per-request deadlines ride the queue.
* **Graceful drain** — SIGTERM stops admissions immediately, lets the
  in-flight round finish (or checkpoint, via the PR-2 supervisor), and
  flushes per-request ``run_manifest.<rid>.json`` slices; the serve CLI
  then exits 0.
* **Per-request observability** — every request gets its own namespaced
  run-health report and solve-ledger slice; the service aggregates queue
  depth, admission rejects, batch occupancy, request latency p50/p99,
  and compile-cache hits under :meth:`ScenarioService.metrics`.

``dervet-tpu serve SPOOL_DIR`` runs the file-spool front end: model-
parameter files dropped into ``SPOOL_DIR/incoming/`` become requests
(request id = file stem), results land in ``SPOOL_DIR/results/<rid>/``.
"""
from __future__ import annotations

import collections
import re
import threading
import time
from concurrent.futures import Future
from pathlib import Path
from typing import Dict, Optional

import numpy as np

from ..scenario.scenario import SolverCache
from ..telemetry import ops as telemetry_ops
from ..telemetry import registry as telemetry_registry
from ..telemetry import trace as telemetry_trace
from ..utils.breaker import BreakerBoard
from ..utils.errors import (BreakerOpenError, PoisonRequestError,
                            PreemptedError, ShardCacheMissError,
                            TellUser)
from ..utils.supervisor import RunSupervisor
from . import resilience
from .batcher import BatchRound
from .queue import (AdmissionQueue, QueuedRequest, QueueFullError,
                    ServiceClosedError, ServiceError)

# request ids name files (checkpoints, manifests, health reports): the
# admission boundary rejects anything that could escape the artifact
# directories or collide after sanitization
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


class ScenarioService:
    """Persistent scenario-solving service (in-process).

    Lifecycle: construct -> :meth:`start` (or drive :meth:`run_once`
    manually, e.g. in tests) -> :meth:`submit` from any thread ->
    :meth:`drain`/:meth:`close`.  Thread model: one batcher thread runs
    the rounds; ``submit`` only touches the admission queue."""

    def __init__(self, backend: str = "jax", solver_opts=None,
                 max_queue_depth: int = 64, max_wait_s: float = 0.25,
                 max_batch_requests: int = 32, checkpoint_dir=None,
                 max_cached_structures: int = 64,
                 gc_checkpoints: bool = True,
                 load_shedding: bool = True,
                 shed_threshold_frac: float = 0.75,
                 shed_sustain_rounds: int = 2,
                 shed_priority_max: int = 0,
                 breaker_opts: Optional[Dict] = None,
                 backend_max_reinits: int = 2,
                 fairness_after_s: float = 30.0):
        self.backend = backend
        self.solver_opts = solver_opts
        self.max_wait_s = float(max_wait_s)
        self.max_batch_requests = int(max_batch_requests)
        self.checkpoint_dir = checkpoint_dir
        self.max_cached_structures = int(max_cached_structures)
        # delivered requests' checkpoints/manifest slices are reclaimed
        # by default (unbounded disk otherwise); failed/preempted
        # requests always keep theirs for resume
        self.gc_checkpoints = bool(gc_checkpoints)
        self.queue = AdmissionQueue(max_queue_depth,
                                    fairness_after_s=fairness_after_s)
        # the hot-service core: compiled solvers + preconditioning live
        # across rounds (see run_dispatch's solver_cache hook), and
        # pad_grid snaps every coalesced batch onto the pdhg compaction
        # bucket widths so varying request mixes reuse compiled shapes
        # warm_start: the cache carries a SolutionMemory across rounds,
        # so repeat/nearby requests seed PDHG from stored converged
        # iterates (exact repeats re-verify + ship the stored solution
        # with zero device work) — see ops/warmstart.py; every seeded
        # window still runs full convergence criteria + certification
        self.solver_cache = SolverCache(pad_grid=(backend != "cpu"),
                                        warm_start=True)
        # -- self-healing layer (see service/resilience.py) ------------
        # circuit breakers around the escalation-ladder rungs, the
        # certification path, and the backend as a whole; thresholds are
        # overridable via breaker_opts (window/min_samples/
        # failure_threshold/cooldown_s)
        self.breakers = BreakerBoard(**(breaker_opts or {}))
        # the backend breaker trips only on TOTAL round failures (post
        # recovery+failover), so it needs consecutive hard evidence
        self.breakers.configure(
            "backend", min_samples=2, failure_threshold=1.0,
            **{k: v for k, v in (breaker_opts or {}).items()
               if k in ("window", "cooldown_s")})
        # load shedding: sustained overload answers low-priority
        # requests with an explicit degraded-fidelity screening solve
        # instead of rejecting them (None = shedding disabled)
        self.shedder = (resilience.LoadShedder(
            threshold_frac=shed_threshold_frac,
            sustain_rounds=shed_sustain_rounds,
            shed_priority_max=shed_priority_max)
            if load_shedding else None)
        # the degraded tier gets its OWN compiled-solver cache: a
        # screening solver (loose tolerance, short budget) must never be
        # handed to a certified-tier round sharing the structure key
        # the degraded tier SHARES the warm-start memory (its screening
        # answers make fine seeds and vice versa — the tolerance tag
        # keeps a loose answer from ever substituting for a certified
        # one) while keeping its own compiled-solver cache
        self.degraded_cache = SolverCache(pad_grid=(backend != "cpu"),
                                          memory=self.solver_cache.memory)
        # design requests (BOOST sizing): persistent per-tier screening
        # caches — a warm service screens a repeat population with zero
        # XLA compiles; finalists ride the certified solver_cache above.
        # One SHARED solution memory across the tiers and the certified
        # cache: tier i+1 re-screens the same candidates seeded from
        # tier i's iterates, and finalists seed from the tightest
        # screening iterates (near-grade only — substitution needs an
        # exact tolerance-tag match)
        from ..design.screen import ScreeningCaches
        self.design_caches = ScreeningCaches(
            pad_grid=(backend != "cpu"),
            memory=self.solver_cache.memory)
        self._design = {"requests": 0, "candidates": 0, "screen_rounds": 0,
                        "screen_s": 0.0, "finalists": 0,
                        "degraded_answers": 0, "screen_dispatches": 0,
                        "screen_compile_events": 0}
        # portfolio-request counters (dervet_tpu/portfolio): coupled
        # fleets solved through the dual-decomposed outer loop
        self._portfolio = {"requests": 0, "outer_rounds": 0,
                           "windows": 0, "dual_iterate_seeds": 0,
                           "degraded_answers": 0, "infeasible": 0,
                           "failed": 0, "portfolio_s": 0.0,
                           # fleet-sharded rounds served FOR other
                           # nodes' dual loops (portfolio/shard.py)
                           "shard_requests": 0, "shard_windows": 0,
                           "shard_failed": 0, "shard_s": 0.0}
        # the last portfolio solve's observability section (gap, rounds,
        # certificate) — the smoke/bench gates' surface
        self.last_portfolio: Optional[Dict] = None
        # the last design screening's per-round stats (the zero-compile
        # warm observable the design smoke gates on)
        self.last_screen_stats: Optional[Dict] = None
        # monte-carlo request counters (dervet_tpu/stochastic): batched
        # uncertainty valuations — the sample mass screens through the
        # design_caches tiers, the quantile-pinning samples certify
        # through the main solver_cache
        self._montecarlo = {"requests": 0, "samples": 0,
                            "certified_samples": 0, "quarantined": 0,
                            "degraded_answers": 0, "mc_s": 0.0,
                            "dispatches": 0, "compile_events": 0}
        # the last MC run's tier mix + per-round dispatch stats (the
        # zero-compile warm observable the mc smoke gates on)
        self.last_mc_stats: Optional[Dict] = None
        # backend-loss recovery policy + poison-request registry
        self.recovery = resilience.BackendRecovery(
            max_reinits=backend_max_reinits)
        self.poison_registry = resilience.PoisonRegistry()
        # drain flag is set from signal context (on_stop must stay
        # lock-free); the queue is closed later, on a normal thread.
        # Handlers install only when the OWNER enters the supervisor
        # (serve loop / tests, main thread); library embedders who never
        # enter it still get programmatic drain via request_stop().
        self._draining = threading.Event()
        self.supervisor = RunSupervisor(install_signals=True,
                                        on_stop=self._draining.set)
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._seq_lock = threading.Lock()
        # ids with an unresolved future: a resubmission of a live id
        # would cross-wire results (scenario maps / checkpoints /
        # manifests key on it), so it is rejected at admission; the id
        # frees the moment its future resolves
        self._active_ids: set = set()
        # replica-side portfolio shard case cache (ROADMAP 1a): the
        # full site payload arrives ONCE per (seed_tag, plan_fp); every
        # later dual round ships just the price vector + the plan
        # fingerprint and resolves the cases here at admission.  A
        # reference that misses (failover moved the shard, eviction,
        # restart) raises the typed ShardCacheMissError, and the shard
        # executor re-sends the full payload once.  Bounded LRU — a
        # replica serving many portfolios must not pin every site set.
        self._shard_cases: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._shard_cases_cap = 32
        self._shard_cases_lock = threading.Lock()
        self._metrics_lock = threading.Lock()
        # bounded: the percentile surface only needs a recent window,
        # and a service that never dies must not grow per-request state
        self._latencies = collections.deque(maxlen=4096)
        self._rounds = {"count": 0, "requests": 0, "cases": 0,
                        "windows": 0, "device_groups": 0,
                        "cross_request_groups": 0, "batch_sum": 0.0,
                        "compile_events": 0, "round_s": 0.0,
                        "preempted": 0, "degraded_rounds": 0,
                        "seeded_windows": 0, "substituted_windows": 0}
        # elastic-scheduler aggregates (parallel/elastic.py): rounds
        # that rode the mesh-wide scheduler, total steals, worst
        # per-device occupancy seen
        self._elastic = {"rounds": 0, "steals": 0,
                         "min_occupancy": None}
        self._requests = {"completed": 0, "failed": 0}
        self.last_round_ledger: Optional[Dict] = None
        self.device_info: Optional[Dict] = None
        self._started = False

    # -- admission ------------------------------------------------------
    def submit(self, cases, *, request_id=None, priority: int = 0,
               deadline_s: Optional[float] = None,
               trace_ctx: Optional[Dict] = None) -> Future:
        """Admit one request (a dict of case key -> ``CaseParams``, or an
        iterable of cases) and return the future its
        :class:`~dervet_tpu.results.result.Result` is delivered through.

        ``trace_ctx`` is an upstream telemetry trace context (the fleet
        router's ``{"trace_id", "span_id"}`` riding the transport
        payload) — the request's span tree parents under it, so one
        stitched trace follows the request across processes.

        Raises :class:`~dervet_tpu.service.queue.QueueFullError` (with a
        ``retry_after_s`` hint) under backpressure and
        :class:`~dervet_tpu.service.queue.ServiceClosedError` once the
        service is draining."""
        if self._draining.is_set():
            raise ServiceClosedError(
                "service is draining — no new admissions")
        if not isinstance(cases, dict):
            cases = dict(enumerate(cases))
        if not cases:
            raise ValueError("a request needs at least one case")
        fingerprint = resilience.request_fingerprint(cases)
        return self._admit(request_id, fingerprint, priority, deadline_s,
                           cases=cases, trace_ctx=trace_ctx)

    def submit_design(self, case, spec=None, *, request_id=None,
                      priority: int = 0,
                      deadline_s: Optional[float] = None,
                      trace_ctx: Optional[Dict] = None,
                      **spec_kwargs) -> Future:
        """Admit one DESIGN request (BOOST sizing): screen a candidate
        population over ``spec``'s bounds, certify the top-k, deliver a
        :class:`~dervet_tpu.design.frontier.DesignFrontier` through the
        returned future.  Admission semantics (priority, deadline,
        backpressure, poison blocklist, draining) are identical to
        :meth:`submit` — a design request is just another request type.

        ``spec`` is a :class:`~dervet_tpu.design.population.DesignSpec`;
        alternatively pass its fields as keyword arguments."""
        from ..design.population import DesignSpec
        from ..design.service import design_fingerprint
        if self._draining.is_set():
            raise ServiceClosedError(
                "service is draining — no new admissions")
        if spec is None:
            spec = DesignSpec(**spec_kwargs)
        spec.validate()       # spec errors raise HERE, at admission
        fingerprint = design_fingerprint(case, spec)
        return self._admit(request_id, fingerprint, priority, deadline_s,
                           kind="design", design_case=case,
                           design_spec=spec, trace_ctx=trace_ctx)

    def submit_montecarlo(self, case, spec=None, *, request_id=None,
                          priority: int = 0,
                          deadline_s: Optional[float] = None,
                          trace_ctx: Optional[Dict] = None,
                          **spec_kwargs) -> Future:
        """Admit one MONTE-CARLO request (uncertainty valuation): sample
        ``spec.n_samples`` seeded perturbations of ``case``, solve them
        as one batch (screening mass + certified quantile-pinning
        re-solves), deliver an
        :class:`~dervet_tpu.stochastic.distribution.MCDistribution`
        through the returned future.  Admission semantics (priority,
        deadline, backpressure, poison blocklist, draining) are
        identical to :meth:`submit` — an MC request is just another
        request type.

        ``spec`` is a :class:`~dervet_tpu.stochastic.sampler.MCSpec`;
        alternatively pass its fields as keyword arguments."""
        from ..stochastic.sampler import MCSpec
        from ..stochastic.service import montecarlo_fingerprint
        if self._draining.is_set():
            raise ServiceClosedError(
                "service is draining — no new admissions")
        if spec is None:
            spec = MCSpec(**spec_kwargs)
        spec.validate()       # spec errors raise HERE, at admission
        fingerprint = montecarlo_fingerprint(case, spec)
        return self._admit(request_id, fingerprint, priority, deadline_s,
                           kind="montecarlo", mc_case=case, mc_spec=spec,
                           trace_ctx=trace_ctx)

    def submit_portfolio(self, spec, *, request_id=None,
                         priority: int = 0,
                         deadline_s: Optional[float] = None,
                         trace_ctx: Optional[Dict] = None) -> Future:
        """Admit one PORTFOLIO request (coupled-fleet co-optimization):
        solve ``spec``'s member sites as one LP under the shared
        coupling constraints via the dual-decomposed outer loop
        (``dervet_tpu.portfolio``), deliver a
        :class:`~dervet_tpu.portfolio.solve.PortfolioResult` through
        the returned future.  Admission semantics (priority, deadline,
        backpressure, poison blocklist, draining) are identical to
        :meth:`submit`.  The dual loop dispatches through the
        service's persistent solver cache, so repeat portfolios reuse
        compiled programs AND the warm-start memory."""
        from ..portfolio.service import portfolio_fingerprint
        if self._draining.is_set():
            raise ServiceClosedError(
                "service is draining — no new admissions")
        spec.validate()       # spec errors raise HERE, at admission
        fingerprint = portfolio_fingerprint(spec)
        return self._admit(request_id, fingerprint, priority, deadline_s,
                           kind="portfolio", portfolio_spec=spec,
                           trace_ctx=trace_ctx)

    def submit_portfolio_shard(self, shard: Dict, *, request_id=None,
                               priority: int = 0,
                               deadline_s: Optional[float] = None,
                               trace_ctx: Optional[Dict] = None) -> Future:
        """Admit one PORTFOLIO SHARD request: a slice of another node's
        dual round (site cases + the round's dual-price vector — see
        ``dervet_tpu.portfolio.shard``), solved against THIS replica's
        persistent solver cache and answered as a
        :class:`~dervet_tpu.portfolio.shard.PortfolioShardResult`.  The
        router keeps shard→replica assignment sticky, so round k+1's
        shard finds the ``dual_iterate`` hints round k stored here."""
        import hashlib
        if self._draining.is_set():
            raise ServiceClosedError(
                "service is draining — no new admissions")
        if not isinstance(shard, dict):
            raise ValueError("a portfolio shard needs a payload dict")
        shard = self._resolve_shard_cases(shard)
        h = hashlib.sha256()
        h.update(str(shard.get("seed_tag")).encode())
        h.update(repr(sorted(str(k) for k in shard["sites"])).encode())
        return self._admit(request_id, h.hexdigest(), priority,
                           deadline_s, kind="portfolio_shard",
                           shard_payload=shard, trace_ctx=trace_ctx)

    def _resolve_shard_cases(self, shard: Dict) -> Dict:
        """Shard case cache admission hook: a FULL payload ("sites"
        present) seeds the ``(seed_tag, plan_fp)`` entry; a REFERENCE
        payload (no "sites", a "plan_fp") resolves against it or raises
        the typed :class:`ShardCacheMissError` so the executor re-sends
        the full payload once.  The returned shard always carries
        resolved sites."""
        sites = shard.get("sites")
        seed_tag = str(shard.get("seed_tag"))
        plan_fp = shard.get("plan_fp")
        if sites:
            if plan_fp:
                key = (seed_tag, str(plan_fp))
                with self._shard_cases_lock:
                    self._shard_cases[key] = sites
                    self._shard_cases.move_to_end(key)
                    while len(self._shard_cases) > self._shard_cases_cap:
                        self._shard_cases.popitem(last=False)
            return shard
        if not plan_fp:
            raise ValueError("a portfolio shard needs a non-empty "
                             "'sites' dict (or a 'plan_fp' reference "
                             "to a previously shipped one)")
        key = (seed_tag, str(plan_fp))
        with self._shard_cases_lock:
            cached = self._shard_cases.get(key)
            if cached is not None:
                self._shard_cases.move_to_end(key)
        if cached is None:
            raise ShardCacheMissError(
                f"shard {seed_tag!r} arrived in reference mode but this "
                f"replica holds no cached site payload for plan "
                f"{str(plan_fp)[:12]!r} — re-dispatch with the full "
                "'sites' payload to re-seed the cache")
        return {**shard, "sites": cached}

    def _admit(self, request_id, fingerprint, priority, deadline_s, *,
               cases=None, kind: str = "scenario", design_case=None,
               design_spec=None, portfolio_spec=None, shard_payload=None,
               mc_case=None, mc_spec=None,
               trace_ctx: Optional[Dict] = None) -> Future:
        """Shared admission tail: backend breaker, poison blocklist,
        id allocation/validation, queue put with typed rejection."""
        if self.breakers.is_open("backend"):
            # the service is alive but cannot currently solve (backend
            # re-init AND the CPU failover both failed): fail fast with
            # the probe schedule instead of queueing work that will die
            raise BreakerOpenError(
                "service backend breaker is open — recent rounds failed "
                "even after re-init and CPU failover; retry after the "
                "probe window",
                probe_in_s=self.breakers.get("backend").probe_in_s())
        # poison blocklist: a request whose content fingerprint crashed
        # the dispatch twice is rejected in microseconds here, instead
        # of re-crashing a round it would share with innocents
        diagnosis = self.poison_registry.blocked(fingerprint)
        if diagnosis is not None:
            raise PoisonRequestError(
                f"request {request_id!r} rejected: its content is "
                "quarantined (crashed the dispatch "
                f"{self.poison_registry.threshold} times) — fix the "
                f"inputs before resubmitting", diagnosis=diagnosis)
        with self._seq_lock:
            if request_id is None:
                self._seq += 1
                request_id = f"r{self._seq:06d}"
            if not _REQUEST_ID_RE.match(str(request_id)):
                raise ValueError(
                    f"request id {request_id!r} must match "
                    "[A-Za-z0-9._-]{1,64} — it names checkpoint/"
                    "manifest/health files")
            if str(request_id) in self._active_ids:
                raise ValueError(
                    f"request id {request_id!r} is still in flight — "
                    "wait for its future (or pick a new id) before "
                    "resubmitting")
            self._active_ids.add(str(request_id))
        req = QueuedRequest(request_id, cases if cases is not None else {},
                            priority=priority, deadline_s=deadline_s,
                            kind=kind)
        req.fingerprint = fingerprint
        req.design_case = design_case
        req.design_spec = design_spec
        req.portfolio_spec = portfolio_spec
        req.shard_payload = shard_payload
        req.mc_case = mc_case
        req.mc_spec = mc_spec
        # telemetry: the request's root span on this process — a child
        # of the upstream (router) context when one rode the transport,
        # else a fresh root whose trace id derives from the request id
        # (so cross-process stitching never depends on in-band context)
        req.trace_ctx = trace_ctx
        span = telemetry_trace.start_span(
            "request", parent=trace_ctx, rid=str(request_id),
            attrs={"request_id": str(request_id), "kind": kind,
                   "priority": int(priority)})
        if span:
            req.span = span
            telemetry_trace.register_request(str(request_id), span)
        # capture rid + span only — a closure over the QueuedRequest
        # would pin the full case payload for the future's lifetime
        # (futures keep their callback list after resolution)
        req.future.add_done_callback(
            lambda f, rid=str(request_id), s=span or None:
            self._request_done(rid, s, f))
        try:
            self.queue.put(req)
        except ServiceError as e:
            self._release_id(str(request_id))
            if span:
                telemetry_trace.release_request(str(request_id))
                span.event("admission_rejected",
                           error=type(e).__name__).end(error=e)
            raise
        return req.future

    def _request_done(self, rid: str, span, fut) -> None:
        """Future-resolution callback (added FIRST, at admission, so it
        runs before the serve loop's trace export): free the id and end
        the request's telemetry span with the delivery outcome."""
        self._release_id(rid)
        if span is not None:
            telemetry_trace.release_request(rid)
            try:
                err = fut.exception()
            except Exception:
                err = None
            span.end(error=err)

    def _release_id(self, rid: str) -> None:
        with self._seq_lock:
            self._active_ids.discard(rid)

    def submit_params(self, path, base_path=None, **kwargs) -> Future:
        """Admit a model-parameters FILE (CSV/JSON/XML) as one request —
        the serve-loop front end; parsing errors raise here, at
        admission, not inside the batch."""
        from ..io.params import Params
        cases = Params.initialize(path, base_path=base_path)
        return self.submit(cases, **kwargs)

    def submit_pickle(self, path, **kwargs) -> Future:
        """Admit a fleet-transport request payload: a pickle of
        ``{"cases": {...}, "priority": int, "deadline_epoch": float}``
        (see :meth:`~dervet_tpu.service.fleet.SpoolReplica.
        encode_payload`).  A same-trust-domain transport — the payload
        was written by our own router process on our own host/cluster,
        never by an external client.  The deadline rides as an absolute
        epoch so time spent in transit between router and replica counts
        against it."""
        import pickle
        with open(path, "rb") as f:
            payload = pickle.load(f)
        deadline_epoch = payload.get("deadline_epoch")
        kwargs.setdefault("priority", int(payload.get("priority", 0)))
        if deadline_epoch is not None:
            kwargs.setdefault("deadline_s",
                              max(0.0, float(deadline_epoch) - time.time()))
        # trace context rides the transport payload: the replica-side
        # span tree parents under the router's transport span
        kwargs.setdefault("trace_ctx", payload.get("trace"))
        if payload.get("portfolio_shard") is not None:
            # fleet-sharded portfolio round: one shard of another
            # node's dual loop (dervet_tpu/portfolio/shard.py)
            return self.submit_portfolio_shard(
                payload["portfolio_shard"], **kwargs)
        cases = payload.get("cases")
        if cases is None and payload.get("cases_pickle") is not None:
            # serialize-once client path: the cases dict rides as its
            # own pre-pickled bytes inside the transport record
            cases = pickle.loads(payload["cases_pickle"])
        return self.submit(cases, **kwargs)

    def submit_design_file(self, path, base_path=None, **kwargs) -> Future:
        """Admit a spool ``design.json`` request file (see
        ``design.service.parse_design_request`` for the shape); parse
        errors raise here, at admission."""
        import json
        from ..design.service import parse_design_request
        with open(path) as f:
            payload = json.load(f)
        case, spec = parse_design_request(payload, base_path=base_path)
        return self.submit_design(case, spec, **kwargs)

    def submit_montecarlo_file(self, path, base_path=None,
                               **kwargs) -> Future:
        """Admit a spool ``montecarlo.json`` request file (see
        ``stochastic.service.parse_montecarlo_request`` for the shape);
        parse errors raise here, at admission."""
        import json
        from ..stochastic.service import parse_montecarlo_request
        with open(path) as f:
            payload = json.load(f)
        case, spec = parse_montecarlo_request(payload,
                                              base_path=base_path)
        return self.submit_montecarlo(case, spec, **kwargs)

    def submit_portfolio_file(self, path, base_path=None,
                              **kwargs) -> Future:
        """Admit a spool ``portfolio.json`` request file (see
        ``portfolio.service.parse_portfolio_request`` for the shape);
        parse errors raise here, at admission."""
        import json
        from ..portfolio.service import parse_portfolio_request
        with open(path) as f:
            payload = json.load(f)
        spec = parse_portfolio_request(payload, base_path=base_path)
        return self.submit_portfolio(spec, **kwargs)

    # -- batching loop --------------------------------------------------
    def start(self) -> "ScenarioService":
        """Warm the device and start the batcher thread."""
        if self._started:
            return self
        if self.backend != "cpu":
            from ..parallel import elastic
            from ..parallel.mesh import warmup_devices
            # per-device warm solves only for the devices the elastic
            # scheduler will actually place groups on — a serial (or
            # single-device) service warms the default device alone
            elastic_devs = elastic.elastic_devices(self.backend)
            self.device_info = warmup_devices(
                per_device_solve=elastic_devs is not None,
                devices=elastic_devs)
            TellUser.info(
                f"service: device warm ({self.device_info['n_devices']}x "
                f"{self.device_info['platform']}:"
                f"{self.device_info['device_kind']}"
                + (f", per-device warm-up {self.device_info['warmup_total_s']}s"
                   if "warmup_total_s" in self.device_info else "") + ")")
        self._started = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dervet-service-batcher")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._draining.is_set():
            try:
                self.run_once(block=True, timeout=0.5)
            except PreemptedError:
                break            # drain signal landed mid-round
            except Exception as e:   # a round crash must not kill serving
                TellUser.error(f"service: batch round errored: {e}")
        self._fail_pending()

    def run_once(self, block: bool = False,
                 timeout: Optional[float] = None) -> int:
        """Run one batch cycle synchronously; returns the number of
        requests served.  The manual drive used by tests and by callers
        embedding the service without the batcher thread.

        Under SUSTAINED overload (queue pressure / deadline misses for
        ``shed_sustain_rounds`` consecutive cycles) the cycle splits in
        two rounds: low-priority requests are answered by the DEGRADED
        tier first (loose-tolerance short-budget screening solve, its
        own solver cache, certification off, results explicitly marked)
        and the rest get the normal certified round — explicit
        degradation instead of rejection or silent death."""
        requests = self.queue.take(max_batch=self.max_batch_requests,
                                   max_wait_s=self.max_wait_s,
                                   block=block, timeout=timeout)
        if not requests:
            return 0
        shed = False
        if self.shedder is not None:
            depth_at_start = self.queue.depth() + len(requests)
            shed = self.shedder.observe(depth_at_start,
                                        self.queue.max_depth,
                                        self.queue.counters["expired"])
        if shed:
            certified, degraded = self.shedder.partition(requests)
            if degraded:
                TellUser.warning(
                    f"service: overload sustained — shedding "
                    f"{len(degraded)} low-priority request(s) to the "
                    "degraded screening tier "
                    f"({len(certified)} stay certified)")
        else:
            certified, degraded = requests, []
        # design requests take the BOOST path: their populations screen
        # NOW (one DesignRound, the service's persistent per-tier caches)
        # and the survivors' finalist cases join the certified round
        # below, co-batching with ordinary scenario requests.  A design
        # request the shedder picked is answered from the screen alone
        # (degraded frontier) — it never reaches the certified round.
        design_shed_ids = {r.request_id for r in degraded
                           if r.kind == "design"}
        design_reqs = [r for r in certified + degraded
                       if r.kind == "design"]
        certified = [r for r in certified if r.kind != "design"]
        degraded = [r for r in degraded if r.kind != "design"]
        # monte-carlo requests run their own round (the engine drives
        # both tiers' dispatches itself); a load-SHED MC request runs
        # the screening tier only over a reduced sample count and is
        # answered degraded — never certificate-stamped
        mc_shed_ids = {r.request_id for r in degraded
                       if r.kind == "montecarlo"}
        mc_reqs = [r for r in certified + degraded
                   if r.kind == "montecarlo"]
        certified = [r for r in certified if r.kind != "montecarlo"]
        degraded = [r for r in degraded if r.kind != "montecarlo"]
        # portfolio requests run their own dual-loop round against the
        # service's persistent caches; a load-SHED portfolio runs the
        # degraded tier (screening inner solves, certification off,
        # answer marked — never certificate-stamped)
        portfolio_shed_ids = {r.request_id for r in degraded
                              if r.kind == "portfolio"}
        portfolio_reqs = [r for r in certified + degraded
                          if r.kind == "portfolio"]
        certified = [r for r in certified if r.kind != "portfolio"]
        degraded = [r for r in degraded if r.kind != "portfolio"]
        # portfolio SHARD requests (one slice of another node's dual
        # round): latency-critical sub-steps of a loop already in
        # flight elsewhere — served first, never shed (the owning
        # loop's degraded decision was made at ITS admission)
        shard_reqs = [r for r in certified + degraded
                      if r.kind == "portfolio_shard"]
        certified = [r for r in certified if r.kind != "portfolio_shard"]
        degraded = [r for r in degraded if r.kind != "portfolio_shard"]
        served = 0
        if shard_reqs:
            from ..portfolio.shard import PortfolioShardRound
            sr = PortfolioShardRound(shard_reqs, backend=self.backend,
                                     solver_opts=self.solver_opts,
                                     solver_cache=self.solver_cache,
                                     supervisor=self.supervisor,
                                     board=self.breakers)
            try:
                sr.run()
            except BaseException as e:
                for req in portfolio_reqs + design_reqs + mc_reqs \
                        + degraded + certified:
                    if not req.future.done():
                        req.future.set_exception(ServiceClosedError(
                            f"request {req.request_id!r} not "
                            "dispatched: the portfolio shard round "
                            f"failed ({e}) — resubmit"))
                        with self._metrics_lock:
                            self._requests["failed"] += 1
                self._absorb_shard_stats(sr)
                raise
            self._absorb_shard_stats(sr)
            served += len(sr.answered)
        if portfolio_reqs:
            from ..portfolio.service import PortfolioRound
            pr = PortfolioRound(portfolio_reqs, backend=self.backend,
                                solver_opts=self.solver_opts,
                                solver_cache=self.solver_cache,
                                degraded_cache=self.degraded_cache,
                                degraded_ids=portfolio_shed_ids,
                                supervisor=self.supervisor,
                                board=self.breakers)
            try:
                pr.run()
            except BaseException as e:
                # the portfolio round answers its own requests (incl.
                # preemption); every OTHER request this cycle already
                # popped from the queue must be answered here or its
                # client hangs forever
                for req in design_reqs + mc_reqs + degraded + certified:
                    if not req.future.done():
                        req.future.set_exception(ServiceClosedError(
                            f"request {req.request_id!r} not "
                            "dispatched: the portfolio round failed "
                            f"({e}) — resubmit"))
                        with self._metrics_lock:
                            self._requests["failed"] += 1
                self._absorb_portfolio_stats(pr)
                raise
            self._absorb_portfolio_stats(pr)
            served += len(pr.answered)
        if design_reqs:
            from ..design.service import DesignRound
            dr = DesignRound(design_reqs, backend=self.backend,
                             solver_opts=self.solver_opts,
                             caches=self.design_caches,
                             degraded_ids=design_shed_ids,
                             supervisor=self.supervisor)
            try:
                dr.run()
            except BaseException as e:
                # the screening phase answers its own requests (incl.
                # preemption); every OTHER request this cycle already
                # popped from the queue must be answered here or its
                # client hangs forever
                for req in design_reqs + mc_reqs + degraded + certified:
                    if not req.future.done():
                        req.future.set_exception(ServiceClosedError(
                            f"request {req.request_id!r} not dispatched: "
                            "the design screening phase failed "
                            f"({e}) — resubmit"))
                        with self._metrics_lock:
                            self._requests["failed"] += 1
                self._absorb_design_stats(dr)
                raise
            self._absorb_design_stats(dr)
            served += len(dr.answered)
            certified = certified + dr.finalist_requests
        if mc_reqs:
            from ..stochastic.service import MonteCarloRound
            mr = MonteCarloRound(mc_reqs, backend=self.backend,
                                 solver_opts=self.solver_opts,
                                 caches=self.design_caches,
                                 final_cache=self.solver_cache,
                                 degraded_ids=mc_shed_ids,
                                 supervisor=self.supervisor)
            try:
                mr.run()
            except BaseException as e:
                # the MC round answers its own requests (incl.
                # preemption); the scenario tiers below were already
                # popped from the queue and must be answered here or
                # their clients hang forever
                for req in mc_reqs + degraded + certified:
                    if not req.future.done():
                        req.future.set_exception(ServiceClosedError(
                            f"request {req.request_id!r} not "
                            "dispatched: the monte-carlo round failed "
                            f"({e}) — resubmit"))
                        with self._metrics_lock:
                            self._requests["failed"] += 1
                self._absorb_mc_stats(mr)
                raise
            self._absorb_mc_stats(mr)
            served += len(mr.answered)
        tiers = [(reqs, is_degraded)
                 for reqs, is_degraded in ((degraded, True),
                                           (certified, False)) if reqs]
        for t_idx, (reqs, is_degraded) in enumerate(tiers):
            rnd = BatchRound(
                reqs, backend=self.backend,
                solver_opts=self.solver_opts,
                # the degraded tier's compiled screening solvers must
                # never leak into a certified round (shared structure
                # keys, different budgets) — separate cache
                solver_cache=(self.degraded_cache if is_degraded
                              else self.solver_cache),
                supervisor=self.supervisor,
                checkpoint_dir=self.checkpoint_dir,
                on_stats=self._absorb_round_stats,
                gc_checkpoints=self.gc_checkpoints,
                board=self.breakers, recovery=self.recovery,
                poison_registry=self.poison_registry,
                degraded=is_degraded)
            try:
                rnd.run()
            except BaseException as e:
                # the raising round answered ITS OWN requests, but any
                # LATER tier was already popped from the queue — its
                # futures must be answered here or clients blocked on
                # them hang forever (neither a round nor _fail_pending
                # would ever see them again)
                for later_reqs, _ in tiers[t_idx + 1:]:
                    for req in later_reqs:
                        if not req.future.done():
                            req.future.set_exception(ServiceClosedError(
                                f"request {req.request_id!r} not "
                                "dispatched: an earlier round of this "
                                f"batch cycle failed ({e}) — resubmit"))
                            with self._metrics_lock:
                                self._requests["failed"] += 1
                if not isinstance(e, PreemptedError):
                    # a round that died even after backend recovery +
                    # failover + poison isolation: hard evidence against
                    # the backend breaker (admissions fail fast when it
                    # trips), then propagate for the loop to log
                    self.breakers.record("backend", False)
                self._absorb_request_outcomes(rnd)
                raise
            else:
                if rnd.requests:
                    self.breakers.record("backend", True)
                self._absorb_request_outcomes(rnd)
            served += len(rnd.requests)
        return served

    def _absorb_design_stats(self, dr) -> None:
        """Design screening bookkeeping: screening-load counters (kept
        separate from scenario round counters so the two workloads are
        distinguishable in ``metrics()``), plus request accounting for
        the design requests the screening phase answered itself
        (degraded frontiers, screen failures, expiries)."""
        st = dr.stats
        with self._metrics_lock:
            self._design["requests"] += int(st.get("requests", 0))
            self._design["candidates"] += int(st.get("candidates", 0))
            self._design["screen_rounds"] += int(st.get("screen_rounds",
                                                        0))
            self._design["screen_s"] += float(st.get("screen_s", 0.0))
            self._design["finalists"] += int(st.get("finalists", 0))
            self._design["degraded_answers"] += int(st.get("degraded", 0))
            self._design["screen_dispatches"] += int(
                st.get("dispatches", 0))
            self._design["screen_compile_events"] += int(
                st.get("compile_events", 0))
            for req in dr.answered:
                fut = req.future
                if fut.done() and fut.exception() is None:
                    self._requests["completed"] += 1
                    self._latencies.append(
                        time.monotonic() - req.t_submit)
                    self._note_request_telemetry(req, True)
                elif fut.done():
                    self._requests["failed"] += 1
                    self._note_request_telemetry(req, False)
        if dr.last_screen is not None:
            self.last_screen_stats = dr.last_screen

    def _absorb_mc_stats(self, mr) -> None:
        """Monte-carlo round bookkeeping + request accounting (the round
        answers every future itself)."""
        st = mr.stats
        with self._metrics_lock:
            self._montecarlo["requests"] += int(st.get("requests", 0))
            self._montecarlo["samples"] += int(st.get("samples", 0))
            self._montecarlo["certified_samples"] += int(
                st.get("certified_samples", 0))
            self._montecarlo["quarantined"] += int(
                st.get("quarantined", 0))
            self._montecarlo["degraded_answers"] += int(
                st.get("degraded", 0))
            self._montecarlo["mc_s"] += float(st.get("mc_s", 0.0))
            self._montecarlo["dispatches"] += int(st.get("dispatches", 0))
            self._montecarlo["compile_events"] += int(
                st.get("compile_events", 0))
            for req in mr.answered:
                fut = req.future
                if fut.done() and fut.exception() is None:
                    self._requests["completed"] += 1
                    self._latencies.append(
                        time.monotonic() - req.t_submit)
                    self._note_request_telemetry(req, True)
                elif fut.done():
                    self._requests["failed"] += 1
                    self._note_request_telemetry(req, False)
        if mr.last_mc is not None:
            self.last_mc_stats = mr.last_mc

    def _absorb_shard_stats(self, sr) -> None:
        """Portfolio-shard-round bookkeeping + request accounting (the
        round answers every future itself)."""
        st = sr.stats
        with self._metrics_lock:
            for k in ("shard_requests", "shard_windows", "shard_failed"):
                self._portfolio[k] += int(st.get(k, 0))
            self._portfolio["shard_s"] += float(st.get("shard_s", 0.0))
            for req in sr.answered:
                fut = req.future
                if fut.done() and fut.exception() is None:
                    self._requests["completed"] += 1
                    self._latencies.append(
                        time.monotonic() - req.t_submit)
                    self._note_request_telemetry(req, True)
                elif fut.done():
                    self._requests["failed"] += 1
                    self._note_request_telemetry(req, False)

    def _absorb_portfolio_stats(self, pr) -> None:
        """Portfolio-round bookkeeping + request accounting (the round
        answers every future itself)."""
        st = pr.stats
        with self._metrics_lock:
            for k in ("requests", "outer_rounds", "windows",
                      "dual_iterate_seeds", "infeasible", "failed"):
                self._portfolio[k] += int(st.get(k, 0))
            self._portfolio["degraded_answers"] += int(
                st.get("degraded", 0))
            self._portfolio["portfolio_s"] += float(
                st.get("portfolio_s", 0.0))
            for req in pr.answered:
                fut = req.future
                if fut.done() and fut.exception() is None:
                    self._requests["completed"] += 1
                    self._latencies.append(
                        time.monotonic() - req.t_submit)
                    self._note_request_telemetry(req, True)
                elif fut.done():
                    self._requests["failed"] += 1
                    self._note_request_telemetry(req, False)
        if pr.last_portfolio is not None:
            self.last_portfolio = pr.last_portfolio

    def _absorb_round_stats(self, rnd: BatchRound) -> None:
        """Round-level bookkeeping, fired by the batcher BEFORE any
        request future resolves — so metrics()/last_round_ledger are
        current the moment a client wakes on its result."""
        st = rnd.stats
        with self._metrics_lock:
            self._rounds["count"] += 1
            if rnd.preempted:
                self._rounds["preempted"] += 1
            if rnd.degraded:
                self._rounds["degraded_rounds"] += 1
            for k in ("requests", "cases", "windows", "device_groups",
                      "cross_request_groups", "compile_events",
                      "seeded_windows", "substituted_windows"):
                self._rounds[k] += int(st.get(k, 0))
            self._rounds["batch_sum"] += float(
                st.get("mean_batch", 0.0)) * int(st.get("device_groups", 0))
            self._rounds["round_s"] += float(st.get("round_s", 0.0))
            el = st.get("elastic")
            if el:
                self._elastic["rounds"] += 1
                self._elastic["steals"] += int(el.get("steals", 0))
                mo = el.get("min_occupancy")
                if mo is not None:
                    prev = self._elastic["min_occupancy"]
                    self._elastic["min_occupancy"] = (
                        mo if prev is None else min(prev, mo))
        if rnd.ledger is not None:
            self.last_round_ledger = rnd.ledger
        self._telemetry_round(
            st, rnd.ledger,
            {(rid, key): getattr(s, "certification", None)
             for rid, scens in rnd.scenarios.items()
             for key, s in scens.items()})
        if st.get("round_s"):
            # the backpressure retry-after hint derives from the
            # OBSERVED drain rate: feed the queue this round's sample
            self.queue.note_round(int(st.get("requests", 0)),
                                  float(st["round_s"]))
        # bound the structure cache: a service fed unbounded distinct
        # structures must not grow device/host memory forever — clearing
        # trades a re-precondition for boundedness (same policy as the
        # structure-key memo)
        if self.solver_cache.structures_cached() > \
                self.max_cached_structures:
            TellUser.warning(
                f"service: solver cache at "
                f"{self.solver_cache.structures_cached()} structures "
                f"(bound {self.max_cached_structures}) — clearing")
            self.solver_cache.clear()

    def _telemetry_round(self, st: Dict, ledger: Optional[Dict],
                         cert_by_case: Optional[Dict] = None) -> None:
        """Feed the round's observables into the process metrics
        registry (dervet_tpu/telemetry) — the numbers already exist in
        the stats/ledger; this just makes them survive as time series
        and cross-replica-mergeable histograms.  No-op under the
        telemetry kill switch."""
        if not telemetry_registry.enabled():
            return
        reg = telemetry_registry.get_registry()
        reg.counter("dervet_rounds_total").inc()
        reg.counter(telemetry_ops.M_WINDOWS).inc(
            int(st.get("windows", 0)))
        reg.counter("dervet_compile_events_total").inc(
            int(st.get("compile_events", 0)))
        el = st.get("elastic")
        if el:
            reg.counter(telemetry_ops.M_STEALS).inc(
                int(el.get("steals", 0)))
        warm = (ledger or {}).get("warm_start")
        if warm:
            for grade in ("exact", "near", "predicted", "dual_iterate",
                          "cold"):
                n = int(warm.get(grade, 0))
                if n:
                    reg.counter(telemetry_ops.M_WARM,
                                grade=grade).inc(n)
        accepted = rejected = 0
        for cert in (cert_by_case or {}).values():
            if not cert or not cert.get("enabled"):
                continue
            accepted += (int(cert.get("certified", 0))
                         + int(cert.get("certified_loose", 0)))
            rejected += int(cert.get("rejected", 0))
        if accepted:
            reg.counter(telemetry_ops.M_CERT, verdict="accepted").inc(
                accepted)
        if rejected:
            reg.counter(telemetry_ops.M_CERT, verdict="rejected").inc(
                rejected)
        reg.gauge(telemetry_ops.M_QUEUE_DEPTH).set(self.queue.depth())
        reg.gauge(telemetry_ops.M_DRAIN_RATE).set(
            self.queue.drain_rate() or 0.0)

    def _note_request_telemetry(self, req, ok: bool) -> None:
        """Per-delivery registry counters (caller may hold the metrics
        lock; the registry has its own)."""
        if not telemetry_registry.enabled():
            return
        reg = telemetry_registry.get_registry()
        reg.counter(telemetry_ops.M_REQUESTS,
                    outcome=("completed" if ok else "failed")).inc()
        if ok:
            reg.histogram(telemetry_ops.M_REQ_LATENCY).observe(
                time.monotonic() - req.t_submit)

    def _absorb_request_outcomes(self, rnd: BatchRound) -> None:
        """Per-request accounting after delivery — including requests
        answered during batch assembly (expiry, duplicate id, assembly
        failure), so admitted == completed + failed + pending always
        reconciles."""
        with self._metrics_lock:
            for req in list(rnd.requests) + list(rnd.answered_early):
                fut = req.future
                if fut.done() and fut.exception() is None:
                    self._requests["completed"] += 1
                    self._latencies.append(
                        time.monotonic() - req.t_submit)
                    self._note_request_telemetry(req, True)
                elif fut.done():
                    self._requests["failed"] += 1
                    self._note_request_telemetry(req, False)

    # -- shutdown -------------------------------------------------------
    def _fail_pending(self) -> None:
        """Answer everything still queued with the typed draining error
        (they never started; there is nothing to resume)."""
        self.queue.close()
        for req in self.queue.drain_pending():
            if not req.future.done():
                req.future.set_exception(ServiceClosedError(
                    f"request {req.request_id!r} not started before "
                    "service drain — resubmit to a live service"))

    def request_stop(self, signum=None) -> None:
        """Programmatic drain trigger (what SIGTERM does in the serve
        loop): admissions close immediately, the in-flight round finishes
        or checkpoints, queued requests are answered as not-started."""
        self.supervisor.request_stop(signum)

    def drain(self, timeout: Optional[float] = None) -> None:
        """Stop admissions and wait for the batcher to go quiet.  Waits
        for the in-flight round by default — abandoning it would break
        the resumable-drain contract (futures unanswered, manifests
        unflushed); a second SIGTERM is the documented escape hatch.
        With a ``timeout``, a still-running round is reported loudly and
        the thread handle kept so a later drain can finish the job."""
        self.request_stop()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                TellUser.warning(
                    f"service: batcher still mid-round after {timeout:g}s "
                    "drain timeout — in-flight requests are NOT yet "
                    "answered; drain again (or send a second signal to "
                    "abort)")
                return
            self._thread = None
        else:
            self._fail_pending()

    def close(self) -> None:
        self.drain()

    def __enter__(self) -> "ScenarioService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- observability --------------------------------------------------
    def request_counters(self) -> Dict:
        """Cheap request counters for the replica heartbeat (the full
        :meth:`metrics` walks percentile arrays — too heavy to run every
        heartbeat tick)."""
        with self._metrics_lock:
            return {"completed": self._requests["completed"],
                    "failed": self._requests["failed"]}

    def metrics(self) -> Dict:
        """Service-level metrics: queue depth/rejects, request counts,
        latency percentiles, batch occupancy, compile-cache hits."""
        with self._metrics_lock:
            lat = np.asarray(self._latencies, dtype=float)
            rounds = dict(self._rounds)
            requests = dict(self._requests)
            design = dict(self._design)
            portfolio = dict(self._portfolio)
            montecarlo = dict(self._montecarlo)
            elastic = dict(self._elastic)
        design["screen_s"] = round(design["screen_s"], 3)
        design["screen_candidates_per_s"] = round(
            design["candidates"] / design["screen_s"], 2) \
            if design["screen_s"] else None
        design["caches"] = self.design_caches.snapshot()
        montecarlo["mc_s"] = round(montecarlo["mc_s"], 3)
        montecarlo["samples_per_s"] = round(
            montecarlo["samples"] / montecarlo["mc_s"], 2) \
            if montecarlo["mc_s"] else None
        groups = rounds.pop("batch_sum"), rounds["device_groups"]
        cache = self.solver_cache
        lookups = cache.builds + cache.hits
        return {
            "queue": {"depth": self.queue.depth(),
                      "max_depth": self.queue.max_depth,
                      "closed": self.queue.closed,
                      **self.queue.counters},
            "requests": {**requests,
                         "pending": self.queue.depth()},
            "rounds": rounds,
            # design-service load, separate from scenario rounds so the
            # two request types are distinguishable under pressure
            "design": design,
            # portfolio co-optimization load (dervet_tpu/portfolio):
            # request/round counters plus the last dual loop's full
            # observability section (gap, per-round seeding, cert)
            "portfolio": {**{k: (round(v, 3)
                                 if k in ("portfolio_s", "shard_s")
                                 else v)
                             for k, v in portfolio.items()},
                          "last": self.last_portfolio},
            # monte-carlo uncertainty valuations (dervet_tpu/stochastic):
            # sample volume, tier mix, and the last run's per-round
            # dispatch stats (the zero-compile warm observable)
            "monte_carlo": {**montecarlo, "last": self.last_mc_stats},
            "batch_occupancy": {
                "mean_windows_per_device_batch":
                    round(groups[0] / groups[1], 2) if groups[1] else 0.0,
                "cross_request_groups": rounds["cross_request_groups"],
            },
            "latency_s": {
                "n": int(lat.size),
                "p50": round(float(np.percentile(lat, 50)), 4)
                if lat.size else None,
                "p99": round(float(np.percentile(lat, 99)), 4)
                if lat.size else None,
                "max": round(float(lat.max()), 4) if lat.size else None,
            },
            "compile_cache": {
                "solver_builds": cache.builds,
                "solver_hits": cache.hits,
                "hit_rate": round(cache.hits / lookups, 4)
                if lookups else None,
                "structures_cached": cache.structures_cached(),
                "compile_events_total": rounds["compile_events"],
            },
            # warm-start solution memory (ops/warmstart.py): entry
            # counts, hit grades (incl. the learned-predictor grade),
            # substitutions, stale-seed drills, predictor model stats
            "warm_start": (cache.memory.snapshot()
                           if cache.memory is not None else None),
            # solver core (ops/pdhg.py variants + adaptive cadence):
            # the last round's variant mix, restart/anchor-reset volume,
            # and realized check cadence — the per-group detail lives in
            # the round ledger's entries
            "solver_core": (self.last_round_ledger or {}
                            ).get("solver_core"),
            "service": {"backend": self.backend,
                        "started": self._started,
                        "draining": self._draining.is_set(),
                        "device": self.device_info},
            # mesh-wide elastic scheduler (parallel/elastic.py): round/
            # steal counts plus the last round's per-device slice
            "elastic": {**elastic,
                        "last_round": (self.last_round_ledger or {}
                                       ).get("elastic")},
            # self-healing layer: breaker states, shed/degraded counts,
            # backend-loss recovery counters, poison quarantine
            "resilience": {
                "breakers": self.breakers.snapshot(),
                "load_shedding": (self.shedder.snapshot()
                                  if self.shedder is not None else None),
                "backend_recovery": self.recovery.snapshot(),
                "poison_quarantine": self.poison_registry.snapshot(),
            },
        }


# ---------------------------------------------------------------------------
# `dervet-tpu serve`: the file-spool serving loop
# ---------------------------------------------------------------------------

def serve_main(argv=None) -> int:
    """CLI loop: watch ``SPOOL/incoming/`` for model-parameter files,
    serve each as a request, write results to ``SPOOL/results/<rid>/``.
    SIGTERM/SIGINT drains gracefully and exits 0 (resumable per-request
    manifests under ``--checkpoint-dir``); a second signal aborts."""
    import argparse
    import json
    import os

    from ..utils.supervisor import atomic_write

    parser = argparse.ArgumentParser(
        prog="dervet-tpu serve",
        description="persistent scenario service: cross-request "
                    "continuous batching over a file spool")
    parser.add_argument("spool_dir",
                        help="spool root (incoming/, results/, done/, "
                             "failed/ are created under it)")
    parser.add_argument("--backend", default="jax",
                        choices=["jax", "cpu"],
                        help="dispatch backend for every request "
                             "(default jax — a hot service amortizes "
                             "the compile bill the auto heuristic "
                             "exists to avoid)")
    parser.add_argument("--base-path", default=None,
                        help="root for relative referenced-data paths")
    parser.add_argument("--max-queue-depth", type=int, default=64)
    parser.add_argument("--max-wait-ms", type=float, default=250.0,
                        help="continuous-batching window: how long a "
                             "round holds for stragglers to coalesce")
    parser.add_argument("--max-batch-requests", type=int, default=32)
    parser.add_argument("--poll-s", type=float, default=0.5,
                        help="incoming-directory scan interval")
    parser.add_argument("--checkpoint-dir", default=None,
                        help="resume checkpoints + per-request manifests "
                             "(default: SPOOL/checkpoints)")
    parser.add_argument("--once", action="store_true",
                        help="serve the files already in incoming/, "
                             "then drain and exit (smoke/CI mode)")
    # fleet-replica surface (service/fleet.py + router.py): heartbeats,
    # probe echo, hedge-cancel markers, warm-start memory handoff
    parser.add_argument("--heartbeat-s", type=float, default=0.5,
                        help="rewrite heartbeat.json at this cadence "
                             "(the fleet router's liveness signal; "
                             "0 disables)")
    parser.add_argument("--replica-name", default=None,
                        help="name this replica reports in heartbeats")
    parser.add_argument("--heartbeat-epoch", type=int, default=None,
                        help="incarnation epoch stamped into every "
                             "heartbeat (the lifecycle supervisor bumps "
                             "it on each respawn, so the router can "
                             "tell a replacement's beats from a fenced "
                             "zombie's late writes over the same spool)")
    parser.add_argument("--memory-export-s", type=float, default=2.0,
                        help="publish the warm-start memory export at "
                             "this cadence when it changed (failover "
                             "handoff; 0 disables)")
    parser.add_argument("--telemetry-port", type=int, default=0,
                        help="also serve the Prometheus exposition on "
                             "localhost:<port>/metrics (0 = file "
                             "exposition only)")
    args = parser.parse_args(argv)

    from . import fleet as fleet_mod

    spool = Path(args.spool_dir)
    incoming = spool / "incoming"
    results_root = spool / "results"
    done_dir = spool / "done"
    failed_dir = spool / "failed"
    cancel_dir = spool / fleet_mod.CANCEL_DIR
    memory_in = spool / fleet_mod.MEMORY_IN_DIR
    for d in (incoming, results_root, done_dir, failed_dir, cancel_dir,
              memory_in):
        d.mkdir(parents=True, exist_ok=True)

    # crash-safe journal: every admission/completion is an fsync'd
    # append, so a HARD kill (SIGKILL — no drain path) loses nothing:
    # the restarted loop replays the journal, re-serves unanswered
    # requests idempotently, and finishes interrupted file moves
    from .journal import ServiceJournal
    journal = ServiceJournal(spool / "service_journal.jsonl")
    journal.recover_spool(incoming, done_dir, failed_dir)

    service = ScenarioService(
        backend=args.backend,
        max_queue_depth=args.max_queue_depth,
        max_wait_s=args.max_wait_ms / 1000.0,
        max_batch_requests=args.max_batch_requests,
        checkpoint_dir=args.checkpoint_dir or spool / "checkpoints")
    service.start()
    if args.telemetry_port and telemetry_registry.enabled():
        port = telemetry_registry.get_registry().serve_http(
            args.telemetry_port)
        TellUser.info(f"serve: telemetry exposition on "
                      f"http://127.0.0.1:{port}/metrics")
    pending: Dict[str, Future] = {}

    # -- fleet-replica machinery (no-ops for a solo serve loop) ---------
    import pickle

    from ..utils import faultinject

    admissions = 0              # spool admissions this process has made
    hb_state = {"last": 0.0, "mem_last": 0.0, "mem_stores": -1}

    def write_heartbeat() -> None:
        """Liveness signal for the fleet router: rewritten atomically on
        the SCAN thread, so it keeps beating while the batcher solves —
        a wedged scan loop (or a dead process) is exactly what stops it.
        Echoes the router's probe nonce (breaker half-open probes cost a
        file read, not a solve)."""
        nonce = probe_trace = None
        try:
            probe_doc = json.loads(
                (spool / fleet_mod.PROBE_FILE).read_text())
            nonce = probe_doc.get("nonce")
            # echo the router's probe telemetry context verbatim: the
            # probe span's round-trip closes on the router side when
            # this heartbeat lands (trace context rides the echo)
            probe_trace = probe_doc.get("trace")
        except (OSError, ValueError):
            pass
        mem = service.solver_cache.memory
        atomic_write(spool / fleet_mod.HEARTBEAT_FILE, json.dumps({
            "t": round(time.time(), 3),
            "pid": os.getpid(),
            "name": args.replica_name,
            # incarnation fence: a respawned replacement beats with a
            # HIGHER epoch, so the router can refuse a SIGKILL-survivor
            # zombie's stale writes over the shared spool
            **({"epoch": int(args.heartbeat_epoch)}
               if args.heartbeat_epoch is not None else {}),
            "draining": service.supervisor.stop_requested(),
            "pending": len(pending),
            "queue_depth": service.queue.depth(),
            **service.request_counters(),
            # lock-free approximate reads on purpose: structures_cached
            # wants the solver-cache lock, which get() holds through a
            # multi-second preconditioning build — a heartbeat that
            # blocks on a cold round reads as a dead replica
            "structures": len(service.solver_cache.solvers),
            "memory_entries": (len(mem._entries)
                               if mem is not None else 0),
            "probe_nonce": nonce,
            **({"probe_trace": probe_trace} if probe_trace else {}),
        }))

    def write_telemetry() -> None:
        """Publish the metrics-registry exposition next to the heartbeat
        (``telemetry.prom``, atomic) — the load signal the fleet router
        scrapes so routing follows PUBLISHED queue depth + drain rate
        instead of router-side inflight guesses.  Gated on the kill
        switch: with telemetry off, no file is ever written."""
        if not telemetry_registry.enabled():
            return
        reg = telemetry_registry.get_registry()
        reg.gauge(telemetry_ops.M_QUEUE_DEPTH).set(service.queue.depth())
        reg.gauge(telemetry_ops.M_DRAIN_RATE).set(
            service.queue.drain_rate() or 0.0)
        reg.gauge(telemetry_ops.M_PENDING).set(len(pending))
        for bname, snap in service.breakers.snapshot().items():
            reg.gauge(telemetry_ops.M_BREAKER_OPEN, breaker=bname).set(
                1.0 if snap.get("state") == "open" else 0.0)
        reg.sample()            # ring-buffer time-series tick
        reg.write_prom(spool / telemetry_ops.PROM_FILE)

    def sync_memory() -> None:
        """Warm-start memory handoff, both directions: install exports
        the router dropped into ``memory_in/`` (a dead sibling's
        converged iterates — imported exact-only, so the failover
        re-solve ships verbatim bytes or runs cold, never a bit-shifting
        near seed), and publish this replica's own export when it
        changed since the last publish."""
        mem = service.solver_cache.memory
        if mem is None:
            return
        for f in sorted(memory_in.glob("*.pkl")):
            try:
                n = mem.import_payload(pickle.loads(f.read_bytes()))
                TellUser.info(f"serve: imported {n} warm-start entr"
                              f"{'y' if n == 1 else 'ies'} from "
                              f"{f.name} (exact-only, + seed models)")
            except Exception as e:
                TellUser.warning(
                    f"serve: warm-start import {f.name} unreadable "
                    f"({e}) — discarded")
            f.unlink(missing_ok=True)
        now = time.monotonic()
        if args.memory_export_s and \
                now - hb_state["mem_last"] >= args.memory_export_s:
            hb_state["mem_last"] = now
            stores = mem.snapshot()["stores"]
            if stores != hb_state["mem_stores"]:
                hb_state["mem_stores"] = stores
                atomic_write(spool / fleet_mod.MEMORY_EXPORT_FILE,
                             pickle.dumps(
                                 mem.export_payload(),
                                 protocol=pickle.HIGHEST_PROTOCOL))

    def replica_tick() -> None:
        nonlocal admissions
        # replica_hang drill: the sleep lands HERE, on the heartbeat
        # thread — the process stays alive, heartbeats stop
        faultinject.maybe_replica_hang(admissions)
        now = time.monotonic()
        if args.heartbeat_s and \
                now - hb_state["last"] >= args.heartbeat_s:
            hb_state["last"] = now
            write_heartbeat()
            write_telemetry()
        sync_memory()

    def _error_payload(err: BaseException) -> dict:
        """Uniform machine-readable error record (the typed-error
        family's as_dict; non-typed errors get the same shape)."""
        from ..utils.errors import TypedError
        if isinstance(err, TypedError):
            return err.as_dict()
        return {"error": type(err).__name__, "kind": "error",
                "message": str(err), "retry_hint": None}

    def _export_traces(rid: str) -> None:
        """Per-request trace export into the spool results dir: the span
        tree as ``trace.<rid>.json`` plus the Chrome trace-event
        timeline.  Gated on the kill switch — with telemetry off this
        writes NOTHING (the zero-telemetry-files contract)."""
        if not telemetry_trace.enabled():
            return
        try:
            telemetry_trace.export_request_trace(
                rid, results_root / rid, chrome=True)
        except Exception as e:      # observability must never block
            TellUser.warning(f"serve: trace export for {rid} failed: "
                             f"{e}")

    def _finish(path: Path, rid: str, fut: Future) -> None:
        """Done-callback: persist the request's outputs (or its error),
        journal the outcome, then move the input file out of incoming/
        — in THAT order, so a hard kill at any point either re-serves
        idempotently or replays only the file move (see journal.py)."""
        try:
            err = fut.exception()
            if err is None:
                res = fut.result()
                res.save_as_csv(results_root / rid)
                if res.fidelity != "certified":
                    # the degraded-answer contract: the mark must be
                    # visible in the spool output, not only in-process
                    atomic_write(results_root / rid / "fidelity.json",
                                 json.dumps({
                                     "fidelity": res.fidelity,
                                     "resubmit_hint": res.resubmit_hint,
                                 }, indent=2))
                _export_traces(rid)
                journal.completed(rid, trace_id=telemetry_trace
                                  .trace_id_for(rid)
                                  if telemetry_trace.enabled() else None)
                path.replace(done_dir / path.name)
                TellUser.info(f"serve: request {rid} done -> "
                              f"{results_root / rid}")
            else:
                payload = _error_payload(err)
                atomic_write(failed_dir / f"{path.name}.error.txt",
                             f"{type(err).__name__}: {err}\n")
                atomic_write(failed_dir / f"{path.name}.error.json",
                             json.dumps(payload, indent=2))
                _export_traces(rid)
                journal.failed(rid, payload,
                               trace_id=telemetry_trace.trace_id_for(rid)
                               if telemetry_trace.enabled() else None)
                path.replace(failed_dir / path.name)
                TellUser.error(f"serve: request {rid} failed: {err}")
        except Exception as e:          # never kill the batcher thread
            TellUser.error(f"serve: could not finalize request {rid}: {e}")
        finally:
            # release the id so a new same-named drop is a new request
            pending.pop(rid, None)

    # the serve loop owns the signal handlers (main thread): first
    # SIGTERM/SIGINT -> graceful drain + exit 0, second -> abort
    with service.supervisor:
        while not service.supervisor.stop_requested():
            replica_tick()
            submitted_any = False
            deferred = False
            for path in sorted(incoming.glob("*")):
                if path.suffix.lower() not in (".csv", ".json", ".xml",
                                               ".pkl"):
                    continue
                # file stems become request ids, which name artifact
                # files — sanitize to the admission-safe alphabet (two
                # stems colliding post-sanitization: the second is
                # rejected as a duplicate and parked in failed/)
                rid = re.sub(r"[^A-Za-z0-9._-]", "_",
                             path.stem)[:64] or "req"
                if rid in pending:
                    continue
                # hedge-loser cancellation (fleet router): a cancel
                # marker retracts the input BEFORE admission — the
                # round-boundary contract; once admitted, the round
                # finishes and the router discards the answer
                if (cancel_dir / rid).exists():
                    journal.note("cancelled", rid)
                    path.unlink(missing_ok=True)
                    (cancel_dir / rid).unlink(missing_ok=True)
                    TellUser.info(f"serve: {rid} retracted by cancel "
                                  "marker before admission")
                    continue
                try:
                    if path.suffix.lower() == ".pkl":
                        # fleet transport: pickled cases payload from
                        # the router (same trust domain)
                        fut = service.submit_pickle(path, request_id=rid)
                        pending[rid] = fut
                        journal.admitted(
                            rid, path.name,
                            trace_id=telemetry_trace.trace_id_of(rid))
                        admissions += 1
                        fut.add_done_callback(
                            lambda f, p=path, r=rid: _finish(p, r, f))
                        submitted_any = True
                        # replica_crash drill: hard-exit (SIGKILL-like)
                        # right after the journal recorded the admission
                        # — the batch this request joined is in flight
                        faultinject.maybe_replica_crash(admissions)
                        continue
                    # a JSON file with a top-level "design" object is a
                    # BOOST design request; "portfolio" a coupled-fleet
                    # request; "montecarlo" an uncertainty valuation —
                    # anything else is a model-parameters file
                    is_design = is_portfolio = is_mc = False
                    if path.suffix.lower() == ".json":
                        from ..design.service import is_design_payload
                        from ..portfolio.service import \
                            is_portfolio_payload
                        from ..stochastic.service import \
                            is_montecarlo_payload
                        try:
                            with open(path) as fh:
                                payload = json.load(fh)
                            is_design = is_design_payload(payload)
                            is_portfolio = is_portfolio_payload(payload)
                            is_mc = is_montecarlo_payload(payload)
                        except Exception:
                            is_design = is_portfolio = is_mc = False
                    if is_mc:
                        fut = service.submit_montecarlo_file(
                            path, base_path=args.base_path,
                            request_id=rid)
                    elif is_portfolio:
                        fut = service.submit_portfolio_file(
                            path, base_path=args.base_path,
                            request_id=rid)
                    elif is_design:
                        fut = service.submit_design_file(
                            path, base_path=args.base_path,
                            request_id=rid)
                    else:
                        fut = service.submit_params(
                            path, base_path=args.base_path,
                            request_id=rid)
                except QueueFullError as e:
                    TellUser.warning(
                        f"serve: {rid} deferred (queue full), retrying "
                        f"in {e.retry_after_s:.1f}s")
                    deferred = True
                    break               # leave in incoming/, rescan later
                except ServiceClosedError:
                    break
                except Exception as e:  # unparseable input: park it
                    atomic_write(failed_dir / f"{path.name}.error.txt",
                                 f"{type(e).__name__}: {e}\n")
                    # the machine-readable form too: typed admission
                    # rejections (shard_cache_miss above all) must keep
                    # their kind/retry_hint across the spool hop — the
                    # shard executor switches on the kind to re-send a
                    # full payload
                    atomic_write(failed_dir / f"{path.name}.error.json",
                                 json.dumps(_error_payload(e)))
                    path.replace(failed_dir / path.name)
                    TellUser.error(f"serve: {rid} rejected at admission: "
                                   f"{e}")
                    continue
                pending[rid] = fut
                journal.admitted(rid, path.name,
                                 trace_id=telemetry_trace.trace_id_of(rid))
                admissions += 1
                fut.add_done_callback(
                    lambda f, p=path, r=rid: _finish(p, r, f))
                submitted_any = True
                faultinject.maybe_replica_crash(admissions)
            if args.once:
                if deferred and not service.supervisor.stop_requested():
                    # --once must still serve EVERY input: rescan the
                    # deferred leftovers once backpressure eases instead
                    # of silently exiting 0 with files unprocessed
                    service.supervisor.wait_stop(min(args.poll_s, 1.0))
                    continue
                for fut in list(pending.values()):
                    while not fut.done() and \
                            not service.supervisor.stop_requested():
                        replica_tick()
                        time.sleep(0.05)
                break
            if not submitted_any:
                service.supervisor.wait_stop(
                    min(args.poll_s, args.heartbeat_s or args.poll_s))
        service.drain()
        if args.heartbeat_s:
            write_heartbeat()   # final beat advertises draining=True
        write_telemetry()       # final exposition (no-op when disabled)
    journal.close()
    metrics = service.metrics()
    atomic_write(spool / "service_metrics.json",
                 json.dumps(metrics, indent=2))
    TellUser.info(
        f"serve: drained; {metrics['requests']['completed']} request(s) "
        f"completed, {metrics['requests']['failed']} failed, "
        f"{metrics['queue']['rejected_full'] + metrics['queue']['rejected_overload']} "
        "rejected — metrics in service_metrics.json")
    return 0
