"""Bounded admission queue for the scenario service.

The serving layer's front door: submissions are admitted into a bounded
priority queue (backpressure by REJECTION, never by unbounded buffering —
a saturated service must shed load with a typed, retryable error instead
of growing until the host OOMs mid-solve), ordered by priority then
strict FIFO, each carrying an optional deadline after which the request
is answered with a typed expiry error instead of wasting a device batch.

The continuous batcher drains the queue with :meth:`AdmissionQueue.take`:
block until at least one request is pending, then hold the batch open for
``max_wait_s`` (or until ``max_batch`` requests) so small requests
arriving close together coalesce into one device dispatch — the
cross-request continuous-batching discipline MPAX-style batched LP
solving assumes (PAPERS.md: arxiv 2412.09734) but never provides a
serving harness for.

The ``overload`` fault kind (``DERVET_TPU_FAULT_OVERLOAD[_N]``) forces
admissions down the queue-full rejection path deterministically, so
backpressure and client retry-after handling are drillable like every
other failure mode.
"""
from __future__ import annotations

import collections
import heapq
import itertools
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

from ..utils import faultinject
# the typed-error family lives in utils.errors (one base, machine-
# readable kind + retry_hint); re-exported here for the historical
# service import path
from ..utils.errors import (BreakerOpenError, DeadlineExpiredError,  # noqa: F401
                            PoisonRequestError, QueueFullError,
                            RequestFailedError, RequestPreemptedError,
                            ServiceClosedError, ServiceError,
                            ShardCacheMissError, TypedError)


class QueuedRequest:
    """One admitted submission: the cases to solve, admission metadata,
    and the future the result is delivered through.

    ``kind`` distinguishes the request types the service serves:
    ``"scenario"`` (solve these cases), ``"design"`` (BOOST sizing —
    ``design_case``/``design_spec`` carry the base case + spec, the
    screening phase fills ``cases`` with the finalist candidate cases,
    and ``design_state`` carries the screening results to frontier
    assembly at delivery), and ``"portfolio"`` (coupled-fleet
    co-optimization — ``portfolio_spec`` carries the member cases +
    coupling constraints; the dual loop runs in its own round).  A
    ``"portfolio_shard"`` request is one shard of ANOTHER node's dual
    round (``shard_payload``: site cases + the round's dual-price
    vector), dispatched against this replica's persistent caches — see
    ``dervet_tpu.portfolio.shard``.  A ``"montecarlo"`` request is a
    batched uncertainty valuation (``mc_case``/``mc_spec`` carry the
    base case + sampler spec; the MC round answers it directly — see
    ``dervet_tpu.stochastic``)."""

    __slots__ = ("request_id", "cases", "priority", "deadline", "future",
                 "seq", "t_submit", "fingerprint", "kind", "design_case",
                 "design_spec", "design_state", "portfolio_spec",
                 "shard_payload", "mc_case", "mc_spec", "span",
                 "trace_ctx")

    def __init__(self, request_id: str, cases: Dict, priority: int = 0,
                 deadline_s: Optional[float] = None, seq: int = 0,
                 kind: str = "scenario"):
        self.request_id = str(request_id)
        self.cases = cases
        self.priority = int(priority)
        now = time.monotonic()
        self.deadline = None if deadline_s is None else now + float(deadline_s)
        self.future: Future = Future()
        self.seq = seq
        self.t_submit = now
        # content fingerprint (poison-quarantine registry key), set by
        # the service at admission; None for direct queue users
        self.fingerprint: Optional[str] = None
        self.kind = str(kind)
        self.design_case = None
        self.design_spec = None
        self.design_state = None
        self.portfolio_spec = None
        self.shard_payload = None
        self.mc_case = None
        self.mc_spec = None
        # telemetry (dervet_tpu/telemetry): the request's root span on
        # THIS process (ends when the future resolves) and the upstream
        # trace context it was propagated under (fleet transport)
        self.span = None
        self.trace_ctx = None

    def expired(self) -> bool:
        return self.deadline is not None and time.monotonic() > self.deadline


class AdmissionQueue:
    """Bounded, priority-then-FIFO admission queue with typed rejection.

    Higher ``priority`` pops first; within a priority level the order is
    strict FIFO (a monotone sequence number breaks ties, so two equal-
    priority requests can never reorder).  ``put`` never blocks: a full
    queue — or an active ``overload`` fault — rejects with
    :class:`QueueFullError` carrying a retry-after hint, which is the
    whole backpressure contract (callers retry or shed; the service's
    memory stays bounded)."""

    def __init__(self, max_depth: int = 64,
                 fairness_after_s: float = 30.0):
        self.max_depth = int(max_depth)
        self._cond = threading.Condition()
        self._heap: List[tuple] = []
        self._seq = itertools.count()
        self.closed = False
        # static retry-after fallback, used until round history exists
        # (note_round) lets the hint track the OBSERVED drain rate
        self.retry_after_s = 1.0
        # recent completed rounds: (requests served, round wall seconds)
        # — the drain-rate sample the retry-after hint is derived from
        self._rounds = collections.deque(maxlen=16)
        # fairness floor: a request that has waited longer than this is
        # served ahead of higher priorities — sustained high-priority
        # load can delay low-priority work but never starve it
        self.fairness_after_s = float(fairness_after_s)
        self.counters = {"admitted": 0, "rejected_full": 0,
                         "rejected_overload": 0, "rejected_closed": 0,
                         "expired": 0, "fairness_promotions": 0}

    # ------------------------------------------------------------------
    def note_round(self, requests_served: int, round_s: float) -> None:
        """Record one completed batch round — the drain-rate sample the
        retry-after hint is computed from (called by the server)."""
        if requests_served > 0 and round_s > 0:
            with self._cond:
                self._rounds.append((int(requests_served), float(round_s)))

    def _drain_rate_locked(self) -> Optional[float]:
        """Requests/sec over the recorded rounds; caller holds the
        lock.  The ONE drain-rate computation — both the published
        routing signal and the retry-after hint read it, so they can
        never diverge."""
        if not self._rounds:
            return None
        served = sum(n for n, _ in self._rounds)
        busy_s = sum(s for _, s in self._rounds)
        return served / busy_s if busy_s > 0 else None

    def drain_rate(self) -> Optional[float]:
        """Observed recent drain rate (requests/sec while solving) —
        the load signal the replica publishes in ``telemetry.prom`` and
        the fleet router routes on; None until any round completed."""
        with self._cond:
            return self._drain_rate_locked()

    def _retry_hint(self) -> float:
        """Seconds a rejected caller should wait: queue depth divided by
        the OBSERVED recent drain rate (requests/sec over the last few
        rounds), so the hint tracks real service speed instead of a
        constant.  Falls back to the static ``retry_after_s`` until any
        round has completed.  Caller holds the lock."""
        rate = self._drain_rate_locked()
        if rate is None:
            return self.retry_after_s
        # a full queue drains max_depth requests before a retried
        # admission can land; +1 for the retry itself
        hint = (len(self._heap) + 1) / rate
        return float(min(600.0, max(0.05, hint)))

    def put(self, req: QueuedRequest) -> None:
        """Admit ``req`` or raise a typed rejection (never blocks)."""
        with self._cond:
            if self.closed:
                self.counters["rejected_closed"] += 1
                raise ServiceClosedError(
                    f"request {req.request_id!r} rejected: the service "
                    "is draining — no new admissions")
            if faultinject.maybe_overload():
                self.counters["rejected_overload"] += 1
                hint = self._retry_hint()
                raise QueueFullError(
                    f"request {req.request_id!r} rejected: queue full "
                    "(overload fault injection); retry after "
                    f"{hint:.2f}s", retry_after_s=hint)
            if len(self._heap) >= self.max_depth:
                self.counters["rejected_full"] += 1
                hint = self._retry_hint()
                raise QueueFullError(
                    f"request {req.request_id!r} rejected: queue depth "
                    f"{len(self._heap)} at capacity {self.max_depth}; "
                    f"retry after {hint:.2f}s", retry_after_s=hint)
            req.seq = next(self._seq)
            heapq.heappush(self._heap, (-req.priority, req.seq, req))
            self.counters["admitted"] += 1
            self._cond.notify_all()

    def depth(self) -> int:
        with self._cond:
            return len(self._heap)

    def close(self) -> None:
        """Stop admissions (drain): subsequent ``put`` raises
        :class:`ServiceClosedError`; pending requests stay takeable."""
        with self._cond:
            self.closed = True
            self._cond.notify_all()

    # ------------------------------------------------------------------
    def take(self, max_batch: int = 64, max_wait_s: float = 0.0,
             block: bool = True,
             timeout: Optional[float] = None) -> List[QueuedRequest]:
        """Drain the next batch of requests, in priority-then-FIFO order.

        Blocks (up to ``timeout``) until at least one request is pending,
        then holds the batch open for up to ``max_wait_s`` — the
        continuous-batching window that lets small requests arriving
        close together share one device dispatch — or until ``max_batch``
        requests are pending.  Returns ``[]`` when nothing arrived (or
        the queue closed while empty).

        Requests whose deadline already passed are answered here with
        :class:`DeadlineExpiredError` and excluded from the batch."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while block and not self._heap and not self.closed:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    break
                self._cond.wait(remaining if remaining is not None else 0.5)
            if self._heap and max_wait_s > 0:
                # batching window: wait for stragglers to coalesce
                until = time.monotonic() + max_wait_s
                while len(self._heap) < max_batch and not self.closed:
                    remaining = until - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            # fairness floor: requests waiting past fairness_after_s are
            # served FIRST (oldest first), ahead of priority order — a
            # sustained stream of high-priority work can delay
            # low-priority requests but never starve them out entirely
            now = time.monotonic()
            max_prio = max((e[2].priority for e in self._heap), default=0)
            starved = sorted(
                (entry for entry in self._heap
                 if now - entry[2].t_submit > self.fairness_after_s
                 and entry[2].priority < max_prio),
                key=lambda e: e[1])
            out: List[QueuedRequest] = []
            for entry in starved:
                if len(out) >= max_batch:
                    break
                self._heap.remove(entry)
                req = entry[2]
                if req.expired():
                    self.counters["expired"] += 1
                    req.future.set_exception(DeadlineExpiredError(
                        f"request {req.request_id!r} expired in queue "
                        "before dispatch"))
                    continue
                self.counters["fairness_promotions"] += 1
                out.append(req)
            if starved:
                heapq.heapify(self._heap)
            while self._heap and len(out) < max_batch:
                _, _, req = heapq.heappop(self._heap)
                if req.expired():
                    self.counters["expired"] += 1
                    req.future.set_exception(DeadlineExpiredError(
                        f"request {req.request_id!r} expired in queue "
                        "before dispatch"))
                    continue
                out.append(req)
            return out

    def drain_pending(self) -> List[QueuedRequest]:
        """Pop everything still queued (shutdown path)."""
        with self._cond:
            out = [req for (_, _, req) in sorted(self._heap)]
            self._heap.clear()
            return out
