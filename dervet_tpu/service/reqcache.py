"""Request-level result memoization: the router's content-addressed
whole-request cache, in-flight dedup keys, and per-window delta digests.

ROADMAP item 3 calls request memoization "the single biggest
requests/sec lever left on the serving path": a fleet serving millions
of users sees mostly near-duplicate requests, yet every one pays
admission + dispatch even when some replica already solved it
bit-for-bit.  The PR-8 warm-start ladder proved exact-grade
substitution is safe at *window* granularity (stored solutions
re-verify in float64 before shipping verbatim); this module lifts the
same contract to *whole requests* — the amortize-repeated-work shape
DuaLip-GPU uses for repeated extreme-scale solves and MPAX gets from
persistent compiled-program reuse (PAPERS.md).

Three pieces, all consumed by :class:`~.router.FleetRouter`:

* **Key material** (:func:`key_material` / :func:`material_key`) — a
  request is addressed by its structure fingerprint, a *content*
  digest over every input that reaches the solver (scenario/DER/stream
  params, finance, overrides, and every dataset frame — strictly more
  than ``resilience.case_fingerprint``, which only covers the
  time-series frame), the router's tolerance tag, the ACTIVE
  certification policy, and the solver version.  A tighter cert policy
  can therefore never be served an answer certified under a looser
  one, and a solver upgrade invalidates everything it might now answer
  differently.  Hits re-compare the FULL material, so even a SHA-256
  collision cannot serve wrong bytes.
* **Result cache** (:class:`RequestResultCache`) — bounded LRU over
  complete certificate-carrying artifact sets persisted under
  ``fleet/result_cache/<key>/`` with the PR-2 atomic-rename
  discipline (build in a dot-tmp dir, ``os.replace`` into place).
  Only certified, audit-clean, quarantine-free answers are stored —
  :func:`cacheable` is the single enforcement point — and a PR-4
  certificate rejection anywhere in the process clears every live
  cache through :func:`notify_memory_invalidation` (conservative: the
  rejection is a trust anomaly, and rejections are rare).
* **Delta digests** (:func:`diff_request`) — per-optimization-window
  digests of the time-series slice (labels from the same
  ``build_optimization_levels`` the scenario itself windows with), so
  ``submit_delta`` can tell exactly which windows an edited case
  changed.  Unchanged windows exact-substitute from the target
  replica's warm memory (zero device work, byte-identical bytes);
  changed windows re-solve with near/``dual_iterate`` seeding.

``DERVET_TPU_REQUEST_CACHE=0`` kills the whole plane: no lookups, no
stores, no dedup keys, no on-disk state — today's path bit for bit.
Cache hygiene (ROADMAP 3(d) starter) is env-tunable:
``DERVET_TPU_REQUEST_CACHE_TTL_S`` ages entries out at lookup time
(default: no TTL — LRU only), ``DERVET_TPU_REQUEST_CACHE_MAX_ENTRIES``
overrides the LRU capacity; eviction/expiry counts ride the router's
fleet telemetry exposition.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import threading
import time
import weakref
from collections import OrderedDict
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

ENV = "DERVET_TPU_REQUEST_CACHE"
TTL_ENV = "DERVET_TPU_REQUEST_CACHE_TTL_S"
MAX_ENTRIES_ENV = "DERVET_TPU_REQUEST_CACHE_MAX_ENTRIES"


def _env_positive_float(name: str) -> Optional[float]:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def current_solver_version() -> str:
    """The solver's version tag (``ops.pdhg.SOLVER_VERSION`` — bumped
    whenever solver numerics can change certified answers).  Part of
    every cache key, so stale-version hits are structurally
    impossible; also stamped into run_health + the solve ledger."""
    try:
        from ..ops.pdhg import SOLVER_VERSION
        return str(SOLVER_VERSION)
    except Exception:
        return "unknown"


# artifact names a dir-kind cache entry carries alongside the copied
# results tree
ENTRY_FILE = "entry.json"
ARTIFACTS_DIR = "artifacts"
RESULT_PICKLE = "result.pkl"


def enabled() -> bool:
    """Live read of the kill switch (default ON)."""
    return os.environ.get(ENV, "1").strip().lower() not in (
        "0", "false", "off", "no")


# ---------------------------------------------------------------------------
# Content digests (the "data" component of the key)
# ---------------------------------------------------------------------------

_FRAME_FIELDS = ("time_series", "monthly", "yearly", "tariff",
                 "cycle_life", "load_shed")


def _hash_frame(h, name: str, df) -> None:
    """Fold one dataset frame into ``h``.  ``hash_pandas_object``
    covers values + index for mixed dtypes; the CSV render is the
    (slow, exact) fallback for frames it cannot hash."""
    if df is None:
        h.update(f"{name}:none".encode())
        return
    h.update(f"{name}:".encode())
    h.update(repr(list(map(str, df.columns))).encode())
    try:
        import pandas as pd
        h.update(pd.util.hash_pandas_object(df, index=True)
                 .to_numpy().tobytes())
    except Exception:
        h.update(df.to_csv().encode())


def case_content_digest(case) -> str:
    """Content hash over EVERY input of one :class:`CaseParams` that
    can reach the solver or the artifact set — a strict superset of
    ``resilience.case_fingerprint`` (which hashes only the time-series
    frame): finance, overrides, CBA re-pricing, and all dataset frames
    are folded in, because any of them can change the answer bytes."""
    h = hashlib.sha256()
    h.update(repr(sorted(case.scenario.items(), key=str)).encode())
    for tag, der_id, keys in case.ders:
        h.update(repr((tag, der_id, sorted(keys.items()))).encode())
    for tag, keys in sorted(case.streams.items()):
        h.update(repr((tag, sorted(keys.items()))).encode())
    h.update(repr(sorted(getattr(case, "finance", {}).items(),
                         key=str)).encode())
    for attr in ("overrides", "cba_overrides"):
        h.update(repr(sorted(getattr(case, attr, {}).items(),
                             key=str)).encode())
    ds = getattr(case, "datasets", None)
    for name in _FRAME_FIELDS:
        _hash_frame(h, name, getattr(ds, name, None))
    return h.hexdigest()


def request_content_digest(cases: Dict) -> str:
    """Order-independent content digest of a whole request."""
    h = hashlib.sha256()
    for key in sorted(cases, key=str):
        h.update(str(key).encode())
        h.update(case_content_digest(cases[key]).encode())
    return h.hexdigest()


def cert_policy_tag() -> str:
    """Canonical JSON of the ACTIVE certification policy — part of the
    key, so a tighter policy can never be served an answer that was
    only certified under a looser one."""
    try:
        from ..ops.certify import policy_from_env
        return json.dumps(policy_from_env().as_dict(), sort_keys=True)
    except Exception:
        return "unknown"


def key_material(cases: Dict, *, content_digest: Optional[str] = None,
                 tolerance_tag: str = "default",
                 solver_version: Optional[str] = None,
                 mc_spec=None) -> Dict[str, str]:
    """The full (human-readable) key material for one request.  Stored
    verbatim in each cache entry and re-compared on every hit, so a
    digest collision can never serve a wrong answer.

    ``mc_spec`` (a :class:`~dervet_tpu.stochastic.sampler.MCSpec`)
    folds the Monte-Carlo sampler identity — seed, sample count, shock
    sigmas, quantile/CVaR request — into the key as an EXTRA field, so
    two MC requests over the same base case but a different seed or
    sample count can never collide.  Plain scenario requests omit the
    field entirely: their key material (and thus every existing cache
    entry) is byte-identical to before the field existed."""
    from .fleet import structure_fingerprint
    material = {
        "structure": structure_fingerprint(cases),
        "data": (str(content_digest) if content_digest
                 else request_content_digest(cases)),
        "tolerance": str(tolerance_tag),
        "cert_policy": cert_policy_tag(),
        "solver_version": (str(solver_version) if solver_version
                           else current_solver_version()),
    }
    if mc_spec is not None:
        material["mc"] = json.dumps(mc_spec.normalized(), sort_keys=True)
    return material


def material_key(material: Dict[str, str]) -> str:
    return hashlib.sha256(
        json.dumps(material, sort_keys=True).encode()).hexdigest()


# ---------------------------------------------------------------------------
# Store guard: what is allowed into the cache
# ---------------------------------------------------------------------------

def cacheable(run_health: Optional[Dict],
              fidelity: Optional[str]) -> Tuple[bool, str]:
    """Single enforcement point for the certificate contract: only a
    CERTIFIED, audit-clean, quarantine-free answer may be memoized.
    Degraded-tier answers, certificate-rejected windows, invariant
    audit failures, and quarantined cases all refuse the store — a
    cache must never launder an uncertified answer into a certified
    byte stream."""
    if fidelity is not None and str(fidelity) != "certified":
        return False, f"fidelity={fidelity!r} (not certified)"
    if not isinstance(run_health, dict):
        return False, "no run_health artifact"
    # cases_quarantined is a list of case keys (io/summary.py)
    if run_health.get("cases_quarantined"):
        return False, "request had quarantined cases"
    windows = run_health.get("windows")
    if isinstance(windows, dict) and \
            int(windows.get("quarantined") or 0) > 0:
        return False, "request had quarantined windows"
    cert = run_health.get("certification")
    if isinstance(cert, dict):
        # per-window certificate counts nest under "windows";
        # rejected_final marks windows whose certificate was REFUSED
        # for good (rejected-then-recovered windows end certified and
        # are cacheable)
        cw = cert.get("windows")
        counts = cw if isinstance(cw, dict) else cert
        if int(counts.get("rejected_final") or 0) > 0:
            return False, "certificate-rejected windows in the answer"
    audit = run_health.get("invariant_audit")
    if isinstance(audit, dict) and audit.get("ok") is False:
        return False, "invariant audit not clean"
    return True, "ok"


# ---------------------------------------------------------------------------
# The on-disk LRU result cache
# ---------------------------------------------------------------------------

class CacheHit:
    """One resolved lookup.  ``results_dir`` for artifact (spool)
    entries, ``result`` for in-process (local transport) entries."""

    __slots__ = ("key", "rid", "results_dir", "result")

    def __init__(self, key, rid, results_dir=None, result=None):
        self.key = key
        self.rid = rid
        self.results_dir: Optional[Path] = results_dir
        self.result = result


# every live cache in the process: a PR-4 certificate rejection
# (SolutionMemory.invalidate) clears them all through
# notify_memory_invalidation below
_LIVE_CACHES: "weakref.WeakSet[RequestResultCache]" = weakref.WeakSet()


def notify_memory_invalidation(skey: Optional[str] = None,
                               reason: str = "cert_rejection") -> int:
    """A warm-memory entry was invalidated by a certificate rejection:
    conservatively clear EVERY live request cache in this process.
    Rejections are rare trust anomalies; dropping the whole cache is
    cheap next to serving one answer whose provenance chain includes a
    solution float64 certification just refused.  (Cross-process
    safety does not depend on this hook — a rejected result is never
    stored in the first place, see :func:`cacheable`.)"""
    dropped = 0
    for cache in list(_LIVE_CACHES):
        try:
            dropped += cache.clear(reason=reason)
        except Exception:
            pass
    return dropped


class RequestResultCache:
    """Bounded LRU of complete request answers under ``root``.

    Layout per entry::

        root/<key>/entry.json        # full key material + rid + kind
        root/<key>/artifacts/**      # copied results/<rid>/ tree, or
        root/<key>/result.pkl        # pickled in-process Result

    Writes follow the PR-2 atomic discipline: the entry is built in a
    ``root/.tmp.*`` dir and ``os.replace``d into place, so readers
    (and a crash) see either nothing or a complete entry.  The root
    dir itself is created lazily on the first store — with the kill
    switch on, no cache files OR dirs ever appear."""

    def __init__(self, root, max_entries: int = 256,
                 ttl_s: Optional[float] = None):
        self.root = Path(root)
        # env knobs win over constructor defaults so a deployment can
        # retune cache hygiene without touching router construction
        env_max = _env_positive_float(MAX_ENTRIES_ENV)
        self.max_entries = (int(env_max) if env_max is not None
                            else int(max_entries))
        self.ttl_s = (ttl_s if ttl_s is not None
                      else _env_positive_float(TTL_ENV))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Dict]" = OrderedDict()
        self._counters = {"hits": 0, "misses": 0, "stores": 0,
                          "evictions": 0, "expired": 0, "refused": 0,
                          "collisions": 0, "invalidations": 0}
        self._load()

    # -- persistence ----------------------------------------------------
    def _load(self) -> None:
        """Adopt entries a previous router left on disk (LRU order =
        entry-file mtime).  Unreadable/partial entries are ignored —
        they can only be dot-tmp leftovers or manual damage."""
        if not self.root.is_dir():
            return
        found = []
        for d in self.root.iterdir():
            if not d.is_dir() or d.name.startswith(".tmp"):
                continue
            ef = d / ENTRY_FILE
            try:
                entry = json.loads(ef.read_text())
                mtime = ef.stat().st_mtime
                # pre-TTL entries carry no store time: the entry file's
                # mtime is exactly when the store landed
                entry.setdefault("t", mtime)
                found.append((mtime, d.name, entry))
            except (OSError, ValueError):
                continue
        for _, key, entry in sorted(found):
            self._entries[key] = entry

    def _entry_dir(self, key: str) -> Path:
        return self.root / key

    # -- lookup ---------------------------------------------------------
    def lookup(self, key: str, material: Dict[str, str]
               ) -> Optional[CacheHit]:
        """Resolve a hit, or None.  The stored material is re-compared
        in full — a key collision on different data counts as a miss
        (and a ``collisions`` tick), never a wrong answer."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self._counters["misses"] += 1
                return None
            if self.ttl_s is not None and \
                    time.time() - float(entry.get("t") or 0) > self.ttl_s:
                # aged out: drop memory + disk, count, miss
                self._entries.pop(key, None)
                self._counters["expired"] += 1
                self._counters["misses"] += 1
                shutil.rmtree(self._entry_dir(key), ignore_errors=True)
                return None
            if entry.get("material") != material:
                self._counters["collisions"] += 1
                self._counters["misses"] += 1
                return None
            d = self._entry_dir(key)
            try:
                if entry.get("kind") == "pickle":
                    blob = (d / RESULT_PICKLE).read_bytes()
                    hit = CacheHit(key, entry.get("rid", ""),
                                   result=pickle.loads(blob))
                else:
                    art = d / ARTIFACTS_DIR
                    if not art.is_dir():
                        raise OSError(f"missing {art}")
                    hit = CacheHit(key, entry.get("rid", ""),
                                   results_dir=art)
            except Exception:
                # damaged on disk (wiped mid-flight): drop and miss
                self._entries.pop(key, None)
                self._counters["misses"] += 1
                return None
            self._entries.move_to_end(key)
            self._counters["hits"] += 1
            return hit

    # -- store ----------------------------------------------------------
    def store(self, key: str, material: Dict[str, str], *, rid: str,
              results_dir: Optional[Path] = None, result=None,
              run_health: Optional[Dict] = None,
              fidelity: Optional[str] = None) -> bool:
        """Persist one delivered answer (certificate contract enforced
        here — see :func:`cacheable`).  Returns True when the entry is
        live on disk."""
        ok, _reason = cacheable(run_health, fidelity)
        if not ok:
            with self._lock:
                self._counters["refused"] += 1
            return False
        entry = {"key": key, "material": material, "rid": str(rid),
                 "kind": "dir" if results_dir is not None else "pickle",
                 "solver_version": material.get("solver_version"),
                 "t": round(time.time(), 3)}
        tmp = self.root / f".tmp.{key[:16]}.{os.getpid()}"
        try:
            self.root.mkdir(parents=True, exist_ok=True)
            if tmp.exists():
                shutil.rmtree(tmp)
            tmp.mkdir()
            if results_dir is not None:
                shutil.copytree(results_dir, tmp / ARTIFACTS_DIR)
                # a cached answer is re-served under NEW rids whose
                # run_health.<rid>.json can't exist — materialize the
                # bare-name fallback load_run_health() reads
                for base in ("run_health.json", "solve_ledger.json"):
                    stem, suffix = base.rsplit(".", 1)
                    named = (tmp / ARTIFACTS_DIR /
                             f"{stem}.{rid}.{suffix}")
                    bare = tmp / ARTIFACTS_DIR / base
                    if named.exists() and not bare.exists():
                        shutil.copyfile(named, bare)
            else:
                (tmp / RESULT_PICKLE).write_bytes(
                    pickle.dumps(result, pickle.HIGHEST_PROTOCOL))
            (tmp / ENTRY_FILE).write_text(
                json.dumps(entry, sort_keys=True, indent=1))
            dest = self._entry_dir(key)
            with self._lock:
                if key in self._entries:        # concurrent store won
                    shutil.rmtree(tmp, ignore_errors=True)
                    self._entries.move_to_end(key)
                    return True
                os.replace(tmp, dest)
                self._entries[key] = entry
                self._counters["stores"] += 1
                while len(self._entries) > self.max_entries:
                    old, _ = self._entries.popitem(last=False)
                    self._counters["evictions"] += 1
                    shutil.rmtree(self._entry_dir(old),
                                  ignore_errors=True)
            return True
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            return False

    # -- invalidation ---------------------------------------------------
    def clear(self, reason: str = "") -> int:
        """Drop every entry (memory + disk).  The conservative answer
        to a warm-memory certificate rejection."""
        with self._lock:
            keys = list(self._entries)
            self._entries.clear()
            if keys:
                self._counters["invalidations"] += 1
        for key in keys:
            shutil.rmtree(self._entry_dir(key), ignore_errors=True)
        return len(keys)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> Dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "max_entries": self.max_entries,
                    "ttl_s": self.ttl_s,
                    **self._counters}


def open_cache(root, max_entries: int = 256,
               ttl_s: Optional[float] = None) -> RequestResultCache:
    """Construct + register a cache with the process-wide invalidation
    registry (so PR-4 rejections reach it)."""
    cache = RequestResultCache(root, max_entries=max_entries, ttl_s=ttl_s)
    _LIVE_CACHES.add(cache)
    return cache


# ---------------------------------------------------------------------------
# Per-window delta digests (submit_delta)
# ---------------------------------------------------------------------------

def window_digests(case) -> Optional[Tuple[str, Dict[int, str]]]:
    """``(non_ts_digest, {window_label: ts_slice_digest})`` for one
    case, labeling the time series with the SAME
    ``build_optimization_levels`` the scenario itself windows with —
    so "window" here is exactly the solver's dispatch window.  None
    when the case has no time series or cannot be labeled (callers
    treat that as "everything changed")."""
    ds = getattr(case, "datasets", None)
    ts = getattr(ds, "time_series", None)
    if ts is None or len(ts) == 0:
        return None
    try:
        from ..scenario.window import build_optimization_levels
        labels = build_optimization_levels(
            ts.index, case.scenario.get("n", "year"),
            float(case.scenario.get("dt", 1)))
        lab = np.asarray(labels.to_numpy(), dtype=np.int64)
        arr = np.ascontiguousarray(
            ts.to_numpy(dtype=np.float64, na_value=np.nan))
    except Exception:
        return None
    per: Dict[int, str] = {}
    for v in np.unique(lab):
        per[int(v)] = hashlib.sha256(
            arr[lab == v].tobytes()).hexdigest()
    h = hashlib.sha256()
    h.update(repr(sorted(case.scenario.items(), key=str)).encode())
    for tag, der_id, keys in case.ders:
        h.update(repr((tag, der_id, sorted(keys.items()))).encode())
    for tag, keys in sorted(case.streams.items()):
        h.update(repr((tag, sorted(keys.items()))).encode())
    h.update(repr(sorted(getattr(case, "finance", {}).items(),
                         key=str)).encode())
    for attr in ("overrides", "cba_overrides"):
        h.update(repr(sorted(getattr(case, attr, {}).items(),
                             key=str)).encode())
    h.update(repr(list(map(str, ts.columns))).encode())
    for name in _FRAME_FIELDS:
        if name != "time_series":
            _hash_frame(h, name, getattr(ds, name, None))
    return h.hexdigest(), per


def diff_case(base_case, edited_case
              ) -> Optional[Tuple[List[int], int]]:
    """``(changed_window_labels, total_windows)`` between two cases,
    or None when they are not window-comparable (different structure,
    window scheme, or any non-time-series input changed) — the caller
    must then treat the whole case as changed."""
    a = window_digests(base_case)
    b = window_digests(edited_case)
    if a is None or b is None:
        return None
    (ga, pa), (gb, pb) = a, b
    if ga != gb or set(pa) != set(pb):
        return None
    changed = sorted(k for k in pb if pa[k] != pb[k])
    return changed, len(pb)


def diff_request(base_cases: Dict, edited_cases: Dict
                 ) -> Optional[Dict]:
    """Whole-request delta summary: ``{"windows_changed",
    "windows_total", "per_case"}`` or None when the requests are not
    comparable case-for-case (conservative: all windows changed)."""
    if set(map(str, base_cases)) != set(map(str, edited_cases)):
        return None
    by_str_b = {str(k): v for k, v in base_cases.items()}
    changed = total = 0
    per_case = {}
    for k, edited in edited_cases.items():
        d = diff_case(by_str_b[str(k)], edited)
        if d is None:
            return None
        c, t = d
        changed += len(c)
        total += t
        per_case[str(k)] = {"changed": c, "total": t}
    return {"windows_changed": changed, "windows_total": total,
            "per_case": per_case}
