"""Serving layer: persistent ScenarioService with cross-request
continuous batching (see server.py for the architecture notes), the
self-healing resilience layer (see resilience.py: circuit breakers,
load shedding with degraded-fidelity answers, backend-loss recovery,
poison-request quarantine, crash-safe serve journal), the BOOST
design request type (``submit_design`` — ordinal screening + certified
frontier; engine in ``dervet_tpu.design``, integration in
``design.service``), and the multi-replica fleet tier (``fleet.py`` /
``router.py``: N serve-loop replicas behind a ``FleetRouter`` with
structure-affinity routing, health-probed failover, and exactly-once
recovery of a dead replica's in-flight requests)."""
from ..utils.errors import (FleetUnavailableError, ReplicaAnswerError,
                            ReplicaQuarantinedError)
from .client import ScenarioClient
from .fleet import (LocalReplica, ReplicaHandle, SpoolReplica,
                    spawn_replica, structure_fingerprint)
from .journal import ServiceJournal
from .lifecycle import FleetSupervisor, ReplicaSpec, supervision_enabled
from .queue import (AdmissionQueue, BreakerOpenError, DeadlineExpiredError,
                    PoisonRequestError, QueueFullError, RequestFailedError,
                    RequestPreemptedError, ServiceClosedError, ServiceError)
from .router import FleetRouter, RoutedResult
from .server import ScenarioService, serve_main

__all__ = [
    "AdmissionQueue", "BreakerOpenError", "DeadlineExpiredError",
    "FleetRouter", "FleetSupervisor", "FleetUnavailableError",
    "LocalReplica", "PoisonRequestError", "QueueFullError",
    "ReplicaAnswerError", "ReplicaHandle", "ReplicaQuarantinedError",
    "ReplicaSpec", "RequestFailedError", "RequestPreemptedError",
    "RoutedResult", "ScenarioClient", "ScenarioService",
    "ServiceClosedError", "ServiceError", "ServiceJournal",
    "SpoolReplica", "serve_main", "spawn_replica",
    "structure_fingerprint", "supervision_enabled",
]
