"""Serving layer: persistent ScenarioService with cross-request
continuous batching (see server.py for the architecture notes)."""
from .client import ScenarioClient
from .queue import (AdmissionQueue, DeadlineExpiredError, QueueFullError,
                    RequestFailedError, RequestPreemptedError,
                    ServiceClosedError, ServiceError)
from .server import ScenarioService, serve_main

__all__ = [
    "AdmissionQueue", "DeadlineExpiredError", "QueueFullError",
    "RequestFailedError", "RequestPreemptedError", "ScenarioClient",
    "ScenarioService", "ServiceClosedError", "ServiceError", "serve_main",
]
