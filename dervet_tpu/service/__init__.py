"""Serving layer: persistent ScenarioService with cross-request
continuous batching (see server.py for the architecture notes), the
self-healing resilience layer (see resilience.py: circuit breakers,
load shedding with degraded-fidelity answers, backend-loss recovery,
poison-request quarantine, crash-safe serve journal), and the BOOST
design request type (``submit_design`` — ordinal screening + certified
frontier; engine in ``dervet_tpu.design``, integration in
``design.service``)."""
from .client import ScenarioClient
from .journal import ServiceJournal
from .queue import (AdmissionQueue, BreakerOpenError, DeadlineExpiredError,
                    PoisonRequestError, QueueFullError, RequestFailedError,
                    RequestPreemptedError, ServiceClosedError, ServiceError)
from .server import ScenarioService, serve_main

__all__ = [
    "AdmissionQueue", "BreakerOpenError", "DeadlineExpiredError",
    "PoisonRequestError", "QueueFullError", "RequestFailedError",
    "RequestPreemptedError", "ScenarioClient", "ScenarioService",
    "ServiceClosedError", "ServiceError", "ServiceJournal", "serve_main",
]
